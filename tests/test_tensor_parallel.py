"""Tensor-parallel layer tests on an 8-device virtual CPU mesh.

Philosophy (SURVEY.md §4): run the sharded path on the smallest real
mesh and compare against the dense single-device math — the analog of
the reference's `tests/L0/run_transformer/run_layers_test.py` which
compares TP layers against plain torch.nn modules.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region,
)


@pytest.fixture
def mesh():
    m = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4
    )
    yield m
    parallel_state.destroy_model_parallel()


def shard_tp(mesh, fn, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def test_mesh_shape(mesh):
    assert parallel_state.get_tensor_model_parallel_world_size() == 4
    assert parallel_state.get_data_parallel_world_size() == 2
    assert parallel_state.get_pipeline_model_parallel_world_size() == 1
    assert parallel_state.model_parallel_is_initialized()


def test_mappings_forward(mesh):
    x = jnp.arange(16.0).reshape(2, 8)

    # scatter then gather round-trips
    def roundtrip(x):
        chunk = scatter_to_tensor_model_parallel_region(x)
        assert chunk.shape == (2, 2)
        return gather_from_tensor_model_parallel_region(chunk)

    out = shard_tp(mesh, roundtrip, (P(),), P())(x)
    np.testing.assert_allclose(out, x)

    # reduce sums over tp ranks
    def reduce(x):
        rank = jax.lax.axis_index("tp").astype(jnp.float32)
        return reduce_from_tensor_model_parallel_region(x * 0 + rank)

    out = shard_tp(mesh, reduce, (P(),), P())(x)
    np.testing.assert_allclose(out, np.full((2, 8), 0.0 + 1 + 2 + 3))


def test_copy_region_backward_reduces(mesh):
    """copy_to region: identity fwd, psum bwd
    (reference: apex/transformer/tensor_parallel/mappings.py:79-93)."""
    x = jnp.ones((4,))

    def loss(x):
        xr = copy_to_tensor_model_parallel_region(x)
        rank = jax.lax.axis_index("tp").astype(jnp.float32)
        return jax.lax.psum(jnp.sum(xr * rank), "tp") / 1.0

    g = shard_tp(mesh, jax.grad(loss), (P(),), P())(x)
    # d/dx sum_r sum(x*r) = sum_r r = 6 per element
    np.testing.assert_allclose(g, np.full((4,), 6.0))


def test_column_parallel_linear_matches_dense(mesh):
    layer = ColumnParallelLinear(8, 16, gather_output=True)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))

    dense = x @ params["weight"] + params["bias"]

    specs = layer.param_specs()
    sharded = jax.device_put(params, jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs))
    out = shard_tp(mesh, layer.apply, (specs, P()), P())(sharded, x)
    np.testing.assert_allclose(out, dense, rtol=1e-5, atol=1e-5)


def test_row_parallel_linear_matches_dense(mesh):
    layer = RowParallelLinear(8, 6, input_is_parallel=False)
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))

    dense = x @ params["weight"] + params["bias"]
    specs = layer.param_specs()
    sharded = jax.device_put(params, jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs))
    out = shard_tp(mesh, layer.apply, (specs, P()), P())(sharded, x)
    np.testing.assert_allclose(out, dense, rtol=1e-5, atol=1e-5)


def test_column_row_stack_grads_match_dense(mesh):
    """Megatron MLP pattern: column (no gather) → row (input parallel).
    Forward AND backward must match the dense computation."""
    col = ColumnParallelLinear(8, 16, gather_output=False)
    row = RowParallelLinear(16, 8, input_is_parallel=True)
    cparams = col.init(jax.random.PRNGKey(0))
    rparams = row.init(jax.random.PRNGKey(1))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 8))

    def dense_loss(cp, rp, x):
        h = jax.nn.gelu(x @ cp["weight"] + cp["bias"])
        y = h @ rp["weight"] + rp["bias"]
        return jnp.sum(y ** 2)

    def tp_loss(cp, rp, x):
        h = jax.nn.gelu(col.apply(cp, x))
        y = row.apply(rp, h)
        return jnp.sum(y ** 2)

    want_loss = dense_loss(cparams, rparams, x)
    want_g = jax.grad(dense_loss, argnums=(0, 1))(cparams, rparams, x)

    cspecs, rspecs = col.param_specs(), row.param_specs()
    csh = jax.device_put(cparams, jax.tree.map(lambda s: NamedSharding(mesh, s), cspecs))
    rsh = jax.device_put(rparams, jax.tree.map(lambda s: NamedSharding(mesh, s), rspecs))

    fn = shard_tp(
        mesh,
        lambda cp, rp, x: (tp_loss(cp, rp, x),
                           jax.grad(tp_loss, argnums=(0, 1))(cp, rp, x)),
        (cspecs, rspecs, P()),
        (P(), (cspecs, rspecs)),
    )
    got_loss, got_g = fn(csh, rsh, x)
    np.testing.assert_allclose(got_loss, want_loss, rtol=1e-4)
    for want, got in zip(jax.tree.leaves(want_g), jax.tree.leaves(got_g)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


def test_vocab_parallel_embedding(mesh):
    emb = VocabParallelEmbedding(32, 8)
    params = emb.init(jax.random.PRNGKey(0))
    ids = jnp.array([[0, 5, 31], [8, 16, 24]])

    dense = jnp.take(params["weight"], ids, axis=0)
    specs = emb.param_specs()
    sharded = jax.device_put(params, jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs))
    out = shard_tp(mesh, emb.apply, (specs, P()), P())(sharded, ids)
    np.testing.assert_allclose(out, dense, rtol=1e-6)


def test_vocab_parallel_cross_entropy(mesh):
    """TP cross-entropy matches dense log-softmax CE
    (reference: tests/L0/run_transformer/run_cross_entropy_test.py)."""
    vocab, batch, seq = 32, 2, 3
    logits = jax.random.normal(jax.random.PRNGKey(0), (batch, seq, vocab))
    target = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, vocab)

    want = -jax.nn.log_softmax(logits, axis=-1)
    want = jnp.take_along_axis(want, target[..., None], axis=-1)[..., 0]

    fn = shard_tp(
        mesh,
        lambda l, t: vocab_parallel_cross_entropy(l, t),
        (P(None, None, "tp"), P()),
        P(),
    )
    got = fn(logits, target)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    # gradient = softmax - onehot, check through the sharded path
    def tp_mean_loss(l, t):
        return jnp.mean(vocab_parallel_cross_entropy(l, t))

    def dense_mean_loss(l, t):
        lsm = -jax.nn.log_softmax(l, axis=-1)
        return jnp.mean(jnp.take_along_axis(lsm, t[..., None], axis=-1))

    gfn = shard_tp(mesh, jax.grad(tp_mean_loss), (P(None, None, "tp"), P()),
                   P(None, None, "tp"))
    got_g = gfn(logits, target)
    want_g = jax.grad(dense_mean_loss)(logits, target)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(want_g),
                               rtol=1e-4, atol=1e-5)


class TestEncoderDecoderSplit:
    """ModelType.encoder_and_decoder pipeline layer split
    (reference: schedules/common.py:18-108, parallel_state split rank)."""

    def test_split_layer_math(self):
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=4,
            pipeline_model_parallel_split_rank_=1,
        )
        try:
            assert parallel_state.get_pipeline_model_parallel_split_rank() == 1
            # 6 encoder layers on 1 stage; 9 decoder layers on 3 stages
            assert parallel_state.get_num_layers(
                6, is_encoder_and_decoder_model=True, decoder_layers=9,
                stage=0,
            ) == 6
            assert parallel_state.get_num_layers(
                6, is_encoder_and_decoder_model=True, decoder_layers=9,
                stage=2,
            ) == 3
            assert parallel_state.is_pipeline_stage_before_split(0)
            assert not parallel_state.is_pipeline_stage_before_split(1)
            assert parallel_state.is_pipeline_stage_after_split(1)
            assert parallel_state.is_pipeline_stage_at_split(0)
            with pytest.raises(ValueError):
                parallel_state.get_num_layers(
                    7, is_encoder_and_decoder_model=True, stage=3
                )
        finally:
            parallel_state.destroy_model_parallel()

    def test_split_requires_configuration(self):
        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=4
        )
        try:
            with pytest.raises(RuntimeError):
                parallel_state.get_num_layers(
                    8, is_encoder_and_decoder_model=True
                )
            # no split configured: every stage counts as both sides,
            # matching the reference's defaults
            assert parallel_state.is_pipeline_stage_before_split(3)
            assert parallel_state.is_pipeline_stage_after_split(0)
        finally:
            parallel_state.destroy_model_parallel()

    def test_split_rank_bounds(self):
        with pytest.raises(RuntimeError):
            parallel_state.initialize_model_parallel(
                pipeline_model_parallel_size_=4,
                pipeline_model_parallel_split_rank_=4,
            )
        parallel_state.destroy_model_parallel()


def test_ce_from_hidden_matches_two_step():
    """Fused chunked CE (logits never materialized) == logits + CE, values
    and grads, on the tp=4 mesh (reference capability:
    apex/contrib/csrc/xentropy fused CE, here fused through the LM head)."""
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy,
        vocab_parallel_cross_entropy_from_hidden,
    )

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4
    )
    try:
        n, h, vocab, chunk = 16, 32, 64, 8
        x = jax.random.normal(jax.random.PRNGKey(0), (n, h), jnp.float32)
        w = 0.5 * jax.random.normal(
            jax.random.PRNGKey(1), (vocab, h), jnp.float32
        )
        t = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, vocab)

        def fused(x, w, t):
            return jnp.mean(vocab_parallel_cross_entropy_from_hidden(
                x, w, t, chunk=chunk
            ))

        def two_step(x, w, t):
            logits = jnp.einsum("nh,vh->nv", x, w)
            return jnp.mean(vocab_parallel_cross_entropy(logits, t))

        wspec = P("tp", None)
        outs = {}
        for name, fn in (("fused", fused), ("two_step", two_step)):
            vg = jax.jit(jax.shard_map(
                jax.value_and_grad(fn, argnums=(0, 1)), mesh=mesh,
                in_specs=(P(), wspec, P()),
                out_specs=(P(), (P(), wspec)),
            ))
            outs[name] = vg(x, w, t)
        (lf, (dxf, dwf)), (l2, (dx2, dw2)) = outs["fused"], outs["two_step"]
        np.testing.assert_allclose(float(lf), float(l2), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(dxf), np.asarray(dx2), rtol=1e-4, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(dwf), np.asarray(dw2), rtol=1e-4, atol=1e-6
        )
        # indivisible chunk falls back to the two-step path
        val = jax.jit(jax.shard_map(
            lambda x, w, t: jnp.mean(vocab_parallel_cross_entropy_from_hidden(
                x, w, t, chunk=7
            )),
            mesh=mesh, in_specs=(P(), wspec, P()), out_specs=P(),
        ))(x, w, t)
        np.testing.assert_allclose(float(val), float(l2), rtol=1e-5)
    finally:
        parallel_state.destroy_model_parallel()


def test_ce_from_hidden_with_bias_matches():
    """Fused CE with a per-vocab bias (the BERT MLM head shape) == the
    two-step logits+bias path, values and all three grads."""
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy,
        vocab_parallel_cross_entropy_from_hidden,
    )

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4
    )
    try:
        n, h, vocab, chunk = 12, 16, 32, 4
        x = jax.random.normal(jax.random.PRNGKey(0), (n, h), jnp.float32)
        w = 0.5 * jax.random.normal(
            jax.random.PRNGKey(1), (vocab, h), jnp.float32
        )
        bias = 0.3 * jax.random.normal(
            jax.random.PRNGKey(3), (vocab,), jnp.float32
        )
        t = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, vocab)

        def fused(x, w, b, t):
            return jnp.mean(vocab_parallel_cross_entropy_from_hidden(
                x, w, t, chunk=chunk, bias=b
            ))

        def two_step(x, w, b, t):
            logits = jnp.einsum("nh,vh->nv", x, w) + b[None, :]
            return jnp.mean(vocab_parallel_cross_entropy(logits, t))

        wspec = P("tp", None)
        bspec = P("tp")
        outs = {}
        for name, fn in (("fused", fused), ("two_step", two_step)):
            vg = jax.jit(jax.shard_map(
                jax.value_and_grad(fn, argnums=(0, 1, 2)), mesh=mesh,
                in_specs=(P(), wspec, bspec, P()),
                out_specs=(P(), (P(), wspec, bspec)),
            ))
            outs[name] = vg(x, w, bias, t)
        (lf, gf), (l2, g2) = outs["fused"], outs["two_step"]
        np.testing.assert_allclose(float(lf), float(l2), rtol=1e-5)
        for a, b in zip(gf, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_ce_smoothing_matches_contrib_xentropy():
    """Label-smoothed vocab-parallel CE (two-step AND fused-from-hidden)
    == the single-device contrib.xentropy formula, values and grads."""
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy,
        vocab_parallel_cross_entropy_from_hidden,
    )

    s = 0.1
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4
    )
    try:
        n, h, vocab, chunk = 12, 16, 32, 4
        x = jax.random.normal(jax.random.PRNGKey(0), (n, h), jnp.float32)
        w = 0.5 * jax.random.normal(
            jax.random.PRNGKey(1), (vocab, h), jnp.float32
        )
        t = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, vocab)

        # single-device reference: dense logits + contrib formula
        def ref(x, w, t):
            from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
            return jnp.mean(softmax_cross_entropy_loss(
                jnp.einsum("nh,vh->nv", x, w), t, smoothing=s
            ))

        ref_loss, ref_dx = jax.value_and_grad(ref)(x, w, t)

        wspec = P("tp", None)
        for name, fn in (
            ("fused", lambda x, w, t: jnp.mean(
                vocab_parallel_cross_entropy_from_hidden(
                    x, w, t, chunk=chunk, smoothing=s))),
            ("two_step", lambda x, w, t: jnp.mean(
                vocab_parallel_cross_entropy(
                    jnp.einsum("nh,vh->nv", x, w), t, smoothing=s))),
        ):
            vg = jax.jit(jax.shard_map(
                jax.value_and_grad(fn), mesh=mesh,
                in_specs=(P(), wspec, P()), out_specs=(P(), P()),
            ))
            loss, dx = vg(x, w, t)
            np.testing.assert_allclose(
                float(loss), float(ref_loss), rtol=1e-5, err_msg=name
            )
            np.testing.assert_allclose(
                np.asarray(dx), np.asarray(ref_dx), rtol=1e-4, atol=1e-6,
                err_msg=name,
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_lm_head_ce_auto_dispatch(monkeypatch):
    """fused=None (the GPT/BERT/T5 default) routes by the materialized-
    logits residual size: <= FUSED_CE_AUTO_BYTES takes the two-step path
    (measured faster on v5e, PROFILE_r05), above it the fused scan
    (memory-bounded).  The boundary is strict-greater: exactly-at-the-
    threshold stays two-step."""
    from apex_tpu.transformer.tensor_parallel import cross_entropy as ce

    calls = []
    real_fused = ce.vocab_parallel_cross_entropy_from_hidden
    real_two = ce.vocab_parallel_cross_entropy
    monkeypatch.setattr(
        ce, "vocab_parallel_cross_entropy_from_hidden",
        lambda *a, **k: (calls.append("fused"), real_fused(*a, **k))[1],
    )
    monkeypatch.setattr(
        ce, "vocab_parallel_cross_entropy",
        lambda *a, **k: (calls.append("two_step"), real_two(*a, **k))[1],
    )

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4
    )
    try:
        n, h, vocab = 16, 32, 64
        x = jax.random.normal(jax.random.PRNGKey(0), (n, h), jnp.float32)
        w = 0.5 * jax.random.normal(
            jax.random.PRNGKey(1), (vocab, h), jnp.float32
        )
        t = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, vocab)

        def run(x, w, t):
            return jnp.mean(ce.lm_head_cross_entropy(x, w, t, chunk=8))

        def call():
            return jax.jit(jax.shard_map(
                run, mesh=mesh,
                in_specs=(P(), P("tp", None), P()), out_specs=P(),
            ))(x, w, t)

        # local shard bytes: n * (vocab/tp) * 4 = 16 * 16 * 4 = 1024
        monkeypatch.setattr(ce, "FUSED_CE_AUTO_BYTES", 1024)
        call()  # == threshold: strict >, stays two-step
        assert calls == ["two_step"]
        monkeypatch.setattr(ce, "FUSED_CE_AUTO_BYTES", 1023)
        call()
        assert calls == ["two_step", "fused"]
        monkeypatch.setattr(ce, "FUSED_CE_AUTO_BYTES", 1 << 31)
        call()  # production threshold: tiny logits -> two-step
        assert calls == ["two_step", "fused", "two_step"]
    finally:
        parallel_state.destroy_model_parallel()


def test_clip_grad_norm_model_parallel_aware():
    """Sharded-leaf contributions psum over tp, replicated leaves count
    once: the tp=4 clipped grads and norm must equal the dense
    single-logical-device computation on the gathered weights."""
    from apex_tpu.transformer.tensor_parallel import (
        ColumnParallelLinear,
        clip_grad_norm,
    )

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4
    )
    try:
        col = ColumnParallelLinear(16, 32, gather_output=True)
        params = col.init(jax.random.PRNGKey(0))
        specs = {"col": col.param_specs(), "ln": {"scale": P()}}
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))

        def loss(p, x):
            y = col.apply(p["col"], x)
            return jnp.sum(jnp.square(y)) + jnp.sum(
                jnp.square(p["ln"]["scale"]))

        full = {"col": params, "ln": {"scale": jnp.ones((16,)) * 2.0}}

        def step(p, x):
            grads = jax.grad(loss)(p, x)
            return clip_grad_norm(grads, specs, max_norm=1.0)

        clipped, norm = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(specs, P()), out_specs=(specs, P()),
        ))(full, x)

        # dense reference: the same math written without collectives
        def dense_loss(p, x):
            y = x @ p["col"]["weight"] + p["col"]["bias"]
            return jnp.sum(jnp.square(y)) + jnp.sum(
                jnp.square(p["ln"]["scale"]))

        ref_grads = jax.grad(dense_loss)(full, x)
        ref_norm = jnp.sqrt(sum(
            jnp.sum(jnp.square(g)) for g in jax.tree.leaves(ref_grads)))
        np.testing.assert_allclose(float(norm), float(ref_norm),
                                   rtol=1e-5)
        scale = min(1.0, 1.0 / float(ref_norm))
        for a, b in zip(jax.tree.leaves(clipped),
                        jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b) * scale, rtol=1e-5,
                atol=1e-7)
    finally:
        parallel_state.destroy_model_parallel()


def test_clip_grad_norm_structure_mismatch_raises():
    from apex_tpu.transformer.tensor_parallel import clip_grad_norm

    with pytest.raises(ValueError, match="structure mismatch"):
        clip_grad_norm({"a": jnp.ones(3)},
                       {"a": P(), "b": P()}, 1.0)


def test_clip_grad_norm_counts_expert_dp_shards():
    """MoE expert leaves ride 'dp' as the ep axis (different experts per
    dp rank): their contributions must psum over dp, or each rank would
    clip by a different 'global' norm."""
    from apex_tpu.transformer.tensor_parallel import clip_grad_norm

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4
    )  # dp=2 x tp=4
    try:
        specs = {"expert": P("dp", None), "rep": P()}
        # expert grads differ per dp rank; replicated leaf identical
        expert = jnp.stack([jnp.full((4,), 3.0), jnp.full((4,), 4.0)])
        grads = {"expert": expert, "rep": jnp.full((2,), 1.0)}

        def step(g):
            return clip_grad_norm(g, specs, max_norm=1e9)[1]

        norm = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=({"expert": P("dp", None),
                                        "rep": P()},), out_specs=P(),
        ))(grads)
        # global: 4*9 + 4*16 (both dp shards) + 2*1 = 102
        np.testing.assert_allclose(float(norm), float(np.sqrt(102.0)),
                                   rtol=1e-6)
    finally:
        parallel_state.destroy_model_parallel()

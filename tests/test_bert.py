"""BERT model tests (the reference's run_bert_minimal_test analog)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models.bert import BertConfig, BertModel
from apex_tpu.transformer import parallel_state


def small_config(**kw):
    base = dict(
        vocab_size=64, num_layers=2, hidden_size=32, num_attention_heads=4,
        max_position_embeddings=16, compute_dtype=jnp.float32, remat=False,
    )
    base.update(kw)
    return BertConfig(**base)


def make_batch(key, b=8, s=12, vocab=64):
    ks = jax.random.split(key, 5)
    return dict(
        tokens=jax.random.randint(ks[0], (b, s), 0, vocab),
        lm_labels=jax.random.randint(ks[1], (b, s), 0, vocab),
        loss_mask=jax.random.bernoulli(ks[2], 0.15, (b, s)),
        attention_mask=jnp.ones((b, s), bool).at[:, -2:].set(False),
        binary_labels=jax.random.randint(ks[3], (b,), 0, 2),
        tokentype_ids=jax.random.randint(ks[4], (b, s), 0, 2),
    )


def run_loss(tp, batch, remat=False):
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=tp
    )
    try:
        model = BertModel(small_config(remat=remat))
        params = model.init(jax.random.PRNGKey(0))
        specs = model.param_specs()

        def loss_fn(p, tokens, lm_labels, loss_mask, attention_mask,
                    binary_labels, tokentype_ids):
            return model.loss(p, tokens, lm_labels, loss_mask,
                              attention_mask, binary_labels, tokentype_ids)

        fn = jax.jit(
            jax.shard_map(
                jax.value_and_grad(loss_fn),
                mesh=mesh,
                in_specs=(specs,) + (P("dp"),) * 6,
                out_specs=(P(), specs),
            )
        )
        placed = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P))
        )
        loss, grads = fn(
            placed, batch["tokens"], batch["lm_labels"], batch["loss_mask"],
            batch["attention_mask"], batch["binary_labels"],
            batch["tokentype_ids"],
        )
        return float(loss), jax.device_get(grads)
    finally:
        parallel_state.destroy_model_parallel()


def test_bert_loss_tp_invariant():
    batch = make_batch(jax.random.PRNGKey(1))
    loss1, grads1 = run_loss(1, batch)
    loss4, grads4 = run_loss(4, batch)
    assert np.isfinite(loss1)
    np.testing.assert_allclose(loss4, loss1, rtol=2e-4)
    for (ka, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(grads4),
        jax.tree_util.tree_leaves_with_path(grads1),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5,
            err_msg=str(ka),
        )


def test_bert_attention_mask_blocks_padding():
    """Changing a masked-out token must not change other positions'
    hidden states."""
    mesh = parallel_state.initialize_model_parallel()
    try:
        model = BertModel(small_config())
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 12), 0, 64)
        mask = jnp.ones((8, 12), bool).at[:, 10:].set(False)

        specs = model.param_specs()
        fn = jax.jit(
            jax.shard_map(
                lambda p, t, m: model.encode(p, t, m),
                mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=P("dp"),
            )
        )
        a = fn(params, tokens, mask)
        tokens2 = tokens.at[:, 11].set(0)
        b = fn(params, tokens2, mask)
        np.testing.assert_allclose(
            np.asarray(a[:, :10]), np.asarray(b[:, :10]), atol=1e-5
        )
    finally:
        parallel_state.destroy_model_parallel()


def test_bert_without_binary_head():
    mesh = parallel_state.initialize_model_parallel()
    try:
        model = BertModel(small_config(add_binary_head=False))
        params = model.init(jax.random.PRNGKey(0))
        assert "binary_head" not in params
        specs = model.param_specs()
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 12), 0, 64)
        fn = jax.jit(
            jax.shard_map(
                lambda p, t: model.apply(p, t)[0],
                mesh=mesh,
                in_specs=(specs, P("dp")),
                out_specs=P("dp", None, "tp"),
            )
        )
        lm = fn(params, tokens)
        assert lm.shape == (8, 12, 64)
    finally:
        parallel_state.destroy_model_parallel()


def test_bert_pipeline_matches_sequential():
    """pp=2 x tp=2 x dp=2 BERT pipeline loss+grads == the sequential
    loss (reference: run_bert_minimal_test.py pipeline tier)."""
    from apex_tpu.transformer.pipeline_parallel import sync_replicated_grads

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2
    )
    try:
        cfg = small_config()
        model = BertModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        ks = jax.random.split(jax.random.PRNGKey(7), 5)
        tokens = jax.random.randint(ks[0], (8, 12), 0, cfg.vocab_size)
        labels = jax.random.randint(ks[1], (8, 12), 0, cfg.vocab_size)
        loss_mask = (jax.random.uniform(ks[2], (8, 12)) < 0.4).astype(
            jnp.float32)
        attn_mask = jax.random.uniform(ks[3], (8, 12)) < 0.9
        bin_labels = jax.random.randint(ks[4], (8,), 0, 2)

        seq_specs = model.param_specs()

        def seq_fn(p, t, l, m, a, b):
            return model.loss(p, t, l, m, attention_mask=a,
                              binary_labels=b)

        seq_grad = jax.jit(jax.shard_map(
            jax.value_and_grad(seq_fn), mesh=mesh,
            in_specs=(seq_specs,) + (P("dp"),) * 5,
            out_specs=(P(), seq_specs),
        ))

        def place(tree, sp):
            return jax.device_put(tree, jax.tree.map(
                lambda s: NamedSharding(mesh, s), sp,
                is_leaf=lambda x: isinstance(x, P)))

        ref_loss, ref_grads = seq_grad(
            place(params, seq_specs), tokens, labels, loss_mask,
            attn_mask, bin_labels,
        )
        expected = float(ref_loss)
        ref_grads = jax.device_get(ref_grads)

        pp_specs = model.pipeline_param_specs()

        def pp_fn(p, t, l, m, a, b):
            loss, grads = jax.value_and_grad(
                lambda pp_: model.pipeline_loss(
                    pp_, t, l, m, 2, attention_mask=a, binary_labels=b)
            )(p)
            grads = sync_replicated_grads(grads, pp_specs)
            return loss, grads

        grad_fn = jax.jit(jax.shard_map(
            pp_fn, mesh=mesh,
            in_specs=(pp_specs,) + (P("dp"),) * 5,
            out_specs=(P(), pp_specs),
        ))
        loss, grads = grad_fn(
            place(params, pp_specs), tokens, labels, loss_mask,
            attn_mask, bin_labels,
        )
        np.testing.assert_allclose(float(loss), expected, rtol=2e-5)
        # leaf-wise grad parity against the sequential path (same
        # logical param tree; the pipeline's "layers" leading dim is
        # merely pp-sharded at placement)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(grads)),
            jax.tree_util.tree_leaves_with_path(ref_grads),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6,
                err_msg=str(path),
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_bert_pipeline_grads_matches_sequential():
    """BERT fwd+bwd through the dispatched 1F1B schedule == sequential
    loss+grads (same comparison as the GPipe pipeline test — the
    per-microbatch scalars fold in the precomputed global mask
    denominator, so gradients are exact)."""
    from apex_tpu.transformer.pipeline_parallel import sync_replicated_grads

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2
    )
    try:
        cfg = small_config()
        model = BertModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        ks = jax.random.split(jax.random.PRNGKey(7), 5)
        tokens = jax.random.randint(ks[0], (8, 12), 0, cfg.vocab_size)
        labels = jax.random.randint(ks[1], (8, 12), 0, cfg.vocab_size)
        loss_mask = (jax.random.uniform(ks[2], (8, 12)) < 0.4).astype(
            jnp.float32)
        attn_mask = jax.random.uniform(ks[3], (8, 12)) < 0.9
        bin_labels = jax.random.randint(ks[4], (8,), 0, 2)

        seq_specs = model.param_specs()

        def place(tree, sp):
            return jax.device_put(tree, jax.tree.map(
                lambda s: NamedSharding(mesh, s), sp,
                is_leaf=lambda x: isinstance(x, P)))

        # NOTE: model.loss psums over dp inside, and the params enter
        # dp-invariant, so autodiff already inserts the dp psum — these
        # grads are the full global gradient, directly comparable to
        # pipeline_grads' explicitly-psum'd ones.
        seq_grad = jax.jit(jax.shard_map(
            jax.value_and_grad(
                lambda p, t, l, m, a, b: model.loss(
                    p, t, l, m, attention_mask=a, binary_labels=b)
            ),
            mesh=mesh,
            in_specs=(seq_specs,) + (P("dp"),) * 5,
            out_specs=(P(), seq_specs),
        ))
        ref_loss, ref_grads = seq_grad(
            place(params, seq_specs), tokens, labels, loss_mask,
            attn_mask, bin_labels,
        )
        ref_grads = jax.device_get(ref_grads)

        pp_specs = model.pipeline_param_specs()
        fb = jax.jit(jax.shard_map(
            lambda p, t, l, m, a, b: model.pipeline_grads(
                p, t, l, m, 2, attention_mask=a, binary_labels=b),
            mesh=mesh,
            in_specs=(pp_specs,) + (P("dp"),) * 5,
            out_specs=(P(), pp_specs),
        ))
        loss, grads = fb(
            place(params, pp_specs), tokens, labels, loss_mask,
            attn_mask, bin_labels,
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(grads)),
            jax.tree_util.tree_leaves_with_path(ref_grads),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6,
                err_msg=str(path),
            )
    finally:
        parallel_state.destroy_model_parallel()

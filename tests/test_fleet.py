"""Fleet tier: SLO policy and admission control, prefix-affinity
routing, the replayable request log, zero-loss replica failover, the
deterministic load generator, and the metrics-report fleet section.

The load-bearing claims, each pinned here:

- :class:`FleetPolicy` is the one validated spec: bad routing modes,
  duplicate classes and unknown class lookups fail loudly at
  construction, not mid-trace;
- admission control rejects (never hangs, never loses) requests that
  can never be served — replay headroom included — and classes at
  ``max_queue``;
- the routing key (:func:`prompt_page_hashes`) is replica-independent
  and affinity routing sends shared-prefix cohorts to the replica
  holding their pages;
- :class:`RequestLog` + :func:`resume_request` reconstruct a migrated
  request as prompt + committed tokens with the budget shrunk, and a
  killed replica's in-flight work completes elsewhere token-identical
  to an unkilled run;
- the SAME ``Request.seed`` produces the SAME sampled stream across
  DIFFERENT batcher instances, admission orders and slot assignments
  (the cross-replica determinism the failover contract stands on);
- ``tools/load_gen.py`` traces are byte-deterministic per seed, and a
  replay's records score into the fleet section of
  ``tools/metrics_report.py``;
- ``bench.py`` extras MERGE into BENCH_EXTRA.json — a fleet-only run
  must not clobber rows an earlier fuller capture wrote.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from apex_tpu.fleet import (
    BATCH,
    INTERACTIVE,
    FleetPolicy,
    FleetRouter,
    LogEntry,
    Replica,
    RequestLog,
    SLOClass,
    resume_request,
)
from apex_tpu.serving.kv_cache import (
    KVCacheConfig,
    PagedKVCache,
    init_pools,
    prompt_page_hashes,
)
from apex_tpu.serving.serve import ContinuousBatcher, Request


# ---------------------------------------------------------------------------
# policy + request log: pure host, no model
# ---------------------------------------------------------------------------


class TestPolicy:
    def test_defaults(self):
        p = FleetPolicy()
        assert p.routing == "affinity"
        assert p.classes == (INTERACTIVE, BATCH)
        assert p.cls("interactive").priority < p.cls("batch").priority

    def test_bad_routing_rejected(self):
        with pytest.raises(ValueError, match="routing"):
            FleetPolicy(routing="hash_ring")

    def test_duplicate_classes_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            FleetPolicy(classes=(INTERACTIVE, SLOClass("interactive")))

    def test_unknown_class_lookup_rejected(self):
        with pytest.raises(ValueError, match="unknown SLO class"):
            FleetPolicy().cls("premium")

    def test_slo_class_validation(self):
        with pytest.raises(ValueError, match="name"):
            SLOClass("")
        with pytest.raises(ValueError, match="max_queue"):
            SLOClass("x", max_queue=0)


class TestRequestLog:
    def _entry(self, log, uid="a", plen=6, new=8, seed=7):
        return log.admit(
            Request(uid=uid, prompt=list(range(1, plen + 1)),
                    max_new_tokens=new, seed=seed),
            slo="interactive", replica="r0", t_arrive=10.0)

    def test_duplicate_uid_rejected(self):
        log = RequestLog()
        self._entry(log)
        with pytest.raises(ValueError, match="already logged"):
            self._entry(log)

    def test_progress_only_from_current_holder(self):
        log = RequestLog()
        e = self._entry(log)
        log.record_progress("r1", {"a": [5, 6]}, now=11.0)
        assert e.emitted == [] and e.t_first is None  # r1 doesn't hold it
        log.record_progress("r0", {"a": [5, 6]}, now=12.0)
        assert e.emitted == [5, 6]
        assert e.t_first == 12.0          # stamped at first non-empty
        log.record_progress("r0", {"a": [5, 6, 7]}, now=13.0)
        assert e.t_first == 12.0          # and never re-stamped

    def test_reassign_commits_emitted_as_replayed(self):
        log = RequestLog()
        e = self._entry(log)
        log.record_progress("r0", {"a": [5, 6]}, now=11.0)
        log.reassign("a", "r1")
        assert e.replica == "r1" and e.replays == 1
        assert e.replayed == [5, 6]
        # the new holder's own progress stacks on top of the replayed
        log.record_progress("r1", {"a": [7]}, now=12.0)
        assert e.emitted == [5, 6, 7]

    def test_resume_request_replays_suffix_and_shrinks_budget(self):
        log = RequestLog()
        e = self._entry(log, plen=4, new=8)
        log.record_progress("r0", {"a": [9, 9, 8]}, now=11.0)
        r = resume_request(e)
        assert r.uid == "a" and r.seed == 7
        assert r.prompt == [1, 2, 3, 4, 9, 9, 8]
        assert r.max_new_tokens == 5
        # the ORIGINAL request is never mutated
        assert list(e.request.prompt) == [1, 2, 3, 4]

    def test_resume_with_spent_budget_rejected(self):
        log = RequestLog()
        e = self._entry(log, new=2)
        log.record_progress("r0", {"a": [3, 4]}, now=11.0)
        with pytest.raises(ValueError, match="no budget"):
            resume_request(e)

    def test_inflight_on_excludes_done_and_other_replicas(self):
        log = RequestLog()
        self._entry(log, uid="a")
        self._entry(log, uid="b")
        log.reassign("b", "r1")
        log.complete("a", [1], "budget", now=11.0)
        assert log.inflight_on("r0") == []
        assert [e.request.uid for e in log.inflight_on("r1")] == ["b"]
        assert log.pending() == 1


class TestRoutingKey:
    def test_prompt_page_hashes_only_full_pages(self):
        p = list(range(1, 11))
        assert len(prompt_page_hashes(p, 4)) == 2     # 10 toks -> 2 pages
        assert prompt_page_hashes(p[:3], 4) == []     # sub-page: no key

    def test_hashes_are_cumulative(self):
        a = prompt_page_hashes([1, 2, 3, 4, 5, 6, 7, 8], 4)
        b = prompt_page_hashes([9, 9, 9, 9, 5, 6, 7, 8], 4)
        assert a[0] != b[0]
        assert a[1] != b[1]   # same page content, different prefix

    def test_match_len_probe_is_read_only(self):
        cfg = KVCacheConfig(num_layers=1, num_heads=1, head_dim=4,
                            num_pages=16, page_size=4, max_seqs=2,
                            pages_per_seq=4)
        cache = PagedKVCache(cfg)
        hashes = prompt_page_hashes(list(range(1, 9)), 4)
        free0 = cache.allocator.num_free
        assert cache.match_len(hashes) == 0           # cold cache
        assert cache.allocator.num_free == free0      # no allocation


# ---------------------------------------------------------------------------
# router over the tiny GPT
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_setup():
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:1])
    model = GPTModel(GPTConfig(
        vocab_size=64, num_layers=2, hidden_size=32,
        num_attention_heads=4, max_position_embeddings=64,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))
    params = model.init(jax.random.PRNGKey(5))
    page, new, maxp = 4, 6, 24
    pps = -(-(maxp + new) // page)
    ccfg = KVCacheConfig(
        num_layers=2, num_heads=4, head_dim=8,
        num_pages=1 + 4 * pps, page_size=page, max_seqs=2,
        pages_per_seq=pps, dtype=jnp.float32)
    fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=maxp,
                           prefill_chunk=4)
    yield mesh, model, params, ccfg, fns, maxp
    parallel_state.destroy_model_parallel()


def _replicas(ccfg, fns, maxp, n=2):
    return [
        Replica(f"r{i}", ContinuousBatcher(
            fns.prefill, fns.decode, PagedKVCache(ccfg),
            init_pools(ccfg), max_prompt_len=maxp, harvest_every=2,
            chunk_fn=fns.chunk, prefill_chunk=4, prefix_cache=True))
        for i in range(n)
    ]


def _req(uid, prompt, new=4, seed=None):
    return Request(uid=uid, prompt=prompt, max_new_tokens=new,
                   seed=seed)


class TestFleetRouter:
    def test_replicas_must_share_page_size(self, fleet_setup):
        mesh, model, params, ccfg, fns, maxp = fleet_setup
        other = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8, num_pages=17,
            page_size=8, max_seqs=2, pages_per_seq=4)
        reps = _replicas(ccfg, fns, maxp, n=1) + [
            Replica("odd", ContinuousBatcher(
                fns.prefill, fns.decode, PagedKVCache(other),
                init_pools(other), max_prompt_len=maxp))]
        with pytest.raises(ValueError, match="page_size"):
            FleetRouter(reps)

    def test_admission_rejects_unservable_and_full_queues(
            self, fleet_setup):
        mesh, model, params, ccfg, fns, maxp = fleet_setup
        policy = FleetPolicy(classes=(
            SLOClass("interactive", 0, max_queue=1),
            SLOClass("batch", 1)))
        router = FleetRouter(_replicas(ccfg, fns, maxp), policy)
        # replay headroom: prompt + max_new - 1 must fit max_prompt_len
        assert not router.submit(_req("big", [1] * 20, new=10))
        assert router.rejected["big"] == "too_large"
        assert router.submit(_req("a", [1, 2, 3], new=4))
        assert not router.submit(_req("b", [1, 2, 4], new=4))
        assert router.rejected["b"] == "queue_full"
        # a lower-priority class still has room
        assert router.submit(_req("c", [1, 2, 5], new=4), "batch")
        assert router.pending == 2
        router.drain()
        assert sorted(router.completions) == ["a", "c"]

    def test_affinity_routes_cohort_to_prefix_holder(self, fleet_setup):
        """After one cohort request lands on a replica, every later
        request sharing its page-aligned prefix follows it — and the
        router's second choice balances to the OTHER replica."""
        mesh, model, params, ccfg, fns, maxp = fleet_setup
        router = FleetRouter(_replicas(ccfg, fns, maxp))
        rng = np.random.RandomState(9)
        pref_a = [int(t) for t in rng.randint(1, 64, (8,))]
        pref_b = [int(t) for t in rng.randint(1, 64, (8,))]
        router.submit(_req("a0", pref_a + [1, 2]))
        router.drain()
        home = router.log.get("a0").replica
        other = ({"r0", "r1"} - {home}).pop()
        router.submit(_req("b0", pref_b + [3, 4]))   # cold: least-loaded
        router.drain()
        assert router.log.get("b0").replica == other
        for i, (tag, pref) in enumerate(
                [("a", pref_a), ("b", pref_b)] * 2):
            router.submit(_req(f"{tag}{i + 1}", pref + [9, i]))
        router.drain()
        for uid, e in router.log._entries.items():
            want = home if uid.startswith("a") else other
            assert e.replica == want, (uid, e.replica)
        assert router.stats["affinity_routed"] >= 4

    def test_round_robin_ignores_affinity_and_priority(
            self, fleet_setup):
        mesh, model, params, ccfg, fns, maxp = fleet_setup
        router = FleetRouter(_replicas(ccfg, fns, maxp),
                             FleetPolicy(routing="round_robin"))
        shared = [7] * 8
        for i in range(4):
            router.submit(_req(f"u{i}", shared + [i]))
        assert router.stats["routed"] == {"r0": 2, "r1": 2}
        assert router.stats["affinity_routed"] == 0
        router.drain()
        assert len(router.completions) == 4

    def test_pump_order_is_class_priority_then_fifo(self, fleet_setup):
        mesh, model, params, ccfg, fns, maxp = fleet_setup
        router = FleetRouter(_replicas(ccfg, fns, maxp, n=1))
        router.submit(_req("b1", [1, 2], new=2), "batch")
        router.submit(_req("i1", [1, 3], new=2), "interactive")
        router.submit(_req("b2", [1, 4], new=2), "batch")
        router.submit(_req("i2", [1, 5], new=2), "interactive")
        order = [r.uid for r in router._pump_order("r0")]
        assert order == ["i1", "i2", "b1", "b2"]
        router.drain()
        assert len(router.completions) == 4


class TestFleetFailover:
    def test_kill_drill_zero_lost_token_identical(self, fleet_setup):
        """r0 dies after 2 windows with work queued AND in flight: every
        request completes, >= 1 migrates, and every greedy stream is
        identical to an unkilled reference run."""
        mesh, model, params, ccfg, fns, maxp = fleet_setup
        rng = np.random.RandomState(17)
        reqs = [
            _req(f"u{i}", [int(t) for t in
                           rng.randint(1, 64, (6 + (i % 3) * 4,))],
                 new=6)
            for i in range(8)
        ]

        def run(fail):
            router = FleetRouter(_replicas(ccfg, fns, maxp))
            if fail:
                router.replicas[0].fail_after(2)
            for r in reqs:
                assert router.submit(r)
            router.drain()
            return router

        ref = run(fail=False)
        drill = run(fail=True)
        assert not drill.replicas[0].alive
        assert drill.stats["migrations"] >= 1
        assert len(drill.completions) == len(reqs)
        for uid, comp in ref.completions.items():
            assert drill.completions[uid].tokens == comp.tokens, uid
        migrated = [u for u, c in drill.completions.items()
                    if c.replays > 0]
        assert migrated, "nothing actually migrated mid-flight"

    def test_dead_fleet_raises_not_hangs(self, fleet_setup):
        mesh, model, params, ccfg, fns, maxp = fleet_setup
        router = FleetRouter(_replicas(ccfg, fns, maxp))
        router.submit(_req("a", [1, 2, 3]))
        for r in router.replicas:
            r.kill()
        with pytest.raises(RuntimeError, match="no replica is alive"):
            router.drain()


class TestCrossReplicaSamplingDeterminism:
    def test_same_seed_same_stream_across_batchers_and_order(
            self, fleet_setup):
        """The failover contract's foundation: a seeded request's
        SAMPLED stream is identical across different batcher
        instances, admission orders and therefore slot assignments."""
        mesh, model, params, ccfg, fns_greedy, maxp = fleet_setup
        fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=maxp,
                               temperature=0.9, top_k=20,
                               prefill_chunk=4)
        rng = np.random.RandomState(23)
        reqs = [
            _req(f"s{i}",
                 [int(t) for t in rng.randint(1, 64, (5 + i,))],
                 new=6, seed=100 + i)
            for i in range(4)
        ]

        def serve(order):
            b = ContinuousBatcher(
                fns.prefill, fns.decode, PagedKVCache(ccfg),
                init_pools(ccfg), max_prompt_len=maxp,
                harvest_every=2, chunk_fn=fns.chunk, prefill_chunk=4,
                prefix_cache=True)
            comps = b.run([reqs[i] for i in order])
            return {u: c.tokens for u, c in comps.items()}

        first = serve([0, 1, 2, 3])
        assert any(len(set(t)) > 1 for t in first.values())
        assert serve([3, 2, 1, 0]) == first
        assert serve([2, 0, 3, 1]) == first


# ---------------------------------------------------------------------------
# load generator + metrics report + bench merge
# ---------------------------------------------------------------------------


class TestLoadGen:
    def test_trace_is_deterministic_per_seed(self):
        from tools.load_gen import make_trace

        kw = dict(n_requests=12, seed=4, vocab_size=64)
        a, b = make_trace(**kw), make_trace(**kw)
        assert [(x.t, x.slo, x.cohort, x.request.prompt,
                 x.request.max_new_tokens, x.request.seed)
                for x in a] == \
               [(x.t, x.slo, x.cohort, x.request.prompt,
                 x.request.max_new_tokens, x.request.seed)
                for x in b]
        c = make_trace(**{**kw, "seed": 5})
        assert [x.request.prompt for x in c] != \
               [x.request.prompt for x in a]

    def test_cohort_requests_share_the_prefix(self):
        from tools.load_gen import make_trace

        trace = make_trace(n_requests=32, seed=1, vocab_size=64,
                           cohorts=2, cohort_frac=1.0, prefix_len=8,
                           prompt_len=(9, 16))
        by_cohort = {}
        for it in trace:
            by_cohort.setdefault(it.cohort, set()).add(
                tuple(it.request.prompt[:8]))
        assert set(by_cohort) == {0, 1}
        assert all(len(v) == 1 for v in by_cohort.values())

    def test_validation(self):
        from tools.load_gen import make_trace

        with pytest.raises(ValueError, match="prefix_len"):
            make_trace(n_requests=1, seed=0, vocab_size=64,
                       prefix_len=48, prompt_len=(8, 48))
        with pytest.raises(ValueError, match="burstiness"):
            make_trace(n_requests=1, seed=0, vocab_size=64,
                       burstiness=0.5)

    def test_summarize_trace_ledger(self):
        from tools.load_gen import summarize_trace

        records = [
            {"uid": "a", "slo": "interactive", "reason": "budget",
             "ttft_s": 0.1, "itl_ms": 2.0, "replays": 1},
            {"uid": "b", "slo": "batch", "reason": "budget",
             "ttft_s": 0.4, "itl_ms": 3.0},
            {"uid": "c", "slo": "interactive", "rejected": "too_large"},
            {"uid": "d", "slo": "batch", "lost": True},
        ]
        s = summarize_trace(records)
        assert (s["requests"], s["completed"], s["rejected"],
                s["lost"], s["migrated"]) == (4, 2, 1, 1, 1)
        assert s["by_class"]["interactive"]["ttft_s"]["p50"] == 0.1
        assert s["overall"]["itl_ms"]["p99"] == 3.0

    @pytest.mark.slow
    def test_replay_end_to_end_scores_in_metrics_report(
            self, fleet_setup, tmp_path):
        """Trace replay through a logged 2-replica fleet: every request
        completes, the replay records summarize, and the jsonl stream
        renders a fleet section plus EXACT admit-to-first-token TTFTs
        in tools/metrics_report.py."""
        from apex_tpu.telemetry.metrics import MetricsLogger
        from tools.load_gen import make_trace, replay, summarize_trace
        import tools.metrics_report as mr

        mesh, model, params, ccfg, fns, maxp = fleet_setup
        jsonl = str(tmp_path / "fleet.jsonl")
        logger = MetricsLogger(jsonl_path=jsonl, console=False)
        reps = [
            Replica(f"r{i}", ContinuousBatcher(
                fns.prefill, fns.decode, PagedKVCache(ccfg),
                init_pools(ccfg), max_prompt_len=maxp,
                harvest_every=2, chunk_fn=fns.chunk, prefill_chunk=4,
                prefix_cache=True, logger=logger))
            for i in range(2)
        ]
        router = FleetRouter(reps, logger=logger)
        # prompt + budget - 1 must clear max_prompt_len=24 (replay
        # headroom), so cap prompts at 18 with a 6-token budget
        trace = make_trace(n_requests=12, seed=3, vocab_size=64,
                           prompt_len=(8, 18), new_tokens=(3, 6),
                           cohorts=2, prefix_len=7)
        recs = replay(router, trace)
        logger.close()
        s = summarize_trace(recs)
        assert s["completed"] == 12 and s["lost"] == 0
        summary = mr.summarize(mr.load_records(jsonl))
        assert summary["serving"]["ttft_s"]["source"] == "exact"
        fl = summary["fleet"]
        assert fl["trace"] == {"requests": 12, "completed": 12,
                               "lost": 0}
        assert sum(fl["routed"].values()) == 12
        text = mr.format_report(summary)
        assert "fleet summary:" in text
        assert "exact admit-to-first-token" in text


class TestBenchExtraMerge:
    def test_merge_preserves_existing_rows(self, tmp_path):
        import bench

        path = str(tmp_path / "BENCH_EXTRA.json")
        with open(path, "w") as f:
            json.dump({"decode": {"metric": "old"},
                       "platform": "tpu"}, f)
        bench._merge_bench_extra(
            path, {"fleet": {"metric": "fleet_x"}, "platform": "cpu"})
        with open(path) as f:
            merged = json.load(f)
        assert merged["decode"] == {"metric": "old"}   # not clobbered
        assert merged["fleet"] == {"metric": "fleet_x"}
        assert merged["platform"] == "cpu"             # fresh key wins

    def test_merge_survives_corrupt_or_missing_file(self, tmp_path):
        import bench

        path = str(tmp_path / "BENCH_EXTRA.json")
        bench._merge_bench_extra(path, {"fleet": 1})
        with open(path) as f:
            assert json.load(f) == {"fleet": 1}
        with open(path, "w") as f:
            f.write("{not json")
        bench._merge_bench_extra(path, {"fleet": 2})
        with open(path) as f:
            assert json.load(f) == {"fleet": 2}

    def test_fleet_child_is_dispatchable(self):
        """The orchestrator's --child fleet row must resolve to the
        child function (a typo'd dispatcher entry dies at gate time,
        not test time)."""
        import bench

        assert callable(bench.child_fleet)
        src = open(bench.__file__).read()
        assert 'kind == "fleet"' in src

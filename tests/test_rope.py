"""Rotary position embeddings: op properties + GPT integration.

The reference fork's BASELINE mentions rope but ships no implementation
(SURVEY.md §2.1, csrc/megatron has only softmax kernels) — this is the
TPU build's closure of that mentioned capability.  Tests follow the
suite philosophy: analytic properties (norm preservation, relative-
position invariance) instead of golden files, then the model-level
integration on the 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.ops.rope import (
    apply_rope,
    apply_rope_at,
    apply_rope_tables,
    rope_cos_sin,
    rope_table,
)
from apex_tpu.transformer import parallel_state


class TestRopeIncremental:
    """Position-indexed application for decode (apply_rope_at) and the
    cached (max_len, dim, dtype)-keyed tables: the incremental path
    must be BIT-identical to the full-sequence path, or a conversation
    would drift from its own prefill."""

    def test_table_rows_bit_identical_to_direct(self):
        cos_t, sin_t = rope_table(64, 16)
        cos_d, sin_d = rope_cos_sin(jnp.arange(64, dtype=jnp.int32), 16)
        assert jnp.array_equal(cos_t, cos_d)
        assert jnp.array_equal(sin_t, sin_d)

    def test_incremental_matches_full_sequence_bitwise(self):
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 3, 12, 16))
        full = apply_rope(x, jnp.arange(12))
        via_tables = apply_rope_at(x, jnp.arange(12), max_len=32)
        direct = apply_rope_at(x, jnp.arange(12))
        assert jnp.array_equal(full, via_tables)
        assert jnp.array_equal(full, direct)

    def test_one_position_at_a_time_matches_batch(self):
        # the decode loop: rotate position p alone == row p of the
        # full-sequence rotation, for every p
        x = jax.random.normal(jax.random.PRNGKey(5), (1, 2, 8, 16))
        full = apply_rope(x, jnp.arange(8))
        for p in range(8):
            one = apply_rope_at(
                x[:, :, p:p + 1], jnp.array([p]), max_len=16)
            assert jnp.array_equal(one, full[:, :, p:p + 1]), p

    def test_per_sequence_offsets(self):
        # (b, s) positions: each sequence rotated at its own offsets
        x = jax.random.normal(jax.random.PRNGKey(6), (2, 3, 4, 16))
        pos = jnp.array([[5, 6, 7, 8], [0, 1, 2, 3]], jnp.int32)
        out = apply_rope_at(x, pos, max_len=16)
        for b in range(2):
            want = apply_rope(x[b:b + 1], pos[b])
            assert jnp.array_equal(out[b:b + 1], want), b

    def test_per_sequence_positions_need_4d(self):
        with pytest.raises(ValueError, match="b, h, s, d"):
            apply_rope_at(jnp.zeros((4, 16)),
                          jnp.zeros((2, 4), jnp.int32))

    def test_table_cache_hit_and_dtype_keying(self):
        a = rope_table(32, 8)
        b = rope_table(32, 8)
        assert a[0] is b[0] and a[1] is b[1]       # cache hit
        c = rope_table(32, 8, dtype=jnp.bfloat16)
        assert c[0].dtype == jnp.bfloat16
        assert c[0] is not a[0]                    # dtype keys the cache
        d = rope_table(32, 8, base=500.0)
        assert d[0] is not a[0]                    # base keys the cache

    def test_tables_broadcast_contract(self):
        # apply_rope_tables with gathered rows == apply_rope_at
        x = jax.random.normal(jax.random.PRNGKey(7), (2, 2, 4, 8))
        pos = jnp.array([[3, 4, 5, 6], [0, 2, 4, 6]], jnp.int32)
        cos, sin = rope_table(16, 8)
        want = apply_rope_tables(
            x, cos[pos][:, None], sin[pos][:, None])
        got = apply_rope_at(x, pos, max_len=16)
        assert jnp.array_equal(got, want)


class TestRopeOp:
    def test_preserves_norm(self):
        # rotation is orthogonal: per-(position, pair) norms are exact
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 16, 8))
        y = apply_rope(x, jnp.arange(16))
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1),
            rtol=1e-5,
        )

    def test_position_zero_is_identity(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 4, 8))
        y = apply_rope(x, jnp.zeros((4,), jnp.int32))
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)

    def test_relative_position_property(self):
        """q·k after rope depends only on the position DIFFERENCE — the
        defining property: shifting both positions by a constant leaves
        every dot product unchanged."""
        d = 16
        q = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 6, d))
        k = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 6, d))

        def scores(offset):
            pos = offset + jnp.arange(6)
            qr, kr = apply_rope(q, pos), apply_rope(k, pos)
            return jnp.einsum("bhsd,bhtd->bhst", qr, kr)

        np.testing.assert_allclose(
            np.asarray(scores(0)), np.asarray(scores(37)), atol=1e-4
        )

    def test_matches_manual_rotation(self):
        # one (position, frequency) pair checked against the closed form
        x = jnp.zeros((1, 1, 2, 4)).at[0, 0, 1, 0].set(1.0)
        y = apply_rope(x, jnp.arange(2))
        cos, sin = rope_cos_sin(jnp.arange(2), 4)
        # x = e_0 at position 1: rotates into (cos t, 0, sin t, 0)
        np.testing.assert_allclose(float(y[0, 0, 1, 0]), float(cos[1, 0]),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(y[0, 0, 1, 2]), float(sin[1, 0]),
                                   rtol=1e-6)

    def test_odd_head_dim_raises(self):
        with pytest.raises(ValueError, match="even head_dim"):
            apply_rope(jnp.zeros((1, 1, 4, 7)))

    def test_fp32_trig_under_bf16_inputs(self):
        # bf16 inputs keep fp32 rotation accuracy: compare against the
        # fp32 path at a large position where bf16 angles would drift
        x32 = jax.random.normal(jax.random.PRNGKey(4), (1, 1, 4, 8))
        pos = 4000 + jnp.arange(4)
        y16 = apply_rope(x32.astype(jnp.bfloat16), pos)
        y32 = apply_rope(x32, pos)
        assert y16.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(y16, np.float32), np.asarray(y32), atol=2e-2
        )


class TestGPTRope:
    def _build(self, cfg_kw, tp=1, cp=1):
        from apex_tpu.models import GPTConfig, GPTModel

        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp,
            context_parallel_size_=cp,
        )
        cfg = GPTConfig(
            vocab_size=64, num_layers=2, hidden_size=32,
            num_attention_heads=4, max_position_embeddings=16,
            compute_dtype=jnp.float32, remat=False, attention_impl="xla",
            position_embedding="rope", **cfg_kw,
        )
        model = GPTModel(cfg)
        return mesh, model

    def test_no_position_table_and_loss_grads_finite(self):
        mesh, model = self._build({})
        try:
            params = model.init(jax.random.PRNGKey(0))
            assert "pos_embedding" not in params
            assert "pos_embedding" not in model.param_specs()
            specs = model.param_specs()
            tokens = jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, 64)
            targets = jnp.roll(tokens, -1, 1)
            fn = jax.jit(jax.shard_map(
                jax.value_and_grad(model.loss), mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=(P(), specs),
            ))
            loss, grads = fn(params, tokens, targets)
            assert jnp.isfinite(loss)
            assert all(bool(jnp.all(jnp.isfinite(g)))
                       for g in jax.tree.leaves(grads))
        finally:
            parallel_state.destroy_model_parallel()

    def test_rope_beats_no_positions(self):
        """rope must actually inject position information: a
        position-sensitive sequence-copy objective separates it from a
        no-position-encoding model after a few steps."""
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.transformer.tensor_parallel.layers import (
            state_specs_like,
        )

        mesh, model = self._build({})
        try:
            specs = model.param_specs()
            params = model.init(jax.random.PRNGKey(0))
            opt = FusedAdam(lr=5e-3)
            opt_state = opt.init(params)
            opt_specs = state_specs_like(specs, opt_state)

            def train_step(params, opt_state, tokens, targets):
                loss, grads = jax.value_and_grad(model.loss)(
                    params, tokens, targets)
                grads = jax.tree.map(
                    lambda g: jax.lax.pmean(g, "dp"), grads)
                p2, s2 = opt.step(opt_state, grads, params)
                return p2, s2, loss

            step = jax.jit(jax.shard_map(
                train_step, mesh=mesh,
                in_specs=(specs, opt_specs, P("dp"), P("dp")),
                out_specs=(specs, opt_specs, P()),
            ))
            # every sequence is the SAME tokens rotated: position is the
            # only signal distinguishing targets
            base = jnp.arange(16, dtype=jnp.int32) % 64
            tokens = jnp.stack([jnp.roll(base, i) for i in range(8)])
            targets = jnp.roll(tokens, -1, axis=1)
            first = None
            for _ in range(60):
                params, opt_state, loss = step(
                    params, opt_state, tokens, targets)
                if first is None:
                    first = float(loss)
            assert float(loss) < first / 2, (first, float(loss))
        finally:
            parallel_state.destroy_model_parallel()

    def test_tp_matches_tp1(self):
        """rope rotation acts per head_dim, so tp-sharding heads cannot
        change the math: tp=4 loss == tp=1 loss."""
        losses = {}
        for tp in (1, 4):
            mesh, model = self._build({}, tp=tp)
            try:
                specs = model.param_specs()
                params = model.init(jax.random.PRNGKey(0))
                tokens = jax.random.randint(
                    jax.random.PRNGKey(2), (8, 16), 0, 64)
                targets = jnp.roll(tokens, -1, 1)
                fn = jax.jit(jax.shard_map(
                    model.loss, mesh=mesh,
                    in_specs=(specs, P("dp"), P("dp")), out_specs=P(),
                ))
                losses[tp] = float(fn(params, tokens, targets))
            finally:
                parallel_state.destroy_model_parallel()
        np.testing.assert_allclose(losses[1], losses[4], rtol=1e-5)

    def test_cp_positions_are_global(self):
        """under context parallelism each rank rotates its chunk by
        GLOBAL positions: the cp-sharded rope model matches the dense
        full-sequence rope model on the same mesh (the
        test_ring_attention comparison, rope edition)."""
        from apex_tpu.models import GPTConfig, GPTModel

        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size_=2
        )
        try:
            cfg = dict(
                vocab_size=64, num_layers=2, hidden_size=32,
                num_attention_heads=4, max_position_embeddings=16,
                compute_dtype=jnp.float32, remat=False,
                position_embedding="rope",
            )
            dense = GPTModel(GPTConfig(**cfg, attention_impl="xla"))
            cp_model = GPTModel(GPTConfig(**cfg, context_parallel=True))
            params = dense.init(jax.random.PRNGKey(0))
            specs = dense.param_specs()
            tokens = jax.random.randint(
                jax.random.PRNGKey(3), (4, 16), 0, 64)
            targets = jnp.roll(tokens, -1, 1)
            ref = jax.jit(jax.shard_map(
                dense.loss, mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")), out_specs=P(),
            ))(params, tokens, targets)
            got = jax.jit(jax.shard_map(
                cp_model.loss, mesh=mesh,
                in_specs=(specs, P("dp", "cp"), P("dp", "cp")),
                out_specs=P(),
            ))(params, tokens, targets)
            np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
        finally:
            parallel_state.destroy_model_parallel()

    def test_pipeline_rope_matches_serial(self):
        """the pp path embeds through the same _embed helper: pp=2
        1F1B loss == serial loss for a rope model."""
        from apex_tpu.models import GPTConfig, GPTModel

        mesh = parallel_state.initialize_model_parallel(
            pipeline_model_parallel_size_=2
        )
        try:
            cfg = GPTConfig(
                vocab_size=64, num_layers=2, hidden_size=32,
                num_attention_heads=4, max_position_embeddings=16,
                compute_dtype=jnp.float32, remat=False,
                attention_impl="xla", position_embedding="rope",
            )
            model = GPTModel(cfg)
            params = model.init(jax.random.PRNGKey(0))
            specs = model.param_specs()
            pp_specs = model.pipeline_param_specs()
            tokens = jax.random.randint(
                jax.random.PRNGKey(4), (8, 16), 0, 64)
            targets = jnp.roll(tokens, -1, 1)

            serial = jax.jit(jax.shard_map(
                model.loss, mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")), out_specs=P(),
            ))(params, tokens, targets)

            def pp_loss(prm, t, g):
                loss, _ = model.pipeline_1f1b_grads(prm, t, g, 2)
                return loss

            pp = jax.jit(jax.shard_map(
                pp_loss, mesh=mesh,
                in_specs=(pp_specs, P("dp"), P("dp")), out_specs=P(),
            ))(params, tokens, targets)
            np.testing.assert_allclose(
                float(serial), float(pp), rtol=1e-5)
        finally:
            parallel_state.destroy_model_parallel()

"""multi_tensor primitive tests (reference analog:
tests/L0/run_amp/test_multi_tensor_scale.py etc.)."""

import jax.numpy as jnp
import numpy as np

from apex_tpu.multi_tensor_apply import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
)


def test_scale():
    tree = {"a": jnp.array([1.0, 2.0]), "b": jnp.array([[3.0]])}
    out, overflow = multi_tensor_scale(tree, 2.0)
    np.testing.assert_allclose(out["a"], [2.0, 4.0])
    np.testing.assert_allclose(out["b"], [[6.0]])
    assert not bool(overflow)


def test_scale_overflow_flag():
    tree = {"a": jnp.array([1.0, jnp.inf])}
    _, overflow = multi_tensor_scale(tree, 0.5)
    assert bool(overflow)
    tree = {"a": jnp.array([1.0, jnp.nan])}
    _, overflow = multi_tensor_scale(tree, 0.5)
    assert bool(overflow)


def test_scale_dtype_preserved():
    tree = {"a": jnp.ones((4,), jnp.bfloat16)}
    out, _ = multi_tensor_scale(tree, 3.0)
    assert out["a"].dtype == jnp.bfloat16


def test_axpby():
    x = {"a": jnp.array([1.0, 2.0])}
    y = {"a": jnp.array([10.0, 20.0])}
    out, overflow = multi_tensor_axpby(2.0, x, 0.5, y)
    np.testing.assert_allclose(out["a"], [7.0, 14.0])
    assert not bool(overflow)


def test_l2norm_matches_numpy():
    rng = np.random.RandomState(0)
    tree = {
        "a": jnp.asarray(rng.randn(17, 3).astype(np.float32)),
        "b": jnp.asarray(rng.randn(5).astype(np.float32)),
    }
    total = multi_tensor_l2norm(tree)
    flat = np.concatenate(
        [np.asarray(tree["a"]).ravel(), np.asarray(tree["b"]).ravel()]
    )
    np.testing.assert_allclose(float(total), np.linalg.norm(flat), rtol=1e-6)


def test_l2norm_per_tensor():
    tree = {"a": jnp.array([3.0, 4.0]), "b": jnp.array([5.0, 12.0])}
    total, per = multi_tensor_l2norm(tree, per_tensor=True)
    np.testing.assert_allclose([float(p) for p in per], [5.0, 13.0])
    np.testing.assert_allclose(float(total), np.sqrt(25 + 169))


def test_scale_inf_from_scale_does_not_flag():
    """The noop_flag contract checks INCOMING values (reference:
    csrc/multi_tensor_scale_kernel.cu's per-element isfinite(r_in)):
    finite inputs with an inf-producing scale must NOT raise it."""
    tree = {"a": jnp.array([1.0, 2.0], jnp.float16)}
    out, overflow = multi_tensor_scale(tree, jnp.float32(1e30))
    assert not bool(overflow)
    assert bool(jnp.isinf(out["a"]).any())  # the output DID overflow


def test_scale_single_pass_checks_half_inputs():
    """inf/nan arriving in half precision is caught on the one fp32
    read the scaling itself uses (the cast is exact for half dtypes)."""
    for bad in (jnp.inf, -jnp.inf, jnp.nan):
        for dt in (jnp.float16, jnp.bfloat16):
            tree = {"a": jnp.array([1.0, bad], dt),
                    "b": jnp.ones((3,), jnp.float32),
                    "n": jnp.arange(3)}  # int leaf: passed through
            out, overflow = multi_tensor_scale(tree, 0.5)
            assert bool(overflow), (bad, dt)
            assert out["n"].dtype == tree["n"].dtype


def test_axpby_inf_in_input_flags_either_side():
    x = {"a": jnp.array([1.0, jnp.inf])}
    y = {"a": jnp.array([1.0, 2.0])}
    assert bool(multi_tensor_axpby(1.0, x, 1.0, y)[1])
    assert bool(multi_tensor_axpby(1.0, y, 1.0, x)[1])


def test_axpby_inf_from_coefficient_does_not_flag():
    x = {"a": jnp.array([1.0, 2.0])}
    y = {"a": jnp.array([3.0, 4.0])}
    out, overflow = multi_tensor_axpby(jnp.float32(3e38), x, 1.0, y)
    assert not bool(overflow)
    assert bool(jnp.isinf(out["a"]).any())

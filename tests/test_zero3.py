"""Full-parameter sharding (ZeRO-3/FSDP) tests.

The contract under test (apex_tpu/parallel/zero3.py +
contrib/optimizers/distributed.py shard_params mode): parameters live
as 1-D fp32 shards in the bucket-shaped flat layout, gather-on-use
reconstructs the model-dtype tree BIT-identically, the sharded update
matches the state-sharding ZeRO path bitwise at compression=None
(Adam; LAMB within reduction-order ulps — its segment norms group
partial sums at different shard boundaries), the int8 gather/RS legs
track the exact path within quantization tolerance with checkpointable
error-feedback residuals, and a ZeRO-3 checkpoint resumes into a
replicated-eval setup with bit-identical weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu._compat import shard_map
from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.ops.quantization import (
    CompressionConfig,
    zero3_residual_sizes,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import (
    Zero3Layout,
    hierarchical_data_parallel_mesh,
)
from apex_tpu.transformer import parallel_state


@pytest.fixture
def mesh():
    m = parallel_state.initialize_model_parallel()
    yield m
    parallel_state.destroy_model_parallel()


@pytest.fixture
def hier_mesh():
    yield hierarchical_data_parallel_mesh(ici_size=4)


def make_params_grads(key, bf16_leaf=False):
    ks = jax.random.split(key, 6)
    params = {
        "w": jax.random.normal(ks[0], (13, 7)),   # odd sizes: padding
        "b": jax.random.normal(ks[1], (5,)),
        "h": jax.random.normal(ks[2], (3, 11)),
    }
    grads = {
        "w": 0.1 * jax.random.normal(ks[3], (13, 7)),
        "b": 0.1 * jax.random.normal(ks[4], (5,)),
        "h": 0.1 * jax.random.normal(ks[5], (3, 11)),
    }
    if bf16_leaf:
        params["h"] = params["h"].astype(jnp.bfloat16)
        grads["h"] = grads["h"].astype(jnp.bfloat16)
    return params, grads


def zero3_roundtrip(mesh, opt, params, grads, steps=3,
                    finite_seq=None, axes_spec=None):
    """Run `steps` ZeRO-3 steps (gather-on-use inside the same compiled
    program) and return (gathered_params, shards, state)."""
    opt.build_layout(params, mesh=mesh)
    pspec = jax.tree.map(lambda _: P(), params)
    sspec, stspecs = opt.shard_spec(), opt.state_specs()
    init_sh = jax.jit(shard_map(
        opt.init_shards, mesh=mesh, in_specs=(pspec,), out_specs=sspec))
    shards = init_sh(params)
    state = jax.jit(shard_map(
        opt.init, mesh=mesh, in_specs=(sspec,), out_specs=stspecs
    ))(shards)

    def train(sh, st, g, fin):
        p, st = opt.gather_params(sh, st)
        del p  # the gathered weights feed fwd/bwd in a real step
        return opt.step(st, g, sh, grads_finite=fin)

    step = jax.jit(shard_map(
        train, mesh=mesh,
        in_specs=(sspec, stspecs, pspec, P()),
        out_specs=(sspec, stspecs),
    ))
    for i in range(steps):
        fin = jnp.array(True if finite_seq is None else finite_seq[i])
        shards, state = step(shards, state, grads, fin)
    gather = jax.jit(shard_map(
        lambda s, t: opt.gather_params(s, t)[0], mesh=mesh,
        in_specs=(sspec, stspecs), out_specs=pspec))
    return gather(shards, state), shards, state


def zero1_reference(mesh, make_opt, params, grads, steps=3):
    opt = make_opt()
    specs = opt.state_specs()
    pspec = jax.tree.map(lambda _: P(), params)
    init = jax.jit(shard_map(
        opt.init, mesh=mesh, in_specs=(pspec,), out_specs=specs))
    state = init(params)
    step = jax.jit(shard_map(
        lambda st, g, p: opt.step(st, g, p), mesh=mesh,
        in_specs=(specs, pspec, pspec), out_specs=(pspec, specs)))
    p = params
    for _ in range(steps):
        p, state = step(state, grads, p)
    return p


class TestLayout:
    def test_plan_invariants(self):
        params, _ = make_params_grads(jax.random.PRNGKey(0))
        lay = Zero3Layout(params, world=8, bucket_bytes=128)
        # every leaf exactly once; reverse-tree bucket order
        seen = [i for b in lay.plan.buckets for i in b.leaf_ids]
        assert sorted(seen) == list(range(lay.num_leaves))
        first_ids = [b.leaf_ids[0] for b in lay.plan.buckets]
        assert first_ids == sorted(first_ids, reverse=True)
        # per-bucket padding to the world, concatenated chunk layout
        for b, padded, chunk in zip(lay.plan.buckets, lay.padded,
                                    lay.chunk_sizes):
            assert padded % 8 == 0 and padded >= b.size
            assert chunk == padded // 8
        assert lay.shard_size == sum(lay.chunk_sizes)
        assert lay.offsets[0] == 0

    def test_segment_ids_cover_leaves_and_padding(self):
        params, _ = make_params_grads(jax.random.PRNGKey(0))
        lay = Zero3Layout(params, world=8, bucket_bytes=128)
        ids = lay.segment_ids()
        counts = np.bincount(ids, minlength=lay.num_leaves + 1)
        sizes = [int(np.prod(jnp.shape(l)))
                 for l in jax.tree.leaves(params)]
        for i, s in enumerate(sizes):
            assert counts[i] == s
        assert counts[lay.num_leaves] == sum(lay.padded) - sum(sizes)

    def test_shard_unshard_roundtrip(self, mesh):
        params, _ = make_params_grads(jax.random.PRNGKey(1),
                                      bf16_leaf=True)
        lay = Zero3Layout(params, world=8, bucket_bytes=64)
        pspec = jax.tree.map(lambda _: P(), params)
        shard = jax.jit(shard_map(
            lambda p: lay.shard_params(p, jax.lax.axis_index("dp")),
            mesh=mesh, in_specs=(pspec,), out_specs=P("dp")))(params)
        rebuilt = lay.unshard(np.asarray(jax.device_get(shard)))
        for a, b in zip(jax.tree.leaves(rebuilt),
                        jax.tree.leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            assert a.dtype == b.dtype

    def test_unshard_wrong_world_rejected(self):
        params, _ = make_params_grads(jax.random.PRNGKey(1))
        lay = Zero3Layout(params, world=8, bucket_bytes=64)
        with pytest.raises(ValueError, match="world"):
            lay.unshard(np.zeros((lay.shard_size * 4,), np.float32))

    def test_residual_sizes_shared_definition(self):
        params, _ = make_params_grads(jax.random.PRNGKey(0))
        lay = Zero3Layout(params, world=4, bucket_bytes=128)
        cfg = CompressionConfig(block_size=32, ici_legs=True)
        sizes = lay.residual_sizes(2, 4, cfg)
        for name, b in zip(lay.names, lay.plan.buckets):
            assert sizes[name] == zero3_residual_sizes(
                b.size, 2, 4, 32, True)
            assert set(sizes[name]) == {"push", "pull", "ici_push",
                                        "ag"}
        no_legs = lay.residual_sizes(2, 4, CompressionConfig(
            block_size=32))
        assert set(no_legs[lay.names[0]]) == {"push", "pull"}


class TestZero3Adam:
    def test_gather_is_bit_identical(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(0),
                                          bf16_leaf=True)
        opt = DistributedFusedAdam(lr=1e-2, shard_params=True,
                                   bucket_bytes=64)
        opt.build_layout(params, mesh=mesh)
        pspec = jax.tree.map(lambda _: P(), params)
        sspec = opt.shard_spec()
        shards = jax.jit(shard_map(
            opt.init_shards, mesh=mesh, in_specs=(pspec,),
            out_specs=sspec))(params)
        gathered = jax.jit(shard_map(
            lambda s: opt.gather_params(s)[0], mesh=mesh,
            in_specs=(sspec,), out_specs=pspec))(shards)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(gathered[k]), np.asarray(params[k]))
            assert gathered[k].dtype == params[k].dtype

    def test_matches_zero1_bitwise(self, mesh):
        """The load-bearing parity: parameter sharding changes the
        storage layout, not one bit of the Adam math."""
        params, grads = make_params_grads(jax.random.PRNGKey(0))
        opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                   shard_params=True, bucket_bytes=64)
        p3, _, _ = zero3_roundtrip(mesh, opt, params, grads)
        p1 = zero1_reference(
            mesh, lambda: DistributedFusedAdam(lr=1e-2,
                                               weight_decay=0.01),
            params, grads)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(p3[k]), np.asarray(p1[k]))

    def test_matches_unsharded_fusedadam(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(0))
        opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                   shard_params=True, bucket_bytes=64)
        p3, _, _ = zero3_roundtrip(mesh, opt, params, grads)
        ref = FusedAdam(lr=1e-2, weight_decay=0.01,
                        master_weights=True)
        rstate = ref.init(params)
        rp = params
        for _ in range(3):
            rp, rstate = ref.step(rstate, grads, rp)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p3[k]), np.asarray(rp[k]),
                rtol=1e-6, atol=1e-7)

    def test_hier_matches_flat(self, hier_mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(2))
        hopt = DistributedFusedAdam(
            lr=1e-2, weight_decay=0.01, axis_name=("dcn", "ici"),
            shard_params=True, bucket_bytes=64)
        hp, _, _ = zero3_roundtrip(hier_mesh, hopt, params, grads)
        fmesh = parallel_state.initialize_model_parallel()
        try:
            fopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                        shard_params=True,
                                        bucket_bytes=64)
            fp, _, _ = zero3_roundtrip(fmesh, fopt, params, grads)
        finally:
            parallel_state.destroy_model_parallel()
        for k in params:
            np.testing.assert_allclose(
                np.asarray(hp[k]), np.asarray(fp[k]),
                rtol=1e-6, atol=1e-7)

    def test_bf16_params_stay_bf16_masters_fp32(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(3),
                                          bf16_leaf=True)
        opt = DistributedFusedAdam(lr=1e-2, shard_params=True,
                                   bucket_bytes=64)
        p3, shards, state = zero3_roundtrip(mesh, opt, params, grads,
                                            steps=1)
        assert p3["h"].dtype == jnp.bfloat16
        assert shards.dtype == jnp.float32
        assert state["exp_avg"].dtype == jnp.float32
        assert "master" not in state  # the shard IS the master

    def test_overflow_skip_freezes_shards_and_state(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(4))
        opt = DistributedFusedAdam(lr=1e-2, shard_params=True,
                                   bucket_bytes=64)
        p3, shards, state = zero3_roundtrip(
            mesh, opt, params, grads, steps=2,
            finite_seq=[True, False])
        ref_p, ref_sh, ref_st = zero3_roundtrip(
            mesh, DistributedFusedAdam(lr=1e-2, shard_params=True,
                                       bucket_bytes=64),
            params, grads, steps=1)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(p3[k]), np.asarray(ref_p[k]))
        np.testing.assert_array_equal(np.asarray(shards),
                                      np.asarray(ref_sh))
        assert int(state["step"]) == 1

    def test_state_specs_have_no_master(self, mesh):
        params, _ = make_params_grads(jax.random.PRNGKey(0))
        opt = DistributedFusedAdam(lr=1e-2, shard_params=True,
                                   bucket_bytes=64)
        opt.build_layout(params, mesh=mesh)
        specs = opt.state_specs()
        assert "master" not in specs
        assert specs["exp_avg"] == P("dp")
        assert specs["step"] == P()


class TestZero3Lamb:
    def test_matches_zero1_lamb(self, mesh):
        """Trust ratios are assembled from per-bucket segment sums —
        same math, different partial-sum grouping than the tree-order
        flat buffer, so ulp-level (not bitwise) agreement."""
        params, grads = make_params_grads(jax.random.PRNGKey(5))
        kw = dict(lr=1e-2, weight_decay=0.01, max_grad_norm=0.05)
        opt = DistributedFusedLAMB(shard_params=True, bucket_bytes=64,
                                   **kw)
        p3, _, _ = zero3_roundtrip(mesh, opt, params, grads)
        p1 = zero1_reference(
            mesh, lambda: DistributedFusedLAMB(**kw), params, grads)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p3[k]), np.asarray(p1[k]),
                rtol=1e-5, atol=1e-7)


class TestZero3Compression:
    def test_dcn_only_int8_leaves_param_gather_untouched(self,
                                                         hier_mesh):
        """ici_legs=False compresses ONLY the grad dcn leg: the param
        gather must stay full-width model dtype, pinned by comparing
        the gathered params against the uncompressed optimizer's after
        identical (compressed-grad) steps would diverge — so compare
        the GATHER itself on the same shards."""
        params, _ = make_params_grads(jax.random.PRNGKey(6))
        cfg = CompressionConfig(block_size=64, error_feedback=False)
        opt = DistributedFusedAdam(
            lr=1e-2, axis_name=("dcn", "ici"), shard_params=True,
            bucket_bytes=64, compression=cfg)
        opt.build_layout(params, mesh=hier_mesh)
        pspec = jax.tree.map(lambda _: P(), params)
        sspec = opt.shard_spec()
        shards = jax.jit(shard_map(
            opt.init_shards, mesh=hier_mesh, in_specs=(pspec,),
            out_specs=sspec))(params)
        gathered = jax.jit(shard_map(
            lambda s: opt.gather_params(s)[0], mesh=hier_mesh,
            in_specs=(sspec,), out_specs=pspec))(shards)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(gathered[k]), np.asarray(params[k]))

    def test_ici_legs_tracks_exact_within_band(self, hier_mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(7))
        exact = DistributedFusedAdam(
            lr=1e-2, axis_name=("dcn", "ici"), shard_params=True,
            bucket_bytes=128)
        pe, _, _ = zero3_roundtrip(hier_mesh, exact, params, grads)
        cfg = CompressionConfig(block_size=64, ici_legs=True)
        quant = DistributedFusedAdam(
            lr=1e-2, axis_name=("dcn", "ici"), shard_params=True,
            bucket_bytes=128, compression=cfg)
        pq, _, state = zero3_roundtrip(hier_mesh, quant, params, grads)
        for k in params:
            amax = float(np.max(np.abs(np.asarray(pe[k]))))
            np.testing.assert_allclose(
                np.asarray(pq[k]), np.asarray(pe[k]),
                atol=max(0.05 * amax, 1e-3))
        for name, res in state["comm"].items():
            assert set(res) == {"push", "pull", "ici_push", "ag"}

    def test_residual_checkpoint_roundtrip_bit_identical(self,
                                                         hier_mesh):
        """Save shards + state after 2 steps, rebuild host-side arrays
        (the checkpoint path), resume 2 more: bit-identical to the
        uninterrupted 4-step run — the EF residuals (incl. the ``ag``
        param-gather one) survive the round trip."""
        params, grads = make_params_grads(jax.random.PRNGKey(8))
        cfg = CompressionConfig(block_size=64, ici_legs=True)

        def make():
            return DistributedFusedAdam(
                lr=1e-2, axis_name=("dcn", "ici"), shard_params=True,
                bucket_bytes=128, compression=cfg)

        opt = make()
        opt.build_layout(params, mesh=hier_mesh)
        pspec = jax.tree.map(lambda _: P(), params)
        sspec, stspecs = opt.shard_spec(), opt.state_specs()
        place = lambda t, sp: jax.device_put(
            t, jax.tree.map(lambda s: NamedSharding(hier_mesh, s), sp,
                            is_leaf=lambda x: isinstance(x, P)))
        init_sh = jax.jit(shard_map(
            opt.init_shards, mesh=hier_mesh, in_specs=(pspec,),
            out_specs=sspec))
        shards = init_sh(params)
        state = jax.jit(shard_map(
            opt.init, mesh=hier_mesh, in_specs=(sspec,),
            out_specs=stspecs))(shards)

        def train(sh, st, g):
            p, st = opt.gather_params(sh, st)
            del p
            return opt.step(st, g, sh)

        step = jax.jit(shard_map(
            train, mesh=hier_mesh,
            in_specs=(sspec, stspecs, pspec), out_specs=(sspec, stspecs)))
        for _ in range(2):
            shards, state = step(shards, state, grads)
        # checkpoint: host round trip, then place anew
        saved = (jax.device_get(shards), jax.device_get(state))
        shards2 = place(saved[0], sspec)
        state2 = place(saved[1], stspecs)
        for _ in range(2):
            shards, state = step(shards, state, grads)
            shards2, state2 = step(shards2, state2, grads)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(shards)),
            np.asarray(jax.device_get(shards2)))
        for a, b in zip(jax.tree.leaves(jax.device_get(state)),
                        jax.tree.leaves(jax.device_get(state2))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_stochastic_rounding_runs(self, hier_mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(9))
        cfg = CompressionConfig(block_size=64, ici_legs=True,
                                rounding="stochastic")
        opt = DistributedFusedAdam(
            lr=1e-2, axis_name=("dcn", "ici"), shard_params=True,
            bucket_bytes=128, compression=cfg)
        p, _, _ = zero3_roundtrip(hier_mesh, opt, params, grads,
                                  steps=2)
        for k in params:
            assert bool(np.all(np.isfinite(np.asarray(p[k]))))


class TestZero3Validation:
    def test_build_layout_requires_shard_params(self):
        opt = DistributedFusedAdam(lr=1e-2)
        with pytest.raises(ValueError, match="shard_params"):
            opt.build_layout({"w": jnp.zeros((4,))}, world=8)

    def test_layout_required_before_use(self):
        opt = DistributedFusedAdam(lr=1e-2, shard_params=True)
        with pytest.raises(ValueError, match="build_layout"):
            opt.gather_params(jnp.zeros((8,)))

    def test_compressed_allgather_rejected(self):
        with pytest.raises(ValueError, match="compressed_allgather"):
            DistributedFusedAdam(lr=1e-2, shard_params=True,
                                 compressed_allgather="bf16")

    def test_data_axis_sharded_leaves_rejected(self):
        with pytest.raises(NotImplementedError, match="ZeRO-3"):
            DistributedFusedAdam(
                lr=1e-2, shard_params=True,
                param_specs={"w": P(), "e": P("dp")})

    def test_init_rejects_replicated_tree(self, mesh):
        params, _ = make_params_grads(jax.random.PRNGKey(0))
        opt = DistributedFusedAdam(lr=1e-2, shard_params=True,
                                   bucket_bytes=64)
        opt.build_layout(params, mesh=mesh)
        with pytest.raises(ValueError, match="flat"):
            jax.jit(shard_map(
                opt.init, mesh=mesh,
                in_specs=(jax.tree.map(lambda _: P(), params),),
                out_specs=opt.state_specs()))(params)


class TestZero3Telemetry:
    def test_param_gather_events_and_phase(self, mesh):
        from apex_tpu.telemetry import events as tlm_events

        params, _ = make_params_grads(jax.random.PRNGKey(0))
        opt = DistributedFusedAdam(lr=1e-2, shard_params=True,
                                   bucket_bytes=64)
        opt.build_layout(params, mesh=mesh)
        pspec = jax.tree.map(lambda _: P(), params)
        sspec = opt.shard_spec()
        shards = jax.jit(shard_map(
            opt.init_shards, mesh=mesh, in_specs=(pspec,),
            out_specs=sspec))(params)

        got = []

        class Sink:
            def event(self, kind, **fields):
                got.append((kind, fields))

        sink = Sink()
        tlm_events.add_sink(sink)
        try:
            fn = jax.jit(shard_map(
                lambda s: opt.gather_params(s)[0], mesh=mesh,
                in_specs=(sspec,), out_specs=pspec))
            txt = fn.lower(shards).compile().as_text()
        finally:
            tlm_events.remove_sink(sink)
        names = [f["bucket"] for k, f in got if k == "param_gather"]
        assert names == opt.layout.names
        for k, f in got:
            assert f["ag_ici_wire_bytes"] > 0
            assert f["compressed"] is False
        assert "tlm.param_gather" in txt

    def test_int8_gather_event_estimates_shrink(self, hier_mesh):
        from apex_tpu.telemetry import events as tlm_events

        params = {"w": jnp.zeros((64, 16))}
        cfgs = [None, CompressionConfig(block_size=64, ici_legs=True,
                                        error_feedback=False)]
        wire = []
        for cfg in cfgs:
            opt = DistributedFusedAdam(
                lr=1e-2, axis_name=("dcn", "ici"), shard_params=True,
                bucket_bytes=1 << 20, compression=cfg)
            opt.build_layout(params, mesh=hier_mesh)
            got = []

            class Sink:
                def event(self, kind, **fields):
                    got.append((kind, fields))

            sink = Sink()
            tlm_events.add_sink(sink)
            try:
                pspec = jax.tree.map(lambda _: P(), params)
                sspec = opt.shard_spec()
                shards = jax.jit(shard_map(
                    opt.init_shards, mesh=hier_mesh, in_specs=(pspec,),
                    out_specs=sspec))(params)
                jax.jit(shard_map(
                    lambda s: opt.gather_params(s)[0], mesh=hier_mesh,
                    in_specs=(sspec,), out_specs=pspec))(shards)
            finally:
                tlm_events.remove_sink(sink)
            assert got, "no param_gather events"
            wire.append(sum(f["ag_ici_wire_bytes"] for _, f in got))
        assert wire[0] / wire[1] > 3.0, (
            f"int8 param-AG estimate only {wire[0] / wire[1]:.2f}x "
            "smaller")


class TestReplicatedResume:
    """Satellite: resume a ZeRO-3 checkpoint into a replicated-eval
    setup — ``unshard_params`` of the checkpointed flat shard buffer
    must be bit-identical to the on-device gather, and a replicated
    forward must reproduce the sharded step's loss exactly."""

    def test_checkpoint_to_replicated_eval_bit_identical(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(11),
                                          bf16_leaf=True)
        opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                   shard_params=True, bucket_bytes=64)
        gathered, shards, state = zero3_roundtrip(
            mesh, opt, params, grads, steps=2)
        # the "checkpoint": the device_get of the placed shard buffer
        ckpt = np.asarray(jax.device_get(shards))
        replicated = opt.unshard_params(ckpt)
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(replicated[k]), np.asarray(gathered[k]))
            assert replicated[k].dtype == params[k].dtype

        # replicated eval: a plain forward on the unsharded weights
        # equals the same forward on the gathered weights
        x = jax.random.normal(jax.random.PRNGKey(12), (4, 13))

        def fwd(p):
            h = jnp.tanh(x @ p["w"])
            return jnp.sum(h * h)

        np.testing.assert_array_equal(
            np.asarray(fwd(replicated)), np.asarray(fwd(gathered)))


class TestZero3GPTTraining:
    """End-to-end: a small GPT trains under ZeRO-3 (gather-on-use
    inside the compiled step) and tracks the replicated-FusedAdam run
    within the established band; bit-identical to the ZeRO-1
    state-sharding path at compression=None."""

    def _train(self, mode, steps=8, compression=None):
        from apex_tpu.models import GPTConfig, GPTModel

        if parallel_state.model_parallel_is_initialized():
            parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        try:
            model = GPTModel(GPTConfig(
                vocab_size=64, num_layers=2, hidden_size=32,
                num_attention_heads=4, max_position_embeddings=16,
                compute_dtype=jnp.float32, remat=False,
                attention_impl="xla"))
            specs = model.param_specs()
            params = model.init(jax.random.PRNGKey(0))
            pspec = specs
            tokens = jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, 64)
            targets = jax.random.randint(
                jax.random.PRNGKey(2), (8, 16), 0, 64)
            losses = []
            if mode == "replicated":
                opt = FusedAdam(lr=1e-2, master_weights=True)
                st = opt.init(params)
                from apex_tpu.transformer.tensor_parallel.layers \
                    import state_specs_like

                stspecs = state_specs_like(specs, st)

                def train(p, s, tok, tgt):
                    loss, grads = jax.value_and_grad(model.loss)(
                        p, tok, tgt)
                    grads = jax.tree.map(
                        lambda g: jax.lax.pmean(g, "dp"), grads)
                    p, s = opt.step(s, grads, p)
                    return p, s, loss

                step = jax.jit(shard_map(
                    train, mesh=mesh,
                    in_specs=(pspec, stspecs, P("dp"), P("dp")),
                    out_specs=(pspec, stspecs, P())))
                p, s = params, st
                for _ in range(steps):
                    p, s, loss = step(p, s, tokens, targets)
                    losses.append(float(loss))
                return losses, p
            opt = DistributedFusedAdam(
                lr=1e-2, shard_params=(mode == "zero3"),
                bucket_bytes=16 * 1024, compression=compression)
            if mode == "zero3":
                opt.build_layout(params, mesh=mesh)
                sspec, stspecs = opt.shard_spec(), opt.state_specs()
                shards = jax.jit(shard_map(
                    opt.init_shards, mesh=mesh, in_specs=(pspec,),
                    out_specs=sspec))(params)
                st = jax.jit(shard_map(
                    opt.init, mesh=mesh, in_specs=(sspec,),
                    out_specs=stspecs))(shards)

                def train(sh, s, tok, tgt):
                    p, s = opt.gather_params(sh, s)
                    loss, grads = jax.value_and_grad(model.loss)(
                        p, tok, tgt)
                    sh, s = opt.step(s, grads, sh)
                    return sh, s, loss

                step = jax.jit(shard_map(
                    train, mesh=mesh,
                    in_specs=(sspec, stspecs, P("dp"), P("dp")),
                    out_specs=(sspec, stspecs, P())))
                for _ in range(steps):
                    shards, st, loss = step(shards, st, tokens,
                                            targets)
                    losses.append(float(loss))
                gather = jax.jit(shard_map(
                    lambda s, t: opt.gather_params(s, t)[0],
                    mesh=mesh, in_specs=(sspec, stspecs),
                    out_specs=pspec))
                return losses, gather(shards, st)
            # zero1
            stspecs = opt.state_specs()
            st = jax.jit(shard_map(
                opt.init, mesh=mesh, in_specs=(pspec,),
                out_specs=stspecs))(params)

            def train(p, s, tok, tgt):
                loss, grads = jax.value_and_grad(model.loss)(
                    p, tok, tgt)
                p, s = opt.step(s, grads, p)
                return p, s, loss

            step = jax.jit(shard_map(
                train, mesh=mesh,
                in_specs=(pspec, stspecs, P("dp"), P("dp")),
                out_specs=(pspec, stspecs, P())))
            p = params
            for _ in range(steps):
                p, st, loss = step(p, st, tokens, targets)
                losses.append(float(loss))
            return losses, p
        finally:
            parallel_state.destroy_model_parallel()

    def test_gpt_zero3_matches_zero1_bitwise_and_band(self):
        l3, p3 = self._train("zero3")
        l1, p1 = self._train("zero1")
        assert l3 == l1, (l3, l1)  # compression=None: bit-identical
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(p3),
            jax.tree_util.tree_leaves_with_path(p1),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=str(path))
        lr, _ = self._train("replicated")
        assert abs(l3[-1] - lr[-1]) < 3e-2, (l3[-1], lr[-1])

"""Small parity modules: multiproc launcher, memory buffers, autocast."""

import subprocess
import sys

import numpy as np
import jax.numpy as jnp

from apex_tpu._autocast_utils import (
    _cast_if_autocast_enabled,
    autocast,
    get_autocast_dtype,
)
from apex_tpu.transformer.tensor_parallel.memory import (
    GlobalMemoryBuffer,
    RingMemBuffer,
)


def test_autocast_context():
    assert get_autocast_dtype() is None
    x = jnp.ones(3, jnp.float32)
    i = jnp.arange(3)
    assert _cast_if_autocast_enabled(x)[0].dtype == jnp.float32
    with autocast(jnp.bfloat16):
        cx, ci = _cast_if_autocast_enabled(x, i)
        assert cx.dtype == jnp.bfloat16 and ci.dtype == jnp.int32
        with autocast(enabled=False):
            assert _cast_if_autocast_enabled(x)[0].dtype == jnp.float32
        assert get_autocast_dtype() == jnp.bfloat16
    assert get_autocast_dtype() is None


def test_global_memory_buffer_reuses():
    buf = GlobalMemoryBuffer()
    a = buf.get_tensor((4, 4), np.float32, "x")
    b = buf.get_tensor((4, 4), np.float32, "x")
    assert a is b
    c = buf.get_tensor((4, 4), np.float32, "y")
    assert c is not a


def test_ring_buffer_cycles():
    ring = RingMemBuffer("r", 3, (2,), np.float32)
    bufs = [ring.get_next_buffer() for _ in range(4)]
    assert bufs[0] is bufs[3]
    assert bufs[0] is not bufs[1]


def test_multiproc_launcher_wires_env(tmp_path):
    child = tmp_path / "child.py"
    child.write_text(
        "import os\n"
        "print(os.environ['APEX_TPU_PROCESS_ID'],"
        " os.environ['APEX_TPU_NUM_PROCESSES'])\n"
    )
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc",
         "--nprocs", "2", str(child)],
        capture_output=True, text=True, timeout=120,
        env={"PYTHONPATH": ".", "PATH": "/usr/bin:/bin:/usr/local/bin"},
    )
    assert out.returncode == 0, out.stderr
    lines = sorted(out.stdout.strip().splitlines())
    assert lines == ["0 2", "1 2"]


def test_platform_detection_tracks_backend(monkeypatch):
    """A mid-process backend switch must not leave is_tpu() stale
    (the situation __graft_entry__._force_cpu_platform creates)."""
    from apex_tpu.utils import platform as plat

    monkeypatch.delenv("APEX_TPU_DISABLE_PALLAS", raising=False)
    monkeypatch.setattr(plat, "_current_platform", lambda: "tpu")
    assert plat.is_tpu()
    assert plat.default_implementation() == "pallas"
    # flip the backend mid-process: detection must follow, no reset needed
    monkeypatch.setattr(plat, "_current_platform", lambda: "cpu")
    assert not plat.is_tpu()
    assert plat.default_implementation() == "xla"
    # env override is honored per call, not cached
    monkeypatch.setattr(plat, "_current_platform", lambda: "tpu")
    monkeypatch.setenv("APEX_TPU_DISABLE_PALLAS", "1")
    assert plat.is_tpu() and not plat.supports_pallas()
    monkeypatch.delenv("APEX_TPU_DISABLE_PALLAS")
    assert plat.supports_pallas()

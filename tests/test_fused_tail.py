"""Fused optimizer tail parity suite.

The tail's contract (docs/optimizers.md): ``fused_tail=True`` is a
pure LAYOUT change at default settings — one multi-tensor pass over
packed bucket buffers whose params, moments, master weights and
scaler interaction are BIT-identical to the seed per-leaf
unscale → clip → adam → cast chain.  The opt-in deviations
(``exp_avg_sq_dtype=bfloat16``) are convergence-tested on the same
8-step GPT training-parity pattern the compression suite uses.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.amp.scaler import LossScaler, all_finite, scale_gradients
from apex_tpu.optimizers import FusedAdam, FusedLAMB, FusedSGD
from apex_tpu.optimizers.fused_tail import (
    TailContext,
    fold_grads,
    tail_plan,
    tail_traffic_bytes,
    time_opt_tail,
)
from apex_tpu.telemetry import events as tlm_events


def _params():
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    return {
        "emb": jax.random.normal(ks[0], (64, 16), jnp.bfloat16),
        "layers": {
            "w": jax.random.normal(ks[1], (2, 16, 16), jnp.bfloat16),
            "b": jnp.zeros((2, 16), jnp.bfloat16),
            "scale": jnp.ones((16,), jnp.float32),
        },
        "head": jax.random.normal(ks[2], (16, 64), jnp.bfloat16),
        "scalar": jnp.float32(0.5),
    }


def _grads_at(params, i, scale=0.1):
    k = jax.random.PRNGKey(100 + i)
    return jax.tree.map(
        lambda p: (scale * jax.random.normal(
            jax.random.fold_in(k, int(jnp.size(p)) % 997),
            jnp.shape(p), jnp.float32)).astype(jnp.asarray(p).dtype),
        params,
    )


def _run(opt, params, steps=8, finite_seq=None):
    state = opt.init(params)
    p = params
    sfn = jax.jit(lambda s, g, p, f: opt.step(s, g, p, grads_finite=f))
    for i in range(steps):
        f = jnp.bool_(True if finite_seq is None else finite_seq[i])
        p, state = sfn(state, _grads_at(params, i), p, f)
    return p, state


def _assert_tree_equal(a, b, msg=""):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert len(la) == len(lb)
    for (ka, va), (kb, vb) in zip(sorted(la, key=lambda t: str(t[0])),
                                  sorted(lb, key=lambda t: str(t[0]))):
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb), err_msg=f"{msg} {ka}")


ADAM_CONFIGS = [
    dict(master_weights=True),
    dict(master_weights=False),
    dict(master_weights=True, weight_decay=0.01),
    dict(master_weights=True, weight_decay=0.01, adam_w_mode=False),
    dict(master_weights=True, bias_correction=False),
    dict(master_weights=True, max_grad_norm=0.5),
]

LAMB_CONFIGS = [
    dict(weight_decay=0.01),
    dict(weight_decay=0.0),
    dict(weight_decay=0.0, use_nvlamb=True),
    dict(weight_decay=0.01, adam_w_mode=False, master_weights=True),
    dict(weight_decay=0.01, max_grad_norm=None),
    dict(weight_decay=0.01, grad_averaging=False),
]


class TestBitIdentity:
    @pytest.mark.parametrize("cfg", ADAM_CONFIGS)
    def test_adam_fused_matches_per_leaf(self, cfg):
        params = _params()
        a_p, a_s = _run(FusedAdam(lr=1e-2, **cfg), params)
        fused = FusedAdam(lr=1e-2, fused_tail=True, bucket_bytes=512,
                          **cfg)
        b_p, b_s = _run(fused, params)
        _assert_tree_equal(a_p, b_p, "params")
        view = fused.unpack_state(b_s, params)
        for key in ("exp_avg", "exp_avg_sq"):
            _assert_tree_equal(a_s[key], view[key], key)
        if cfg.get("master_weights"):
            _assert_tree_equal(a_s["master"], view["master"], "master")
        assert int(a_s["step"]) == int(b_s["step"])

    @pytest.mark.parametrize("cfg", LAMB_CONFIGS)
    def test_lamb_fused_matches_per_leaf(self, cfg):
        params = _params()
        a_p, a_s = _run(FusedLAMB(lr=1e-2, **cfg), params)
        b_p, b_s = _run(FusedLAMB(lr=1e-2, fused_tail=True,
                                  bucket_bytes=512, **cfg), params)
        if cfg.get("master_weights"):
            # LAMB + master: the trust-ratio norms reduce over buffer
            # VIEWS of the master; some CPU backends contract the
            # square-accumulate to FMA differently there than over a
            # standalone array, a 1-ulp wobble in w_norm.  Everything
            # downstream of the norms is exact — bound at 2 ulp.
            for (ka, va), (_, vb) in zip(
                sorted(jax.tree_util.tree_leaves_with_path(a_p),
                       key=lambda t: str(t[0])),
                sorted(jax.tree_util.tree_leaves_with_path(b_p),
                       key=lambda t: str(t[0]))):
                np.testing.assert_allclose(
                    np.asarray(va, np.float32),
                    np.asarray(vb, np.float32),
                    rtol=3e-7, atol=0, err_msg=str(ka))
        else:
            _assert_tree_equal(a_p, b_p, "params")

    def test_skip_steps_bit_identical(self):
        # non-finite verdicts interleaved: the no-op must preserve the
        # same state bits in both layouts
        params = _params()
        seq = [True, False, True, True, False, True, True, True]
        a_p, _ = _run(FusedAdam(lr=1e-2, master_weights=True), params,
                      finite_seq=seq)
        b_p, _ = _run(FusedAdam(lr=1e-2, master_weights=True,
                                fused_tail=True, bucket_bytes=512),
                      params, finite_seq=seq)
        _assert_tree_equal(a_p, b_p)

    def test_bucket_size_independence(self):
        # the plan is a layout choice: any bucket_bytes gives the bits
        params = _params()
        ref_p, _ = _run(FusedAdam(lr=1e-2, fused_tail=True,
                                  bucket_bytes=128), params)
        for bb in (64, 4096, 1 << 22):
            p, _ = _run(FusedAdam(lr=1e-2, fused_tail=True,
                                  bucket_bytes=bb), params)
            _assert_tree_equal(ref_p, p, f"bucket_bytes={bb}")


class TestStepScaled:
    def test_per_leaf_matches_seed_chain(self):
        params = _params()
        scaler = LossScaler()
        sstate = scaler.init()
        opt = FusedAdam(lr=1e-2, master_weights=True)
        state = opt.init(params)
        g = _grads_at(params, 0)
        # seed: unscale pass -> finite -> step(grads_finite)
        g_un, finite = scaler.unscale(sstate, g)
        seed_p, seed_s = opt.step(state, g_un, params,
                                  grads_finite=finite)
        got_p, got_s, got_f = opt.step_scaled(
            state, g, params, scaler.inv_scale(sstate))
        assert bool(got_f) == bool(finite)
        _assert_tree_equal(seed_p, got_p)
        _assert_tree_equal(seed_s, got_s)

    def test_fused_matches_per_leaf(self):
        params = _params()
        scaler = LossScaler()
        sstate = scaler.init()
        inv = scaler.inv_scale(sstate)
        g = _grads_at(params, 0, scale=float(sstate.loss_scale) * 1e-4)
        a = FusedAdam(lr=1e-2, master_weights=True)
        b = FusedAdam(lr=1e-2, master_weights=True, fused_tail=True,
                      bucket_bytes=512)
        a_p, _, a_f = a.step_scaled(a.init(params), g, params, inv)
        b_p, _, b_f = b.step_scaled(b.init(params), g, params, inv)
        assert bool(a_f) == bool(b_f) is True
        _assert_tree_equal(a_p, b_p)

    def test_overflow_skips_and_reports(self):
        params = _params()
        g = _grads_at(params, 0)
        g["head"] = (jnp.asarray(g["head"], jnp.float32)
                     * jnp.inf).astype(g["head"].dtype)
        for fused in (False, True):
            opt = FusedAdam(lr=1e-2, master_weights=True,
                            fused_tail=fused, bucket_bytes=512)
            state = opt.init(params)
            p, s, finite = opt.step_scaled(state, g, params,
                                           jnp.float32(1.0))
            assert not bool(finite)
            _assert_tree_equal(params, p, "skipped params")
            assert int(s["step"]) == 0  # reverted with the state

    def test_finite_reduce_hook_runs(self):
        params = _params()
        calls = []

        def reduce_hook(f):
            calls.append(True)
            return f & jnp.bool_(False)  # simulate a peer's overflow

        opt = FusedAdam(lr=1e-2, fused_tail=True, bucket_bytes=512)
        p, _, finite = opt.step_scaled(
            opt.init(params), _grads_at(params, 0), params,
            jnp.float32(1.0), finite_reduce=reduce_hook)
        assert calls and not bool(finite)
        _assert_tree_equal(params, p)


class TestSubFp32Moments:
    def test_bf16_v_tracks_fp32(self):
        params = _params()
        a_p, _ = _run(FusedAdam(lr=1e-2, master_weights=True), params)
        b_p, b_s = _run(FusedAdam(lr=1e-2, master_weights=True,
                                  fused_tail=True,
                                  exp_avg_sq_dtype=jnp.bfloat16),
                        params)
        for n, buf in b_s["exp_avg_sq"].items():
            assert buf.dtype == jnp.bfloat16, n
        err = max(
            float(jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)
                                  - jnp.asarray(y, jnp.float32))))
            for x, y in zip(jax.tree.leaves(a_p), jax.tree.leaves(b_p))
            if jnp.size(x)
        )
        # 8 steps at lr=1e-2: bf16 second-moment storage rounds the
        # denominator by ~2^-8 relative — parameter drift stays an
        # order under the accumulated update scale
        assert err < 0.05

    def test_per_leaf_path_honors_dtype_too(self):
        params = _params()
        opt = FusedAdam(lr=1e-2, exp_avg_sq_dtype=jnp.bfloat16)
        state = opt.init(params)
        for leaf in jax.tree.leaves(state["exp_avg_sq"]):
            assert leaf.dtype == jnp.bfloat16
        p, s = opt.step(state, _grads_at(params, 0), params)
        for leaf in jax.tree.leaves(s["exp_avg_sq"]):
            assert leaf.dtype == jnp.bfloat16

    def test_invalid_dtype_rejected(self):
        with pytest.raises(ValueError, match="floating"):
            FusedAdam(exp_avg_sq_dtype=jnp.int8)


class TestGPTTrainingParity:
    """The ISSUE-specified gate: 8 GPT steps, fused vs seed chain —
    params, moments and scaler state bit-identical at defaults;
    sub-fp32 moments within the documented tolerance."""

    VOCAB, LAYERS, HIDDEN, HEADS, SEQ = 64, 2, 32, 4, 8
    LOSS_ATOL = 3e-2  # the compression suite's documented tolerance

    def _train(self, fused, exp_avg_sq_dtype=jnp.float32, steps=8):
        from jax.sharding import PartitionSpec as P

        from apex_tpu.models.gpt import GPTConfig, GPTModel
        from apex_tpu.transformer import parallel_state
        from apex_tpu.transformer.tensor_parallel.layers import (
            state_specs_like,
        )
        from apex_tpu._compat import shard_map

        if parallel_state.model_parallel_is_initialized():
            parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        try:
            cfg = GPTConfig(
                vocab_size=self.VOCAB, num_layers=self.LAYERS,
                hidden_size=self.HIDDEN,
                num_attention_heads=self.HEADS,
                max_position_embeddings=self.SEQ,
                compute_dtype=jnp.float32, remat=False,
                attention_impl="xla",
            )
            model = GPTModel(cfg)
            params = model.init(jax.random.PRNGKey(0))
            specs = model.param_specs()
            opt = FusedAdam(lr=1e-2, master_weights=True,
                            fused_tail=fused,
                            exp_avg_sq_dtype=exp_avg_sq_dtype)
            scaler = LossScaler(loss_scale=2.0 ** 8)
            sstate = scaler.init()
            state = opt.init(params)
            opt_specs = state_specs_like(specs, state)
            rng = np.random.default_rng(0)
            tokens = jnp.asarray(
                rng.integers(0, self.VOCAB, (8, self.SEQ)), jnp.int32)
            targets = jnp.roll(tokens, -1, axis=1)

            def step_fn(p, s, ss, tok, tgt):
                grads, loss = jax.grad(
                    lambda pp: (scaler.scale(
                        ss, model.loss(pp, tok, tgt)),
                        model.loss(pp, tok, tgt)),
                    has_aux=True)(p)
                grads = jax.tree.map(
                    lambda g: jax.lax.pmean(g, "dp"), grads)
                new_p, new_s, finite = opt.step_scaled(
                    s, grads, p, scaler.inv_scale(ss))
                return (new_p, new_s, scaler.adjust(ss, finite),
                        jax.lax.pmean(loss, "dp"))

            sspec = jax.tree.map(lambda _: P(), sstate)
            step = jax.jit(shard_map(
                step_fn, mesh=mesh,
                in_specs=(specs, opt_specs, sspec, P("dp"), P("dp")),
                out_specs=(specs, opt_specs, sspec, P()),
            ))
            trace = []
            for _ in range(steps):
                params, state, sstate, loss = step(
                    params, state, sstate, tokens, targets)
                trace.append(float(loss))
            return params, state, sstate, np.asarray(trace)
        finally:
            parallel_state.destroy_model_parallel()

    def test_fused_bit_identical_after_8_steps(self):
        p_a, s_a, ss_a, tr_a = self._train(fused=False)
        p_b, s_b, ss_b, tr_b = self._train(fused=True)
        assert np.all(np.isfinite(tr_a)) and tr_a[-1] < tr_a[0]
        np.testing.assert_array_equal(tr_a, tr_b)
        _assert_tree_equal(p_a, p_b, "params")
        opt = FusedAdam(lr=1e-2, master_weights=True, fused_tail=True)
        view = opt.unpack_state(s_b, p_a)
        for key in ("exp_avg", "exp_avg_sq", "master"):
            _assert_tree_equal(s_a[key], view[key], key)
        # scaler state too (the tail returns the same finite verdicts)
        for f in ss_a._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(ss_a, f)),
                np.asarray(getattr(ss_b, f)), err_msg=f)

    def test_sub_fp32_moments_converge_within_tolerance(self):
        _, _, _, base = self._train(fused=False)
        _, _, _, sub = self._train(fused=True,
                                   exp_avg_sq_dtype=jnp.bfloat16)
        assert np.all(np.isfinite(sub)) and sub[-1] < sub[0]
        np.testing.assert_allclose(sub, base, atol=self.LOSS_ATOL)


class TestMachinery:
    def test_unsupported_optimizer_rejected(self):
        from apex_tpu.optimizers.base import FusedOptimizer

        opt = FusedOptimizer(lr=0.1, fused_tail=True)
        with pytest.raises(ValueError, match="fused_tail"):
            opt.init(_params())
        # optimizers without a tail implementation don't grow the flag
        import inspect

        assert "fused_tail" not in inspect.signature(
            FusedSGD.__init__).parameters

    def test_fold_grads_finiteness_and_unscale(self):
        params = {"a": jnp.ones((4,), jnp.bfloat16),
                  "b": jnp.ones((3,), jnp.float32)}
        leaves = jax.tree.leaves(params)
        views, finite = fold_grads(leaves, inv_scale=None)
        assert bool(finite)
        assert sum(v.size for v in views) == 7
        assert all(v.dtype == jnp.float32 for v in views)
        bad = [leaves[0], jnp.asarray([1.0, jnp.nan, 1.0])]
        _, finite = fold_grads(bad)
        assert not bool(finite)
        # the fold reproduces the seed unscale's grad-dtype round trip
        views, _ = fold_grads(leaves, inv_scale=jnp.float32(1 / 3))
        seed = scale_gradients(params, jnp.float32(1 / 3))
        for v, l in zip(views, jax.tree.leaves(seed)):
            np.testing.assert_array_equal(
                np.asarray(v),
                np.asarray(jnp.asarray(l).astype(jnp.float32)))

    def test_views_pack_roundtrip(self):
        params = _params()
        plan = tail_plan(params, 512)
        leaves = jax.tree.leaves(params)
        ctx = TailContext(plan, tuple(jnp.shape(l) for l in leaves))
        bufs = ctx.pack_views(
            [jnp.asarray(l).astype(jnp.float32) for l in leaves])
        back = ctx.views(bufs)
        for l, v in zip(leaves, back):
            np.testing.assert_array_equal(
                np.asarray(jnp.asarray(l), np.float32), np.asarray(v))

    def test_traffic_model_counts_master(self):
        params = {"w": jnp.zeros((10,), jnp.bfloat16)}
        with_master = tail_traffic_bytes(
            params, FusedAdam(master_weights=True))
        without = tail_traffic_bytes(params, FusedAdam())
        # +2 fp32 passes (read+write master) vs +1 bf16 read of params
        assert with_master - without == 10 * (2 * 4 - 2)

    def test_opt_tail_event_emitted(self):
        events = []

        class Sink:
            def event(self, kind, **fields):
                events.append((kind, fields))

        sink = Sink()
        params = _params()
        opt = FusedAdam(lr=1e-2, fused_tail=True, bucket_bytes=512)
        tlm_events.add_sink(sink)
        try:
            rep = time_opt_tail(opt, opt.init(params),
                                _grads_at(params, 0), params,
                                inv_scale=1.0, iters=2, warmup=1)
        finally:
            tlm_events.remove_sink(sink)
        kinds = [k for k, _ in events]
        assert "opt_tail" in kinds
        # the in-step trace-time event has only the static pass shape;
        # the measurement event (last) carries the self-timed numbers
        timed = [f for k, f in events
                 if k == "opt_tail" and "self_ms" in f]
        assert timed, "time_opt_tail must emit a measured event"
        fields = timed[-1]
        assert fields["fused"] and fields["unscale_folded"]
        assert fields["buffers"] >= 1
        assert fields["self_ms"] > 0 and fields["gbs"] > 0
        assert rep["bytes"] == tail_traffic_bytes(params, opt)

    def test_trace_time_event_in_step(self):
        events = []

        class Sink:
            def event(self, kind, **fields):
                events.append(kind)

        params = _params()
        opt = FusedAdam(lr=1e-2, fused_tail=True, bucket_bytes=512)
        state = opt.init(params)
        tlm_events.add_sink(sink := Sink())
        try:
            jax.jit(lambda s, g, p: opt.step(s, g, p))(
                state, _grads_at(params, 0), params)
        finally:
            tlm_events.remove_sink(sink)
        assert "opt_tail" in events

    def test_optimizer_phase_in_hlo(self):
        # the tlm.optimizer span must reach the compiled metadata so
        # xprof segments the fused pass (docs/observability.md)
        params = _params()
        opt = FusedAdam(lr=1e-2, fused_tail=True, bucket_bytes=512)
        state = opt.init(params)
        lowered = jax.jit(
            lambda s, g, p: opt.step(s, g, p)
        ).lower(state, _grads_at(params, 0), params)
        try:  # newer jax: scope names in the lowering's debug info
            txt = lowered.as_text(debug_info=True)
        except TypeError:
            txt = lowered.compile().as_text()
        assert "tlm.optimizer" in txt

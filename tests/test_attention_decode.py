"""Decode-tier attention (fmha_decode): paged-cache parity + dispatch.

Suite philosophy: the Pallas kernel (interpret mode on CPU) is checked
against the XLA paged reference at every cache layout a serving batch
can produce — shuffled physical pages, ragged lengths ending on
partially-filled pages, idle zero-length slots, int8 pages with
per-block scales, fused q-RoPE — and the contiguous
``flash_attention(implementation="decode")`` seam is pinned against
``mha_reference`` (the training ladder's ground truth).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.attention import flash_attention, mha_reference
from apex_tpu.ops.attention_decode import (
    decode_contiguous,
    fmha_decode,
    paged_attention_reference,
)
from apex_tpu.ops.quantization import quantize_rows
from apex_tpu.ops.rope import apply_rope_tables, rope_cos_sin


def make_cache(key, pool_pages, h, ps, d, b, npp, dtype=jnp.float32,
               shuffle=True):
    """Pools + a shuffled page table: physical layout uncorrelated with
    logical order, like a real allocator's reuse pattern."""
    k0, k1, k2, k3 = jax.random.split(key, 4)
    k_pages = jax.random.normal(k0, (pool_pages, h, ps, d), dtype)
    v_pages = jax.random.normal(k1, (pool_pages, h, ps, d), dtype)
    q = jax.random.normal(k2, (b, h, 1, d), dtype)
    ids = jnp.arange(1, pool_pages, dtype=jnp.int32)
    if shuffle:
        ids = jax.random.permutation(k3, ids)
    page_table = ids[: b * npp].reshape(b, npp)
    return q, k_pages, v_pages, page_table


def quant_pages(pages, kv_block):
    d = pages.shape[-1]
    vals, scales = quantize_rows(
        pages.reshape(-1, d).astype(jnp.float32), kv_block)
    return vals.reshape(pages.shape), scales.reshape(
        *pages.shape[:-1], -1)


class TestPagedParity:
    @pytest.mark.parametrize("sq", [1, 4])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_pallas_matches_xla_ragged_lengths(self, sq, dtype):
        h, ps, d, b, npp = 4, 8, 32, 5, 4
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(0), 1 + b * npp, h, ps, d, b, npp, dtype)
        q = jax.random.normal(jax.random.PRNGKey(9), (b, h, sq, d),
                              dtype)
        # every layout class: full, partial tail page, exactly one
        # page, barely past a boundary, minimum (sq tokens)
        lengths = jnp.array(
            [npp * ps, 2 * ps + 3, ps, ps + 1, max(sq, 2)], jnp.int32)
        out_p = fmha_decode(q, kp, vp, pt, lengths,
                            implementation="pallas")
        out_x = fmha_decode(q, kp, vp, pt, lengths,
                            implementation="xla")
        tol = 1e-5 if dtype == jnp.float32 else 2e-2
        np.testing.assert_allclose(
            np.asarray(out_p, np.float32), np.asarray(out_x, np.float32),
            atol=tol)

    def test_matches_dense_reference_exactly_where_defined(self):
        """The paged gather + masking reproduces plain dense causal
        attention over the valid prefix."""
        h, ps, d, b, npp = 2, 8, 16, 3, 3
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(1), 1 + b * npp, h, ps, d, b, npp)
        lengths = jnp.array([20, 24, 9], jnp.int32)
        out = fmha_decode(q, kp, vp, pt, lengths,
                          implementation="pallas")
        # dense per-sequence reference from the gathered pages
        for i in range(int(pt.shape[0])):
            n = int(lengths[i])
            kd = jnp.moveaxis(
                kp[pt[i]], 1, 0).reshape(1, h, npp * ps, d)[:, :, :n]
            vd = jnp.moveaxis(
                vp[pt[i]], 1, 0).reshape(1, h, npp * ps, d)[:, :, :n]
            want = mha_reference(q[i:i + 1], kd, vd, causal=False)
            np.testing.assert_allclose(
                np.asarray(out[i:i + 1]), np.asarray(want), atol=1e-5,
                err_msg=f"seq {i}")

    def test_small_sq_causal_masks_each_row(self):
        """sq=4 chunked-prefill rows: row i attends exactly
        lengths - sq + i + 1 positions."""
        h, ps, d, b, npp, sq = 2, 8, 16, 2, 3, 4
        _, kp, vp, pt = make_cache(
            jax.random.PRNGKey(2), 1 + b * npp, h, ps, d, b, npp)
        q = jax.random.normal(jax.random.PRNGKey(3), (b, h, sq, d))
        lengths = jnp.array([19, 11], jnp.int32)
        out = fmha_decode(q, kp, vp, pt, lengths, causal=True,
                          implementation="pallas")
        for i in range(b):
            for r in range(sq):
                n = int(lengths[i]) - sq + r + 1
                kd = jnp.moveaxis(
                    kp[pt[i]], 1, 0).reshape(1, h, npp * ps, d)[:, :, :n]
                vd = jnp.moveaxis(
                    vp[pt[i]], 1, 0).reshape(1, h, npp * ps, d)[:, :, :n]
                want = mha_reference(
                    q[i:i + 1, :, r:r + 1], kd, vd, causal=False)
                np.testing.assert_allclose(
                    np.asarray(out[i:i + 1, :, r:r + 1]),
                    np.asarray(want), atol=1e-5,
                    err_msg=f"seq {i} row {r}")

    def test_noncausal_attends_full_length(self):
        h, ps, d, b, npp = 2, 8, 16, 2, 2
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(4), 1 + b * npp, h, ps, d, b, npp)
        lengths = jnp.array([13, 16], jnp.int32)
        out_p = fmha_decode(q, kp, vp, pt, lengths, causal=False,
                            implementation="pallas")
        out_x = fmha_decode(q, kp, vp, pt, lengths, causal=False,
                            implementation="xla")
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                                   atol=1e-5)
        # at sq=1, causal and non-causal are the same mask
        out_c = fmha_decode(q, kp, vp, pt, lengths, causal=True,
                            implementation="pallas")
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_c),
                                   atol=1e-6)

    def test_block_h_grouping_is_bit_identical(self):
        """Head packing is a scheduling choice: every block_h produces
        the SAME bits (per-head state never crosses heads)."""
        h, ps, d, b, npp = 8, 8, 16, 2, 2
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(5), 1 + b * npp, h, ps, d, b, npp)
        lengths = jnp.array([12, 16], jnp.int32)
        outs = [
            np.asarray(fmha_decode(q, kp, vp, pt, lengths,
                                   block_h=bh, implementation="pallas"))
            for bh in (1, 2, 4, 8)
        ]
        for o in outs[1:]:
            np.testing.assert_array_equal(outs[0], o)

    def test_idle_zero_length_slot_is_finite_and_isolated(self):
        """A zero-length slot (an idle serving slot, table all null
        pages) must produce finite garbage and not perturb live
        slots."""
        h, ps, d, b, npp = 2, 8, 16, 3, 2
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(6), 1 + b * npp, h, ps, d, b, npp)
        lengths = jnp.array([12, 0, 16], jnp.int32)
        pt = pt.at[1].set(0)
        out = fmha_decode(q, kp, vp, pt, lengths,
                          implementation="pallas")
        assert bool(jnp.all(jnp.isfinite(out)))
        # live slots bit-match a run where slot 1 holds real pages
        q2, kp2, vp2, pt2 = make_cache(
            jax.random.PRNGKey(6), 1 + b * npp, h, ps, d, b, npp)
        out2 = fmha_decode(q2, kp2, vp2, pt2,
                           jnp.array([12, 16, 16], jnp.int32),
                           implementation="pallas")
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.asarray(out2[0]))
        np.testing.assert_array_equal(np.asarray(out[2]),
                                      np.asarray(out2[2]))


class TestInt8Pages:
    @pytest.mark.parametrize("kv_block", [8, 16, 32])
    def test_int8_pallas_matches_int8_xla(self, kv_block):
        h, ps, d, b, npp = 4, 8, 32, 3, 3
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(7), 1 + b * npp, h, ps, d, b, npp)
        k8, ks = quant_pages(kp, kv_block)
        v8, vs = quant_pages(vp, kv_block)
        lengths = jnp.array([24, 17, 8], jnp.int32)
        out_p = fmha_decode(q, k8, v8, pt, lengths, k_scales=ks,
                            v_scales=vs, kv_block=kv_block,
                            implementation="pallas")
        out_x = fmha_decode(q, k8, v8, pt, lengths, k_scales=ks,
                            v_scales=vs, kv_block=kv_block,
                            implementation="xla")
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                                   atol=1e-5)

    def test_int8_round_trip_band_vs_fp32(self):
        """int8 pages with per-block scales stay inside the documented
        band of the full-precision cache: per-element error <= a few
        ulp of the block amax, attention output well under 5e-2 for
        unit-scale data."""
        h, ps, d, b, npp = 4, 8, 32, 3, 3
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(8), 1 + b * npp, h, ps, d, b, npp)
        k8, ks = quant_pages(kp, 16)
        v8, vs = quant_pages(vp, 16)
        lengths = jnp.array([24, 17, 8], jnp.int32)
        out_fp = fmha_decode(q, kp, vp, pt, lengths,
                             implementation="pallas")
        out_i8 = fmha_decode(q, k8, v8, pt, lengths, k_scales=ks,
                             v_scales=vs, kv_block=16,
                             implementation="pallas")
        err = float(jnp.max(jnp.abs(out_fp - out_i8)))
        assert err < 5e-2, err
        assert err > 0.0     # it IS quantized (the band is not a no-op)

    def test_int8_requires_both_scales(self):
        h, ps, d, b, npp = 2, 8, 16, 1, 1
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(9), 1 + b * npp, h, ps, d, b, npp)
        k8, ks = quant_pages(kp, 16)
        with pytest.raises(ValueError, match="BOTH"):
            fmha_decode(q, k8, vp, pt, jnp.array([8]), k_scales=ks)
        with pytest.raises(ValueError, match="int8 pages require"):
            fmha_decode(q, k8, k8, pt, jnp.array([8]))


class TestFusedRope:
    def test_fused_rope_matches_prerotated_q(self):
        h, ps, d, b, npp = 4, 8, 32, 3, 2
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(10), 1 + b * npp, h, ps, d, b, npp)
        lengths = jnp.array([12, 16, 5], jnp.int32)
        pos = (lengths[:, None] - 1).astype(jnp.int32)      # sq=1
        cos, sin = rope_cos_sin(pos, d)                     # (b, 1, d/2)
        fused = fmha_decode(q, kp, vp, pt, lengths, rope=(cos, sin),
                            implementation="pallas")
        q_pre = apply_rope_tables(q, cos[:, None], sin[:, None])
        pre = fmha_decode(q_pre, kp, vp, pt, lengths,
                          implementation="pallas")
        np.testing.assert_allclose(np.asarray(fused), np.asarray(pre),
                                   atol=1e-5)
        # and the XLA path applies the same rotation
        xla = fmha_decode(q, kp, vp, pt, lengths, rope=(cos, sin),
                          implementation="xla")
        np.testing.assert_allclose(np.asarray(fused), np.asarray(xla),
                                   atol=1e-5)

    def test_rope_shape_validated(self):
        h, ps, d, b, npp = 2, 8, 16, 2, 1
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(11), 1 + b * npp, h, ps, d, b, npp)
        bad = jnp.zeros((b, 2, d // 2))                     # sq=1 != 2
        with pytest.raises(ValueError, match="rope tables"):
            fmha_decode(q, kp, vp, pt, jnp.array([8, 8]),
                        rope=(bad, bad), implementation="pallas")


class TestContiguousSeam:
    def test_flash_attention_decode_matches_reference_causal(self):
        b, h, s, d = 2, 4, 64, 32
        ks = jax.random.split(jax.random.PRNGKey(12), 3)
        q = jax.random.normal(ks[0], (b, h, s, d))
        k = jax.random.normal(ks[1], (b, h, s, d))
        v = jax.random.normal(ks[2], (b, h, s, d))
        out = flash_attention(q, k, v, causal=True,
                              implementation="decode")
        want = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-5)

    def test_sq1_tail_matches_full_attention_row(self):
        b, h, s, d = 2, 4, 50, 32
        ks = jax.random.split(jax.random.PRNGKey(13), 3)
        q = jax.random.normal(ks[0], (b, h, s, d))
        k = jax.random.normal(ks[1], (b, h, s, d))
        v = jax.random.normal(ks[2], (b, h, s, d))
        full = mha_reference(q, k, v, causal=True)
        tail = flash_attention(q[:, :, -1:], k, v, causal=True,
                               implementation="decode")
        np.testing.assert_allclose(
            np.asarray(tail), np.asarray(full[:, :, -1:]), atol=1e-5)

    def test_page_size_is_a_scheduling_choice(self):
        # ragged split (s not a page multiple) and different page
        # sizes agree
        b, h, s, d = 2, 2, 50, 16
        ks = jax.random.split(jax.random.PRNGKey(14), 3)
        q = jax.random.normal(ks[0], (b, h, 1, d))
        k = jax.random.normal(ks[1], (b, h, s, d))
        v = jax.random.normal(ks[2], (b, h, s, d))
        outs = [
            np.asarray(decode_contiguous(q, k, v, page_size=ps))
            for ps in (8, 16, 64, 128)
        ]
        for o in outs[1:]:
            np.testing.assert_allclose(outs[0], o, atol=1e-5)

    def test_decode_rejects_bias_segments_dropout(self):
        x = jnp.zeros((1, 1, 8, 16))
        with pytest.raises(ValueError, match="decode"):
            flash_attention(x, x, x, implementation="decode",
                            bias=jnp.zeros((1, 1, 8, 8)))
        with pytest.raises(ValueError, match="decode"):
            flash_attention(x, x, x, implementation="decode",
                            q_segment_ids=jnp.zeros((1, 8), jnp.int32),
                            kv_segment_ids=jnp.zeros((1, 8), jnp.int32))
        with pytest.raises(ValueError, match="decode"):
            flash_attention(x, x, x, implementation="decode",
                            dropout_rate=0.1, dropout_seed=0)

    def test_causal_sq_gt_sk_rejected(self):
        q = jnp.zeros((1, 1, 9, 16))
        k = jnp.zeros((1, 1, 8, 16))
        with pytest.raises(ValueError, match="sq <= sk"):
            decode_contiguous(q, k, k, causal=True)


class TestValidation:
    def test_head_and_dim_mismatch(self):
        q = jnp.zeros((1, 4, 1, 16))
        pool = jnp.zeros((2, 2, 8, 16))
        with pytest.raises(ValueError, match="heads"):
            fmha_decode(q, pool, pool, jnp.zeros((1, 1), jnp.int32),
                        jnp.array([4]))
        pool = jnp.zeros((2, 4, 8, 32))
        with pytest.raises(ValueError, match="head_dim"):
            fmha_decode(q, pool, pool, jnp.zeros((1, 1), jnp.int32),
                        jnp.array([4]))

    def test_page_table_shape(self):
        q = jnp.zeros((2, 2, 1, 16))
        pool = jnp.zeros((3, 2, 8, 16))
        with pytest.raises(ValueError, match="page_table"):
            fmha_decode(q, pool, pool, jnp.zeros((1, 1), jnp.int32),
                        jnp.array([4, 4]))

    def test_unknown_implementation(self):
        q = jnp.zeros((1, 2, 1, 16))
        pool = jnp.zeros((2, 2, 8, 16))
        with pytest.raises(ValueError, match="implementation"):
            fmha_decode(q, pool, pool, jnp.zeros((1, 1), jnp.int32),
                        jnp.array([4]), implementation="fast")

    def test_block_h_must_divide(self):
        q = jnp.zeros((1, 4, 1, 16))
        pool = jnp.zeros((2, 4, 8, 16))
        with pytest.raises(ValueError, match="block_h"):
            fmha_decode(q, pool, pool, jnp.zeros((1, 1), jnp.int32),
                        jnp.array([4]), block_h=3,
                        implementation="pallas")


class TestChunkedPrefill:
    """The s_q-chunk path the stall-free scheduler drives: a chunk
    attends over the prior cache AND its own just-written pages, and
    the head packing shrinks with s_q so the VMEM accumulator scratch
    stays bounded (kernel_validation sweeps the timed s_q in {64, 256}
    cells on TPU; here the semantics are pinned cheaply)."""

    def test_pick_block_h_caps_rows_by_sq(self):
        from apex_tpu.ops.attention_decode import (
            FMHA_DECODE_BLOCK_H,
            FMHA_DECODE_MAX_ROWS,
            _pick_block_h,
        )

        # the s_q = 1 decode default is untouched
        assert _pick_block_h(16) == FMHA_DECODE_BLOCK_H
        assert _pick_block_h(16, 1) == FMHA_DECODE_BLOCK_H
        # chunk s_q's shrink the packing to the row budget
        assert _pick_block_h(16, 64) == FMHA_DECODE_MAX_ROWS // 64
        assert _pick_block_h(16, 256) == FMHA_DECODE_MAX_ROWS // 256
        for h in (3, 6, 12):
            bh = _pick_block_h(h, 256)
            assert bh >= 1 and h % bh == 0
        # past the budget the PALLAS path refuses (even block_h=1
        # cannot honor the scratch bound) — surfaced through
        # run_kernel's strict contract for explicit pallas requests;
        # the XLA path (and auto-mode fallback) still serves
        from apex_tpu.ops.common import KernelLoweringError

        sq = FMHA_DECODE_MAX_ROWS + 1
        q = jnp.zeros((1, 2, sq, 16))
        pool = jnp.zeros((1 + sq // 8 + 1, 2, 8, 16))
        pt = jnp.arange(1, 2 + sq // 8, dtype=jnp.int32)[None]
        with pytest.raises(KernelLoweringError, match="row budget"):
            fmha_decode(q, pool, pool, pt, jnp.array([sq]),
                        implementation="pallas")
        out = fmha_decode(q, pool, pool, pt, jnp.array([sq]),
                          implementation="xla")
        assert out.shape == q.shape

    def test_chunk_attends_over_own_just_written_pages(self):
        """Write-before-attend: scatter a chunk's K/V into tail pages
        through the serving write path, then attend with s_q = chunk —
        pallas and XLA must match the dense reference over [hist +
        chunk]."""
        from apex_tpu.serving.kv_cache import write_targets, write_tokens

        h, ps, d, npp, hist, chunk = 2, 8, 16, 4, 11, 8
        b = 1
        key = jax.random.PRNGKey(5)
        kh, kv_, kc, kq = jax.random.split(key, 4)
        # history already in the cache
        k_hist = jax.random.normal(kh, (hist, h, d))
        v_hist = jax.random.normal(kv_, (hist, h, d))
        # the chunk's own K/V, written before the attend
        k_chunk = jax.random.normal(kc, (chunk, h, d))
        v_chunk = -k_chunk
        q = jax.random.normal(kq, (b, h, chunk, d))
        pools = {
            "k": jnp.zeros((1 + npp, h, ps, d)),
            "v": jnp.zeros((1 + npp, h, ps, d)),
        }
        row = jnp.arange(1, npp + 1, dtype=jnp.int32)
        pos_h = jnp.arange(hist, dtype=jnp.int32)
        wp, wo = write_targets(row, pos_h, pos_h < hist, ps)
        pools = write_tokens(pools, k_hist, v_hist, wp, wo)
        pos_c = hist + jnp.arange(chunk, dtype=jnp.int32)
        wp, wo = write_targets(row, pos_c, pos_c < hist + chunk, ps)
        pools = write_tokens(pools, k_chunk, v_chunk, wp, wo)
        lengths = jnp.array([hist + chunk], jnp.int32)
        out_p = fmha_decode(q, pools["k"], pools["v"], row[None],
                            lengths, implementation="pallas")
        out_x = fmha_decode(q, pools["k"], pools["v"], row[None],
                            lengths, implementation="xla")
        # dense reference: chunk token i sits at position hist + i
        k_all = jnp.concatenate([k_hist, k_chunk]).transpose(1, 0, 2)
        v_all = jnp.concatenate([v_hist, v_chunk]).transpose(1, 0, 2)
        s = jnp.einsum("bhqd,hkd->bhqk", q, k_all) / d**0.5
        k_pos = jnp.arange(hist + chunk)[None, None, None, :]
        q_pos = (hist + jnp.arange(chunk))[None, None, :, None]
        s = jnp.where(k_pos <= q_pos, s, -1e30)
        ref = jnp.einsum("bhqk,hkd->bhqd", jax.nn.softmax(s, axis=-1),
                         v_all)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(ref),
                                   atol=2e-5)
        np.testing.assert_allclose(np.asarray(out_x), np.asarray(ref),
                                   atol=2e-5)

    def test_large_sq_block_h_auto_shrink_matches_explicit(self):
        """At an s_q past the row budget the auto pick must equal an
        explicitly shrunken block_h, bitwise."""
        h, ps, d, npp, sq = 4, 8, 16, 8, 64
        q, kp, vp, pt = make_cache(
            jax.random.PRNGKey(7), 1 + npp, h, ps, d, 1, npp)
        q = jax.random.normal(jax.random.PRNGKey(8), (1, h, sq, d))
        lengths = jnp.array([ps * npp], jnp.int32)
        auto = fmha_decode(q, kp, vp, pt, lengths,
                           implementation="pallas")
        explicit = fmha_decode(q, kp, vp, pt, lengths, block_h=4,
                               implementation="pallas")
        np.testing.assert_array_equal(np.asarray(auto),
                                      np.asarray(explicit))

"""fmha-mid (pipelined mid-sequence attention) vs flash and XLA.

The mid kernel's parity contract matches the flash/short kernels':
values and all four gradients (dq/dk/dv/dbias) within the existing
tolerances against BOTH the streamed flash kernel and the XLA
reference, and BIT-IDENTICAL dropout masks across every implementation
for a given seed.  Interpret mode runs the real kernel bodies on CPU.

Also pins the three-tier dispatch ladder: short at/below its crossover,
mid inside (short, FMHA_MID_MAX_SEQ], flash above — with the env knobs
moving/disabling each window (APEX_TPU_FMHA_MID_MAX_SEQ=0 pins the mid
band back to the flash kernel's exact code path, the default-off
safety of the acceptance contract).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import flash_attention, fmha_mid, mha_reference
from apex_tpu.ops.attention_mid import (
    FMHA_MID_MAX_SEQ,
    _bwd_block_bh,
    default_mid_block_bh,
    default_mid_blocks,
    mid_seq_threshold,
)
from apex_tpu.ops.attention_short import FMHA_SHORT_MAX_SEQ


def _qkv(key, shape):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, shape), jax.random.normal(kk, shape),
            jax.random.normal(kv, shape))


def _grads(fn, *args, argnums=None):
    argnums = tuple(range(len(args))) if argnums is None else argnums

    def loss(*a):
        return jnp.sum(fn(*a).astype(jnp.float32) ** 2)

    return jax.value_and_grad(loss, argnums=argnums)(*args)


class TestMidParity:
    """The satellite matrix: s ∈ {576, 640, 1024, 2048} × causality ×
    feature, value + all grads vs flash AND XLA.  The 576/640 rows are
    the fast tier; 1024/2048 ride the slow tier (interpret-mode block
    loops grow with s²)."""

    @pytest.mark.parametrize("s", [576, 640])
    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_parity_ragged_band(self, s, causal):
        q, k, v = _qkv(jax.random.PRNGKey(s), (1, 2, s, 64))
        got = fmha_mid(q, k, v, causal=causal, implementation="pallas")
        flash = flash_attention(q, k, v, causal=causal,
                                implementation="pallas")
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(got, want, atol=2e-5)
        np.testing.assert_allclose(got, flash, atol=2e-5)

    @pytest.mark.parametrize("feature", ["plain", "bias", "segments",
                                         "dropout"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_vs_flash_and_xla_s576(self, feature, causal):
        s = 576
        q, k, v = _qkv(jax.random.PRNGKey(60 + causal), (1, 2, s, 64))
        kw = dict(causal=causal)
        args = (q, k, v)
        if feature == "bias":
            bias = 0.1 * jax.random.normal(jax.random.PRNGKey(61),
                                           (1, 2, s, s))
            args = (q, k, v, bias)

            def wrap(impl):
                return lambda q, k, v, bias: _impl_call(
                    impl, q, k, v, bias=bias, **kw)
        else:
            if feature == "segments":
                seg = (jnp.arange(s) // 200).astype(jnp.int32)[None]
                kw.update(q_segment_ids=seg, kv_segment_ids=seg)
            elif feature == "dropout":
                kw.update(dropout_rate=0.2, dropout_seed=7)

            def wrap(impl):
                return lambda q, k, v: _impl_call(impl, q, k, v, **kw)

        vals, grads = {}, {}
        for impl in ("mid", "flash", "xla"):
            vals[impl], grads[impl] = _grads(wrap(impl), *args)
        for other in ("flash", "xla"):
            np.testing.assert_allclose(vals["mid"], vals[other], rtol=1e-4)
            for a, b in zip(grads["mid"], grads[other]):
                assert a.shape == b.shape
                np.testing.assert_allclose(a, b, atol=5e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_s1024_fwd_and_grads(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(1024), (1, 1, 1024, 64))
        v_m, g_m = _grads(lambda q, k, v: _impl_call(
            "mid", q, k, v, causal=causal), q, k, v)
        v_x, g_x = _grads(lambda q, k, v: _impl_call(
            "xla", q, k, v, causal=causal), q, k, v)
        np.testing.assert_allclose(v_m, v_x, rtol=1e-5)
        for a, b in zip(g_m, g_x):
            np.testing.assert_allclose(a, b, atol=1e-3)

    @pytest.mark.slow
    @pytest.mark.parametrize("s", [1024, 2048])
    @pytest.mark.parametrize("causal", [False, True])
    def test_everything_composes_big(self, s, causal):
        # bias + segments + dropout + causality at the band's top —
        # value and all FOUR grads vs flash and XLA
        q, k, v = _qkv(jax.random.PRNGKey(s + causal), (1, 1, s, 64))
        bias = 0.1 * jax.random.normal(jax.random.PRNGKey(3), (1, 1, s, s))
        seg = (jnp.arange(s) // (s // 3)).astype(jnp.int32)[None]
        kw = dict(causal=causal, q_segment_ids=seg, kv_segment_ids=seg,
                  dropout_rate=0.1, dropout_seed=42)
        vals, grads = {}, {}
        for impl in ("mid", "flash", "xla"):
            vals[impl], grads[impl] = _grads(
                lambda q, k, v, bias, impl=impl: _impl_call(
                    impl, q, k, v, bias=bias, **kw),
                q, k, v, bias)
        for other in ("flash", "xla"):
            np.testing.assert_allclose(vals["mid"], vals[other], rtol=1e-4)
            for a, b in zip(grads["mid"], grads[other]):
                np.testing.assert_allclose(a, b, atol=5e-3)

    def test_dropout_bit_identical_mask_across_impls(self):
        # same hash, same seed → identical masks on mid / flash / XLA;
        # and the mask must not depend on block configuration
        q, k, v = _qkv(jax.random.PRNGKey(31), (2, 2, 576, 64))
        kw = dict(dropout_rate=0.3, dropout_seed=1234, causal=True)
        m = fmha_mid(q, k, v, implementation="pallas", **kw)
        m2 = fmha_mid(q, k, v, implementation="pallas", block_q=128,
                      block_k=256, block_bh=1, **kw)
        f = flash_attention(q, k, v, implementation="pallas", block_q=256,
                            block_k=256, **kw)
        x = mha_reference(q, k, v, **kw)
        np.testing.assert_allclose(m, m2, atol=1e-5)
        np.testing.assert_allclose(m, f, atol=1e-5)
        np.testing.assert_allclose(m, x, atol=1e-5)
        other = fmha_mid(q, k, v, implementation="pallas", causal=True,
                         dropout_rate=0.3, dropout_seed=99)
        assert float(jnp.max(jnp.abs(m - other))) > 1e-3

    @pytest.mark.parametrize(
        "bias_shape", [(1, 1), (2, 1), (2, 3)]
    )
    def test_bias_broadcast_batchings_and_dbias(self, bias_shape):
        # all three flattened-bias batchings incl. the per-batch mode's
        # block_bh-divides-heads clamp (h=3)
        s = 192
        q, k, v = _qkv(jax.random.PRNGKey(70), (2, 3, s, 32))
        bias = jax.random.normal(jax.random.PRNGKey(71),
                                 bias_shape + (s, s))
        g1 = _grads(lambda q, k, v, bias: fmha_mid(
            q, k, v, bias=bias, causal=True, implementation="pallas",
            block_q=128, block_k=128, block_bh=3), q, k, v, bias)[1]
        g2 = _grads(lambda q, k, v, bias: mha_reference(
            q, k, v, bias=bias, causal=True), q, k, v, bias)[1]
        for a, b in zip(g1, g2):
            assert a.shape == b.shape
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_constant_mask_bias_skips_dbias(self):
        q, k, v = _qkv(jax.random.PRNGKey(29), (1, 2, 160, 64))
        keep = jnp.logical_or(
            jax.random.bernoulli(jax.random.PRNGKey(30), 0.8,
                                 (1, 1, 160, 160)),
            jnp.eye(160, dtype=bool),
        )
        bias = jnp.where(keep, 0.0, -1e30)
        _, g = _grads(lambda q, k, v, bias: fmha_mid(
            q, k, v, bias=bias, bias_requires_grad=False, causal=True,
            implementation="pallas", block_q=128, block_k=128),
            q, k, v, bias)
        _, gr = _grads(lambda q, k, v: mha_reference(
            q, k, v, bias=bias, causal=True), q, k, v)
        for a, b in zip(g[:3], gr):
            np.testing.assert_allclose(a, b, atol=1e-4)
        np.testing.assert_allclose(g[3], 0.0, atol=0)

    def test_cross_attention_sq_ne_sk(self):
        q, _, _ = _qkv(jax.random.PRNGKey(23), (1, 2, 200, 40))
        _, k, v = _qkv(jax.random.PRNGKey(24), (1, 2, 600, 40))
        got = fmha_mid(q, k, v, implementation="pallas")
        np.testing.assert_allclose(got, mha_reference(q, k, v), atol=2e-5)

    def test_return_lse_value_and_cotangent(self):
        q, k, v = _qkv(jax.random.PRNGKey(40), (1, 2, 320, 64))
        out_p, lse_p = fmha_mid(q, k, v, causal=True, return_lse=True,
                                implementation="pallas")
        out_x, lse_x = fmha_mid(q, k, v, causal=True, return_lse=True,
                                implementation="xla")
        np.testing.assert_allclose(out_p, out_x, atol=2e-5)
        np.testing.assert_allclose(lse_p, lse_x, atol=2e-5)

        def loss(impl):
            def f(q, k, v):
                o, l = fmha_mid(q, k, v, causal=True, return_lse=True,
                                implementation=impl)
                return jnp.sum(o ** 2) + jnp.sum(jnp.sin(l))
            return f

        gp = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_packed_vs_unpacked_bit_identical(self):
        q, k, v = _qkv(jax.random.PRNGKey(25), (2, 3, 160, 64))
        packed = fmha_mid(q, k, v, causal=True, implementation="pallas",
                          block_bh=3, block_q=128, block_k=128)
        single = fmha_mid(q, k, v, causal=True, implementation="pallas",
                          block_bh=1, block_q=128, block_k=128)
        np.testing.assert_allclose(packed, single, atol=0)

    def test_bf16(self):
        q, k, v = (x.astype(jnp.bfloat16)
                   for x in _qkv(jax.random.PRNGKey(5), (1, 2, 640, 128)))
        got = fmha_mid(q, k, v, causal=True, implementation="pallas")
        want = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            got.astype(jnp.float32), want.astype(jnp.float32), atol=3e-2)

    def test_explicit_pallas_raises_without_pallas(self, monkeypatch):
        from apex_tpu.ops import attention_mid as mod
        from apex_tpu.ops.common import KernelLoweringError

        q = jnp.ones((1, 1, 8, 8))
        monkeypatch.setattr(mod, "pl", None)
        with pytest.raises(KernelLoweringError):
            mod.fmha_mid(q, q, q, implementation="pallas")
        out = mod.fmha_mid(q, q, q)  # auto degrades gracefully
        assert out.shape == (1, 1, 8, 8)

    def test_unknown_implementation_rejected(self):
        q = jnp.ones((1, 1, 8, 8))
        with pytest.raises(ValueError, match="unknown implementation"):
            fmha_mid(q, q, q, implementation="short")


def _impl_call(impl, q, k, v, **kw):
    if impl == "mid":
        return fmha_mid(q, k, v, implementation="pallas", **kw)
    if impl == "flash":
        return flash_attention(q, k, v, implementation="pallas",
                               block_q=256, block_k=256, **kw)
    return mha_reference(q, k, v, **kw)


class TestBlockSizing:
    def test_default_blocks_prefer_256_else_128(self):
        assert default_mid_blocks(1024, 1024) == (256, 256)
        assert default_mid_blocks(2048, 2048) == (256, 256)
        assert default_mid_blocks(640, 640) == (128, 128)
        assert default_mid_blocks(640, 1024) == (128, 256)
        # never exceeds the (padded) extent
        assert default_mid_blocks(128, 128) == (128, 128)

    def test_block_bh_budgeted_by_score_area(self):
        assert default_mid_block_bh(256, 256, 64) == 8
        assert default_mid_block_bh(128, 128, 64) == 16   # unroll cap
        assert default_mid_block_bh(512, 512, 64) == 2
        assert default_mid_block_bh(256, 256, 3) == 3     # bh bound

    def test_bwd_block_bh_divides_and_fits(self):
        # dq scratch budget: bb * sq_p * d_p <= 512K elements
        assert _bwd_block_bh(8, 1024, 128) == 4
        assert _bwd_block_bh(8, 2048, 128) == 2
        assert _bwd_block_bh(3, 640, 128) == 3
        assert _bwd_block_bh(8, 8192, 128) == 1
        for bb in (1, 2, 3, 4, 6, 8, 16):
            assert bb % _bwd_block_bh(bb, 2048, 128) == 0


class TestLadderDispatch:
    """Auto mode walks short → mid → flash by the measured crossovers;
    each window is env-movable and env-disableable."""

    def _spy(self, monkeypatch):
        from apex_tpu.ops import attention as attn_mod
        from apex_tpu.ops import attention_mid as mid_mod
        from apex_tpu.ops import attention_short as short_mod
        from apex_tpu.utils import platform as plat

        calls = []

        def fake(tag):
            def f(q, *a, **kw):
                calls.append(tag)
                return jnp.zeros(q.shape, q.dtype)
            return f

        monkeypatch.setattr(attn_mod, "_flash_attention_pallas",
                            fake("flash"))
        monkeypatch.setattr(short_mod, "_fmha_short_pallas", fake("short"))
        monkeypatch.setattr(mid_mod, "_fmha_mid_pallas", fake("mid"))
        monkeypatch.setattr(plat, "_current_platform", lambda: "tpu")
        for var in ("APEX_TPU_DISABLE_PALLAS", "APEX_TPU_STRICT_KERNELS",
                    "APEX_TPU_FMHA_SHORT_MAX_SEQ",
                    "APEX_TPU_FMHA_MID_MAX_SEQ"):
            monkeypatch.delenv(var, raising=False)
        return calls

    def test_short_window_unchanged(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 2, FMHA_SHORT_MAX_SEQ, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        assert calls == ["short"]

    def test_mid_window_above_short(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 2, FMHA_SHORT_MAX_SEQ + 64, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        assert calls == ["mid"]

    def test_mid_boundary_inclusive(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, FMHA_MID_MAX_SEQ, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        assert calls == ["mid"]

    def test_above_mid_picks_flash(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, FMHA_MID_MAX_SEQ + 128, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        assert calls == ["flash"]

    def test_cross_attention_keys_on_max_extent(self, monkeypatch):
        # short q + mid-band kv: short disqualified (whole-kv premise),
        # mid takes it (its window keys on max(sq, sk))
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, 256, 64), jnp.bfloat16)
        kv = jnp.ones((1, 1, 1024, 64), jnp.bfloat16)
        flash_attention(q, kv, kv)
        assert calls == ["mid"]

    def test_env_override_moves_mid_crossover(self, monkeypatch):
        calls = self._spy(monkeypatch)
        monkeypatch.setenv("APEX_TPU_FMHA_MID_MAX_SEQ", "1024")
        assert mid_seq_threshold() == 1024
        q = jnp.ones((1, 1, 1536, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        assert calls == ["flash"]

    def test_env_zero_pins_ladder_to_flash(self, monkeypatch):
        # the acceptance contract's default-off safety: with the mid
        # window disabled, auto mode runs the EXACT flash path HEAD ran
        calls = self._spy(monkeypatch)
        monkeypatch.setenv("APEX_TPU_FMHA_MID_MAX_SEQ", "0")
        q = jnp.ones((1, 1, 1024, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        assert calls == ["flash"]

    def test_fp32_keeps_xla_window_then_mid(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, 1024, 64), jnp.float32)
        flash_attention(q, q, q)
        assert calls == []  # measured fp32 window still routes to XLA
        q = jnp.ones((1, 1, 1536, 64), jnp.float32)
        flash_attention(q, q, q)
        assert calls == ["mid"]

    def test_explicit_mid_honored_any_shape(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, 256, 64), jnp.float32)
        flash_attention(q, q, q, implementation="mid")
        assert calls == ["mid"]

    def test_explicit_pallas_still_means_flash(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, 1024, 64), jnp.bfloat16)
        flash_attention(q, q, q, implementation="pallas")
        assert calls == ["flash"]

    def test_pinned_flash_numerics_identical(self, monkeypatch):
        # numeric half of the default-off safety: on this (CPU) host
        # the pinned ladder and HEAD both resolve to the same XLA
        # reference path — assert bit-identity end to end
        monkeypatch.setenv("APEX_TPU_FMHA_MID_MAX_SEQ", "0")
        q, k, v = _qkv(jax.random.PRNGKey(90), (1, 2, 1024, 64))
        pinned = flash_attention(q, k, v, causal=True)
        head = mha_reference(q, k, v, causal=True)
        np.testing.assert_array_equal(np.asarray(pinned), np.asarray(head))


class TestRingInnerImpl:
    """ring_attention(attention_impl=...): the per-shard inner
    attention through the kernel family via the lse merge, with
    fully-masked source shards skipped under causal."""

    @pytest.fixture
    def mesh(self):
        from apex_tpu.transformer import parallel_state

        m = parallel_state.initialize_model_parallel(
            context_parallel_size_=4)
        yield m
        parallel_state.destroy_model_parallel()

    def _run(self, mesh, fn, *args):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        spec = P(None, None, "cp")
        return jax.jit(shard_map(
            fn, mesh=mesh, in_specs=(spec,) * len(args),
            out_specs=spec, check_rep=False,
        ))(*args)

    @pytest.mark.parametrize("impl", ["mid", "xla"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mesh, impl, causal):
        from apex_tpu.ops.ring_attention import ring_attention

        q, k, v = _qkv(jax.random.PRNGKey(0), (2, 2, 64, 16))
        ref = mha_reference(q, k, v, causal=causal)
        out = self._run(mesh, lambda q, k, v: ring_attention(
            q, k, v, causal=causal, attention_impl=impl), q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("remat", [False, True])
    def test_grads_match_dense(self, mesh, remat):
        from apex_tpu.ops.ring_attention import ring_attention

        q, k, v = _qkv(jax.random.PRNGKey(1), (2, 2, 64, 16))

        def ring_loss(q, k, v):
            return jnp.sum(ring_attention(
                q, k, v, causal=True, attention_impl="mid",
                remat=remat) ** 2)

        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        spec = P(None, None, "cp")
        rg = jax.jit(shard_map(
            jax.grad(ring_loss, argnums=(0, 1, 2)), mesh=mesh,
            in_specs=(spec,) * 3, out_specs=(spec,) * 3,
            check_rep=False))(q, k, v)
        dg = jax.grad(
            lambda q, k, v: jnp.sum(
                mha_reference(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(rg, dg):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

    def test_default_path_untouched(self, mesh):
        # attention_impl=None must keep the legacy inline walk
        from apex_tpu.ops.ring_attention import ring_attention

        q, k, v = _qkv(jax.random.PRNGKey(2), (2, 2, 64, 16))
        legacy = self._run(mesh, lambda q, k, v: ring_attention(
            q, k, v, causal=True), q, k, v)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(legacy), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)

    def test_bad_impl_rejected(self, mesh):
        from apex_tpu.ops.ring_attention import ring_attention

        q, k, v = _qkv(jax.random.PRNGKey(3), (2, 2, 64, 16))
        with pytest.raises(ValueError, match="attention_impl"):
            self._run(mesh, lambda q, k, v: ring_attention(
                q, k, v, causal=True, attention_impl="nope"), q, k, v)


class TestContribWiring:
    """The mid kernel is reachable through the reference-parity
    wrappers, same as PR 1 proved for the short kernel."""

    def test_fmha_varlen_mid_kernel(self):
        from apex_tpu.contrib.fmha import fmha

        key = jax.random.PRNGKey(60)
        lens = [300, 420]
        total, heads, d = sum(lens), 2, 64
        qkv = jax.random.normal(key, (total, 3, heads, d))
        cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
        got = fmha(qkv, cu, max_seq_len=576, causal=True,
                   implementation="mid")
        want = fmha(qkv, cu, max_seq_len=576, causal=True,
                    implementation="xla")
        np.testing.assert_allclose(got, want, atol=2e-5)

    def test_self_mha_attention_impl_mid(self):
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        x = jax.random.normal(jax.random.PRNGKey(61), (576, 1, 64))
        mha_m = SelfMultiheadAttn(64, 4, impl="fast",
                                  attention_impl="mid")
        mha_d = SelfMultiheadAttn(64, 4, impl="default")
        params = mha_m.init(jax.random.PRNGKey(62))
        got = mha_m.apply(params, x, causal=True)
        want = mha_d.apply(params, x, causal=True)
        np.testing.assert_allclose(got, want, atol=2e-5)

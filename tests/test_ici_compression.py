"""int8 ICI gather-leg compression parity suite (EQuARX's ICI half).

Covers the new ``CompressionConfig(ici_legs=True)`` surface end to
end on the 8-device virtual (dcn=2 x ici=4) mesh: row-wise quantize
numerics, the chunk-preserving quantized reduce-scatter / all-gather
legs, the hierarchical reduce with both ICI legs compressed (stateless
and with error feedback), the DEFAULT-PATH pin (``ici_legs=False``
stays bit-identical to an inlined copy of the dcn-only int8 reduce),
bucketed/Reducer threading, ZeRO's compressed RS leg, and the residual
state's checkpoint round trip.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.quantization import (
    CompressionConfig,
    dequantize_rows,
    hierarchical_residual_sizes,
    quantize_blockwise,
    quantize_rows,
    quantized_all_gather,
    quantized_psum,
    quantized_reduce_scatter,
)
from apex_tpu.parallel import (
    all_reduce_gradients,
    hierarchical_data_parallel_mesh,
)
from apex_tpu.parallel.distributed import (
    Reducer,
    comm_state_specs,
    init_comm_state,
)

try:  # jax >= 0.6 spelling
    _shard_map = jax.shard_map
    _SM_KW = {"check_vma": False}
except AttributeError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _SM_KW = {"check_rep": False}


def smap(f, mesh, in_specs, out_specs):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **_SM_KW)


DCN, ICI = 2, 4
AXES = ("dcn", "ici")


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests require 8 virtual devices"
    return hierarchical_data_parallel_mesh(ici_size=ICI)


def _grads():
    return {
        "w": jax.random.normal(jax.random.PRNGKey(0), (8, 41, 3)),
        "b": jax.random.normal(jax.random.PRNGKey(1), (8, 17)),
    }


def _mean_ref(g):
    return np.broadcast_to(
        np.mean(np.asarray(g), axis=0, keepdims=True), g.shape)


# ---------------------------------------------------------------- numerics


class TestQuantizeRows:
    def test_single_row_matches_blockwise(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 100))
        q1, s1 = quantize_rows(x, 32)
        q2, s2 = quantize_blockwise(x[0], 32)
        np.testing.assert_array_equal(np.asarray(q1[0]), np.asarray(q2))
        np.testing.assert_array_equal(np.asarray(s1[0]), np.asarray(s2))

    def test_blocks_never_straddle_rows(self):
        # rows quantized together vs separately must agree exactly —
        # the chunk-preservation property the RS/AG legs rely on
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 37))
        q, s = quantize_rows(x, 16)
        assert q.shape == (4, 37) and s.shape == (4, 3)
        for r in range(4):
            qr, sr = quantize_rows(x[r:r + 1], 16)
            np.testing.assert_array_equal(np.asarray(q[r]),
                                          np.asarray(qr[0]))
            np.testing.assert_array_equal(np.asarray(s[r]),
                                          np.asarray(sr[0]))

    def test_roundtrip_error_bounded(self):
        x = jax.random.normal(jax.random.PRNGKey(4), (3, 128)) * 5.0
        q, s = quantize_rows(x, 64)
        back = dequantize_rows(q, s, 64)
        err = np.abs(np.asarray(x - back))
        bound = np.repeat(np.asarray(s), 64, axis=1) / 2 + 1e-7
        assert np.all(err <= bound)

    def test_stochastic_needs_key(self):
        x = jnp.ones((2, 8))
        with pytest.raises(ValueError, match="key"):
            quantize_rows(x, 4, rounding="stochastic")


class TestResidualSizes:
    def test_dcn_only_sizes_unchanged(self):
        # ici_legs=False must size exactly like the PR 3 layout
        sizes = hierarchical_residual_sizes(100, DCN, ICI, 16)
        chunk = (100 + 3) // 4  # ici-padded chunk
        padded = chunk + (-chunk) % (DCN * 16)
        assert sizes == {"push": padded, "pull": padded // DCN}

    def test_ici_legs_adds_leg_buffers(self):
        sizes = hierarchical_residual_sizes(100, DCN, ICI, 16,
                                            ici_legs=True)
        chunk = (100 + 3) // 4
        assert sizes["ici_push"] == ICI * chunk
        assert sizes["ici_pull"] == chunk

    def test_init_comm_state_sizes_from_config(self, mesh):
        local = {"w": jnp.zeros((1, 41, 3)), "b": jnp.zeros((1, 17))}
        cfg = CompressionConfig(block_size=64, ici_legs=True)
        state = init_comm_state(local, AXES, cfg, mesh=mesh)
        for k, leaf in local.items():
            sizes = hierarchical_residual_sizes(
                int(jnp.size(leaf)), DCN, ICI, 64, True)
            res = state["residuals"][k]
            assert set(res) == set(sizes)
            for name, n in sizes.items():
                assert res[name].size == 8 * n, (k, name)


# ------------------------------------------------------------- collectives


class TestLegCollectives:
    def test_quantized_rs_preserves_chunks(self, mesh):
        g = jax.random.normal(jax.random.PRNGKey(5), (8, 120))
        cfg = CompressionConfig(block_size=16, error_feedback=False)

        def rs(x):
            c, _ = quantized_reduce_scatter(x.reshape(-1), "ici", cfg)
            return c

        def rs_ref(x):
            return jax.lax.psum_scatter(x.reshape(-1), "ici",
                                        tiled=True)

        out = jax.jit(smap(rs, mesh, (P(AXES),), P(AXES)))(g)
        ref = jax.jit(smap(rs_ref, mesh, (P(AXES),), P(AXES)))(g)
        amax = np.max(np.abs(np.asarray(ref)))
        assert np.max(np.abs(np.asarray(out) - np.asarray(ref))) \
            < 0.05 * amax

    def test_quantized_rs_rejects_undivisible(self, mesh):
        cfg = CompressionConfig(error_feedback=False)

        def bad(x):
            # local (1, 7) -> 7 elements, not divisible by ici=4
            c, _ = quantized_reduce_scatter(x.reshape(-1), "ici", cfg)
            return c

        with pytest.raises(ValueError, match="size % world"):
            jax.jit(smap(bad, mesh, (P(AXES),), P(AXES))
                    )(jnp.ones((8, 7)))

    def test_quantized_ag_matches_gather(self, mesh):
        x = jax.random.normal(jax.random.PRNGKey(6), (8, 25))
        cfg = CompressionConfig(block_size=8, error_feedback=False)

        def ag(c):
            out, _ = quantized_all_gather(c.reshape(-1), "ici", cfg)
            return out

        out = jax.jit(smap(
            lambda c: ag(c),
            mesh, (P((*AXES,)),), P(("dcn",)),
        ))(x.reshape(8, 25))
        # each dcn group gathers its own 4 ici chunks: compare against
        # the exact concatenation
        got = np.asarray(out).reshape(DCN, ICI * 25)
        ref = np.asarray(x).reshape(DCN, ICI * 25)
        amax = np.max(np.abs(ref))
        assert np.max(np.abs(got - ref)) < 0.02 * amax


class TestHierarchicalICILegs:
    def test_default_path_bit_identical_to_inlined_seed(self, mesh):
        """ici_legs=False must not move a bit of the dcn-only int8
        reduce: pinned against an inlined copy of its seed semantics."""
        from apex_tpu.transformer.tensor_parallel.mappings import (
            all_gather_invariant,
        )

        grads = _grads()
        spec = jax.tree.map(lambda _: P(AXES), grads)
        cfg = CompressionConfig(block_size=64, error_feedback=False)

        def seed(g):
            def one(x):
                n = x.size
                flat = x.reshape(-1)
                pad = (-n) % ICI
                if pad:
                    flat = jnp.concatenate(
                        [flat, jnp.zeros((pad,), flat.dtype)])
                chunk = jax.lax.psum_scatter(flat, "ici", tiled=True)
                chunk, _ = quantized_psum(chunk, "dcn", cfg)
                out = all_gather_invariant(chunk, "ici", axis=0,
                                           tiled=True)
                if pad:
                    out = out[:n]
                return out.reshape(x.shape) / 8.0
            return jax.tree.map(one, g)

        ours = jax.jit(smap(
            lambda g: all_reduce_gradients(g, AXES, compression=cfg),
            mesh, (spec,), spec))(grads)
        ref = jax.jit(smap(seed, mesh, (spec,), spec))(grads)
        for k in grads:
            np.testing.assert_array_equal(
                np.asarray(ours[k]), np.asarray(ref[k]))

    def test_ici_legs_stateless_tracks_mean(self, mesh):
        grads = _grads()
        spec = jax.tree.map(lambda _: P(AXES), grads)
        cfg = CompressionConfig(block_size=64, error_feedback=False,
                                ici_legs=True)
        out = jax.jit(smap(
            lambda g: all_reduce_gradients(g, AXES, compression=cfg),
            mesh, (spec,), spec))(grads)
        for k in grads:
            ref = _mean_ref(grads[k])
            amax = np.max(np.abs(ref))
            # three quantization events instead of two: a wider but
            # still small band
            assert np.max(np.abs(np.asarray(out[k]) - ref)) \
                < 0.15 * amax

    def test_error_feedback_improves_time_average(self, mesh):
        grads = _grads()
        local = jax.tree.map(
            lambda g: jnp.zeros((1,) + g.shape[1:]), grads)
        spec = jax.tree.map(lambda _: P(AXES), grads)
        cfg = CompressionConfig(block_size=64, ici_legs=True)
        state = init_comm_state(local, AXES, cfg, mesh=mesh)
        cspecs = comm_state_specs(state, AXES)
        step = jax.jit(smap(
            lambda g, st: all_reduce_gradients(
                g, AXES, compression=cfg, comm_state=st),
            mesh, (spec, cspecs), (spec, cspecs)))
        outs = []
        for _ in range(20):
            out, state = step(grads, state)
            outs.append(np.asarray(out["w"]))
        assert int(state["step"]) == 20
        ref = _mean_ref(grads["w"])
        single = np.max(np.abs(outs[0] - ref))
        averaged = np.max(np.abs(np.mean(outs, axis=0) - ref))
        assert averaged < single / 3

    def test_stale_comm_state_rejected(self, mesh):
        # a comm state built WITHOUT ici_legs cannot silently feed the
        # ici-compressed reduce
        grads = _grads()
        local = jax.tree.map(
            lambda g: jnp.zeros((1,) + g.shape[1:]), grads)
        spec = jax.tree.map(lambda _: P(AXES), grads)
        old = init_comm_state(local, AXES,
                              CompressionConfig(block_size=64),
                              mesh=mesh)
        cfg = CompressionConfig(block_size=64, ici_legs=True)
        cspecs = comm_state_specs(old, AXES)
        with pytest.raises(ValueError, match="ici_push"):
            jax.jit(smap(
                lambda g, st: all_reduce_gradients(
                    g, AXES, compression=cfg, comm_state=st),
                mesh, (spec, cspecs), (spec, cspecs)))(grads, old)
        # ...and the opposite direction: an ici-sized state with
        # ici_legs=False would silently drop the leg residuals from
        # the returned state — refused, not mis-shaped
        new = init_comm_state(local, AXES, cfg, mesh=mesh)
        nspecs = comm_state_specs(new, AXES)
        off = CompressionConfig(block_size=64)
        with pytest.raises(ValueError, match="ici_legs"):
            jax.jit(smap(
                lambda g, st: all_reduce_gradients(
                    g, AXES, compression=off, comm_state=st),
                mesh, (spec, nspecs), (spec, nspecs)))(grads, new)

    def test_bucketed_reduce_with_ici_state(self, mesh):
        grads = _grads()
        local = jax.tree.map(
            lambda g: jnp.zeros((1,) + g.shape[1:]), grads)
        spec = jax.tree.map(lambda _: P(AXES), grads)
        cfg = CompressionConfig(block_size=64, ici_legs=True)
        state = init_comm_state(local, AXES, cfg, mesh=mesh,
                                bucket_bytes=256)
        for res in state["residuals"].values():
            assert {"push", "pull", "ici_push", "ici_pull"} == set(res)
        cspecs = comm_state_specs(state, AXES)
        step = jax.jit(smap(
            lambda g, st: all_reduce_gradients(
                g, AXES, compression=cfg, comm_state=st,
                overlap_grad_sync=True, bucket_bytes=256),
            mesh, (spec, cspecs), (spec, cspecs)))
        out, state = step(grads, state)
        for k in grads:
            ref = _mean_ref(grads[k])
            assert np.max(np.abs(np.asarray(out[k]) - ref)) \
                < 0.15 * np.max(np.abs(ref))

    def test_reducer_pipelined_with_ici_compression(self, mesh):
        x = jax.random.normal(jax.random.PRNGKey(7), (8, 120))

        def run_loop(red):
            def stp(xs):
                acc = red.init(xs)
                for k in range(3):
                    acc = red.accumulate(acc, (1.0 + 0.5 * k) * xs)
                g, _ = red.reduce(acc)
                return g
            return jax.jit(smap(stp, mesh, (P(AXES),), P(AXES)))(x)

        deferred = run_loop(Reducer(axis_name=AXES))
        pip = run_loop(Reducer(
            axis_name=AXES, overlap_grad_sync=True, bucket_bytes=256,
            compression=CompressionConfig(block_size=64,
                                          ici_legs=True)))
        amax = np.max(np.abs(np.asarray(deferred)))
        assert np.max(np.abs(np.asarray(pip) - np.asarray(deferred))) \
            < 0.1 * amax

    def test_residual_checkpoint_roundtrip_bit_identical(
            self, mesh, tmp_path):
        from apex_tpu import checkpoint

        grads = _grads()
        local = jax.tree.map(
            lambda g: jnp.zeros((1,) + g.shape[1:]), grads)
        spec = jax.tree.map(lambda _: P(AXES), grads)
        cfg = CompressionConfig(block_size=64, ici_legs=True)
        cstate = init_comm_state(local, AXES, cfg, mesh=mesh)
        cspecs = comm_state_specs(cstate, AXES)
        step = jax.jit(smap(
            lambda g, st: all_reduce_gradients(
                g, AXES, compression=cfg, comm_state=st),
            mesh, (spec, cspecs), (spec, cspecs)))

        def run(resume_at=None):
            state = jax.tree.map(jnp.array, cstate)
            outs = []
            for i in range(6):
                out, state = step(grads, state)
                outs.append(np.asarray(out["w"]))
                if resume_at is not None and i == resume_at:
                    path = str(tmp_path / f"ck{i}")
                    saved = {"comm": jax.device_get(state)}
                    checkpoint.save(path, saved)
                    state = checkpoint.restore(
                        path, target=saved,
                        verify_integrity=True)["comm"]
            return outs

        a = run()
        b = run(resume_at=2)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


class TestZeroICILegs:
    @pytest.fixture()
    def zmesh(self):
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            data_parallel_ici_size_=ICI)
        yield mesh
        parallel_state.destroy_model_parallel()

    def test_adam_ici_tracks_uncompressed(self, zmesh):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        params = {"w": jax.random.normal(jax.random.PRNGKey(8),
                                         (37, 5)),
                  "b": jnp.zeros((11,))}
        pspec = jax.tree.map(lambda _: P(), params)
        g = jax.tree.map(
            lambda p: 0.1 * jax.random.normal(
                jax.random.PRNGKey(9), jnp.shape(p)), params)

        def run(comp):
            opt = DistributedFusedAdam(lr=1e-3, axis_name=AXES,
                                       compression=comp)
            sspecs = opt.state_specs()
            if comp is not None and comp.ici_legs:
                assert "ici_push" in sspecs["comm"]
            st = jax.jit(smap(opt.init, zmesh, (pspec,), sspecs)
                         )(params)
            newp, st = jax.jit(smap(
                lambda s, gg, p: opt.step(s, gg, p),
                zmesh, (sspecs, pspec, pspec), (pspec, sspecs)))(
                    st, g, params)
            return newp, st

        base, _ = run(None)
        comp, st = run(CompressionConfig(block_size=32, ici_legs=True))
        assert st["comm"]["ici_push"].size > 0
        for k in params:
            # Adam's sign-normalized update can flip where a gradient
            # sits at quantization-noise scale: bound by the 2*lr that
            # one flipped element can move
            np.testing.assert_allclose(
                np.asarray(comp[k]), np.asarray(base[k]), atol=2.5e-3)

    def test_lamb_ici_runs(self, zmesh):
        from apex_tpu.contrib.optimizers import DistributedFusedLAMB

        params = {"w": jax.random.normal(jax.random.PRNGKey(10),
                                         (24, 6))}
        pspec = jax.tree.map(lambda _: P(), params)
        g = jax.tree.map(
            lambda p: 0.1 * jax.random.normal(
                jax.random.PRNGKey(11), jnp.shape(p)), params)
        opt = DistributedFusedLAMB(
            lr=1e-3, axis_name=AXES,
            compression=CompressionConfig(block_size=32,
                                          ici_legs=True))
        sspecs = opt.state_specs()
        st = jax.jit(smap(opt.init, zmesh, (pspec,), sspecs))(params)
        newp, st = jax.jit(smap(
            lambda s, gg, p: opt.step(s, gg, p),
            zmesh, (sspecs, pspec, pspec), (pspec, sspecs)))(
                st, g, params)
        assert np.all(np.isfinite(np.asarray(newp["w"])))


class TestCommEvents:
    def test_bucket_events_report_compressed_ici_estimates(self, mesh):
        from apex_tpu.telemetry import events as tlm_events

        captured = []

        class Sink:
            def event(self, kind, **fields):
                if kind == "comm_bucket":
                    captured.append(fields)

        grads = _grads()
        spec = jax.tree.map(lambda _: P(AXES), grads)

        def trace_with(cfg):
            captured.clear()
            sink = Sink()
            tlm_events.add_sink(sink)
            try:
                jax.jit(smap(
                    lambda g: all_reduce_gradients(
                        g, AXES, compression=cfg,
                        overlap_grad_sync=True, bucket_bytes=256),
                    mesh, (spec,), spec)).lower(grads)
            finally:
                tlm_events.remove_sink(sink)
            return list(captured)

        plain = trace_with(CompressionConfig(block_size=64,
                                             error_feedback=False))
        ici = trace_with(CompressionConfig(block_size=64,
                                           error_feedback=False,
                                           ici_legs=True))
        assert plain and ici
        for a, b in zip(plain, ici):
            assert not a["ici_compressed"] and b["ici_compressed"]
            # every bucket shrinks; the ~4x asymptote needs the chunk
            # to amortize the fp32 scale sidecar (tiny buckets pay
            # one scale per block regardless)
            assert b["rs_ici_wire_bytes"] < a["rs_ici_wire_bytes"]
            assert b["ag_ici_wire_bytes"] < a["ag_ici_wire_bytes"]
            assert b["ar_dcn_wire_bytes"] == a["ar_dcn_wire_bytes"]
            if a["elements"] >= 100:
                assert b["rs_ici_wire_bytes"] \
                    < a["rs_ici_wire_bytes"] / 3
                assert b["ag_ici_wire_bytes"] \
                    < a["ag_ici_wire_bytes"] / 3

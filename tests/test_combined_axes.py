"""Combined-axes proof on the 8-device virtual CPU mesh: ONE jitted
train step over dp x pp x cp x tp simultaneously with a Switch-MoE layer
in the stack (ep over "dp"), parity vs a single device — the same case
``dryrun_multichip`` runs (VERDICT r3 item 7)."""

import pytest

pytestmark = pytest.mark.slow


def test_combined_axes_train_step():
    import __graft_entry__ as ge

    ge._dryrun_combined(8)

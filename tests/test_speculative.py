"""Speculative decoding: draft sources, the fused acceptance rule, the
verify step, and the speculative continuous-batching window end-to-end
on the tiny GPT — plus the multi-token failover contract.

The load-bearing claims, each pinned here:

- the n-gram draft source attributes hits to prompt-lookup vs
  self-repetition, prefers the MOST RECENT occurrence, caps at k, and
  never drafts from a context too short to match;
- ``spec_accept`` is greedy-exact (accepted prefix == argmax prefix
  match) and, for sampled rows, COUPLED to the plain sampler: row j's
  target is bitwise the token ``sample`` would draw with row j's key —
  the identity that makes every downstream gate exact, not statistical;
- ``verify_step`` with zero drafts degenerates to ``decode_step``
  (same logits, row 0), so the speculative path is a strict superset
  of the plain one;
- speculative greedy serving is token-identical to the plain decode
  path under 6-requests/2-slots admit/retire churn, including
  mid-verify EOS cuts; seeded SAMPLED serving is token-identical too,
  across admission orders (cross-replica determinism survives
  variable advances);
- rejected drafts roll back by length truncation: the pool pages a
  speculative run leaves at committed positions are bit-identical to
  a never-drafted run's, and the allocator's free count / refcounts
  match throughout;
- acceptance patterns change CONTENTS, never shapes — the verify step
  adds zero jit entries across request waves;
- the request log survives multi-token commits: ``record_progress``
  folds k-token jumps exactly, over-commit fails loudly at the
  recording boundary, and ``resume_request`` budget math is by token
  count; the replica-kill drill completes every request
  token-identical to an unkilled fleet WITH speculation on.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from apex_tpu.fleet import FleetRouter, Replica, RequestLog, \
    resume_request
from apex_tpu.serving.kv_cache import (
    KVCacheConfig,
    PagedKVCache,
    init_pools,
)
from apex_tpu.serving.sampling import greedy, sample, spec_accept
from apex_tpu.serving.serve import ContinuousBatcher, Request
from apex_tpu.serving.speculate import (
    ModelDraftSource,
    NGramDraftSource,
    NullDraftSource,
    chain_tree,
    offramp_tree,
    tree_ancestors,
    tree_chain_rows,
    tree_depths,
    tree_max_depth,
    validate_tree,
)


# ---------------------------------------------------------------------------
# draft sources: pure host, no model
# ---------------------------------------------------------------------------


class TestNGramDraftSource:
    def test_prompt_lookup_attribution(self):
        src = NGramDraftSource(3, max_ngram=3)
        # tail [1,2,3] recurs at the prompt's start: continuation is
        # the tokens that followed it there
        toks, tag = src.draft([1, 2, 3, 4, 5, 1, 2, 3], prompt_len=8)
        assert toks == [4, 5, 1]
        assert tag == "prompt_lookup"

    def test_ngram_attribution_in_generated_region(self):
        src = NGramDraftSource(2, max_ngram=3)
        ctx = [9, 9] + [1, 2, 3, 1, 2, 3, 1, 2]
        toks, tag = src.draft(ctx, prompt_len=2)
        assert toks == [3, 1]
        assert tag == "ngram"          # the match lives in generation

    def test_most_recent_occurrence_wins(self):
        src = NGramDraftSource(1, max_ngram=2)
        # [1,2] occurs twice with different continuations: the drafter
        # must follow the LATEST one (recency tracks the model's loop)
        toks, _ = src.draft([1, 2, 5, 1, 2, 7, 1, 2], prompt_len=8)
        assert toks == [7]

    def test_no_match_and_short_context_draft_nothing(self):
        src = NGramDraftSource(4)
        assert src.draft([1, 2, 3, 4, 5], prompt_len=5) == ([], None)
        assert src.draft([1], prompt_len=1) == ([], None)
        assert src.draft([], prompt_len=0) == ([], None)

    def test_continuation_capped_at_k(self):
        src = NGramDraftSource(2, max_ngram=2)
        toks, _ = src.draft([5, 6, 7, 8, 9, 5, 6], prompt_len=7)
        assert toks == [7, 8]          # not [7, 8, 9]

    def test_validation(self):
        with pytest.raises(ValueError):
            NGramDraftSource(0)
        with pytest.raises(ValueError):
            NGramDraftSource(2, max_ngram=0)

    def test_null_source_never_drafts(self):
        assert NullDraftSource().draft([1, 2, 3], 3) == ([], None)

    def test_model_draft_source_validation(self):
        # validation fires before any model machinery is touched
        with pytest.raises(ValueError, match="k must be"):
            ModelDraftSource(object(), {}, None, None, k=0)
        with pytest.raises(ValueError, match="arbitrary trees"):
            ModelDraftSource(object(), {}, None, None, k=2,
                             tree=(-1, 0, 0, 1))


# ---------------------------------------------------------------------------
# spec_accept: the fused acceptance rule
# ---------------------------------------------------------------------------


def _one_hot_logits(targets, vocab=32):
    rows = np.full((len(targets), vocab), -5.0, np.float32)
    for j, t in enumerate(targets):
        rows[j, t] = 5.0
    return jnp.asarray(rows)


class TestSpecAccept:
    def test_greedy_accepts_exact_prefix_match(self):
        logits = _one_hot_logits([5, 6, 7, 8])
        targets, n_acc = spec_accept(
            logits, jnp.asarray([5, 6, 9]), jnp.int32(3), None)
        assert list(np.asarray(targets)) == [5, 6, 7, 8]
        assert int(n_acc) == 2          # 5, 6 match; 9 != 7 stops it

    def test_greedy_full_and_zero_acceptance(self):
        logits = _one_hot_logits([5, 6, 7, 8])
        _, full = spec_accept(
            logits, jnp.asarray([5, 6, 7]), jnp.int32(3), None)
        assert int(full) == 3
        _, none = spec_accept(
            logits, jnp.asarray([9, 6, 7]), jnp.int32(3), None)
        assert int(none) == 0

    def test_draft_len_masks_padding_rows(self):
        logits = _one_hot_logits([5, 6, 7, 8])
        # rows past draft_len "match" by accident (padding 0 vs row 1
        # target) — they must not count
        targets, n_acc = spec_accept(
            logits, jnp.asarray([5, 6, 7]), jnp.int32(1), None)
        assert int(n_acc) == 1
        assert list(np.asarray(targets)) == [5, 6, 7, 8]

    def test_sampled_rows_are_coupled_to_plain_sample(self):
        """Row j's target must be BITWISE the token ``sample`` draws
        from row j's logits with row j's key — the coupling that turns
        distribution preservation into an exact identity."""
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(4, 32).astype(np.float32))
        keys = jax.random.split(jax.random.PRNGKey(42), 4)
        targets, _ = spec_accept(
            logits, jnp.zeros((3,), jnp.int32), jnp.int32(0), keys,
            temperature=0.7, top_k=8, top_p=0.9)
        want = [int(sample(logits[j][None], keys[j], 0.7, 8, 0.9)[0])
                for j in range(4)]
        assert list(np.asarray(targets)) == want

    def test_greedy_targets_are_argmax_bitwise(self):
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(5, 64).astype(np.float32))
        targets, _ = spec_accept(
            logits, jnp.zeros((4,), jnp.int32), jnp.int32(0), None)
        assert np.array_equal(np.asarray(targets),
                              np.asarray(greedy(logits)))

    def test_validation(self):
        logits = _one_hot_logits([1, 2])
        with pytest.raises(ValueError, match="keys"):
            spec_accept(logits, jnp.asarray([1]), jnp.int32(1), None,
                        temperature=0.5)
        with pytest.raises(ValueError):
            spec_accept(logits[0], jnp.asarray([1]), jnp.int32(1),
                        None)
        with pytest.raises(ValueError):
            spec_accept(logits, jnp.asarray([1, 2]), jnp.int32(1),
                        None)


# ---------------------------------------------------------------------------
# the tiny-GPT serving stack with speculation
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def spec_setup():
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:1])
    model = GPTModel(GPTConfig(
        vocab_size=64, num_layers=2, hidden_size=32,
        num_attention_heads=4, max_position_embeddings=64,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))
    params = model.init(jax.random.PRNGKey(0))
    # repetitive prompts (tiled 4-cycles, ragged lengths) so the
    # n-gram drafter gets real acceptance even on untrained weights —
    # the identity gates below hold for ANY acceptance pattern, but a
    # pattern of all-rejects would test less
    rng = np.random.RandomState(3)
    prompts, plens = [], [12, 11, 9, 12, 10, 8]
    for i in range(6):
        pat = rng.randint(1, 64, (4,))
        prompts.append([int(t) for t in np.tile(pat, 3)[:plens[i]]])
    yield mesh, model, params, prompts, 12
    parallel_state.destroy_model_parallel()


PAGE, NEW, K = 4, 12, 3


def _batcher(setup, *, spec=True, temperature=0.0, draft=None,
             eos_id=None, max_seqs=2, logger=None, tree=None,
             draft_model=None):
    mesh, model, params, prompts, maxp = setup
    pps = -(-(maxp + NEW) // PAGE)
    ccfg = KVCacheConfig(
        num_layers=2, num_heads=4, head_dim=8,
        num_pages=1 + max_seqs * pps, page_size=PAGE,
        max_seqs=max_seqs, pages_per_seq=pps, dtype=jnp.float32)
    fns = model.decode_fns(
        params, mesh, ccfg, max_prompt_len=maxp,
        temperature=temperature, eos_id=eos_id,
        speculate_k=K if spec else None,
        spec_tree=tree, draft_model=draft_model)
    kw = {}
    if spec:
        # a bound draft_model rides in on fns.spec; otherwise the
        # explicit source (or the n-gram default) drafts
        src = (None if draft_model is not None
               else draft or NGramDraftSource(K))
        kw = dict(spec_fn=fns.spec, speculate_k=K, draft_source=src)
    return ContinuousBatcher(
        fns.prefill, fns.decode, PagedKVCache(ccfg), init_pools(ccfg),
        max_prompt_len=maxp, harvest_every=3, eos_id=eos_id,
        logger=logger, **kw), fns


def _reqs(prompts, *, new=NEW, seed=None, tag=""):
    return [Request(uid=f"{tag}{i}", prompt=list(p),
                    max_new_tokens=new,
                    seed=None if seed is None else seed + i)
            for i, p in enumerate(prompts)]


class TestSpeculativeServing:
    def test_greedy_identity_under_churn(self, spec_setup):
        """6 requests through 2 slots: every speculative completion
        (tokens AND finish reason) matches the plain decode path's."""
        prompts = spec_setup[3]
        plain, _ = _batcher(spec_setup, spec=False)
        ref = plain.run(_reqs(prompts))
        spec, _ = _batcher(spec_setup)
        got = spec.run(_reqs(prompts))
        for i in range(6):
            uid = str(i)
            assert got[uid].tokens == ref[uid].tokens, uid
            assert got[uid].reason == ref[uid].reason, uid
        # the identity gate is only meaningful if drafts were accepted
        assert spec.spec_stats["accepted"] > 0
        assert spec.spec_stats["committed"] > spec.spec_stats["steps"]

    def test_eos_cut_inside_verify_window(self, spec_setup):
        """An EOS landing mid-verify must truncate the commit exactly
        where the plain path stops — committed THROUGH the eos, never
        past it."""
        prompts = spec_setup[3]
        plain, _ = _batcher(spec_setup, spec=False)
        flat = [t for c in plain.run(_reqs(prompts)).values()
                for t in c.tokens]
        eos = max(set(flat), key=flat.count)
        plain_e, _ = _batcher(spec_setup, spec=False, eos_id=eos)
        ref = plain_e.run(_reqs(prompts))
        spec_e, _ = _batcher(spec_setup, eos_id=eos)
        got = spec_e.run(_reqs(prompts))
        assert any(c.reason == "eos" for c in ref.values())
        for i in range(6):
            uid = str(i)
            assert got[uid].tokens == ref[uid].tokens, uid
            assert got[uid].reason == ref[uid].reason, uid

    def test_seeded_sampled_identity_across_orders(self, spec_setup):
        """Seeded sampled speculative streams equal plain sampling's,
        and survive a different admission order — the cross-replica
        determinism the failover contract needs, now under variable
        multi-token advances."""
        prompts = spec_setup[3]
        plain, _ = _batcher(spec_setup, spec=False, temperature=0.8)
        ref = plain.run(_reqs(prompts, seed=100))
        spec, _ = _batcher(spec_setup, temperature=0.8)
        got = spec.run(_reqs(prompts, seed=100))
        spec2, _ = _batcher(spec_setup, temperature=0.8)
        got2 = spec2.run(list(reversed(_reqs(prompts, seed=100))))
        for i in range(6):
            uid = str(i)
            assert got[uid].tokens == ref[uid].tokens, uid
            assert got2[uid].tokens == ref[uid].tokens, uid

    def test_null_draft_source_degenerates_to_plain(self, spec_setup):
        prompts = spec_setup[3]
        plain, _ = _batcher(spec_setup, spec=False)
        ref = plain.run(_reqs(prompts))
        null_b, _ = _batcher(spec_setup, draft=NullDraftSource())
        got = null_b.run(_reqs(prompts))
        for i in range(6):
            assert got[str(i)].tokens == ref[str(i)].tokens, i
        assert null_b.spec_stats["drafted"] == 0
        # every verify step still commits exactly one token per slot
        assert (null_b.spec_stats["committed"]
                == null_b.spec_stats["slot_steps"])

    def test_zero_new_jit_entries_across_acceptance_patterns(
            self, spec_setup):
        """Wave 2's prompts (random, mostly-rejecting) produce commit
        patterns wave 1 (repetitive, mostly-accepting) never saw; the
        verify step must not add a single jit entry."""
        prompts = spec_setup[3]
        spec, fns = _batcher(spec_setup)
        spec.run(_reqs(prompts))
        size = fns.spec_jit._cache_size()
        assert size <= 2, size
        rng = np.random.RandomState(11)
        adv = [[int(t) for t in rng.randint(1, 64, (12,))]
               for _ in range(4)]
        spec.run(_reqs(adv, tag="w2-"))
        assert fns.spec_jit._cache_size() == size
        assert fns.prefill_jit._cache_size() <= 2

    def test_rollback_leaves_pool_bits_identical_to_never_drafted(
            self, spec_setup):
        """Rejection is length-truncation, not data repair: at every
        COMMITTED position the pool a drafting run leaves is
        bit-identical to a never-drafted (NullDraftSource) run's, and
        the allocator ends fully recycled in both."""
        prompts = spec_setup[3][:2]

        def run(draft):
            b, _ = _batcher(spec_setup, draft=draft)
            snaps = {}
            orig = b._retire

            def spy(done_h, t_h):
                snaps["pt"] = np.array(b.cache.page_table).copy()
                snaps["lengths"] = np.array(b.cache.lengths).copy()
                snaps["free"] = b.cache.allocator.num_free
                orig(done_h, t_h)

            b._retire = spy
            comps = b.run(_reqs(prompts))
            return b, snaps, comps

        ng_b, ng_s, ng_c = run(NGramDraftSource(K))
        nl_b, nl_s, nl_c = run(NullDraftSource())
        assert ng_b.spec_stats["accepted"] > 0   # drafting happened
        for i in range(2):
            assert ng_c[str(i)].tokens == nl_c[str(i)].tokens, i
        # same allocation history -> same physical pages, lengths, and
        # mid-flight free count
        assert np.array_equal(ng_s["pt"], nl_s["pt"])
        assert np.array_equal(ng_s["lengths"], nl_s["lengths"])
        assert ng_s["free"] == nl_s["free"]
        for slot in range(2):
            pages = [p for p in ng_s["pt"][slot] if p != 0]
            ln = int(ng_s["lengths"][slot])
            for a, b_ in zip(jax.tree.leaves(ng_b.pools),
                             jax.tree.leaves(nl_b.pools)):
                # (layers, pages, heads, page_size, dim) -> rows in
                # logical position order, truncated at the committed
                # length — the only region the contract covers
                ga = np.moveaxis(np.asarray(a)[:, pages], 3, 2)
                gb = np.moveaxis(np.asarray(b_)[:, pages], 3, 2)
                ga = ga.reshape(ga.shape[0], -1, *ga.shape[3:])[:, :ln]
                gb = gb.reshape(gb.shape[0], -1, *gb.shape[3:])[:, :ln]
                assert np.array_equal(ga, gb), slot
        # both runs end fully recycled
        npages = ng_b.cache.config.num_pages
        assert ng_b.cache.allocator.num_free == npages - 1
        assert nl_b.cache.allocator.num_free == npages - 1

    def test_verify_step_with_zero_drafts_matches_decode_step(
            self, spec_setup):
        """Row 0 of a draft-free verify step IS the plain decode step:
        same logits (argmax-identical, numerically tight), same
        committed semantics."""
        from jax.sharding import PartitionSpec as P

        from apex_tpu._compat import shard_map

        mesh, model, params, prompts, maxp = spec_setup
        # a LIVE cache state (retired tables alias the null-page sink,
        # which the two paths fill with different scratch): admit two
        # slots and prefill their prompts explicitly
        pps = -(-(maxp + NEW) // PAGE)
        ccfg = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8,
            num_pages=1 + 2 * pps, page_size=PAGE, max_seqs=2,
            pages_per_seq=pps, dtype=jnp.float32)
        fns = model.decode_fns(params, mesh, ccfg,
                               max_prompt_len=maxp, speculate_k=K)
        cache = PagedKVCache(ccfg)
        pools = init_pools(ccfg)
        S = 2
        firsts = []
        for slot in range(S):
            cache.admit(slot, maxp + NEW)
            padded = np.zeros((1, maxp), np.int32)
            padded[0, :len(prompts[slot])] = prompts[slot]
            pools, first = fns.prefill(
                pools, jnp.asarray(padded),
                jnp.int32(len(prompts[slot])),
                jnp.asarray(cache.page_table[slot]),
                jax.random.PRNGKey(slot))
            firsts.append(int(jax.device_get(first)))
        pt = jnp.asarray(cache.page_table)

        def both(p, pools, toks, lens, pt):
            active = jnp.ones((S,), bool)
            l1, _ = model.decode_step(p, toks, lens, active, pt, pools)
            rows = jnp.concatenate(
                [toks[:, None], jnp.zeros((S, K), jnp.int32)], axis=1)
            valid = jnp.broadcast_to(
                jnp.arange(K + 1)[None] <= 0, (S, K + 1))
            l2, _ = model.verify_step(p, rows, lens, active, valid,
                                      pt, pools)
            return l1, l2[:, 0]

        specs = model.param_specs()
        pool_specs = jax.tree.map(lambda _: P(), pools)
        run = jax.jit(shard_map(
            both, mesh=mesh,
            in_specs=(specs, pool_specs, P(), P(), P()),
            out_specs=(P(), P())))
        toks = jnp.asarray(firsts, jnp.int32)
        lens = jnp.asarray([len(prompts[0]), len(prompts[1])],
                           jnp.int32)
        l1, l2 = jax.device_get(run(params, pools, toks, lens, pt))
        np.testing.assert_allclose(l1, l2, rtol=0, atol=1e-5)
        assert np.array_equal(np.argmax(l1, -1), np.argmax(l2, -1))

    def test_spec_telemetry_reaches_metrics_report(
            self, spec_setup, tmp_path):
        """spec_accept events land in the jsonl stream and the report
        renders the speculation scoreboard — histogram, per-source hit
        rates, wasted-verify fraction — from them alone."""
        from apex_tpu.telemetry.metrics import MetricsLogger

        import tools.metrics_report as mr

        prompts = spec_setup[3]
        jsonl = str(tmp_path / "spec.jsonl")
        logger = MetricsLogger(jsonl_path=jsonl, console=False)
        b, _ = _batcher(spec_setup, logger=logger)
        b.run(_reqs(prompts))
        logger.close()
        summary = mr.summarize(mr.load_records(jsonl))
        sp = summary["serving"]["speculation"]
        assert sp["verify_steps"] == b.spec_stats["steps"]
        assert sp["drafted"] == b.spec_stats["drafted"]
        assert sp["accepted"] == b.spec_stats["accepted"]
        assert sp["committed"] == b.spec_stats["committed"]
        assert sp["committed_per_slot_step"] > 1.0
        assert 0.0 <= sp["wasted_verify_fraction"] <= 1.0
        assert sum(sp["accepted_per_step_hist"].values()) \
            == b.spec_stats["slot_steps"]
        assert any(src in sp["by_source"]
                   for src in ("ngram", "prompt_lookup"))
        for src, rec in sp["by_source"].items():
            assert 0.0 <= rec["hit_rate"] <= 1.0
        assert sp["offramp_commits"] == b.spec_stats["offramp"]
        assert sp["draft_wall_s"] >= 0.0
        assert 0.0 <= sp["draft_wall_fraction"] < 1.0
        text = mr.format_report(summary)
        assert "speculation:" in text
        assert "tokens/slot-step" in text

    def test_batcher_spec_validation(self, spec_setup):
        mesh, model, params, prompts, maxp = spec_setup
        pps = -(-(maxp + NEW) // PAGE)
        ccfg = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8,
            num_pages=1 + 2 * pps, page_size=PAGE, max_seqs=2,
            pages_per_seq=pps, dtype=jnp.float32)
        fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=maxp,
                               speculate_k=K)
        base = dict(max_prompt_len=maxp, harvest_every=3)

        def make(**kw):
            return ContinuousBatcher(
                fns.prefill, fns.decode, PagedKVCache(ccfg),
                init_pools(ccfg), **base, **kw)

        with pytest.raises(ValueError, match="speculate_k"):
            make(spec_fn=fns.spec)
        with pytest.raises(ValueError, match="spec_fn"):
            make(speculate_k=K)
        with pytest.raises(ValueError, match="speculate_k"):
            make(spec_fn=fns.spec, speculate_k=K + 1)
        with pytest.raises(ValueError, match="draft_source"):
            make(draft_source=NGramDraftSource(K))
        with pytest.raises(TypeError, match="DraftSource"):
            model.decode_fns(params, mesh, ccfg, max_prompt_len=maxp,
                             speculate_k=K, draft_model=object())
        with pytest.raises(ValueError, match="speculate_k"):
            model.decode_fns(params, mesh, ccfg, max_prompt_len=maxp,
                             spec_tree=chain_tree(K))
        with pytest.raises(ValueError, match="max depth"):
            model.decode_fns(params, mesh, ccfg, max_prompt_len=maxp,
                             speculate_k=K + 1,
                             spec_tree=chain_tree(K))


# ---------------------------------------------------------------------------
# candidate trees: helpers, the coupled tree walk, tree serving
# ---------------------------------------------------------------------------


class TestTreeHelpers:
    def test_shapes_and_depths(self):
        assert chain_tree(3) == (-1, 0, 1, 2)
        assert offramp_tree(3) == (-1, 0, 1, 2, 0, 1, 2)
        assert tree_depths(offramp_tree(3)) == (0, 1, 2, 3, 1, 2, 3)
        assert tree_max_depth(offramp_tree(3)) == 3
        assert tree_chain_rows(offramp_tree(3)) == (1, 2, 3)
        assert tree_chain_rows(chain_tree(2)) == (1, 2)

    def test_ancestor_matrix(self):
        A = np.asarray(tree_ancestors(offramp_tree(2)))  # (-1,0,1,0,1)
        assert (np.diag(A) == 1).all()          # write-before-attend
        assert np.triu(A, 1).sum() == 0         # topological order
        assert (A[:, 0] == 1).all()             # root in every path
        # off-ramp row 3 hangs off the ROOT: it must not see the
        # chain rows it is an alternative to
        assert A[3, 1] == 0 and A[3, 2] == 0
        # off-ramp row 4 hangs off chain row 1: sees it, not row 2
        assert A[4, 1] == 1 and A[4, 2] == 0

    def test_validate_tree_rejections(self):
        with pytest.raises(ValueError):
            validate_tree(())
        with pytest.raises(ValueError):
            validate_tree((0,))                # root's parent is -1
        with pytest.raises(ValueError):
            validate_tree((-1, 1))             # parent precedes child
        with pytest.raises(ValueError):
            validate_tree((-1, -1))            # ONE root


class TestSpecAcceptTree:
    V = 16

    def _logits(self, rows, seed=0):
        return jax.random.normal(jax.random.PRNGKey(seed),
                                 (rows, self.V), jnp.float32)

    def _keys(self, rows):
        return jnp.stack([jax.random.PRNGKey(100 + i)
                          for i in range(rows)])

    @pytest.mark.parametrize("temperature", [0.0, 0.8])
    def test_chain_tree_reduces_to_spec_accept(self, temperature):
        """A chain-shaped parents tuple must reproduce spec_accept
        bit-for-bit — the tree walk is a strict generalization."""
        from apex_tpu.serving.sampling import spec_accept_tree

        k = 3
        logits = self._logits(k + 1, seed=1)
        keys = self._keys(k + 1)
        t_ref = (np.asarray(jnp.argmax(logits, axis=-1))
                 if temperature == 0.0 else
                 np.asarray(jax.vmap(
                     lambda l, kk: sample(l[None], kk, temperature)[0]
                 )(logits, keys)))
        drafts = jnp.asarray(
            [t_ref[0], t_ref[1], (t_ref[2] + 1) % self.V], jnp.int32)
        out, n, path = spec_accept_tree(
            logits, drafts, chain_tree(k), jnp.ones((k,), bool), keys,
            temperature)
        t_chain, n_chain = spec_accept(
            logits, drafts, jnp.int32(k), keys, temperature)
        assert int(n) == int(n_chain) == 2
        nc = int(n) + 1
        assert (np.asarray(out)[:nc].tolist()
                == np.asarray(t_chain)[:nc].tolist())
        assert np.asarray(path).tolist() == [0, 1, 2, 2]  # stalls

    def test_offramp_rescues_rejected_chain(self):
        from apex_tpu.serving.sampling import spec_accept_tree

        tree = offramp_tree(2)                 # (-1, 0, 1, 0, 1)
        logits = self._logits(5, seed=3)
        g = np.asarray(jnp.argmax(logits, axis=-1))
        # chain row 1 misses the target; off-ramp row 3 carries it
        drafts = jnp.asarray(
            [(g[0] + 1) % self.V, 0, g[0], (g[1] + 1) % self.V],
            jnp.int32)
        out, n, path = spec_accept_tree(
            logits, drafts, tree, jnp.ones((4,), bool), None)
        assert int(n) == 1
        p = np.asarray(path).tolist()
        assert p[0] == 0 and p[1] == 3
        o = np.asarray(out)
        # committed token = the coupled draw; correction comes from
        # the ACCEPTED node's logits row
        assert o[0] == g[0] and o[1] == g[3]

    def test_equal_token_siblings_resolve_first_in_row_order(self):
        from apex_tpu.serving.sampling import spec_accept_tree

        tree = offramp_tree(2)
        logits = self._logits(5, seed=4)
        g = np.asarray(jnp.argmax(logits, axis=-1))
        drafts = jnp.asarray([g[0], 0, g[0], 0], jnp.int32)
        out, n, path = spec_accept_tree(
            logits, drafts, tree, jnp.ones((4,), bool), None)
        # both depth-1 candidates carry the target token: the CHAIN
        # row wins (committed token is identical either way)
        assert np.asarray(path).tolist()[1] == 1

    def test_invalid_nodes_never_accepted(self):
        from apex_tpu.serving.sampling import spec_accept_tree

        tree = offramp_tree(2)
        logits = self._logits(5, seed=5)
        g = np.asarray(jnp.argmax(logits, axis=-1))
        drafts = jnp.asarray([g[0], g[1], g[0], g[1]], jnp.int32)
        out, n, _ = spec_accept_tree(
            logits, drafts, tree, jnp.zeros((4,), bool), None)
        assert int(n) == 0
        assert int(np.asarray(out)[0]) == g[0]  # the correction draw


class TestTreeServing:
    @pytest.mark.parametrize(
        "tree_fn", [chain_tree, offramp_tree],
        ids=["chain", "offramp"])
    def test_greedy_identity_both_tree_shapes(self, spec_setup,
                                              tree_fn):
        """Tree-verified greedy serving under 6-requests/2-slots churn
        is token-identical to plain decode, for both tree shapes."""
        prompts = spec_setup[3]
        plain, _ = _batcher(spec_setup, spec=False)
        ref = plain.run(_reqs(prompts))
        b, _ = _batcher(spec_setup, tree=tree_fn(K))
        got = b.run(_reqs(prompts))
        for i in range(6):
            uid = str(i)
            assert got[uid].tokens == ref[uid].tokens, uid
            assert got[uid].reason == ref[uid].reason, uid
        assert b.spec_stats["accepted"] > 0

    def test_seeded_sampled_identity_offramp(self, spec_setup):
        """Seeded SAMPLED streams through the off-ramp tree equal
        plain sampling's — the coupled walk preserves the per-slot
        absolute-position key schedule exactly."""
        prompts = spec_setup[3]
        plain, _ = _batcher(spec_setup, spec=False, temperature=0.8)
        ref = plain.run(_reqs(prompts, seed=100))
        b, _ = _batcher(spec_setup, tree=offramp_tree(K),
                        temperature=0.8)
        got = b.run(_reqs(prompts, seed=100))
        for i in range(6):
            assert got[str(i)].tokens == ref[str(i)].tokens, i

    def test_tree_shapes_never_change_jit_entries(self, spec_setup):
        """Waves with different acceptance/tree-draft patterns change
        CONTENTS, never shapes: zero jit growth after warmup."""
        prompts = spec_setup[3]
        b, fns = _batcher(spec_setup, tree=offramp_tree(K))
        b.run(_reqs(prompts[:2]))
        warm = fns.spec_jit._cache_size()
        b.run(_reqs(prompts, tag="w2-"))
        b.run(_reqs(list(reversed(prompts)), tag="w3-"))
        assert fns.spec_jit._cache_size() == warm

    def test_draft_source_rides_the_compiled_step(self, spec_setup):
        """decode_fns(draft_model=...) stamps the source onto spec;
        the batcher picks it up without an explicit draft_source."""
        mesh, model, params, prompts, maxp = spec_setup
        ds = NGramDraftSource(K)
        b, fns = _batcher(spec_setup, draft_model=ds)
        assert fns.draft_source is ds
        assert b.draft_source is ds

    def test_tree_mismatch_rejected(self, spec_setup):
        """A draft source built for one tree cannot drive a spec step
        compiled for another (or for a chain)."""

        class _TreeSrc(NGramDraftSource):
            tree = offramp_tree(K)

        with pytest.raises(ValueError, match="tree"):
            _batcher(spec_setup, tree=chain_tree(K),
                     draft=_TreeSrc(K))
        with pytest.raises(ValueError, match="tree"):
            _batcher(spec_setup, draft=_TreeSrc(K))


class TestModelDraftServing:
    def _source(self, setup, tree=None):
        mesh, model, params, prompts, maxp = setup
        pps = -(-(maxp + NEW + K) // PAGE)
        dcfg = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8,
            num_pages=1 + 2 * pps, page_size=PAGE, max_seqs=2,
            pages_per_seq=pps, dtype=jnp.float32)
        # weight_block=16: the tiny model's fused qkv rows (96) must
        # tile 2*block for the packed int4 halves
        return ModelDraftSource(model, params, mesh, dcfg, k=K,
                                tree=tree, weight_dtype="int4",
                                weight_block=16)

    def test_greedy_identity_with_draft_model(self, spec_setup):
        """A real int4 draft model drafting into the verify step keeps
        greedy serving token-identical to plain decode — and actually
        accepts (the draft model IS the target here, quantized)."""
        prompts = spec_setup[3]
        plain, _ = _batcher(spec_setup, spec=False)
        ref = plain.run(_reqs(prompts))
        b, _ = _batcher(spec_setup, draft_model=self._source(
            spec_setup))
        got = b.run(_reqs(prompts))
        for i in range(6):
            uid = str(i)
            assert got[uid].tokens == ref[uid].tokens, uid
            assert got[uid].reason == ref[uid].reason, uid
        st = b.spec_stats
        assert st["by_source"]["draft_model"]["accepted"] > 0
        assert st["draft_s"] > 0.0

    def test_tree_draft_model_identity_and_stream_bytes(
            self, spec_setup):
        """Off-ramp tree drafting from the int4 draft model: identity
        holds and the draft's weight stream is a fraction of the
        full-precision pool's."""
        prompts = spec_setup[3]
        plain, _ = _batcher(spec_setup, spec=False)
        ref = plain.run(_reqs(prompts))
        ds = self._source(spec_setup, tree=offramp_tree(K))
        b, fns = _batcher(spec_setup, tree=offramp_tree(K),
                          draft_model=ds)
        got = b.run(_reqs(prompts))
        for i in range(6):
            assert got[str(i)].tokens == ref[str(i)].tokens, i
        assert ds.weight_dtype == "int4"
        assert ds.weight_stream_bytes < fns.weight_stream_bytes

    def test_draft_is_pure_function_of_context(self, spec_setup):
        """Drafting twice from the same context — cold and through the
        per-slot KV memoization — returns identical tokens (the
        failover-replay requirement)."""
        ds = self._source(spec_setup, tree=offramp_tree(K))
        ctx = [3, 7, 11, 5, 3, 7, 11, 5, 3, 7]
        first, src = ds.draft(ctx, len(ctx))
        assert src == "draft_model" and len(first) == 2 * K
        again, _ = ds.draft(ctx, len(ctx))          # memoized prefix
        assert again == first
        cold = self._source(spec_setup, tree=offramp_tree(K))
        fresh, _ = cold.draft(ctx, len(ctx))
        assert fresh == first


# ---------------------------------------------------------------------------
# failover under multi-token advances
# ---------------------------------------------------------------------------


class TestFailoverMultiToken:
    def _log(self, new=10):
        log = RequestLog()
        req = Request(uid="u", prompt=[1, 2, 3], max_new_tokens=new,
                      seed=7)
        log.admit(req, "interactive", "r0", 0.0)
        return log, req

    def test_multi_token_jumps_fold_exactly(self):
        """progress() may grow by any count between harvests (a verify
        step commits up to k+1); the log stores streams, so resume
        math stays count-exact."""
        log, req = self._log()
        log.record_progress("r0", {"u": [4, 5, 6]}, 1.0)
        log.record_progress("r0", {"u": [4, 5, 6, 7, 8, 9, 1]}, 2.0)
        e = log.get("u")
        assert e.emitted == [4, 5, 6, 7, 8, 9, 1]
        log.reassign("u", "r1")
        resumed = resume_request(e)
        assert resumed.prompt == [1, 2, 3, 4, 5, 6, 7, 8, 9, 1]
        assert resumed.max_new_tokens == 3
        assert resumed.seed == 7

    def test_over_commit_fails_at_recording_boundary(self):
        log, req = self._log(new=4)
        with pytest.raises(ValueError, match="over-committed"):
            log.record_progress("r0", {"u": [1, 2, 3, 4, 5]}, 1.0)
        log2, _ = self._log(new=4)
        with pytest.raises(ValueError, match="over-committed"):
            log2.complete("u", [1, 2, 3, 4, 5], "budget", 1.0)

    def test_exact_budget_commit_is_legal(self):
        log, req = self._log(new=4)
        log.record_progress("r0", {"u": [1, 2, 3, 4]}, 1.0)
        e = log.complete("u", [1, 2, 3, 4], "budget", 2.0)
        assert e.emitted == [1, 2, 3, 4]

    def test_kill_drill_under_speculation(self, spec_setup):
        """r0 dies after 2 windows with speculative replicas: every
        request completes, >= 1 migrates, streams and budgets are
        identical to an unkilled speculative fleet."""
        mesh, model, params, prompts, maxp = spec_setup
        # replay headroom: a migrated request re-admits with
        # prompt + emitted as its prompt, so max_prompt_len must cover
        # len(prompt) + max_new - 1
        new_f, maxp_f = 6, 18
        pps = -(-(maxp_f + new_f) // PAGE)
        ccfg = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8,
            num_pages=1 + 4 * pps, page_size=PAGE, max_seqs=2,
            pages_per_seq=pps, dtype=jnp.float32)
        fns = model.decode_fns(params, mesh, ccfg,
                               max_prompt_len=maxp_f, speculate_k=K)

        def replicas():
            return [
                Replica(f"r{i}", ContinuousBatcher(
                    fns.prefill, fns.decode, PagedKVCache(ccfg),
                    init_pools(ccfg), max_prompt_len=maxp_f,
                    harvest_every=2, spec_fn=fns.spec, speculate_k=K,
                    draft_source=NGramDraftSource(K)))
                for i in range(2)
            ]

        reqs = [Request(uid=f"u{i}", prompt=list(prompts[i % 6]),
                        max_new_tokens=new_f) for i in range(8)]

        def run(fail):
            router = FleetRouter(replicas())
            if fail:
                router.replicas[0].fail_after(2)
            for r in reqs:
                assert router.submit(r)
            router.drain()
            return router

        ref = run(fail=False)
        drill = run(fail=True)
        assert not drill.replicas[0].alive
        assert drill.stats["migrations"] >= 1
        assert len(drill.completions) == len(reqs)
        for uid, comp in ref.completions.items():
            assert drill.completions[uid].tokens == comp.tokens, uid
            assert len(drill.completions[uid].tokens) <= new_f
        assert any(c.replays > 0 for c in drill.completions.values())
        # the drill actually exercised speculation, not a plain path
        assert any(r.batcher.spec_stats["committed"] > 0
                   for r in drill.replicas)

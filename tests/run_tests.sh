#!/usr/bin/env bash
# Tiered test runner — the analog of the reference's L0/L1 scripts
# (/root/reference/tests/L0/run_test.py:1-29, tests/L1/common/run_test.sh)
# and the .jenkins CI harness:
#
#   tests/run_tests.sh l0       fast gate: every subsystem smoke-covered,
#                               ~7 min on a 1-core host (283 tests, r5)
#   tests/run_tests.sh full     the whole suite, chunked so no single
#                               pytest invocation exceeds a CI timeout
#   tests/run_tests.sh strict   l0 with APEX_TPU_STRICT_KERNELS=1 — any
#                               silent Pallas->XLA kernel fallback FAILS
#
# Exit code is nonzero on any failure, so this is CI-ready as-is.
set -euo pipefail
cd "$(dirname "$0")/.."

tier="${1:-l0}"

case "$tier" in
  l0)
    exec python -m pytest tests/ -m l0 -q --durations=10
    ;;
  strict)
    APEX_TPU_STRICT_KERNELS=1 exec python -m pytest tests/ -m l0 -q
    ;;
  full)
    # chunked: the full suite needs ~20 min serial on a 1-core host, so
    # no single invocation may own the whole wall-clock budget
    python -m pytest tests/test_cross_product.py -q
    python -m pytest tests/test_bert.py tests/test_t5.py -q
    python -m pytest tests/test_gpt.py tests/test_pipeline.py \
        tests/test_combined_axes.py -q
    python -m pytest tests/test_resnet_examples.py \
        tests/test_softmax_attention.py tests/test_moe.py \
        tests/test_ring_attention.py -q
    exec python -m pytest tests/ -q \
        --ignore=tests/test_cross_product.py \
        --ignore=tests/test_bert.py --ignore=tests/test_t5.py \
        --ignore=tests/test_gpt.py --ignore=tests/test_pipeline.py \
        --ignore=tests/test_combined_axes.py \
        --ignore=tests/test_resnet_examples.py \
        --ignore=tests/test_softmax_attention.py \
        --ignore=tests/test_moe.py --ignore=tests/test_ring_attention.py
    ;;
  *)
    echo "usage: tests/run_tests.sh [l0|full|strict]" >&2
    exit 2
    ;;
esac

"""Native runtime (apex_C analog) + checkpoint tests."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from apex_tpu import csrc
from apex_tpu import checkpoint as ckpt


class TestNative:
    def test_native_compiles(self):
        assert csrc.native_available(), (
            "g++ toolchain present but native lib failed to build"
        )

    def test_flatten_unflatten_roundtrip(self):
        rng = np.random.default_rng(0)
        arrays = [
            rng.normal(size=(13, 7)).astype(np.float32),
            rng.integers(0, 100, (5,)).astype(np.int64),
            rng.normal(size=(2, 3, 4)).astype(np.float16),
            np.asarray(3.5, np.float64),
        ]
        flat = csrc.flatten(arrays)
        assert flat.nbytes == sum(a.nbytes for a in arrays)
        out = csrc.unflatten(
            flat, [a.shape for a in arrays], [a.dtype for a in arrays]
        )
        for a, b in zip(arrays, out):
            np.testing.assert_array_equal(a, b)

    def test_matches_python_fallback(self):
        rng = np.random.default_rng(1)
        arrays = [rng.normal(size=(64, 64)).astype(np.float32)
                  for _ in range(10)]
        native = csrc.flatten(arrays)
        expected = np.concatenate([a.view(np.uint8).reshape(-1)
                                   for a in arrays])
        np.testing.assert_array_equal(native, expected)

    def test_unflatten_size_mismatch_raises(self):
        with pytest.raises(ValueError, match="describe"):
            csrc.unflatten(np.zeros(10, np.uint8), [(4,)], [np.float32])

    def test_plan_buckets(self):
        # 4-byte floats: sizes in bytes
        ids = csrc.plan_buckets([400, 400, 400, 1200, 100], 1000)
        # [400+400]=800, +400 would be 1200 → new bucket; 1200 alone
        # exceeds the cap but still gets its own bucket; 100 joins... a
        # new bucket since 400+1200 spill
        assert ids.tolist() == [0, 0, 1, 2, 3]
        assert csrc.plan_buckets([], 100).tolist() == []


class TestCheckpoint:
    def test_roundtrip_pytree(self, tmp_path):
        tree = {
            "params": {"w": jnp.arange(12.0).reshape(3, 4),
                       "b": jnp.ones((4,), jnp.bfloat16)},
            "step": jnp.int32(7),
            "nested": [jnp.zeros((2, 2)), jnp.float32(1.5)],
        }
        ckpt.save(str(tmp_path / "c"), tree)
        out = ckpt.restore(str(tmp_path / "c"))
        for (ka, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(out),
            jax.tree_util.tree_leaves_with_path(tree),
        ):
            assert np.asarray(a).dtype == np.asarray(b).dtype, ka
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_restore_with_target_validates(self, tmp_path):
        tree = {"w": jnp.ones((3, 3))}
        ckpt.save(str(tmp_path / "c"), tree)
        out = ckpt.restore(str(tmp_path / "c"), target={"w": jnp.zeros((3, 3))})
        np.testing.assert_array_equal(np.asarray(out["w"]), 1.0)
        with pytest.raises(ValueError, match="structure mismatch"):
            ckpt.restore(str(tmp_path / "c"), target={"v": jnp.zeros((3, 3))})
        with pytest.raises(ValueError, match="shape mismatch"):
            ckpt.restore(str(tmp_path / "c"), target={"w": jnp.zeros((2, 3))})

    def test_step_workflow(self, tmp_path):
        root = str(tmp_path / "run")
        assert ckpt.latest_step(root) is None
        for step in (10, 20, 30):
            ckpt.save_step(root, step, {"w": jnp.full((2,), float(step))})
        assert ckpt.latest_step(root) == 30
        out = ckpt.restore_step(root)
        np.testing.assert_array_equal(np.asarray(out["w"]), 30.0)
        out10 = ckpt.restore_step(root, step=10)
        np.testing.assert_array_equal(np.asarray(out10["w"]), 10.0)

    def test_training_state_roundtrip(self, tmp_path):
        """Full train-state checkpoint: params + optimizer + amp scaler
        (the reference README checkpoint recipe, README.md:60-100)."""
        from apex_tpu import amp
        from apex_tpu.optimizers import FusedAdam

        mp = amp.initialize(opt_level="O2")
        opt = FusedAdam(lr=1e-3, master_weights=True)
        params = {"w": jnp.ones((4, 4), jnp.bfloat16)}
        opt_state = opt.init(params)
        amp_state = mp.init()
        state = {
            "params": params,
            "opt": opt_state,
            "amp": mp.state_dict(amp_state),
        }
        ckpt.save(str(tmp_path / "c"), state)
        restored = ckpt.restore(str(tmp_path / "c"))
        amp_restored = mp.load_state_dict(restored["amp"])
        assert float(amp_restored.scaler_states[0].loss_scale) == float(
            amp_state.scaler_states[0].loss_scale
        )
        assert restored["params"]["w"].dtype == np.asarray(params["w"]).dtype
        np.testing.assert_array_equal(
            np.asarray(restored["opt"]["master"]["w"]),
            np.asarray(opt_state["master"]["w"]),
        )


class TestIntegrityRoundtrip:
    """Checksum/verify integration with the core save/restore flow
    (the corruption-detection cases live in tests/test_resilience.py)."""

    def test_save_verify_restore_roundtrip(self, tmp_path):
        tree = {
            "params": {"w": jnp.arange(48.0).reshape(6, 8),
                       "b": jnp.ones((8,), jnp.bfloat16)},
            "step": jnp.int32(11),
        }
        path = str(tmp_path / "c")
        ckpt.save(path, tree)
        assert ckpt.verify(path) == []
        out = ckpt.restore(path, verify_integrity=True)
        for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(tree)):
            assert np.asarray(a).dtype == np.asarray(b).dtype
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_multi_chunk_checksums_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setenv("APEX_TPU_CKPT_CHUNK_BYTES", "32")
        path = str(tmp_path / "c")
        tree = {"w": jnp.arange(256.0)}  # 1 KiB blob → 32 chunks
        ckpt.save(path, tree)
        assert ckpt.verify(path) == []
        out = ckpt.restore(path, verify_integrity=True)
        np.testing.assert_array_equal(np.asarray(out["w"]),
                                      np.arange(256.0, dtype=np.float32))

    def test_async_save_records_verifiable_checksums(self, tmp_path):
        h = ckpt.save_async(str(tmp_path / "a"),
                            {"w": jnp.full((16,), 2.5)})
        h.result(timeout=30)
        assert ckpt.verify(str(tmp_path / "a")) == []

    def test_restore_latest_valid_on_healthy_root(self, tmp_path):
        for step in (3, 6):
            ckpt.save_step(str(tmp_path), step,
                           {"w": jnp.full((4,), float(step))})
        tree, step = ckpt.restore_latest_valid(str(tmp_path))
        assert step == 6
        np.testing.assert_array_equal(np.asarray(tree["w"]), 6.0)

    def test_empty_tree_verifies(self, tmp_path):
        path = str(tmp_path / "empty")
        ckpt.save(path, {})
        assert ckpt.verify(path) == []
        assert ckpt.restore(path, verify_integrity=True) == {}


class TestAsyncSave:
    def test_async_roundtrip_bitwise(self, tmp_path):
        tree = {
            "w": jnp.arange(1024, dtype=jnp.float32).reshape(32, 32),
            "m": jnp.ones((7,), jnp.bfloat16) * 0.5,
            "step": jnp.int32(42),
        }
        h = ckpt.save_async(str(tmp_path / "a"), tree)
        h.result(timeout=30)
        back = ckpt.restore(str(tmp_path / "a"))
        for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(tree)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_snapshot_is_taken_before_return(self, tmp_path):
        """The device->host copy happens synchronously: mutating (or
        deleting) the source after save_async returns must not change
        what lands on disk — the donation-safety contract."""
        x = jnp.zeros((64, 64), jnp.float32) + 3.0
        h = ckpt.save_async(str(tmp_path / "s"), {"x": x})
        x = x * 0 - 1.0  # new value; old buffer may be reused
        del x
        h.result(timeout=30)
        back = ckpt.restore(str(tmp_path / "s"))
        np.testing.assert_array_equal(np.asarray(back["x"]),
                                      np.full((64, 64), 3.0, np.float32))

    def test_concurrent_step_saves_and_drain(self, tmp_path):
        for step in range(4):
            ckpt.save_async(str(tmp_path / f"step_{step}"),
                            {"v": jnp.full((8,), step, jnp.float32)})
        ckpt.wait_pending_saves(timeout=60)
        assert ckpt.latest_step(str(tmp_path)) == 3
        for step in range(4):
            back = ckpt.restore_step(str(tmp_path), step=step)
            np.testing.assert_array_equal(
                np.asarray(back["v"]), np.full((8,), step, np.float32))

    def test_writer_exception_surfaces(self, tmp_path):
        target = tmp_path / "blocked"
        target.write_text("a file where a directory must go")
        h = ckpt.save_async(str(target), {"x": jnp.ones((2,))})
        with pytest.raises(Exception):
            h.result(timeout=30)

    def test_tmp_dirs_invisible_to_latest_step(self, tmp_path):
        """Atomicity: a crashed writer's .tmp husk is never selected."""
        ckpt.save_step(str(tmp_path), 4, {"v": jnp.ones((2,))})
        (tmp_path / "step_5.tmp").mkdir()  # simulated mid-write crash
        assert ckpt.latest_step(str(tmp_path)) == 4
        back = ckpt.restore_step(str(tmp_path))
        assert back is not None

    def test_drain_reports_failure_and_joins_all(self, tmp_path):
        blocked = tmp_path / "blocked"
        blocked.write_text("file blocks dir rename")
        ckpt.save_async(str(blocked), {"x": jnp.ones((2,))})
        ckpt.save_async(str(tmp_path / "fine"), {"x": jnp.ones((2,))})
        with pytest.raises(Exception):
            ckpt.wait_pending_saves(timeout=30)
        # the healthy sibling still landed before the raise
        back = ckpt.restore(str(tmp_path / "fine"))
        np.testing.assert_array_equal(np.asarray(back["x"]), [1.0, 1.0])

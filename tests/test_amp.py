"""amp policy + scaler tests.

Mirrors the reference L0 amp tier (reference: tests/L0/run_amp/): cast
behaviour per opt level, dynamic scaler growth/backoff, checkpoint
round-trip, per-loss scalers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


class TestPolicy:
    def test_presets_exist(self):
        for lvl in ["O0", "O1", "O2", "O3", "O4", "O5"]:
            p = amp.get_policy(lvl)
            assert p.opt_level == lvl

    def test_bad_level(self):
        with pytest.raises(ValueError):
            amp.get_policy("O6")

    def test_o0_fp32(self):
        p = amp.get_policy("O0")
        assert p.param_dtype == jnp.float32
        assert p.compute_dtype == jnp.float32
        assert p.loss_scale == 1.0
        assert not p.master_weights

    def test_o2_master_fp16(self):
        p = amp.get_policy("O2")
        assert p.param_dtype == jnp.float16
        assert p.master_weights
        assert p.loss_scale == "dynamic"

    def test_o4_o5_bf16_no_scaling(self):
        for lvl in ["O4", "O5"]:
            p = amp.get_policy(lvl)
            assert p.compute_dtype == jnp.bfloat16
            assert p.loss_scale is None
        assert amp.get_policy("O5").master_weights

    def test_overrides_beat_preset(self):
        p = amp.get_policy("O2", loss_scale=128.0, keep_norm_fp32=False)
        assert p.loss_scale == 128.0
        assert not p.keep_norm_fp32

    def test_cast_keeps_norms_fp32(self):
        params = {
            "dense": {"kernel": jnp.ones((4, 4))},
            "layernorm": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
        }
        p = amp.get_policy("O2")
        cast = p.cast_to_param(params)
        assert cast["dense"]["kernel"].dtype == jnp.float16
        assert cast["layernorm"]["scale"].dtype == jnp.float32

    def test_cast_integers_untouched(self):
        tree = {"x": jnp.ones((2,)), "i": jnp.arange(3)}
        cast = amp.get_policy("O3").cast_to_param(tree)
        assert cast["i"].dtype == jnp.int32
        assert cast["x"].dtype == jnp.float16


class TestScaler:
    def test_static_scale(self):
        s = amp.LossScaler(loss_scale=128.0)
        st = s.init()
        assert float(st.loss_scale) == 128.0
        scaled = s.scale(st, jnp.float32(2.0))
        assert float(scaled) == 256.0
        st2 = s.adjust(st, jnp.bool_(True))
        assert float(st2.loss_scale) == 128.0
        assert int(st2.unskipped) == 1

    def test_dynamic_backoff(self):
        s = amp.LossScaler("dynamic")
        st = s.init()
        assert float(st.loss_scale) == 2.0 ** 16
        st = s.adjust(st, jnp.bool_(False))
        assert float(st.loss_scale) == 2.0 ** 15
        assert int(st.growth_tracker) == 0

    def test_dynamic_growth(self):
        s = amp.LossScaler("dynamic", init_scale=4.0, growth_interval=3)
        st = s.init()
        for _ in range(2):
            st = s.adjust(st, jnp.bool_(True))
            assert float(st.loss_scale) == 4.0
        st = s.adjust(st, jnp.bool_(True))
        assert float(st.loss_scale) == 8.0
        assert int(st.growth_tracker) == 0

    def test_max_scale_clamp(self):
        s = amp.LossScaler("dynamic", init_scale=2.0 ** 24, growth_interval=1)
        st = s.init()
        st = s.adjust(st, jnp.bool_(True))
        assert float(st.loss_scale) == 2.0 ** 24

    def test_unscale_detects_inf(self):
        s = amp.LossScaler(loss_scale=2.0)
        st = s.init()
        grads = {"a": jnp.array([2.0, 4.0]), "b": jnp.array([jnp.inf])}
        out, finite = s.unscale(st, grads)
        assert not bool(finite)
        grads = {"a": jnp.array([2.0, 4.0]), "b": jnp.array([6.0])}
        out, finite = s.unscale(st, grads)
        assert bool(finite)
        np.testing.assert_allclose(out["a"], [1.0, 2.0])

    def test_jit_roundtrip(self):
        s = amp.LossScaler("dynamic")

        @jax.jit
        def step(st, g):
            g, finite, st = s.unscale_and_adjust(st, g)
            return g, finite, st

        st = s.init()
        g, finite, st = step(st, {"w": jnp.ones((3,))})
        assert bool(finite)
        assert int(st.unskipped) == 1

    def test_checkpoint_roundtrip(self):
        s = amp.LossScaler("dynamic")
        st = s.init()
        st = s.adjust(st, jnp.bool_(False))
        d = s.state_dict(st)
        st2 = s.load_state_dict(d)
        assert float(st2.loss_scale) == float(st.loss_scale)
        assert int(st2.growth_tracker) == int(st.growth_tracker)


class TestMixedPrecision:
    def test_initialize_and_per_loss_scalers(self):
        mp = amp.initialize("O2", num_losses=3)
        state = mp.init()
        assert len(state.scaler_states) == 3
        # adjust loss 1 only
        grads = {"w": jnp.array([jnp.nan])}
        _, finite, state = mp.unscale_and_adjust(state, grads, loss_id=1)
        assert not bool(finite)
        assert float(state.scaler_states[1].loss_scale) == 2.0 ** 15
        assert float(state.scaler_states[0].loss_scale) == 2.0 ** 16

    def test_state_dict_roundtrip(self):
        mp = amp.initialize("O1", num_losses=2)
        state = mp.init()
        _, _, state = mp.unscale_and_adjust(
            state, {"w": jnp.array([jnp.inf])}, loss_id=0
        )
        d = mp.state_dict(state)
        state2 = mp.load_state_dict(d)
        for a, b in zip(state.scaler_states, state2.scaler_states):
            assert float(a.loss_scale) == float(b.loss_scale)

    def test_apply_if_finite(self):
        old = {"w": jnp.zeros((2,))}
        new = {"w": jnp.ones((2,))}
        kept = amp.MixedPrecision.apply_if_finite(jnp.bool_(False), old, new)
        np.testing.assert_allclose(kept["w"], 0.0)
        applied = amp.MixedPrecision.apply_if_finite(jnp.bool_(True), old, new)
        np.testing.assert_allclose(applied["w"], 1.0)

    def test_master_weight_flow(self):
        mp = amp.initialize("O5")
        params = {"dense": {"kernel": jnp.ones((2, 2))}}
        cast, state = mp.init(params)
        assert cast["dense"]["kernel"].dtype == jnp.bfloat16
        master = mp.make_master(cast)
        assert master["dense"]["kernel"].dtype == jnp.float32
        back = mp.master_to_model(master)
        assert back["dense"]["kernel"].dtype == jnp.bfloat16

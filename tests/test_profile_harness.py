"""Regression tests for the tools/profile_r05.py decomposition harness.

The r05 capture lost its "fwd+bwd, no optimizer" row to a harness bug:
the variant folds a zero grad-sum into the loss for the data
dependency, and tp-sharded grad leaves made that sum tp-varying — which
the step's ``out_specs P()`` (replicated loss) rejects.  The fix pmeans
the sum back to replicated; this test compiles and runs the EXACT
harness step (``profile_r05.make_step``) on a tp>1 mesh so the bug
class cannot recur silently until the next scarce chip session.
"""

import os
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.layers import state_specs_like

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import profile_r05  # noqa: E402


@pytest.fixture
def tp2_mesh():
    m = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2
    )
    yield m
    parallel_state.destroy_model_parallel()


def _build_small(mesh):
    model = GPTModel(GPTConfig(
        vocab_size=64, num_layers=1, hidden_size=32,
        num_attention_heads=2, max_position_embeddings=16,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))
    params = model.init(jax.random.PRNGKey(0))
    specs = model.param_specs()
    opt = FusedAdam(lr=1e-4, master_weights=True)
    opt_state = opt.init(params)
    opt_specs = state_specs_like(specs, opt_state)
    place = lambda tree, sp: jax.device_put(
        tree, jax.tree.map(lambda s: NamedSharding(mesh, s), sp,
                           is_leaf=lambda x: isinstance(x, P)))
    return (model, opt, specs, opt_specs,
            place(params, specs), place(opt_state, opt_specs))


# the optimizer-stepping variants are exercised end-to-end by the real
# capture and need newer jax's vma-aware out_specs replication checking
# (0.4.x cannot statically infer the opt-state replication); the bug
# class this file guards is the loss-only variants' out_specs P()
@pytest.mark.parametrize("variant", ["no_opt", "fwd_only"])
def test_variants_compile_and_run_on_tp2(tp2_mesh, variant):
    """The loss-returning decomposition variants must compile on a tp>1
    mesh — the no_opt row is the one that failed during the r05
    capture."""
    model, opt, specs, opt_specs, params, opt_state = _build_small(tp2_mesh)
    kw = {"no_opt": variant == "no_opt", "fwd_only": variant == "fwd_only"}
    step = profile_r05.make_step(model, opt, tp2_mesh, specs, opt_specs,
                                 **kw)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    targets = jnp.roll(tokens, -1, axis=1)
    params, opt_state, loss = step(params, opt_state, tokens, targets)
    assert jnp.isfinite(jax.device_get(loss))


def test_no_opt_loss_matches_fwd_only(tp2_mesh):
    """The folded zero grad-sum must not perturb the loss value."""
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, 64)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = {}
    for variant in ("no_opt", "fwd_only"):
        # rebuild per variant: the step donates params/opt_state, and
        # init is keyed so both variants see identical values
        model, opt, specs, opt_specs, params, opt_state = _build_small(
            tp2_mesh)
        step = profile_r05.make_step(
            model, opt, tp2_mesh, specs, opt_specs,
            no_opt=variant == "no_opt", fwd_only=variant == "fwd_only",
        )
        _, _, loss = step(params, opt_state, tokens, targets)
        losses[variant] = float(jax.device_get(loss))
    assert losses["no_opt"] == pytest.approx(losses["fwd_only"], rel=1e-6)

"""amp cast decorators + model-parallel GradScaler tests (the reference's
test_basic_casts.py / test_promotion.py analog, SURVEY.md §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.amp import (
    bfloat16_function,
    float_function,
    half_function,
    promote_function,
    set_low_precision_dtype,
)
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.amp import GradScaler, model_parallel_all_finite


def dtype_probe(*args, **kwargs):
    return jax.tree.leaves((args, kwargs))[0].dtype


class TestCastDecorators:
    def teardown_method(self):
        set_low_precision_dtype(jnp.bfloat16)

    def test_half_function_default_bf16(self):
        f = half_function(dtype_probe)
        assert f(jnp.ones(3)) == jnp.bfloat16

    def test_half_function_fp16_mode(self):
        set_low_precision_dtype(jnp.float16)
        f = half_function(dtype_probe)
        assert f(jnp.ones(3)) == jnp.float16

    def test_float_function(self):
        f = float_function(dtype_probe)
        assert f(jnp.ones(3, jnp.bfloat16)) == jnp.float32

    def test_bfloat16_function(self):
        f = bfloat16_function(dtype_probe)
        assert f(jnp.ones(3, jnp.float32)) == jnp.bfloat16

    def test_promote_widest_wins(self):
        f = promote_function(dtype_probe)
        assert f(jnp.ones(3, jnp.bfloat16), jnp.ones(3, jnp.float32)) == (
            jnp.float32
        )

    def test_int_args_pass_through(self):
        @half_function
        def probe(x, i):
            return x.dtype, i.dtype

        xd, idt = probe(jnp.ones(3), jnp.arange(3))
        assert xd == jnp.bfloat16 and idt == jnp.int32

    def test_value_preserved(self):
        @float_function
        def add(a, b):
            return a + b

        out = add(jnp.ones(3, jnp.bfloat16), jnp.ones(3, jnp.bfloat16))
        np.testing.assert_allclose(np.asarray(out), 2.0)


class TestModelParallelGradScaler:
    def test_consensus_across_tp(self):
        """A rank-local overflow must veto the step on every rank
        (reference: apex/transformer/amp/grad_scaler.py:25-36)."""
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=4
        )
        try:
            scaler = GradScaler(axis_names=("tp",))
            state = scaler.init()

            def check(grads):
                # grads sharded over tp: only one rank sees the inf
                unscaled, finite = scaler.unscale(state, grads)
                return finite

            grads = jnp.zeros((4, 2)).at[2, 0].set(np.inf)
            finite = jax.jit(
                jax.shard_map(
                    check, mesh=mesh, in_specs=(P("tp"),), out_specs=P(),
                )
            )(grads)
            assert not bool(finite)

            finite_ok = jax.jit(
                jax.shard_map(
                    check, mesh=mesh, in_specs=(P("tp"),), out_specs=P(),
                )
            )(jnp.zeros((4, 2)))
            assert bool(finite_ok)
        finally:
            parallel_state.destroy_model_parallel()

    def test_all_finite_helper(self):
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2
        )
        try:
            def f(x):
                local_finite = jnp.all(jnp.isfinite(x))
                return model_parallel_all_finite(local_finite, ("tp",))

            x = jnp.zeros((2, 2)).at[1, 1].set(np.nan)
            out = jax.jit(
                jax.shard_map(f, mesh=mesh, in_specs=(P("tp"),),
                              out_specs=P())
            )(x)
            assert not bool(out)
        finally:
            parallel_state.destroy_model_parallel()


class TestCastLists:
    """Curated cast lists (reference: apex/amp/lists/torch_overrides.py:7-47):
    the blacklist keeps softmax/log/norm-class ops in fp32 under O1, the
    whitelist casts BLAS/conv ops to the low-precision dtype, and the
    O1<->O4 dtype flip reaches every wrapper."""

    def test_blacklist_keeps_fp32_under_o1(self):
        from apex_tpu.amp import cast_namespaces, set_low_precision_dtype

        set_low_precision_dtype(jnp.float16)  # O1
        try:
            ns = cast_namespaces()
            x16 = jnp.linspace(-4, 4, 64, dtype=jnp.float16)
            # softmax/log/sum run in fp32 even on fp16 inputs
            assert ns.nn.softmax(x16).dtype == jnp.float32
            assert ns.nn.log_softmax(x16).dtype == jnp.float32
            assert ns.numpy.log(jnp.abs(x16) + 1).dtype == jnp.float32
            assert ns.numpy.sum(x16).dtype == jnp.float32
            assert ns.numpy.power(jnp.abs(x16), 3.0).dtype == jnp.float32
            # fp32 internals, not just an output cast: exp of 12 overflows
            # fp16 (inf) but is finite in fp32
            big = jnp.asarray([12.0], jnp.float16)
            assert bool(jnp.isfinite(ns.numpy.exp(big))[0])
        finally:
            set_low_precision_dtype(jnp.bfloat16)

    def test_whitelist_casts_to_low_precision_and_flips(self):
        from apex_tpu.amp import cast_namespaces, set_low_precision_dtype

        ns = cast_namespaces()
        a = jnp.ones((8, 8), jnp.float32)
        set_low_precision_dtype(jnp.float16)  # O1
        try:
            assert ns.numpy.matmul(a, a).dtype == jnp.float16
            assert ns.numpy.einsum("ij,jk->ik", a, a).dtype == jnp.float16
            set_low_precision_dtype(jnp.bfloat16)  # O4
            assert ns.numpy.matmul(a, a).dtype == jnp.bfloat16
            assert ns.lax.dot(a, a).dtype == jnp.bfloat16
        finally:
            set_low_precision_dtype(jnp.bfloat16)

    def test_unlisted_passthrough(self):
        from apex_tpu.amp import cast_namespaces

        ns = cast_namespaces()
        x = jnp.ones((4,), jnp.float16)
        # not on any list → untouched dtype semantics
        assert ns.numpy.abs(x).dtype == jnp.float16
        assert ns.numpy.zeros((2,)).dtype == jnp.float32

    def test_promote_wrappers(self):
        from apex_tpu.amp import cast_namespaces

        ns = cast_namespaces()
        a = jnp.ones((4,), jnp.float16)
        b = jnp.ones((4,), jnp.float32)
        assert ns.numpy.add(a, b).dtype == jnp.float32
        assert ns.numpy.concatenate([a, b]).dtype == jnp.float32

    def test_patch_and_restore(self):
        from apex_tpu.amp import patch, set_low_precision_dtype

        orig = jnp.matmul
        a = jnp.ones((4, 4), jnp.float32)
        set_low_precision_dtype(jnp.bfloat16)
        with patch():
            assert jnp.matmul is not orig
            assert jnp.matmul(a, a).dtype == jnp.bfloat16
            assert jax.nn.softmax(a[0].astype(jnp.bfloat16)).dtype == jnp.float32
        assert jnp.matmul is orig
        assert jnp.matmul(a, a).dtype == jnp.float32

    def test_works_under_jit(self):
        from apex_tpu.amp import cast_namespaces

        ns = cast_namespaces()

        @jax.jit
        def f(a, b):
            h = ns.numpy.matmul(a, b)
            return ns.nn.softmax(h, axis=-1)

        out = f(jnp.ones((4, 8)), jnp.ones((8, 8)))
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out.sum(-1)), 1.0, rtol=1e-6)

"""amp cast decorators + model-parallel GradScaler tests (the reference's
test_basic_casts.py / test_promotion.py analog, SURVEY.md §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.amp import (
    bfloat16_function,
    float_function,
    half_function,
    promote_function,
    set_low_precision_dtype,
)
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.amp import GradScaler, model_parallel_all_finite


def dtype_probe(*args, **kwargs):
    return jax.tree.leaves((args, kwargs))[0].dtype


class TestCastDecorators:
    def teardown_method(self):
        set_low_precision_dtype(jnp.bfloat16)

    def test_half_function_default_bf16(self):
        f = half_function(dtype_probe)
        assert f(jnp.ones(3)) == jnp.bfloat16

    def test_half_function_fp16_mode(self):
        set_low_precision_dtype(jnp.float16)
        f = half_function(dtype_probe)
        assert f(jnp.ones(3)) == jnp.float16

    def test_float_function(self):
        f = float_function(dtype_probe)
        assert f(jnp.ones(3, jnp.bfloat16)) == jnp.float32

    def test_bfloat16_function(self):
        f = bfloat16_function(dtype_probe)
        assert f(jnp.ones(3, jnp.float32)) == jnp.bfloat16

    def test_promote_widest_wins(self):
        f = promote_function(dtype_probe)
        assert f(jnp.ones(3, jnp.bfloat16), jnp.ones(3, jnp.float32)) == (
            jnp.float32
        )

    def test_int_args_pass_through(self):
        @half_function
        def probe(x, i):
            return x.dtype, i.dtype

        xd, idt = probe(jnp.ones(3), jnp.arange(3))
        assert xd == jnp.bfloat16 and idt == jnp.int32

    def test_value_preserved(self):
        @float_function
        def add(a, b):
            return a + b

        out = add(jnp.ones(3, jnp.bfloat16), jnp.ones(3, jnp.bfloat16))
        np.testing.assert_allclose(np.asarray(out), 2.0)


class TestModelParallelGradScaler:
    def test_consensus_across_tp(self):
        """A rank-local overflow must veto the step on every rank
        (reference: apex/transformer/amp/grad_scaler.py:25-36)."""
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=4
        )
        try:
            scaler = GradScaler(axis_names=("tp",))
            state = scaler.init()

            def check(grads):
                # grads sharded over tp: only one rank sees the inf
                unscaled, finite = scaler.unscale(state, grads)
                return finite

            grads = jnp.zeros((4, 2)).at[2, 0].set(np.inf)
            finite = jax.jit(
                jax.shard_map(
                    check, mesh=mesh, in_specs=(P("tp"),), out_specs=P(),
                )
            )(grads)
            assert not bool(finite)

            finite_ok = jax.jit(
                jax.shard_map(
                    check, mesh=mesh, in_specs=(P("tp"),), out_specs=P(),
                )
            )(jnp.zeros((4, 2)))
            assert bool(finite_ok)
        finally:
            parallel_state.destroy_model_parallel()

    def test_all_finite_helper(self):
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2
        )
        try:
            def f(x):
                local_finite = jnp.all(jnp.isfinite(x))
                return model_parallel_all_finite(local_finite, ("tp",))

            x = jnp.zeros((2, 2)).at[1, 1].set(np.nan)
            out = jax.jit(
                jax.shard_map(f, mesh=mesh, in_specs=(P("tp"),),
                              out_specs=P())
            )(x)
            assert not bool(out)
        finally:
            parallel_state.destroy_model_parallel()

"""Modern-decoder (Llama-style) GPT mode: rope + RMSNorm + SwiGLU.

GPTConfig(position_embedding="rope", normalization="rmsnorm",
activation="swiglu") expresses the family on the same tp/pp/cp-ready
model; these tests pin the param-structure changes (no norm biases, a
gate projection) and the parallel parity the options must preserve.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.transformer import parallel_state

LLAMA_KW = dict(
    position_embedding="rope", normalization="rmsnorm",
    activation="swiglu",
)


def _cfg(**kw):
    base = dict(
        vocab_size=64, num_layers=2, hidden_size=32,
        num_attention_heads=4, max_position_embeddings=16,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
        **LLAMA_KW,
    )
    base.update(kw)
    return GPTConfig(**base)


def test_param_structure():
    mesh = parallel_state.initialize_model_parallel()
    try:
        model = GPTModel(_cfg())
        params = model.init(jax.random.PRNGKey(0))
        specs = model.param_specs()
        layer0 = jax.tree.map(lambda x: x, params["layers"])
        assert "bias" not in layer0["ln1"]
        assert "bias" not in params["final_ln"]
        assert "fc_gate" in layer0
        assert "pos_embedding" not in params
        # specs mirror the structure exactly
        assert (jax.tree.structure(params)
                == jax.tree.structure(
                    jax.tree.map(lambda s: 0, specs,
                                 is_leaf=lambda x: isinstance(x, P))))
    finally:
        parallel_state.destroy_model_parallel()


def test_swiglu_matches_dense_reference():
    """The sharded SwiGLU MLP equals the dense formula
    silu(x W_g) * (x W_1) @ W_2 computed from the gathered weights."""
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4
    )
    try:
        model = GPTModel(_cfg(num_layers=1))
        params = model.init(jax.random.PRNGKey(0))
        # perturb EVERY bias to nonzero — at the zero init the reference
        # formula would agree even if gate/up biases were mis-sharded or
        # dropped, making the parity check vacuous for them
        k = iter(jax.random.split(jax.random.PRNGKey(9), 16))
        params = jax.tree.map(
            lambda a: a + 0.1 * jax.random.normal(next(k), a.shape, a.dtype)
            if a.ndim == 2 and a.shape[0] == 1 else a,  # stacked biases
            params,
        )
        specs = model.param_specs()
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32))

        def mlp_only(prm, x):
            lp = jax.tree.map(lambda a: a[0], prm["layers"])
            y = (jax.nn.silu(model.fc_gate.apply(lp["fc_gate"], x))
                 * model.fc1.apply(lp["fc1"], x))
            return model.fc2.apply(lp["fc2"], y)

        got = jax.jit(jax.shard_map(
            mlp_only, mesh=mesh,
            in_specs=(specs, P()), out_specs=P(),
        ))(params, x)

        lp = jax.tree.map(lambda a: np.asarray(a[0]), params["layers"])
        wg, w1, w2 = (lp["fc_gate"]["weight"], lp["fc1"]["weight"],
                      lp["fc2"]["weight"])
        xn = np.asarray(x)

        def silu(a):
            return a / (1.0 + np.exp(-a))

        ref = (silu(xn @ wg + lp["fc_gate"]["bias"])
               * (xn @ w1 + lp["fc1"]["bias"])) @ w2 + lp["fc2"]["bias"]
        np.testing.assert_allclose(np.asarray(got), ref,
                                   rtol=2e-5, atol=2e-5)
    finally:
        parallel_state.destroy_model_parallel()


def test_tp_parity_and_training():
    losses = {}
    for tp in (1, 4):
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp
        )
        try:
            model = GPTModel(_cfg())
            params = model.init(jax.random.PRNGKey(0))
            specs = model.param_specs()
            tokens = jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, 64)
            targets = jnp.roll(tokens, -1, 1)
            fn = jax.jit(jax.shard_map(
                jax.value_and_grad(model.loss), mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=(P(), specs),
            ))
            loss, grads = fn(params, tokens, targets)
            assert all(bool(jnp.all(jnp.isfinite(g)))
                       for g in jax.tree.leaves(grads))
            losses[tp] = float(loss)
        finally:
            parallel_state.destroy_model_parallel()
    np.testing.assert_allclose(losses[1], losses[4], rtol=1e-5)


def test_pipeline_parity():
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2
    )
    try:
        model = GPTModel(_cfg())
        params = model.init(jax.random.PRNGKey(0))
        specs = model.param_specs()
        pp_specs = model.pipeline_param_specs()
        tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 64)
        targets = jnp.roll(tokens, -1, 1)
        serial = jax.jit(jax.shard_map(
            model.loss, mesh=mesh,
            in_specs=(specs, P("dp"), P("dp")), out_specs=P(),
        ))(params, tokens, targets)

        def pp_loss(prm, t, g):
            loss, _ = model.pipeline_1f1b_grads(prm, t, g, 2)
            return loss

        pp = jax.jit(jax.shard_map(
            pp_loss, mesh=mesh,
            in_specs=(pp_specs, P("dp"), P("dp")), out_specs=P(),
        ))(params, tokens, targets)
        np.testing.assert_allclose(float(serial), float(pp), rtol=1e-5)
    finally:
        parallel_state.destroy_model_parallel()


def test_validation_errors():
    with pytest.raises(ValueError, match="activation"):
        _cfg(activation="relu")
    with pytest.raises(ValueError, match="normalization"):
        _cfg(normalization="batchnorm")
    with pytest.raises(ValueError, match="MoE experts"):
        _cfg(num_experts=4)


def test_checkpoint_roundtrip(tmp_path):
    """The llama-mode params pytree (no norm biases, fc_gate leaves,
    no position table) survives the flat-blob checkpoint byte-exactly."""
    from apex_tpu import checkpoint

    mesh = parallel_state.initialize_model_parallel()
    try:
        model = GPTModel(_cfg())
        params = model.init(jax.random.PRNGKey(0))
        path = str(tmp_path / "llama.ckpt")
        checkpoint.save(path, {"params": params, "step": jnp.int32(7)})
        back = checkpoint.restore(path)
        assert int(back["step"]) == 7
        la, lb = jax.tree.leaves(params), jax.tree.leaves(back["params"])
        assert len(la) == len(lb)
        for a, b in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        parallel_state.destroy_model_parallel()

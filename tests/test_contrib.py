"""Contrib-tier tests: fused_dense, MLP, xentropy, multihead_attn, ASP,
transducer, FMHA — each against dense/analytic references, mirroring the
reference's extension suites (apex/contrib/test/)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.contrib.fmha import fmha
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
from apex_tpu.contrib.sparsity import ASP, create_mask
from apex_tpu.contrib.transducer import (
    TransducerJoint,
    transducer_loss,
)
from apex_tpu.contrib.xentropy import (
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense
from apex_tpu.mlp import MLP


class TestFusedDense:
    def test_forward_and_grad(self):
        layer = FusedDense(16, 8)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
        y = layer.apply(params, x)
        expected = x @ params["weight"] + params["bias"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected), rtol=1e-6)

        g = jax.grad(lambda p: jnp.sum(layer.apply(p, x) ** 2))(params)
        assert g["weight"].shape == (16, 8) and g["bias"].shape == (8,)

    def test_gelu_dense(self):
        layer = FusedDenseGeluDense(8, 32, 8)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
        y = layer.apply(params, x)
        h = jax.nn.gelu(x @ params["weight1"] + params["bias1"],
                        approximate=True)
        expected = h @ params["weight2"] + params["bias2"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected),
                                   rtol=1e-6)

    def test_no_bias_gelu_raises(self):
        with pytest.raises(RuntimeError):
            FusedDenseGeluDense(8, 32, 8, bias=False)


class TestMLP:
    def test_matches_chained_linear(self):
        mlp = MLP([16, 32, 8], activation="relu")
        params = mlp.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
        y = mlp.apply(params, x)
        h = jax.nn.relu(x @ params[0]["weight"] + params[0]["bias"])
        expected = h @ params[1]["weight"] + params[1]["bias"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected),
                                   rtol=1e-6)

    def test_bad_activation(self):
        with pytest.raises(TypeError):
            MLP([4, 4], activation="tanh")

    def test_vs_torch_reference(self):
        """Cross-check against torch.nn functional math (the reference's
        own test pattern, tests/L0/run_mlp/test_mlp.py)."""
        import torch

        mlp = MLP([8, 16, 4], activation="sigmoid")
        params = mlp.init(jax.random.PRNGKey(0))
        x = np.random.default_rng(0).normal(size=(3, 8)).astype(np.float32)
        y = mlp.apply(params, jnp.asarray(x))

        tx = torch.from_numpy(x)
        h = torch.sigmoid(
            tx @ torch.from_numpy(np.asarray(params[0]["weight"]))
            + torch.from_numpy(np.asarray(params[0]["bias"]))
        )
        ty = h @ torch.from_numpy(np.asarray(params[1]["weight"])) + \
            torch.from_numpy(np.asarray(params[1]["bias"]))
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=1e-5,
                                   atol=1e-6)


class TestXentropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_matches_analytic(self, smoothing):
        v = 32
        logits = jax.random.normal(jax.random.PRNGKey(0), (6, v))
        labels = jax.random.randint(jax.random.PRNGKey(1), (6,), 0, v)
        loss = softmax_cross_entropy_loss(logits, labels, smoothing)

        logp = np.asarray(jax.nn.log_softmax(logits))
        nll = -logp[np.arange(6), np.asarray(labels)]
        smooth = -logp.mean(axis=-1)
        expected = (1 - smoothing) * nll + smoothing * smooth
        np.testing.assert_allclose(np.asarray(loss), expected, rtol=1e-5)

    def test_grad_matches_autodiff_reference(self):
        v = 16
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, v))
        labels = jax.random.randint(jax.random.PRNGKey(1), (4,), 0, v)

        def custom(lo):
            return jnp.sum(softmax_cross_entropy_loss(lo, labels, 0.1))

        def ref(lo):
            logp = jax.nn.log_softmax(lo)
            nll = -jnp.take_along_axis(logp, labels[:, None], 1)[:, 0]
            return jnp.sum(0.9 * nll - 0.1 * logp.mean(axis=-1))

        np.testing.assert_allclose(
            np.asarray(jax.grad(custom)(logits)),
            np.asarray(jax.grad(ref)(logits)),
            rtol=1e-5, atol=1e-7,
        )

    def test_padding_idx(self):
        crit = SoftmaxCrossEntropyLoss(padding_idx=0)
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 8))
        labels = jnp.array([0, 1, 0, 3])
        losses = crit(logits, labels)
        assert float(losses[0]) == 0.0 and float(losses[2]) == 0.0
        assert float(losses[1]) > 0.0


class TestMultiheadAttn:
    def test_self_fast_vs_default(self):
        """The reference's own cross-check: impl='fast' vs impl='default'
        (apex/contrib/test/multihead_attn)."""
        s, b, h = 16, 2, 32
        x = jax.random.normal(jax.random.PRNGKey(1), (s, b, h))
        outs = {}
        for impl in ("default", "fast"):
            attn = SelfMultiheadAttn(h, 4, impl=impl)
            params = attn.init(jax.random.PRNGKey(0))
            outs[impl] = attn.apply(params, x, causal=True)
        np.testing.assert_allclose(
            np.asarray(outs["fast"]), np.asarray(outs["default"]),
            rtol=2e-4, atol=2e-5,
        )

    def test_self_norm_add(self):
        s, b, h = 8, 2, 16
        x = jax.random.normal(jax.random.PRNGKey(1), (s, b, h))
        attn = SelfMultiheadAttn(h, 4, include_norm_add=True, bias=True,
                                 impl="default")
        params = attn.init(jax.random.PRNGKey(0))
        y = attn.apply(params, x)
        # residual-add: zeroing the attention output weight leaves x
        params2 = dict(params, out_weight=jnp.zeros_like(params["out_weight"]),
                       out_bias=jnp.zeros_like(params["out_bias"]))
        y2 = attn.apply(params2, x)
        np.testing.assert_allclose(np.asarray(y2), np.asarray(x), atol=1e-6)
        assert not np.allclose(np.asarray(y), np.asarray(x))

    def test_self_key_padding_mask(self):
        s, b, h = 8, 2, 16
        x = jax.random.normal(jax.random.PRNGKey(1), (s, b, h))
        attn = SelfMultiheadAttn(h, 4, impl="default")
        params = attn.init(jax.random.PRNGKey(0))
        mask = jnp.zeros((b, s), bool).at[:, 4:].set(True)
        y_masked = attn.apply(params, x, key_padding_mask=mask)
        # changing masked-out keys must not change the output
        x2 = x.at[6].add(10.0)
        y_masked2 = attn.apply(params, x2, key_padding_mask=mask)
        np.testing.assert_allclose(
            np.asarray(y_masked[:4]), np.asarray(y_masked2[:4]), atol=1e-5
        )

    def test_encdec(self):
        sq, sk, b, h = 6, 10, 2, 16
        q = jax.random.normal(jax.random.PRNGKey(1), (sq, b, h))
        kv = jax.random.normal(jax.random.PRNGKey(2), (sk, b, h))
        for impl in ("default", "fast"):
            attn = EncdecMultiheadAttn(h, 4, impl=impl)
            params = attn.init(jax.random.PRNGKey(0))
            y = attn.apply(params, q, kv)
            assert y.shape == (sq, b, h)
            assert np.all(np.isfinite(np.asarray(y)))


class TestASP:
    def test_mask_is_2_4(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (8, 16))
        mask = create_mask(w)
        groups = np.asarray(mask).reshape(8, 4, 4)
        assert (groups.sum(-1) == 2).all()
        # keeps the two largest magnitudes per group
        wg = np.abs(np.asarray(w)).reshape(8, 4, 4)
        kept = np.where(groups, wg, -1)
        dropped = np.where(~groups, wg, np.inf)
        assert (kept.max(-1) >= dropped.min(-1) - 1e-7).all()

    def test_asp_end_to_end(self):
        params = {
            "dense": {"weight": jax.random.normal(jax.random.PRNGKey(0), (8, 16)),
                      "bias": jnp.ones((16,))},
            "ln": {"scale": jnp.ones((8,))},
        }
        asp = ASP()
        masks = asp.compute_sparse_masks(params)
        assert np.asarray(masks["ln"]["scale"]).all()  # ineligible → all-True
        assert np.asarray(masks["dense"]["bias"]).all()
        pruned = asp.apply_masks(params, masks)
        assert abs(ASP.sparsity({"w": masks["dense"]["weight"]}) - 0.5) < 1e-6
        assert (np.asarray(pruned["dense"]["weight"]) == 0).sum() == 64

        # wrapped optimizer step keeps sparsity
        from apex_tpu.optimizers import FusedAdam

        opt = FusedAdam(lr=0.1)
        state = opt.init(pruned)
        grads = jax.tree.map(jnp.ones_like, pruned)
        step = asp.wrap_optimizer_step(opt.step, masks)
        new_params, _ = step(state, grads, pruned)
        w = np.asarray(new_params["dense"]["weight"])
        assert (w == 0).sum() == 64

    @staticmethod
    def _brute_best_2d(block, m=4, n=2):
        """Exhaustive numpy search over all doubly-n:m 4x4 masks."""
        import itertools

        rows = [p for p in set(itertools.permutations([1] * n + [0] * (m - n)))]
        best, best_score = None, -1.0
        for combo in itertools.product(rows, repeat=m):
            cand = np.array(combo)
            if (cand.sum(0) > n).any():
                continue
            score = (np.abs(block) * cand).sum()
            if score > best_score:
                best, best_score = cand, score
        return best, best_score

    def test_2d_best_structure_and_optimality(self):
        from apex_tpu.contrib.sparsity import mn_2d_best

        w = jax.random.normal(jax.random.PRNGKey(3), (8, 8))
        mask = np.asarray(mn_2d_best(w))
        # doubly 2:4 — every 4-row and 4-col group of each block has 2 kept
        blocks = mask.reshape(2, 4, 2, 4).transpose(0, 2, 1, 3)
        assert (blocks.sum(-1) == 2).all()  # rows
        assert (blocks.sum(-2) == 2).all()  # cols
        # magnitude-optimal vs independent brute force, block by block
        wb = np.asarray(w).reshape(2, 4, 2, 4).transpose(0, 2, 1, 3)
        for i in range(2):
            for j in range(2):
                _, brute = self._brute_best_2d(wb[i, j])
                got = (np.abs(wb[i, j]) * blocks[i, j]).sum()
                assert got >= brute - 1e-5

    def test_2d_greedy_structure(self):
        from apex_tpu.contrib.sparsity import mn_2d_greedy

        w = jax.random.normal(jax.random.PRNGKey(4), (12, 8))
        mask = np.asarray(mn_2d_greedy(w))
        blocks = mask.reshape(3, 4, 2, 4).transpose(0, 2, 1, 3)
        assert (blocks.sum(-1) == 2).all() and (blocks.sum(-2) == 2).all()
        # greedy keeps the single largest |w| of every block (it is
        # visited first and nothing blocks it)
        wb = np.abs(np.asarray(w)).reshape(3, 4, 2, 4).transpose(0, 2, 1, 3)
        flat_idx = wb.reshape(6, 16).argmax(-1)
        kept = blocks.reshape(6, 16)
        assert all(kept[b, flat_idx[b]] for b in range(6))
        # non-divisible trailing rows stay dense
        w_odd = jax.random.normal(jax.random.PRNGKey(5), (6, 8))
        m_odd = np.asarray(mn_2d_greedy(w_odd))
        assert m_odd[4:].all()

    def test_mn_generalized(self):
        from apex_tpu.contrib.sparsity import mn_1d_best

        w = jax.random.normal(jax.random.PRNGKey(6), (4, 16))
        mask = np.asarray(mn_1d_best(w, m=8, n=4))
        assert (mask.reshape(4, 2, 8).sum(-1) == 4).all()

    def test_conv_hwio_mask(self):
        # 4d kernels prune along the input-channel axis (HWIO axis 2)
        w = jax.random.normal(jax.random.PRNGKey(7), (3, 3, 8, 16))
        mask = np.asarray(create_mask(w))
        assert mask.shape == w.shape
        assert (mask.sum(2) == 4).all()  # 2 of every 4 along I = 8 → 4 kept

    def test_prune_trained_model_lifecycle(self):
        from apex_tpu.contrib.sparsity import ASP, prune_trained_model
        from apex_tpu.optimizers import FusedAdam

        params = {"w": jax.random.normal(jax.random.PRNGKey(8), (8, 16)),
                  "b": jnp.ones((16,))}
        opt = FusedAdam(lr=0.1)
        pruned, masks, step = prune_trained_model(params, opt.step)
        assert (np.asarray(pruned["w"]) == 0).sum() == 64
        state = opt.init(pruned)
        grads = jax.tree.map(jnp.ones_like, pruned)
        new_params, _ = step(state, grads, pruned)
        assert (np.asarray(new_params["w"]) == 0).sum() == 64

        # dense restore round-trip (allow_recompute_mask analog)
        asp = ASP()
        residue = asp.extract_pruned(params, masks)
        restored = asp.restore_dense(pruned, masks, residue)
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.asarray(params["w"]))


def _brute_force_rnnt(logp, target, t_len, u_len, blank):
    """O(T·U) reference DP in numpy."""
    T, U1, _ = logp.shape
    alpha = np.full((T, U1), -np.inf)
    alpha[0, 0] = 0.0
    for t in range(T):
        for u in range(U1):
            cands = []
            if t == 0 and u == 0:
                continue
            if t > 0:
                cands.append(alpha[t - 1, u] + logp[t - 1, u, blank])
            if u > 0 and u - 1 < u_len:
                cands.append(alpha[t, u - 1] + logp[t, u - 1, target[u - 1]])
            alpha[t, u] = np.logaddexp.reduce(cands) if cands else -np.inf
    return -(alpha[t_len - 1, u_len] + logp[t_len - 1, u_len, blank])


class TestTransducer:
    def test_joint(self):
        f = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 8))
        g = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 8))
        joint = TransducerJoint(relu=True)
        h = joint(f, g)
        assert h.shape == (2, 5, 3, 8)
        expected = jax.nn.relu(f[:, :, None] + g[:, None, :])
        np.testing.assert_allclose(np.asarray(h), np.asarray(expected))

    def test_loss_matches_brute_force(self):
        rng = np.random.default_rng(0)
        B, T, U, V = 3, 6, 4, 8
        logits = jnp.asarray(rng.normal(size=(B, T, U + 1, V)).astype(np.float32))
        targets = jnp.asarray(rng.integers(1, V, (B, U)).astype(np.int32))
        f_len = jnp.array([6, 5, 4], jnp.int32)
        y_len = jnp.array([4, 3, 2], jnp.int32)
        loss = transducer_loss(logits, targets, f_len, y_len, blank_idx=0)

        logp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
        for i in range(B):
            expected = _brute_force_rnnt(
                logp[i], np.asarray(targets[i]), int(f_len[i]),
                int(y_len[i]), 0,
            )
            np.testing.assert_allclose(float(loss[i]), expected, rtol=1e-5)

    def test_loss_grad_finite(self):
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.normal(size=(2, 4, 3, 6)).astype(np.float32))
        targets = jnp.asarray(rng.integers(1, 6, (2, 2)).astype(np.int32))
        g = jax.grad(
            lambda lo: jnp.sum(
                transducer_loss(lo, targets, jnp.array([4, 3]),
                                jnp.array([2, 1]))
            )
        )(logits)
        assert np.all(np.isfinite(np.asarray(g)))


class TestFMHA:
    def test_varlen_matches_per_sequence(self):
        rng = np.random.default_rng(0)
        heads, d = 2, 16
        lens = [5, 9, 3]
        cu = jnp.asarray(np.cumsum([0] + lens).astype(np.int32))
        total = sum(lens)
        qkv = jnp.asarray(
            rng.normal(size=(total, 3, heads, d)).astype(np.float32)
        )
        out = fmha(qkv, cu, max_seq_len=16, causal=True)
        assert out.shape == (total, heads, d)

        from apex_tpu.ops.attention import mha_reference

        for i, L in enumerate(lens):
            seg = qkv[int(cu[i]) : int(cu[i + 1])]
            q, k, v = (
                jnp.moveaxis(seg[:, j], 1, 0)[None] for j in range(3)
            )  # (1, heads, L, d)
            expected = mha_reference(q, k, v, causal=True)[0]  # (h, L, d)
            got = out[int(cu[i]) : int(cu[i + 1])]  # (L, h, d)
            np.testing.assert_allclose(
                np.asarray(jnp.moveaxis(got, 0, 1)), np.asarray(expected),
                rtol=1e-5, atol=1e-6,
            )

    def test_varlen_flash_kernel_path(self):
        """fmha must ride the flash kernel (not a dense fallback):
        forced-pallas output equals the XLA route bit-for-tolerance."""
        rng = np.random.default_rng(1)
        heads, d = 2, 32
        lens = [7, 12, 4]
        cu = jnp.asarray(np.cumsum([0] + lens).astype(np.int32))
        total = sum(lens)
        qkv = jnp.asarray(
            rng.normal(size=(total, 3, heads, d)).astype(np.float32)
        )
        got = fmha(qkv, cu, max_seq_len=16, causal=True,
                   implementation="pallas")
        want = fmha(qkv, cu, max_seq_len=16, causal=True,
                    implementation="xla")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

"""Data-parallel runtime tests on the 8-device virtual CPU mesh
(reference analog: tests/distributed/DDP/ddp_race_condition_test.py and
tests/distributed/synced_batchnorm/ — same philosophy: smallest real
mesh, analytic expectations)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import (
    DistributedDataParallel,
    all_reduce_gradients,
    data_parallel_mesh,
    sync_batch_norm,
)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests require 8 virtual devices"
    return data_parallel_mesh()


class TestAllReduce:
    def test_grad_mean(self, mesh):
        grads = {"w": jnp.arange(8.0).reshape(8, 1)}

        f = jax.shard_map(
            lambda g: all_reduce_gradients(g, "dp"),
            mesh=mesh,
            in_specs=(P("dp"),),
            out_specs=P("dp"),
        )
        out = f(grads)
        # every shard gets the mean over the axis: mean(0..7) = 3.5
        np.testing.assert_allclose(np.asarray(out["w"]), 3.5)

    def test_no_average(self, mesh):
        grads = {"w": jnp.ones((8, 1))}
        f = jax.shard_map(
            lambda g: all_reduce_gradients(g, "dp", gradient_average=False),
            mesh=mesh,
            in_specs=(P("dp"),),
            out_specs=P("dp"),
        )
        out = f(grads)
        np.testing.assert_allclose(np.asarray(out["w"]), 8.0)

    def test_predivide_factor_is_mean_in_exact_arithmetic(self, mesh):
        grads = {"w": jnp.arange(8.0).reshape(8, 1)}
        f = jax.shard_map(
            lambda g: all_reduce_gradients(g, "dp", gradient_predivide_factor=2.0),
            mesh=mesh,
            in_specs=(P("dp"),),
            out_specs=P("dp"),
        )
        out = f(grads)
        np.testing.assert_allclose(np.asarray(out["w"]), 3.5, rtol=1e-6)

    def test_fp32_allreduce_of_bf16(self, mesh):
        grads = {"w": jnp.full((8, 1), 0.1, jnp.bfloat16)}
        f = jax.shard_map(
            lambda g: all_reduce_gradients(g, "dp", allreduce_always_fp32=True),
            mesh=mesh,
            in_specs=(P("dp"),),
            out_specs=P("dp"),
        )
        out = f(grads)
        assert out["w"].dtype == jnp.bfloat16


class TestDDP:
    def test_value_and_grad_matches_single_device(self, mesh):
        # analytic: loss = mean((x@w - y)^2); DP over batch must equal
        # the full-batch gradient computed on one device.
        rng = np.random.RandomState(0)
        w0 = rng.randn(4, 2).astype(np.float32)
        x = rng.randn(16, 4).astype(np.float32)
        y = rng.randn(16, 2).astype(np.float32)

        def loss_fn(params, batch):
            xb, yb = batch
            pred = xb @ params["w"]
            return jnp.mean(jnp.square(pred - yb))

        ddp = DistributedDataParallel(axis_name="dp")
        grad_fn = ddp.value_and_grad(loss_fn, mesh)
        params = {"w": jnp.asarray(w0)}
        loss, grads = grad_fn(params, (jnp.asarray(x), jnp.asarray(y)))

        ref_loss, ref_grads = jax.value_and_grad(loss_fn)(
            params, (jnp.asarray(x), jnp.asarray(y))
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads["w"]), np.asarray(ref_grads["w"]), rtol=1e-5,
            atol=1e-6,
        )


class TestSyncBatchNorm:
    def test_matches_full_batch_bn(self, mesh):
        # SyncBN over 8 shards == plain BN over the concatenated batch
        rng = np.random.RandomState(1)
        x = rng.randn(16, 6).astype(np.float32)
        w = rng.rand(6).astype(np.float32) + 0.5
        b = rng.randn(6).astype(np.float32)

        def local(xs):
            out, _, _ = sync_batch_norm(
                xs, jnp.asarray(w), jnp.asarray(b), None, None,
                training=True, axis_name="dp",
            )
            return out

        f = jax.shard_map(
            local, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")
        )
        out = np.asarray(f(jnp.asarray(x)))

        mean = x.mean(0)
        var = x.var(0)
        ref = (x - mean) / np.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_different_per_rank_batches_via_masking(self, mesh):
        # the stats use summed counts, matching the reference's support for
        # unequal per-rank batch sizes
        rng = np.random.RandomState(2)
        x = rng.randn(8, 3, 4).astype(np.float32)  # 8 ranks x 3 rows

        def local(xs):
            out, _, _ = sync_batch_norm(
                xs, None, None, None, None, training=True, axis_name="dp"
            )
            return out

        f = jax.shard_map(
            local, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")
        )
        out = np.asarray(f(jnp.asarray(x))).reshape(24, 4)
        flat = x.reshape(24, 4)
        ref = (flat - flat.mean(0)) / np.sqrt(flat.var(0) + 1e-5)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_group_size(self, mesh):
        # group_size=4: ranks 0-3 share stats, ranks 4-7 share stats
        x = np.zeros((8, 2, 2), np.float32)
        x[:4] = 1.0  # group 0 constant 1 → normalized output 0
        x[4:] = np.linspace(0, 1, 16).reshape(4, 2, 2)

        def local(xs):
            out, _, _ = sync_batch_norm(
                xs, None, None, None, None, training=True,
                axis_name="dp", process_group_size=4,
            )
            return out

        f = jax.shard_map(
            local, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")
        )
        out = np.asarray(f(jnp.asarray(x)))
        np.testing.assert_allclose(out[:4], 0.0, atol=1e-5)
        # group 1 normalized within itself
        g1 = x[4:].reshape(8, 2)
        ref = (g1 - g1.mean(0)) / np.sqrt(g1.var(0) + 1e-5)
        np.testing.assert_allclose(out[4:].reshape(8, 2), ref, rtol=1e-4, atol=1e-4)

    def test_running_stats_update(self):
        x = jnp.asarray(np.random.RandomState(3).randn(10, 4).astype(np.float32))
        rm = jnp.zeros((4,))
        rv = jnp.ones((4,))
        _, new_rm, new_rv = sync_batch_norm(
            x, None, None, rm, rv, training=True, momentum=0.1
        )
        xn = np.asarray(x)
        np.testing.assert_allclose(
            np.asarray(new_rm), 0.1 * xn.mean(0), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(new_rv),
            0.9 * 1.0 + 0.1 * xn.var(0, ddof=1),
            rtol=1e-5,
        )

    def test_eval_uses_running_stats(self):
        x = jnp.ones((4, 2))
        rm = jnp.asarray([1.0, 1.0])
        rv = jnp.asarray([1.0, 1.0])
        out, _, _ = sync_batch_norm(
            x, None, None, rm, rv, training=False
        )
        np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-5)


class TestConvertSyncBN:
    """Recursive BatchNorm -> SyncBatchNorm conversion
    (reference: apex/parallel/__init__.py:21-95)."""

    def _model(self):
        import flax.linen as nn

        class Block(nn.Module):
            feats: int
            norm: nn.Module = None

            @nn.compact
            def __call__(self, x, train):
                x = nn.Dense(self.feats)(x)
                x = self.norm(x, use_running_average=not train) \
                    if self.norm is not None else x
                return jax.nn.relu(x)

        class Net(nn.Module):
            block: nn.Module

            @nn.compact
            def __call__(self, x, train):
                x = self.block(x, train)
                return nn.Dense(4)(x)

        import flax.linen as nn2
        bn = nn2.BatchNorm(momentum=0.9, epsilon=1e-5)
        return Net(block=Block(feats=8, norm=bn))

    def test_recursive_swap_preserves_hparams(self):
        from apex_tpu.parallel import SyncBatchNorm, convert_syncbn_model

        net = self._model()
        conv = convert_syncbn_model(net, process_group_size=2)
        sbn = conv.block.norm
        assert isinstance(sbn, SyncBatchNorm)
        assert sbn.eps == 1e-5
        # flax momentum (ra decay) 0.9 -> torch-style update weight 0.1
        assert abs(sbn.momentum - 0.1) < 1e-9
        assert sbn.process_group_size == 2
        # untouched parts survive
        assert conv.block.feats == 8

    def test_converted_model_matches_full_batch_bn(self):
        """SyncBN over dp shards == plain BN over the full batch."""
        import flax.linen as nn

        from apex_tpu.parallel import convert_syncbn_model
        from apex_tpu.transformer import parallel_state

        mesh = parallel_state.initialize_model_parallel()
        try:
            net = self._model()
            conv = convert_syncbn_model(net)
            x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))

            ref_vars = net.init(jax.random.PRNGKey(1), x, train=True)
            out_ref, _ = net.apply(
                ref_vars, x, train=True, mutable=["batch_stats"]
            )

            conv_vars = conv.init(jax.random.PRNGKey(1), x, train=False)

            def fwd(v, xs):
                out, upd = conv.apply(
                    v, xs, train=True, mutable=["batch_stats"]
                )
                return out

            sharded = jax.jit(jax.shard_map(
                fwd, mesh=mesh,
                in_specs=(P(), P("dp")), out_specs=P("dp"),
                check_vma=False,
            ))
            out_sync = sharded(conv_vars, x)
            np.testing.assert_allclose(
                np.asarray(out_sync), np.asarray(out_ref),
                rtol=1e-5, atol=1e-5,
            )
        finally:
            parallel_state.destroy_model_parallel()

    def test_variables_rename(self):
        from apex_tpu.parallel import convert_syncbn_variables

        vars_in = {
            "params": {
                "bn": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
                # LayerNorm also has a 'scale' param but no running stats:
                # it must NOT be renamed
                "ln": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
                "dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.zeros((4,))},
            },
            "batch_stats": {
                "bn": {"mean": jnp.zeros((4,)), "var": jnp.ones((4,))},
            },
        }
        out = convert_syncbn_variables(vars_in)
        assert "weight" in out["params"]["bn"]
        assert "bias" in out["params"]["bn"]
        assert "scale" in out["params"]["ln"]      # LayerNorm untouched
        assert "weight" not in out["params"]["ln"]
        assert "kernel" in out["params"]["dense"]  # untouched
        assert "running_mean" in out["batch_stats"]["bn"]
        assert "running_var" in out["batch_stats"]["bn"]

    def test_scale_only_bn_refused(self):
        import flax.linen as nn

        from apex_tpu.parallel import convert_syncbn_model

        with pytest.raises(ValueError, match="use_scale"):
            convert_syncbn_model(nn.BatchNorm(use_scale=True, use_bias=False))


class TestReducer:
    """Deferred manual reduction (reference:
    apex/parallel/distributed.py:89-126): accumulating K microbatches
    locally then reducing once must equal the mean gradient over the
    full (axis world x K) batch."""

    def test_accumulate_then_reduce_matches_big_batch(self, mesh):
        from apex_tpu.parallel import Reducer

        w = jnp.asarray([[2.0], [1.0]])  # (2, 1)
        # per-device data: 8 devices x K=3 microbatches x 4 rows
        rng = np.random.default_rng(0)
        xs = jnp.asarray(rng.normal(size=(8, 3, 4, 2)), jnp.float32)
        ys = jnp.asarray(rng.normal(size=(8, 3, 4, 1)), jnp.float32)

        def loss(w, x, y):
            return jnp.mean((x @ w - y) ** 2)

        red = Reducer(axis_name="dp")

        def step(w, xs, ys):
            # xs: (1, 3, 4, 2) local shard.  pvary keeps per-device
            # grads LOCAL (grad wrt replicated w would already psum —
            # the transpose of the replicated->varying broadcast), so
            # there is something left to defer (Reducer docstring)
            w_local = jax.lax.pcast(w, "dp", to="varying")
            acc = red.init(w)
            for k in range(3):
                g = jax.grad(loss)(w_local, xs[0, k], ys[0, k])
                acc = red.accumulate(acc, g)
            mean_g, fresh = red.reduce(acc)
            # reset really is zero
            resid = sum(jnp.sum(jnp.abs(l))
                        for l in jax.tree.leaves(fresh["sum"]))
            return mean_g, jax.lax.pmax(resid, "dp")

        mean_g, resid = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P("dp"), P("dp")), out_specs=(P(), P()),
        ))(w, xs, ys)

        # reference: gradient of the mean loss over all 8*3 microbatches
        ref = jax.grad(
            lambda w: jnp.mean(jnp.stack([
                loss(w, xs[d, k], ys[d, k])
                for d in range(8) for k in range(3)
            ]))
        )(w)
        np.testing.assert_allclose(
            np.asarray(mean_g), np.asarray(ref), rtol=1e-5, atol=1e-6)
        assert float(resid) == 0.0

    def test_no_collective_during_accumulate(self, mesh):
        """accumulate is local: per-device sums differ across ranks
        until reduce runs."""
        from apex_tpu.parallel import Reducer

        red = Reducer(axis_name="dp")

        def step(x):
            acc = red.init(x[0])
            acc = red.accumulate(acc, x[0])
            # local sum equals the local shard — no cross-device mixing
            return jnp.sum(jnp.abs(acc["sum"] - x[0]))

        out = jax.jit(jax.shard_map(
            lambda x: jax.lax.psum(step(x), "dp"), mesh=mesh,
            in_specs=(P("dp"),), out_specs=P(),
        ))(jnp.arange(8.0).reshape(8, 1))
        assert float(out) == 0.0

    def test_gradient_average_false_returns_sum(self, mesh):
        """gradient_average=False: raw sum over (world x K) — the
        all_reduce_gradients sum semantics extended to accumulation."""
        from apex_tpu.parallel import Reducer

        red = Reducer(axis_name="dp", gradient_average=False)

        def step(x):
            acc = red.init(x[0])
            acc = red.accumulate(acc, x[0])
            acc = red.accumulate(acc, x[0])
            g, _ = red.reduce(acc)
            return g

        out = jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P("dp"),), out_specs=P(),
        ))(jnp.arange(8.0).reshape(8, 1))
        # sum over devices (0+..+7 = 28) x 2 accumulations
        assert float(out[0]) == 56.0

    def test_reference_scaling_flag(self, mesh):
        """average_over_microbatches=False reproduces the reference
        Reducer's scaling: mean over world, SUM over the K accumulated
        microbatches (the default deliberately deviates by also
        dividing by K — Reducer docstring)."""
        from apex_tpu.parallel import Reducer

        try:
            shard_map = jax.shard_map
        except AttributeError:  # jax 0.4.x spelling
            from jax.experimental.shard_map import shard_map

        ours = Reducer(axis_name="dp")
        ref = Reducer(axis_name="dp", average_over_microbatches=False)

        def step(x):
            outs = []
            for red in (ours, ref):
                acc = red.init(x[0])
                for _ in range(4):  # K=4 identical microbatches
                    acc = red.accumulate(acc, x[0])
                g, _ = red.reduce(acc)
                outs.append(g)
            return tuple(outs)

        g_ours, g_ref = jax.jit(shard_map(
            step, mesh=mesh, in_specs=(P("dp"),), out_specs=(P(), P()),
        ))(jnp.arange(8.0).reshape(8, 1))
        # mean over world of the per-device value 0..7 is 3.5
        assert float(g_ours[0]) == 3.5        # also averaged over K
        assert float(g_ref[0]) == 3.5 * 4     # reference: sum over K

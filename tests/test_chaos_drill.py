"""Slow-tier serving chaos drills (``tools/chaos_drill.py``).

Two legs, both scored by the drill's own zero-loss / token-identity
ledger and both asserting a PASSED stdout line plus exit 0:

- the in-process chaos matrix — replica kill, quarantine-by-faults,
  transient fault, brownout pressure, deadline/hedge scenario, and the
  < 2% journal-overhead gate, each compared token-for-token against an
  unfaulted reference replay;
- the SIGKILL restart drill — a real ``kill -9`` mid-serve (in-process
  mocks don't survive one), then a next life that restores the
  checkpoint seam, re-derives the quantized pool bit-identically,
  replays the durable journal and resumes every in-flight request
  token-identically.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(extra):
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "chaos_drill.py")]
        + extra,
        capture_output=True, text=True, timeout=560,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 PYTHONPATH=_REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")),
    )


def _ledger(stdout):
    for line in stdout.splitlines():
        if line.startswith("CHAOS "):
            return json.loads(line[len("CHAOS "):])
    raise AssertionError(f"no CHAOS ledger line in:\n{stdout}")


def test_chaos_matrix_in_process(tmp_path):
    proc = _run([])
    assert proc.returncode == 0, (
        f"chaos matrix failed:\n{proc.stdout}\n{proc.stderr}")
    assert "chaos drill PASSED" in proc.stdout
    led = _ledger(proc.stdout)
    assert led["zero_loss"] and led["token_identical"]
    sc = led["scenarios"]
    assert sc["nonfinite_quarantine"]["quarantined"] == "faults"
    assert sc["brownout"]["transitions"] >= 1
    assert sc["deadline_hedge"]["deadline_misses"] >= 1
    assert sc["deadline_hedge"]["hedges"] >= 1
    assert sc["journal_overhead"]["frac"] < 0.02


def test_chaos_restart_drill_sigkill_mid_serve(tmp_path):
    proc = _run(["--subprocess", "--root", str(tmp_path / "drill")])
    assert proc.returncode == 0, (
        f"restart drill failed:\n{proc.stdout}\n{proc.stderr}")
    assert "chaos drill PASSED" in proc.stdout
    led = _ledger(proc.stdout)
    assert led["zero_loss"] and led["token_identical"]
    assert led["replayed"]["resumed"] >= 1
    assert led["replayed"]["corrupt"] == 0

"""fmha-short (single-pass short-sequence attention) vs mha_reference.

The short kernel's parity contract matches the flash kernel's: values
and gradients within the existing flash tolerances, and BIT-IDENTICAL
dropout masks (both paths draw from the same counter-based hash).
Interpret mode runs the real kernel bodies on CPU.

Also pins the measured auto-dispatch: ``flash_attention`` routes to the
short kernel at/below the crossover (``FMHA_SHORT_MAX_SEQ``), to the
flash kernel above it, and keeps fp32 short sequences on their
measured XLA window.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import flash_attention, fmha_short, mha_reference
from apex_tpu.ops.attention_short import (
    FMHA_SHORT_MAX_BLOCK_BH,
    FMHA_SHORT_MAX_SEQ,
    default_block_bh,
    short_seq_threshold,
)


def _qkv(key, shape):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, shape), jax.random.normal(kk, shape),
            jax.random.normal(kv, shape))


class TestShortParity:
    """Sweep of the reference's fmha seqlen window {128,256,384,512}
    (+1024 in the slow tier) across causal/bias/segments/dropout."""

    @pytest.mark.parametrize("s", [128, 256, 384, 512])
    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_parity_swept_seqlens(self, s, causal):
        q, k, v = _qkv(jax.random.PRNGKey(s), (1, 2, s, 64))
        got = fmha_short(q, k, v, causal=causal, implementation="pallas")
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(got, want, atol=1e-5)

    @pytest.mark.parametrize("s", [128, 256])
    def test_grads_match_reference(self, s):
        q, k, v = _qkv(jax.random.PRNGKey(50 + s), (1, 2, s, 64))

        def f_short(q, k, v):
            return jnp.sum(fmha_short(
                q, k, v, causal=True, implementation="pallas", block_bh=2
            ) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

        g1 = jax.grad(f_short, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4)

    @pytest.mark.slow
    def test_parity_s1024(self):
        # above the default dispatch window but must still be correct
        # (the validation sweep times this shape to find the crossover)
        q, k, v = _qkv(jax.random.PRNGKey(1024), (1, 1, 1024, 64))

        def f_short(q, k, v):
            return jnp.sum(fmha_short(
                q, k, v, causal=True, implementation="pallas") ** 2)

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

        v1, g1 = jax.value_and_grad(f_short, argnums=(0, 1, 2))(q, k, v)
        v2, g2 = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(v1, v2, rtol=1e-5)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_unpadded_seq_and_head_dim(self):
        # seq not a lane multiple + head_dim < 128 exercises every pad
        # path (q rows, kv cols, lanes)
        q, _, _ = _qkv(jax.random.PRNGKey(23), (1, 2, 100, 40))
        _, k, v = _qkv(jax.random.PRNGKey(24), (1, 2, 72, 40))
        got = fmha_short(q, k, v, implementation="pallas")
        want = mha_reference(q, k, v)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_bh_packing_and_ragged_bh(self):
        # bh=6 with block_bh=4 pads the bh axis; results must match the
        # unpacked (block_bh=1) kernel bit-for-bit and the reference
        q, k, v = _qkv(jax.random.PRNGKey(25), (2, 3, 128, 64))
        packed = fmha_short(q, k, v, causal=True, implementation="pallas",
                            block_bh=4)
        single = fmha_short(q, k, v, causal=True, implementation="pallas",
                            block_bh=1)
        np.testing.assert_allclose(packed, single, atol=0)
        np.testing.assert_allclose(
            packed, mha_reference(q, k, v, causal=True), atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_segment_ids(self, causal):
        q, k, v = _qkv(jax.random.PRNGKey(26), (2, 2, 96, 64))
        seg = jnp.concatenate(
            [jnp.zeros((2, 40), jnp.int32), jnp.ones((2, 56), jnp.int32)],
            axis=1,
        )
        got = fmha_short(q, k, v, causal=causal, q_segment_ids=seg,
                         kv_segment_ids=seg, implementation="pallas",
                         block_bh=2)
        want = mha_reference(q, k, v, causal=causal, q_segment_ids=seg,
                             kv_segment_ids=seg)
        np.testing.assert_allclose(got, want, atol=1e-5)

    @pytest.mark.parametrize(
        "bias_shape", [(1, 1, 64, 64), (2, 1, 64, 64), (2, 2, 64, 64)]
    )
    def test_bias_broadcast_and_grad(self, bias_shape):
        q, k, v = _qkv(jax.random.PRNGKey(27), (2, 2, 64, 64))
        bias = jax.random.normal(jax.random.PRNGKey(28), bias_shape)

        def loss(fn, **kw):
            def f(q, k, v, bias):
                return jnp.sum(fn(q, k, v, bias=bias, **kw) ** 2)
            return f

        got = fmha_short(q, k, v, bias=bias, implementation="pallas",
                         block_bh=2)
        np.testing.assert_allclose(
            got, mha_reference(q, k, v, bias=bias), atol=1e-5)
        g1 = jax.grad(loss(fmha_short, implementation="pallas", block_bh=2),
                      argnums=(0, 1, 2, 3))(q, k, v, bias)
        g2 = jax.grad(loss(mha_reference), argnums=(0, 1, 2, 3))(
            q, k, v, bias)
        for a, b in zip(g1, g2):
            assert a.shape == b.shape
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_per_batch_bias_native_mode_odd_heads(self):
        # (b, 1, sq, sk) bias rides its native per-batch layout (no
        # h-times broadcast); h=5 forces the block_bh-divides-heads
        # clamp, and the dbias fold must return the (b, 1, sq, sk) shape
        q, k, v = _qkv(jax.random.PRNGKey(70), (3, 5, 64, 32))
        bias = jax.random.normal(jax.random.PRNGKey(71), (3, 1, 64, 64))

        def loss(fn, **kw):
            def f(q, k, v, bias):
                return jnp.sum(fn(q, k, v, bias=bias, causal=True,
                                  **kw) ** 2)
            return f

        got = fmha_short(q, k, v, bias=bias, causal=True,
                         implementation="pallas", block_bh=4)
        np.testing.assert_allclose(
            got, mha_reference(q, k, v, bias=bias, causal=True), atol=1e-5)
        g1 = jax.grad(loss(fmha_short, implementation="pallas",
                           block_bh=4), argnums=(0, 1, 2, 3))(q, k, v, bias)
        g2 = jax.grad(loss(mha_reference), argnums=(0, 1, 2, 3))(
            q, k, v, bias)
        for a, b in zip(g1, g2):
            assert a.shape == b.shape
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_constant_mask_bias_skips_dbias(self):
        q, k, v = _qkv(jax.random.PRNGKey(29), (1, 2, 32, 64))
        # keep the diagonal unmasked: a row with NO live causal entry is
        # degenerate (grad through it is convention-dependent, and the
        # single-pass and spread-then-zero softmaxes legitimately differ)
        keep = jnp.logical_or(
            jax.random.bernoulli(jax.random.PRNGKey(30), 0.8, (1, 1, 32, 32)),
            jnp.eye(32, dtype=bool),
        )
        bias = jnp.where(keep, 0.0, -1e30)

        def loss(q, k, v, bias):
            return jnp.sum(fmha_short(
                q, k, v, bias=bias, bias_requires_grad=False, causal=True,
                implementation="pallas",
            ) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, bias)

        def loss_ref(q, k, v):
            return jnp.sum(
                mha_reference(q, k, v, bias=bias, causal=True) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g[:3], gr):
            np.testing.assert_allclose(a, b, atol=1e-4)
        np.testing.assert_allclose(g[3], 0.0, atol=0)

    def test_dropout_bit_identical_mask(self):
        # same hash, same seed → identical mask across short / flash /
        # XLA — the mha_reference parity contract from the flash kernel
        # carried over bit-for-bit
        q, k, v = _qkv(jax.random.PRNGKey(31), (2, 2, 64, 64))
        kw = dict(dropout_rate=0.3, dropout_seed=1234)
        got = fmha_short(q, k, v, implementation="pallas", block_bh=4, **kw)
        want = mha_reference(q, k, v, **kw)
        np.testing.assert_allclose(got, want, atol=1e-5)
        again = fmha_short(q, k, v, implementation="pallas", block_bh=1, **kw)
        np.testing.assert_allclose(got, again, atol=1e-5)
        other = fmha_short(q, k, v, implementation="pallas", block_bh=4,
                           dropout_rate=0.3, dropout_seed=99)
        assert float(jnp.max(jnp.abs(got - other))) > 1e-3

    def test_dropout_gradients(self):
        q, k, v = _qkv(jax.random.PRNGKey(32), (1, 2, 64, 64))

        def loss(fn, **kw):
            def f(q, k, v):
                return jnp.sum(fn(
                    q, k, v, causal=True, dropout_rate=0.2, dropout_seed=7,
                    **kw) ** 2)
            return f

        g1 = jax.grad(loss(fmha_short, implementation="pallas", block_bh=2),
                      argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss(mha_reference), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4)

    @pytest.mark.slow
    def test_everything_composes(self):
        # segments + bias + dropout + causal + ragged seq + ragged bh
        q, k, v = _qkv(jax.random.PRNGKey(33), (2, 3, 50, 64))
        seg = (jnp.arange(50) // 20).astype(jnp.int32)[None, :].repeat(2, 0)
        bias = 0.1 * jax.random.normal(jax.random.PRNGKey(34), (2, 1, 50, 50))
        kwargs = dict(
            causal=True, bias=bias, q_segment_ids=seg, kv_segment_ids=seg,
            dropout_rate=0.1, dropout_seed=42,
        )
        got = fmha_short(q, k, v, implementation="pallas", block_bh=4,
                         **kwargs)
        want = mha_reference(q, k, v, **kwargs)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_explicit_pallas_raises_without_pallas(self, monkeypatch):
        from apex_tpu.ops import attention_short as mod
        from apex_tpu.ops.common import KernelLoweringError

        q = jnp.ones((1, 1, 8, 8))
        monkeypatch.setattr(mod, "pl", None)
        with pytest.raises(KernelLoweringError):
            mod.fmha_short(q, q, q, implementation="pallas")
        out = mod.fmha_short(q, q, q)  # auto degrades gracefully
        assert out.shape == (1, 1, 8, 8)


class TestBlockBhSizing:
    def test_budgeted_by_score_area(self):
        assert default_block_bh(128, 128, 64) == FMHA_SHORT_MAX_BLOCK_BH
        assert default_block_bh(512, 512, 64) == 2
        assert default_block_bh(1024, 1024, 64) == 1
        # never exceeds the actual bh
        assert default_block_bh(128, 128, 3) == 3


class TestShortDispatch:
    """Auto mode picks the short kernel at/below the measured crossover
    and the flash kernel above it; fp32 keeps its XLA window."""

    def _spy(self, monkeypatch):
        from apex_tpu.ops import attention as attn_mod
        from apex_tpu.ops import attention_short as short_mod
        from apex_tpu.utils import platform as plat

        calls = []

        def fake(tag):
            def f(q, *a, **kw):
                calls.append(tag)
                return jnp.zeros(q.shape, q.dtype)
            return f

        from apex_tpu.ops import attention_mid as mid_mod

        monkeypatch.setattr(attn_mod, "_flash_attention_pallas",
                            fake("flash"))
        monkeypatch.setattr(short_mod, "_fmha_short_pallas", fake("short"))
        monkeypatch.setattr(mid_mod, "_fmha_mid_pallas", fake("mid"))
        monkeypatch.setattr(plat, "_current_platform", lambda: "tpu")
        monkeypatch.delenv("APEX_TPU_DISABLE_PALLAS", raising=False)
        monkeypatch.delenv("APEX_TPU_STRICT_KERNELS", raising=False)
        monkeypatch.delenv("APEX_TPU_FMHA_SHORT_MAX_SEQ", raising=False)
        monkeypatch.delenv("APEX_TPU_FMHA_MID_MAX_SEQ", raising=False)
        return calls

    def test_bf16_below_crossover_picks_short(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 2, 256, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        assert calls == ["short"]

    def test_crossover_boundary_inclusive(self, monkeypatch):
        calls = self._spy(monkeypatch)
        s = FMHA_SHORT_MAX_SEQ
        q = jnp.ones((1, 1, s, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        assert calls == ["short"]

    def test_bf16_above_crossover_leaves_short(self, monkeypatch):
        # just above the short window the ladder's NEXT tier (the
        # pipelined mid kernel) takes over — never short
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, FMHA_SHORT_MAX_SEQ + 128, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        assert calls == ["mid"]

    def test_long_kv_disqualifies_short(self, monkeypatch):
        # cross-attention with short q but long kv: the whole-kv-in-one-
        # block premise fails, so a streaming tier must run (the mid
        # kernel here — kv sits at its window edge)
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, 256, 64), jnp.bfloat16)
        kv = jnp.ones((1, 1, 2048, 64), jnp.bfloat16)
        flash_attention(q, kv, kv)
        assert calls == ["mid"]

    def test_fp32_short_keeps_xla_window(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, 256, 64), jnp.float32)
        flash_attention(q, q, q)
        assert calls == []  # measured fp32 window still routes to XLA

    def test_explicit_short_honored_any_dtype(self, monkeypatch):
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, 256, 64), jnp.float32)
        flash_attention(q, q, q, implementation="short")
        assert calls == ["short"]

    def test_env_override_moves_crossover(self, monkeypatch):
        calls = self._spy(monkeypatch)
        monkeypatch.setenv("APEX_TPU_FMHA_SHORT_MAX_SEQ", "128")
        assert short_seq_threshold() == 128
        q = jnp.ones((1, 1, 256, 64), jnp.bfloat16)
        flash_attention(q, q, q)
        # shapes pushed out of the short window fall to the next tier
        assert calls == ["mid"]

    def test_explicit_pallas_still_means_flash(self, monkeypatch):
        # the strict flash request must not be silently re-routed
        calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, 256, 64), jnp.bfloat16)
        flash_attention(q, q, q, implementation="pallas")
        assert calls == ["flash"]


class TestContribWiring:
    """The short kernel is reachable through the reference-parity
    wrappers: contrib.fmha (packed varlen — the reference's exact
    seqlen window) and contrib.multihead_attn (attention_impl knob)."""

    def test_fmha_varlen_short_kernel(self):
        from apex_tpu.contrib.fmha import fmha

        key = jax.random.PRNGKey(60)
        lens = [24, 40]
        total, heads, d = sum(lens), 2, 64
        qkv = jax.random.normal(key, (total, 3, heads, d))
        cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
        got = fmha(qkv, cu, max_seq_len=64, causal=True,
                   implementation="short")
        want = fmha(qkv, cu, max_seq_len=64, causal=True,
                    implementation="xla")
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_self_mha_attention_impl_short(self):
        from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn

        x = jax.random.normal(jax.random.PRNGKey(61), (48, 2, 64))
        mha_s = SelfMultiheadAttn(64, 4, impl="fast",
                                  attention_impl="short")
        mha_d = SelfMultiheadAttn(64, 4, impl="default")
        params = mha_s.init(jax.random.PRNGKey(62))
        got = mha_s.apply(params, x, causal=True)
        want = mha_d.apply(params, x, causal=True)
        np.testing.assert_allclose(got, want, atol=1e-5)

"""Profiling subsystem tests."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu.pyprof import Timers, annotate, cost_analysis, summarize


def test_annotate_preserves_semantics():
    @annotate
    def f(x):
        return x * 2

    np.testing.assert_allclose(np.asarray(f(jnp.ones(3))), 2.0)
    np.testing.assert_allclose(
        np.asarray(jax.jit(f)(jnp.ones(3))), 2.0
    )


def test_annotate_names_hlo():
    @annotate(name="my_region")
    def f(x):
        return jnp.sin(x) + 1

    text = jax.jit(f).lower(jnp.ones(4)).as_text(debug_info=True)
    assert "my_region" in text


def test_cost_analysis_matmul_flops():
    def f(a, b):
        return a @ b

    a = jnp.ones((64, 64))
    costs = cost_analysis(f, a, a)
    # 2*M*N*K = 524288 flops for a 64^3 matmul
    assert costs.get("flops", 0) >= 2 * 64**3 * 0.9


def test_summarize_roofline():
    def f(a, b):
        return a @ b

    a = jnp.ones((128, 128))
    rep = summarize(f, a, a, peak_flops=1e12, peak_bandwidth=1e11)
    assert rep["flops"] > 0
    assert "compute_bound" in rep and "min_time_s" in rep
    assert rep["arithmetic_intensity"] > 0


def test_timers():
    timers = Timers()
    t = timers("fwd")
    t.start()
    x = jnp.ones((256, 256)) @ jnp.ones((256, 256))
    t.stop(barrier_on=x)
    assert timers("fwd").elapsed(reset=False) > 0
    log = timers.log()
    assert "fwd" in log
    # start/stop state machine guards
    t2 = timers("bwd")
    t2.start()
    try:
        t2.start()
        raised = False
    except AssertionError:
        raised = True
    assert raised


def test_parse_per_op_table(tmp_path):
    """Trace a jitted step, parse the xplane file into per-op rows
    (reference: apex/pyprof/parse/parse.py -> prof per-op tables)."""
    from apex_tpu.pyprof import op_table, parse, trace

    @jax.jit
    def step(x, w):
        return jnp.tanh(x @ w).sum()

    x = jnp.ones((128, 128))
    w = jnp.ones((128, 128))
    jax.block_until_ready(step(x, w))  # compile outside the trace
    log_dir = str(tmp_path / "trace")
    with trace(log_dir):
        for _ in range(3):
            jax.block_until_ready(step(x, w))

    rows = parse(log_dir)
    assert rows, "parse returned no rows"
    names = " ".join(r["name"] for r in rows)
    # the dot kernel must show up as a device event
    assert "dot" in names or "tanh" in names, names[:500]
    r0 = rows[0]
    assert r0["count"] >= 1 and r0["total_ms"] > 0
    assert abs(sum(r["pct"] for r in rows) - 100.0) < 1e-6
    # repeated events aggregate: some op should have count >= 3
    assert any(r["count"] >= 3 for r in rows)
    table = op_table(rows)
    assert "total ms" in table and rows[0]["name"][:20] in table


def test_parse_missing_dir_raises(tmp_path):
    from apex_tpu.pyprof import parse

    with pytest.raises(FileNotFoundError):
        parse(str(tmp_path / "nope"))


def test_classify_op_classes():
    """HLO names land in the reference-taxonomy op classes
    (reference: apex/pyprof/prof/ 27 op-class modules)."""
    from apex_tpu.pyprof import classify

    assert classify("%dot.12") == ("gemm", "compute")
    assert classify("fusion.3")[0] == "fusion"
    assert classify("while.2")[0] == "loop_control"
    assert classify("%copy-start.5 = (bf16[8,8,1024,128]{3,2,1,0}, u32[]{})")[0] == "copy_layout"
    assert classify("convert.9")[0] == "copy_layout"
    assert classify("all-reduce.1") == ("all_reduce", "collective")
    assert classify("collective-permute.7")[1] == "collective"
    assert classify("copy.2") == ("copy_layout", "memory")
    assert classify("convolution.4")[0] == "convolution"
    assert classify("flash_attention_fwd")[0] == "flash_attention"
    assert classify("threefry2x32")[0] == "rng"
    assert classify("mystery_kernel_xyz") == ("other", "other")


def test_prof_class_report(tmp_path):
    """parse → prof → per-class table with time-by-kind split
    (reference: python -m apex.pyprof.prof)."""
    from apex_tpu.pyprof import parse, prof, prof_table, trace

    @jax.jit
    def step(x, w):
        return jnp.tanh(x @ w).sum()

    x = jnp.ones((128, 128))
    w = jnp.ones((128, 128))
    jax.block_until_ready(step(x, w))
    log_dir = str(tmp_path / "trace")
    with trace(log_dir):
        for _ in range(3):
            jax.block_until_ready(step(x, w))

    classes = prof(parse(log_dir))
    assert classes, "prof returned no classes"
    by_name = {r["op_class"]: r for r in classes}
    # a matmul step must produce gemm (or fused) compute time
    assert "gemm" in by_name or "fusion" in by_name
    for r in classes:
        assert r["count"] >= 1 and r["total_ms"] >= 0 and r["ops"]
    assert abs(sum(r["pct"] for r in classes) - 100.0) < 1e-6
    table = prof_table(classes)
    assert "time by kind" in table and "class" in table


def test_trace_region_nesting(tmp_path):
    """Nested trace_region scopes — the pieces the telemetry
    TraceTrigger + phase spans reuse — compose: inner/outer names both
    land in the compiled HLO metadata, and the host-side annotation
    stack unwinds cleanly inside an active xplane capture."""
    from apex_tpu.pyprof import trace, trace_region

    def f(x):
        with trace_region("outer"):
            y = x @ x
            with trace_region("inner"):
                y = jnp.tanh(y)
        return y.sum()

    lowered = jax.jit(f).lower(jnp.ones((16, 16)))
    try:  # newer jax spells it debug_info=; 0.4.x has compiled HLO only
        text = lowered.as_text(debug_info=True)
    except TypeError:
        text = lowered.compile().as_text()
    assert "outer" in text and "inner" in text
    # named scopes nest: the inner op's metadata carries BOTH scopes
    assert "outer/inner" in text

    # host side: nested regions inside a live capture neither raise nor
    # leave the annotation stack unbalanced (a second capture works)
    x = jnp.ones((16, 16))
    jf = jax.jit(f)
    jax.block_until_ready(jf(x))
    for round_ in ("t1", "t2"):
        with trace(str(tmp_path / round_)):
            with trace_region("outer"):
                with trace_region("inner"):
                    jax.block_until_ready(jf(x))
        assert (tmp_path / round_).is_dir()


def test_cost_analysis_sharded_mesh_function():
    """cost_analysis on a shard_map'd (mesh) function — the sharded
    path the telemetry StepStats MFU model sits on top of; the seed
    suite only exercised single-device cost analysis."""
    from apex_tpu._compat import shard_map
    from apex_tpu.pyprof import cost_analysis, summarize
    from apex_tpu.transformer import parallel_state
    from jax.sharding import PartitionSpec as P

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel()
    try:
        dp = mesh.shape["dp"]
        N = 64

        def local_step(w, x):
            y = jnp.tanh(x @ w)
            return jax.lax.pmean(jnp.sum(y * y) / y.size, "dp")

        fn = shard_map(local_step, mesh=mesh,
                       in_specs=(P(), P("dp")), out_specs=P())
        w = jnp.ones((N, N))
        x = jnp.ones((8 * dp, N))
        costs = cost_analysis(fn, w, x)
        # the dominant matmul's flops must be visible through the
        # sharded lowering.  XLA's cost model prices the PER-DEVICE
        # program: 8 local rows x N x N, not the global batch —
        # multiply by device count for machine-scale numbers
        local_flops = 2 * 8 * N * N
        assert costs.get("flops", 0) >= local_flops * 0.9
        assert costs.get("flops", 0) < local_flops * dp
        rep = summarize(fn, w, x, peak_flops=1e12, peak_bandwidth=1e11)
        assert rep["flops"] > 0 and rep["bytes_accessed"] > 0
        assert "min_time_s" in rep
    finally:
        parallel_state.destroy_model_parallel()


def test_utilization_report(tmp_path):
    """trace -> prof -> utilization with cost analysis: the reference
    prof stage's FLOPs/efficiency columns (apex/pyprof/prof/)."""
    from apex_tpu.pyprof import cost_analysis, parse, prof, trace, utilization

    @jax.jit
    def step(x, w):
        return jnp.tanh(x @ w).sum()

    x = jnp.ones((256, 256))
    w = jnp.ones((256, 256))
    jax.block_until_ready(step(x, w))
    log_dir = str(tmp_path / "trace")
    steps = 4
    with trace(log_dir):
        for _ in range(steps):
            jax.block_until_ready(step(x, w))
    classes = prof(parse(log_dir))
    costs = cost_analysis(step, x, w)
    rep = utilization(classes, costs, peak_flops=1e12, steps=steps)
    assert rep["flops"] >= 2 * 256**3 * 0.9
    assert rep["compute_ms"] >= 0 and rep["achieved_flops_per_sec"] >= 0
    if rep["compute_ms"] > 0:
        assert "compute_utilization" in rep

"""Tensor-parallel decode: the sharded serving stack must be
token-identical to the tp=1 replicated reference.

The contract under test (docs/serving.md "Tensor-parallel decode"):
each tp shard owns its head slice of every layer's KV pool and 1/tp of
every projection's (quantized) weight pool, all shards see the SAME
page tables (one host free-list), and logits are gathered only at the
sampling seam — so greedy AND seeded generation, chunked prefill,
prefix-cache hits and speculation land on the very tokens the
replicated build produces, while each chip holds (and streams) a
1/tp-sized pool.  Zero-recompile and fleet behaviour must survive the
sharding unchanged.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.serving.kv_cache import (
    KVCacheConfig, PagedKVCache, init_pools,
)
from apex_tpu.serving.serve import ContinuousBatcher, Request
from apex_tpu.transformer import parallel_state

# int4 at tp=4 needs the per-shard projection slice divisible by
# 2*block: qkv streams 96 columns -> 24 per shard -> block 4
WQ_BLOCK = 4
NEW = 8


@pytest.fixture(scope="module")
def tp_setup():
    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    model = GPTModel(GPTConfig(
        vocab_size=64, num_layers=2, hidden_size=32,
        num_attention_heads=4, max_position_embeddings=64,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(3)
    prompts = rng.randint(1, 64, (4, 10)).astype(np.int32)
    plens = np.array([10, 8, 6, 9], np.int32)
    for i in range(4):
        prompts[i, plens[i]:] = 0
    yield model, params, prompts, plens
    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()


def _mesh(tp):
    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    return parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=tp, devices=jax.devices()[:tp])


def _gen(setup, tp, **kw):
    model, params, prompts, plens = setup
    mesh = _mesh(tp)
    return model.generate(params, prompts, plens, NEW, mesh=mesh,
                          page_size=4, **kw)


class TestTokenIdentity:
    def test_greedy_tp2_tp4_match_tp1(self, tp_setup):
        base = _gen(tp_setup, 1)
        assert _gen(tp_setup, 2) == base
        assert _gen(tp_setup, 4) == base

    def test_seeded_chunked_speculative_tp2_matches_tp1(self, tp_setup):
        # every decode seam at once: temperature sampling on the
        # per-slot key schedule, chunked prefill, prefix-cache hits on
        # the shared free-list page tables, Gumbel-coupled speculation
        kw = dict(temperature=0.8, top_k=8, key=jax.random.PRNGKey(7),
                  prefill_chunk=4, prefix_cache=True, speculate_k=3)
        assert _gen(tp_setup, 2, **kw) == _gen(tp_setup, 1, **kw)

    def test_int8_tp2_matches_tp1(self, tp_setup):
        kw = dict(weight_dtype="int8", weight_block=WQ_BLOCK)
        assert _gen(tp_setup, 2, **kw) == _gen(tp_setup, 1, **kw)

    def test_int4_tp4_matches_tp1(self, tp_setup):
        # tp=4 exercises the per-shard int4 nibble packing: each
        # shard's half-columns pair within the SHARD, not globally
        kw = dict(weight_dtype="int4", weight_block=WQ_BLOCK)
        assert _gen(tp_setup, 4, **kw) == _gen(tp_setup, 1, **kw)


def _fns(model, params, mesh, max_seqs=2, maxp=10, **kw):
    pps = -(-(maxp + NEW) // 4)
    ccfg = KVCacheConfig(
        num_layers=2, num_heads=4, head_dim=8,
        num_pages=1 + 2 * max_seqs * pps, page_size=4,
        max_seqs=max_seqs, pages_per_seq=pps, dtype=jnp.float32)
    return ccfg, model.decode_fns(params, mesh, ccfg,
                                  max_prompt_len=maxp, **kw)


class TestShardedBuild:
    def test_per_chip_weight_stream_bytes_shrink_and_tp_stamped(
            self, tp_setup):
        model, params, prompts, plens = tp_setup
        sizes = {}
        for tp in (1, 2):
            _, fns = _fns(model, params, _mesh(tp),
                          weight_dtype="int8", weight_block=WQ_BLOCK)
            assert fns.tp == tp
            # the decode callable carries the stamp the serving spans
            # (and metrics_report's GB/s/chip line) read
            assert fns.decode.tp == tp
            sizes[tp] = int(fns.weight_stream_bytes)
        # sharded leaves halve; embedding/norm full-precision leaves
        # shard too (vocab-parallel) so the drop is strictly real
        assert sizes[2] < sizes[1]

    def test_quantize_rejects_indivisible_tp_shards(self, tp_setup):
        model, params, _, _ = tp_setup
        from apex_tpu.models.gpt import quantize_gpt_weights
        # qkv n=96 -> 24/shard at tp=4: block 16 leaves no whole
        # int4 half-block pair per shard -> loud refusal, not garbage
        with pytest.raises(ValueError, match="qkv"):
            quantize_gpt_weights(params, "int4", 16, tp=4)

    def test_mesh_is_source_of_truth_for_tp(self, tp_setup):
        model, params, _, _ = tp_setup
        mesh = _mesh(2)
        with pytest.raises(ValueError, match="tp"):
            _fns(model, params, mesh, tp=4)


class TestZeroRecompile:
    def test_waves_reuse_compilations_at_tp2(self, tp_setup):
        """Ragged request waves through the sharded batcher must not
        recompile decode/chunk/verify — the fixed-shape contract is
        per (width, tp): one warmup compile each, then flat."""
        model, params, prompts, plens = tp_setup
        mesh = _mesh(2)
        ccfg, fns = _fns(model, params, mesh, weight_dtype="int8",
                         weight_block=WQ_BLOCK, prefill_chunk=4,
                         speculate_k=3)
        from apex_tpu.serving.speculate import NGramDraftSource

        def wave(uids, lens):
            batcher = ContinuousBatcher(
                fns.prefill, fns.decode, PagedKVCache(ccfg),
                init_pools(ccfg), max_prompt_len=10, harvest_every=2,
                chunk_fn=fns.chunk, prefill_chunk=4,
                spec_fn=fns.spec, speculate_k=3,
                draft_source=NGramDraftSource(3))
            reqs = [Request(uid=u, prompt=list(map(int, prompts[i][:l])),
                            max_new_tokens=NEW)
                    for i, (u, l) in enumerate(zip(uids, lens))]
            out = batcher.run(reqs)
            assert sorted(out) == sorted(uids)

        wave(["a", "b", "c"], [10, 8, 6])
        counts = {n: int(getattr(fns, n)._cache_size())
                  for n in ("decode_jit", "chunk_jit", "spec_jit")}
        wave(["d", "e", "f", "g"], [5, 9, 7, 10])   # new raggedness
        for n, c in counts.items():
            assert int(getattr(fns, n)._cache_size()) == c, n


class TestFleetTPGroup:
    def test_tp_group_replicas_complete_routed_trace_zero_loss(
            self, tp_setup):
        """A fleet replica backed by a tp=2 sharded batcher completes
        a routed trace with every request answered — FleetRouter is
        untouched by sharding (it sees batchers, not meshes)."""
        from apex_tpu.fleet import FleetRouter, Replica

        model, params, prompts, plens = tp_setup
        mesh = _mesh(2)
        ccfg, fns = _fns(model, params, mesh, prefill_chunk=4)
        reps = [
            Replica(f"r{i}", ContinuousBatcher(
                fns.prefill, fns.decode, PagedKVCache(ccfg),
                init_pools(ccfg), max_prompt_len=10, harvest_every=2,
                chunk_fn=fns.chunk, prefill_chunk=4,
                prefix_cache=True))
            for i in range(2)
        ]
        router = FleetRouter(reps)
        uids = []
        for i in range(6):
            u = f"q{i}"
            # replay headroom: prompt + max_new - 1 <= max_prompt_len
            p = list(map(int, prompts[i % 4][: min(int(plens[i % 4]), 7)]))
            assert router.submit(Request(uid=u, prompt=p,
                                         max_new_tokens=4))
            uids.append(u)
        router.drain()
        assert sorted(router.completions) == sorted(uids)
        assert all(len(router.completions[u].tokens) > 0 for u in uids)

        # and the routed trace is token-identical to an unsharded
        # single batcher serving the same requests
        mesh1 = _mesh(1)
        ccfg1, fns1 = _fns(model, params, mesh1, prefill_chunk=4)
        solo = ContinuousBatcher(
            fns1.prefill, fns1.decode, PagedKVCache(ccfg1),
            init_pools(ccfg1), max_prompt_len=10, harvest_every=2,
            chunk_fn=fns1.chunk, prefill_chunk=4)
        ref = solo.run([
            Request(uid=u,
                    prompt=list(map(int,
                                    prompts[i % 4][: min(int(plens[i % 4]), 7)])),
                    max_new_tokens=4)
            for i, u in enumerate(uids)])
        for u in uids:
            assert router.completions[u].tokens == ref[u].tokens

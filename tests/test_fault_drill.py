"""Slow-tier process-level resilience drills.

These cross a real process boundary — ``kill -9`` mid-``save_async``,
SIGABRT from the watchdog — which no in-process mock can exercise.
"""

import os
import signal
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fault_drill_kill9_mid_async_save(tmp_path):
    """Parent kills the toy trainer mid-save_async; the next life must
    resume from the last valid step with verified checksums (the drill
    asserts all of it and exits nonzero on any miss)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "fault_drill.py"),
         "--root", str(tmp_path / "drill"), "--steps", "6",
         "--kill-after-saves", "2", "--write-delay", "0.08"],
        capture_output=True, text=True, timeout=560,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, (
        f"drill failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "drill PASSED" in proc.stdout


def test_watchdog_abort_kills_stalled_process(tmp_path):
    """abort=True: a stalled loop dies by SIGABRT (so the scheduler
    requeues it) instead of hanging forever."""
    script = """
import time
from apex_tpu.resilience import Watchdog

wd = Watchdog(deadline_s=0.3, poll_s=0.05, abort=True).start()
print("STALLING", flush=True)
time.sleep(30)   # never beats; the watchdog must kill us long before
print("UNREACHABLE", flush=True)
"""
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 PYTHONPATH=_REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")),
    )
    assert proc.returncode == -signal.SIGABRT, (
        f"expected SIGABRT exit, got {proc.returncode}:\n{proc.stderr}"
    )
    assert "UNREACHABLE" not in proc.stdout
    assert "watchdog stack dump" in proc.stderr

"""AutoResume subsystem tests."""

import numpy as np
import jax.numpy as jnp

from apex_tpu.utils.autoresume import AutoResume


def test_fresh_start_then_resume(tmp_path):
    root = str(tmp_path / "run")
    ar = AutoResume(root, interval_steps=5, keep=2)
    state, step = ar.resume()
    assert state is None and step == 0

    # simulate a training loop
    for step in range(1, 13):
        state = {"w": jnp.full((3,), float(step)), "step": jnp.int32(step)}
        ar.maybe_save(step, state)

    # saved at 5 and 10; keep=2 → both present
    ar2 = AutoResume(root, interval_steps=5, keep=2)
    state, step = ar2.resume()
    assert step == 10
    np.testing.assert_array_equal(np.asarray(state["w"]), 10.0)


def test_gc_keeps_last_n(tmp_path):
    root = str(tmp_path / "run")
    ar = AutoResume(root, interval_steps=1, keep=2)
    for step in range(1, 6):
        ar.maybe_save(step, {"w": jnp.zeros(2)})
    import os

    dirs = sorted(os.listdir(root))
    assert dirs == ["step_4", "step_5"]


def test_termination_request_forces_save(tmp_path):
    root = str(tmp_path / "run")
    ar = AutoResume(root, interval_steps=1000, keep=1)
    assert not ar.maybe_save(3, {"w": jnp.zeros(2)})
    ar.request_termination()
    assert ar.termination_requested()
    assert ar.maybe_save(4, {"w": jnp.zeros(2)})
    _, step = AutoResume(root).resume()
    assert step == 4


def test_gc_ignores_tmp_husks(tmp_path):
    """A crashed atomic writer's step_<N>.tmp husk must not crash GC or
    count as a checkpoint (checkpoint.save writes into .tmp + rename)."""
    from apex_tpu.utils.autoresume import AutoResume

    ar = AutoResume(str(tmp_path), interval_steps=1, keep=2)
    for step in (1, 2, 3):
        ar.maybe_save(step, {"v": jnp.float32(step)})
    (tmp_path / "step_9.tmp").mkdir()  # simulated mid-write crash
    assert ar.maybe_save(4, {"v": jnp.float32(4)})  # _gc must not raise
    state, step = ar.resume()
    assert step == 4
    assert float(state["v"]) == 4.0

"""Fused layernorm tests vs analytic reference
(reference analog: tests/L0/run_fused_layer_norm/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization import (
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
)
from apex_tpu.ops.layer_norm import (
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm_affine,
    mixed_dtype_fused_layer_norm_affine,
)


def _ref_ln(x, w=None, b=None, eps=1e-5):
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    y = (x - mean) / np.sqrt(var + eps)
    if w is not None:
        y = y * w
    if b is not None:
        y = y + b
    return y


def test_forward_matches_reference():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6, 32).astype(np.float32)
    w = rng.randn(32).astype(np.float32)
    b = rng.randn(32).astype(np.float32)
    out = fused_layer_norm_affine(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), 32)
    np.testing.assert_allclose(np.asarray(out), _ref_ln(x, w, b), rtol=1e-5, atol=1e-5)


def test_non_affine():
    rng = np.random.RandomState(1)
    x = rng.randn(8, 16).astype(np.float32)
    out = fused_layer_norm(jnp.asarray(x), 16)
    np.testing.assert_allclose(np.asarray(out), _ref_ln(x), rtol=1e-5, atol=1e-5)


def test_multidim_normalized_shape():
    rng = np.random.RandomState(2)
    x = rng.randn(3, 4, 8).astype(np.float32)
    out = fused_layer_norm(jnp.asarray(x), (4, 8))
    ref = _ref_ln(x.reshape(3, 32)).reshape(3, 4, 8)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_gradients_match_autodiff():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(5, 24).astype(np.float32))
    w = jnp.asarray(rng.randn(24).astype(np.float32))
    b = jnp.asarray(rng.randn(24).astype(np.float32))

    def ours(x, w, b):
        return jnp.sum(jnp.sin(fused_layer_norm_affine(x, w, b, 24)))

    def ref(x, w, b):
        mean = jnp.mean(x, -1, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), -1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + 1e-5) * w + b
        return jnp.sum(jnp.sin(y))

    g1 = jax.grad(ours, argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(ref, argnums=(0, 1, 2))(x, w, b)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4)


def test_bf16_input_fp32_stats():
    rng = np.random.RandomState(4)
    x = rng.randn(16, 64).astype(np.float32)
    out_bf = fused_layer_norm(jnp.asarray(x, jnp.bfloat16), 64)
    assert out_bf.dtype == jnp.bfloat16
    ref = _ref_ln(x)
    np.testing.assert_allclose(
        np.asarray(out_bf, np.float32), ref, rtol=0.05, atol=0.05
    )


def test_mixed_dtype_output_follows_weight():
    x = jnp.ones((4, 8), jnp.bfloat16)
    w = jnp.ones((8,), jnp.float32)
    b = jnp.zeros((8,), jnp.float32)
    out = mixed_dtype_fused_layer_norm_affine(x, w, b, 8)
    assert out.dtype == jnp.float32


def test_rms_norm():
    rng = np.random.RandomState(5)
    x = rng.randn(6, 16).astype(np.float32)
    w = rng.randn(16).astype(np.float32)
    out = fused_rms_norm_affine(jnp.asarray(x), jnp.asarray(w), 16)
    ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-5) * w
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_pallas_interpret_matches_xla():
    from apex_tpu.ops.layer_norm import _ln_fwd_pallas, _ln_fwd_xla
    pytest.importorskip("jax.experimental.pallas")
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(16, 128).astype(np.float32))
    try:
        with jax.disable_jit(False):
            from jax.experimental import pallas as pl  # noqa: F401
            # interpret mode exercises the pallas kernel body on CPU
            import functools
            from jax.experimental import pallas as pl
            from apex_tpu.ops import layer_norm as L

            out_x, mean_x, inv_x = _ln_fwd_xla(x, 1e-5, False)
    except Exception:
        pytest.skip("pallas unavailable")
    np.testing.assert_allclose(
        np.asarray(out_x),
        _ref_ln(np.asarray(x)),
        rtol=1e-5,
        atol=1e-5,
    )


class TestModules:
    def test_fused_layer_norm_module(self):
        m = FusedLayerNorm(32)
        x = jnp.ones((2, 32))
        params = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(params, x)
        assert out.shape == (2, 32)
        np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-5)

    def test_mixed_module(self):
        m = MixedFusedLayerNorm(16, param_dtype=jnp.float32)
        x = jnp.ones((2, 16), jnp.bfloat16)
        params = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(params, x)
        assert out.dtype == jnp.float32

    def test_rms_module(self):
        m = FusedRMSNorm(16)
        x = jnp.ones((2, 16))
        params = m.init(jax.random.PRNGKey(0), x)
        assert "bias" not in params["params"]
        out = m.apply(params, x)
        assert out.shape == (2, 16)


class TestKernelFallbackPolicy:
    """A Pallas lowering failure must be loud where it matters
    (VERDICT r2: no silent kernel regressions)."""

    def _broken(self, monkeypatch):
        from apex_tpu.ops import layer_norm as ln

        def boom(*a, **k):
            raise RuntimeError("mosaic lowering exploded")

        monkeypatch.setattr(ln, "_ln_fwd_pallas", boom)

    def test_explicit_pallas_raises(self, monkeypatch):
        from apex_tpu.ops.common import KernelLoweringError

        self._broken(monkeypatch)
        x = jnp.ones((4, 64))
        with pytest.raises(KernelLoweringError):
            fused_layer_norm(x, 64, implementation="pallas")

    def test_strict_env_raises_in_auto_mode(self, monkeypatch):
        # flash attention is the kernel whose auto mode resolves to
        # pallas on TPU (layernorm/softmax auto-route to XLA by
        # measurement, so strict mode does not apply to them)
        from apex_tpu.ops import attention as attn_mod
        from apex_tpu.ops.common import KernelLoweringError
        from apex_tpu.utils import platform as plat

        def boom(*a, **k):
            raise RuntimeError("mosaic lowering exploded")

        monkeypatch.setattr(attn_mod, "_flash_attention_pallas", boom)
        monkeypatch.setattr(plat, "_current_platform", lambda: "tpu")
        monkeypatch.delenv("APEX_TPU_DISABLE_PALLAS", raising=False)
        monkeypatch.setenv("APEX_TPU_STRICT_KERNELS", "1")
        # bf16: fp32 short-seq auto-routes to XLA by measurement and
        # would never reach the pallas machinery under test
        q = jnp.ones((1, 1, 8, 8), jnp.bfloat16)
        with pytest.raises(KernelLoweringError):
            attn_mod.flash_attention(q, q, q, implementation=None)

    def test_auto_mode_falls_back_with_warning(self, monkeypatch, caplog):
        import logging

        from apex_tpu.ops import attention as attn_mod
        from apex_tpu.utils import platform as plat

        def boom(*a, **k):
            raise RuntimeError("mosaic lowering exploded")

        monkeypatch.setattr(attn_mod, "_flash_attention_pallas", boom)
        monkeypatch.setattr(plat, "_current_platform", lambda: "tpu")
        monkeypatch.delenv("APEX_TPU_DISABLE_PALLAS", raising=False)
        monkeypatch.delenv("APEX_TPU_STRICT_KERNELS", raising=False)
        q = jax.random.normal(
            jax.random.PRNGKey(0), (1, 1, 8, 8), jnp.bfloat16
        )
        with caplog.at_level(logging.WARNING, logger="apex_tpu"):
            out = attn_mod.flash_attention(q, q, q, implementation=None)
        assert any("falling back to XLA" in r.message for r in caplog.records)
        want = attn_mod.mha_reference(q, q, q)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(want, np.float32),
            atol=1e-2,
        )

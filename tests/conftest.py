"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's test philosophy (SURVEY.md §4): smallest real
world size, analytic expectations.  Multi-"chip" behaviour is tested on
8 virtual CPU devices via XLA host-platform device count.
"""

import os

# force CPU: the suite relies on 8 virtual devices regardless of what the
# surrounding environment selected (e.g. a live TPU via JAX_PLATFORMS=axon)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

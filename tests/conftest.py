"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's test philosophy (SURVEY.md §4): smallest real
world size, analytic expectations.  Multi-"chip" behaviour is tested on
8 virtual CPU devices via XLA host-platform device count.

The environment may pre-register a TPU PJRT plugin at interpreter start
(sitecustomize) and force ``jax_platforms`` to prefer it; backend
discovery would then dial the TPU from every test process.  Overriding
at the *config* level (not just the env var) wins over that hook, and
XLA_FLAGS must be set before the first backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Auto-apply the ``l0`` mark to everything not marked ``slow`` so
    ``pytest -m l0`` is the fast tier and ``pytest`` (no -m) the full
    suite — the reference's L0/L1 test tiering
    (/root/reference/tests/L0/run_test.py:1-29)."""
    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.l0)

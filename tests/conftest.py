"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's test philosophy (SURVEY.md §4): smallest real
world size, analytic expectations.  Multi-"chip" behaviour is tested on
8 virtual CPU devices via XLA host-platform device count.

The environment may pre-register a TPU PJRT plugin at interpreter start
(sitecustomize) and force ``jax_platforms`` to prefer it; backend
discovery would then dial the TPU from every test process.  Overriding
at the *config* level (not just the env var) wins over that hook, and
XLA_FLAGS must be set before the first backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import signal  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402

# Long-running tests (measured: tests/run_tests.sh keeps `-m l0` around
# 7 min for 283 tests on a 1-core host, r5; full-suite --durations
# picked these).  Whole
# modules are marked in-file (test_cross_product — the L1-style tier —
# test_combined_axes); individual heavyweights live here so the split
# stays visible in one place.
SLOW_TESTS = {
    "test_moe_aux_threads_through_pipeline",
    "test_encdec_fused_1f1b_grads_match_gpipe_pp4",
    "test_ring_grads_match_dense",
    "test_no_pipelining_matches_serial",
    "test_varlen_matches_per_sequence",
    "test_loss_grad_finite",
    "test_flash_kernels_fwd_bwd",
    "test_example_runs",
    "test_resnet50_builds",
    "test_forward_shapes_and_stats_update",
    "test_sync_bn_matches_single_device",
    "test_t5_pipeline_matches_sequential",
    "test_t5_pipeline_grads_matches_gpipe",
    "test_t5_loss_tp_invariant",
    "test_t5_grads_finite",
    "test_bert_loss_tp_invariant",
    "test_bert_pipeline_matches_sequential",
    "test_bert_pipeline_grads_matches_sequential",
    "test_gpt_1f1b_matches_gpipe_pipeline",
    "test_gpt_interleaved_1f1b_matches_gpipe_pipeline",
    "test_gpt_pipeline_matches_non_pipeline",
    "test_gpt_moe_trains",
    "test_pipeline_matches_serial",
    "test_1f1b_matches_serial",
    "test_1f1b_interleaved_matches_serial",
    "test_interleaved_pipeline_matches_serial",
    "test_gpt_context_parallel_matches_dense",
    "test_bias_broadcast_and_grad",
    "test_gradient_matches_naive",
    "test_segment_ids_gradients",
    "test_bias_with_causal_grad",
    "test_padding_mask",
    "test_constant_mask_bias_skips_dbias",
    "test_everything_composes",
    "test_ep_matches_dense",
}


# Per-test timeout for the slow tier: the full 387-test suite runs on a
# 1-core gate host, where one wedged collective or runaway compile in a
# slow test would otherwise eat the whole suite budget (VERDICT r5).
# SIGALRM-based (no pytest-timeout in the image): the handler raises in
# the main thread at the next bytecode boundary, which bounds every
# pure-Python/jit-dispatch hang; override with
# APEX_TPU_SLOW_TEST_TIMEOUT (seconds, 0 disables).
SLOW_TEST_TIMEOUT_S = int(os.environ.get("APEX_TPU_SLOW_TEST_TIMEOUT",
                                         "600"))


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    usable = (
        SLOW_TEST_TIMEOUT_S > 0
        and "slow" in item.keywords
        and hasattr(signal, "SIGALRM")
        and threading.current_thread() is threading.main_thread()
    )
    if not usable:
        yield
        return

    def _alarm(signum, frame):
        raise TimeoutError(
            f"slow-tier test exceeded the {SLOW_TEST_TIMEOUT_S}s "
            "per-test timeout (APEX_TPU_SLOW_TEST_TIMEOUT to adjust)"
        )

    old = signal.signal(signal.SIGALRM, _alarm)
    signal.setitimer(signal.ITIMER_REAL, SLOW_TEST_TIMEOUT_S)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


def pytest_collection_modifyitems(config, items):
    """Auto-apply the ``l0`` mark to everything not marked ``slow`` so
    ``pytest -m l0`` is the fast tier and ``pytest`` (no -m) the full
    suite — the reference's L0/L1 test tiering
    (/root/reference/tests/L0/run_test.py:1-29)."""
    for item in items:
        if item.originalname in SLOW_TESTS or item.name in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.l0)

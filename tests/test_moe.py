"""Expert-parallel MoE tests: ep-sharded == dense, routing behaviour."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.moe import MoEMLP

H, F, E = 16, 32, 8
N = 32  # global tokens (b=8, s=4)


def build(mesh, layer):
    specs = layer.param_specs()

    def fwd(params, x):
        out, aux = layer.apply(params, x)
        return out, jax.lax.pmean(aux, "dp")

    fn = jax.jit(
        jax.shard_map(
            fwd, mesh=mesh,
            in_specs=(specs, P("dp")),
            out_specs=(P("dp"), P()),
        )
    )
    return fn, specs


def place(mesh, tree, specs):
    return jax.device_put(
        tree, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                           is_leaf=lambda x: isinstance(x, P))
    )


def test_ep_matches_dense():
    """ep=8-sharded MoE == the same params applied densely, when the
    capacity is large enough that nothing drops."""
    layer = MoEMLP(H, F, E, capacity_factor=float(E))  # no drops
    params = layer.init(jax.random.PRNGKey(0))
    x = 0.5 * jax.random.normal(jax.random.PRNGKey(1), (8, 4, H))

    # dense: dp=1 mesh (cp soaks up the devices)
    mesh = parallel_state.initialize_model_parallel(context_parallel_size_=8)
    try:
        fn, specs = build(mesh, layer)
        ref, ref_aux = fn(params, x)
        ref, ref_aux = np.asarray(ref), float(ref_aux)
    finally:
        parallel_state.destroy_model_parallel()

    # expert-parallel: dp=8, experts sharded across ranks
    mesh = parallel_state.initialize_model_parallel()
    try:
        fn, specs = build(mesh, layer)
        placed = place(mesh, params, specs)
        out, aux = fn(placed, x)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=1e-5)
        # aux loss is per-shard routing stats; just sanity it
        assert np.isfinite(float(aux))
    finally:
        parallel_state.destroy_model_parallel()


def test_capacity_drops_tokens():
    """With a tiny capacity most tokens get zero output (residual path)."""
    mesh = parallel_state.initialize_model_parallel(context_parallel_size_=8)
    try:
        layer = MoEMLP(H, F, E, capacity_factor=0.25)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, H))
        fn, specs = build(mesh, layer)
        out, _ = fn(params, x)
        flat = np.asarray(out).reshape(-1, H)
        zero_rows = np.sum(np.all(flat == 0, axis=-1))
        assert zero_rows > 0  # overflow tokens dropped
        assert zero_rows < flat.shape[0]  # but not all
    finally:
        parallel_state.destroy_model_parallel()


def test_moe_trains_and_grads_are_per_expert():
    """End-to-end: grads flow, expert grads differ across ep ranks, and
    a few SGD steps reduce the loss."""
    mesh = parallel_state.initialize_model_parallel()
    try:
        layer = MoEMLP(H, F, E, capacity_factor=8.0)
        params = layer.init(jax.random.PRNGKey(0))
        specs = layer.param_specs()
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, H))
        y = jax.random.normal(jax.random.PRNGKey(2), (8, 4, H))

        def loss_fn(params, x, y):
            out, aux = layer.apply(params, x)
            mse = jnp.mean((out - y) ** 2)
            return jax.lax.pmean(mse, "dp") + 0.01 * jax.lax.pmean(aux, "dp")

        step = jax.jit(
            jax.shard_map(
                lambda p, x, y: jax.value_and_grad(loss_fn)(p, x, y),
                mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=(P(), specs),
            )
        )
        placed = place(mesh, params, specs)
        losses = []
        for _ in range(200):
            loss, grads = step(placed, x, y)
            losses.append(float(loss))
            placed = jax.tree.map(lambda p, g: p - 1.0 * g, placed, grads)
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0] * 0.9
        # expert grads are ep-sharded arrays of global shape (E, ...)
        g_w1 = grads["w1"]
        assert g_w1.shape == (E, H, F)
    finally:
        parallel_state.destroy_model_parallel()


def _dense_topk_reference(layer, params, x, k):
    """Token-by-token numpy mixture: Σ_{i<=k} gate_i * FFN_{e_i}(x)."""
    b, s, h = x.shape
    flat = np.asarray(x).reshape(-1, h)
    w_r = np.asarray(params["router"]["weight"], np.float32)
    w1 = np.asarray(params["w1"], np.float32)
    w2 = np.asarray(params["w2"], np.float32)
    logits = flat.astype(np.float32) @ w_r
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    out = np.zeros_like(flat, dtype=np.float32)
    for t in range(flat.shape[0]):
        idx = np.argsort(-probs[t])[:k]
        g = probs[t, idx]
        if k > 1:
            g = g / g.sum()
        for e, gi in zip(idx, g):
            h1 = flat[t] @ w1[e]
            h1 = 0.5 * h1 * (1 + np.tanh(
                np.sqrt(2 / np.pi) * (h1 + 0.044715 * h1 ** 3)))
            out[t] += gi * (h1 @ w2[e])
    return out.reshape(b, s, h)


def test_top2_matches_dense_mixture():
    """top_k=2 ep-sharded routing == the dense 2-expert mixture
    (GShard/Mixtral convention: renormalized top-2 gates), capacity
    large enough that nothing drops, on the 8-device mesh."""
    layer = MoEMLP(H, F, E, top_k=2, capacity_factor=float(E))
    params = layer.init(jax.random.PRNGKey(2))
    x = 0.5 * jax.random.normal(jax.random.PRNGKey(3), (8, 4, H))
    ref = _dense_topk_reference(layer, params, x, k=2)

    mesh = parallel_state.initialize_model_parallel()  # dp=8 = ep
    try:
        fn, specs = build(mesh, layer)
        placed = place(mesh, params, specs)
        out, aux = fn(placed, x)
        np.testing.assert_allclose(
            np.asarray(out), ref, rtol=2e-4, atol=2e-5
        )
        assert np.isfinite(float(aux))
    finally:
        parallel_state.destroy_model_parallel()


def test_top2_capacity_priority():
    """Choice-major priority: every 1st choice outranks every 2nd choice
    for capacity (GShard ordering).  Alternating-preference setup with
    cap=2 per expert: choice-major keeps tokens {0,2} on expert 0 and
    {1,3} on expert 1 (all 1st choices); token-major order would keep
    {0,1} on both instead — so tokens 2 and 3 surviving, and 4-7
    dropping, pins the ordering."""
    E2, k, n = 2, 2, 8
    # cap = int(cf * k * n / E) = 2
    layer = MoEMLP(H, F, E2, top_k=2, capacity_factor=0.25)
    params = layer.init(jax.random.PRNGKey(4))
    # router reads feature 0: even tokens prefer e0, odd prefer e1
    params["router"]["weight"] = (
        jnp.zeros((H, 2)).at[0, 0].set(1.0).at[0, 1].set(-1.0)
    )
    # distinguishable experts: e0 ≈ +gelu(x), e1 ≈ -gelu(x)
    eye = jnp.eye(H)
    w1 = jnp.zeros((E2, H, F)).at[:, :, :H].set(eye[None])
    w2 = jnp.zeros((E2, F, H))
    w2 = w2.at[0, :H, :].set(eye).at[1, :H, :].set(-eye)
    params = {**params, "w1": w1, "w2": w2}

    # token t: feature0 = +1 (even) / -1 (odd), rest 0.3
    flat = jnp.full((n, H), 0.3)
    flat = flat.at[:, 0].set(jnp.where(jnp.arange(n) % 2 == 0, 1.0, -1.0))
    x = flat.reshape(2, 4, H)

    mesh = parallel_state.initialize_model_parallel(context_parallel_size_=8)
    try:
        fn, specs = build(mesh, layer)
        out, aux = fn(params, x)
        s = np.asarray(out).reshape(n, H).sum(-1)
        # tokens 0,2 kept on e0 (+), 1,3 on e1 (−); 4-7 fully dropped
        assert s[0] > 1e-3 and s[2] > 1e-3, s
        assert s[1] < -1e-3 and s[3] < -1e-3, s
        np.testing.assert_allclose(s[4:], 0.0, atol=1e-6)
    finally:
        parallel_state.destroy_model_parallel()


def test_router_z_loss():
    """router_z_loss_weight adds mean(logsumexp²) to the aux scalar."""
    base = MoEMLP(H, F, E, top_k=2)
    withz = MoEMLP(H, F, E, top_k=2, router_z_loss_weight=1.0)
    params = base.init(jax.random.PRNGKey(5))
    x = 0.5 * jax.random.normal(jax.random.PRNGKey(6), (2, 4, H))
    mesh = parallel_state.initialize_model_parallel(context_parallel_size_=8)
    try:
        _, aux0 = build(mesh, base)[0](params, x)
        _, aux1 = build(mesh, withz)[0](params, x)
        flat = np.asarray(x).reshape(-1, H).astype(np.float32)
        logits = flat @ np.asarray(params["router"]["weight"], np.float32)
        z = np.log(np.exp(logits - logits.max(-1, keepdims=True))
                   .sum(-1)) + logits.max(-1)
        np.testing.assert_allclose(
            float(aux1) - float(aux0), np.mean(z * z), rtol=1e-5
        )
    finally:
        parallel_state.destroy_model_parallel()


def test_top_k_validation():
    with pytest.raises(ValueError, match="top_k"):
        MoEMLP(H, F, E, top_k=0)
    with pytest.raises(ValueError, match="top_k"):
        MoEMLP(H, F, E, top_k=E + 1)


def test_moe_aux_threads_through_pipeline():
    """MoE under pp>1: the aux-loss accumulator rides the activation
    stream, so the pipeline loss equals mean-over-microbatches of the
    sequential per-microbatch (ce + w*aux), and the aux weight reaches
    the router gradients (the round-4 advisor gap, now closed)."""
    from apex_tpu.models.gpt import GPTConfig, GPTModel

    W = 0.1
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2
    )
    try:
        cfg = dict(
            vocab_size=64, num_layers=2, hidden_size=32,
            num_attention_heads=4, max_position_embeddings=16,
            compute_dtype=jnp.float32, remat=False, attention_impl="xla",
            num_experts=4, moe_capacity_factor=8.0, moe_aux_weight=W,
        )
        model = GPTModel(GPTConfig(**cfg))
        params = model.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 12), 0, 64)
        targets = jax.random.randint(jax.random.PRNGKey(2), (8, 12), 0, 64)
        num_micro = 2

        # pipeline: pp-sharded params through pipeline_1f1b_grads
        pspecs = model.pipeline_param_specs()

        def pp_fb(p, t, y):
            return model.pipeline_1f1b_grads(p, t, y, num_micro)

        pp_fn = jax.jit(jax.shard_map(
            pp_fb, mesh=mesh,
            in_specs=(pspecs, P("dp"), P("dp")),
            out_specs=(P(), pspecs),
        ))
        placed_pp = place(mesh, params, pspecs)
        pp_loss, pp_grads = pp_fn(placed_pp, tokens, targets)

        # sequential reference: full stack replicated on the same mesh,
        # per-microbatch loss (ce + W*aux on identical dp shards)
        sspecs = model.param_specs()
        seq_loss = jax.jit(jax.shard_map(
            model.loss, mesh=mesh,
            in_specs=(sspecs, P("dp"), P("dp")), out_specs=P(),
        ))
        placed_seq = place(mesh, params, sspecs)
        mb = tokens.shape[0] // num_micro
        expected = np.mean([
            float(seq_loss(placed_seq,
                           tokens[m * mb:(m + 1) * mb],
                           targets[m * mb:(m + 1) * mb]))
            for m in range(num_micro)
        ])
        np.testing.assert_allclose(float(pp_loss), expected, rtol=2e-5)

        # the aux weight must influence the router gradient
        model0 = GPTModel(GPTConfig(**{**cfg, "moe_aux_weight": 0.0}))

        def pp_fb0(p, t, y):
            return model0.pipeline_1f1b_grads(p, t, y, num_micro)

        pp_fn0 = jax.jit(jax.shard_map(
            pp_fb0, mesh=mesh,
            in_specs=(pspecs, P("dp"), P("dp")),
            out_specs=(P(), pspecs),
        ))
        _, pp_grads0 = pp_fn0(place(mesh, params, pspecs), tokens, targets)
        g_router = np.asarray(pp_grads["layers"]["moe"]["router"]["weight"])
        g_router0 = np.asarray(
            pp_grads0["layers"]["moe"]["router"]["weight"])
        assert np.isfinite(g_router).all()
        assert np.abs(g_router - g_router0).max() > 1e-7, (
            "aux weight does not reach the router gradient under pp"
        )
    finally:
        parallel_state.destroy_model_parallel()


def test_moe_decode_raises_with_design_note():
    """The serving decode path through an expert layer must refuse
    LOUDLY (silent dense fallback would corrupt generations); the
    error carries the expert-parallel design pointer, and every
    gpt.py decode entry point routes through it."""
    layer = MoEMLP(H, F, E)
    with pytest.raises(NotImplementedError,
                       match="expert-parallel serving decode"):
        layer.decode()

    from apex_tpu.models import GPTConfig, GPTModel

    model = GPTModel(GPTConfig(
        vocab_size=64, num_layers=2, hidden_size=H,
        num_attention_heads=2, max_position_embeddings=32,
        num_experts=E, compute_dtype=jnp.float32, remat=False,
        attention_impl="xla"))
    # the guard fires before any argument is touched — decode through
    # an MoE model is refused at every serving entry point
    for entry, nargs in ((model.decode_step, 6),
                         (model.prefill_chunk, 7),
                         (model.verify_step, 7)):
        with pytest.raises(NotImplementedError,
                           match="expert-parallel serving decode"):
            entry(*([None] * nargs))

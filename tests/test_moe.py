"""Expert-parallel MoE tests: ep-sharded == dense, routing behaviour."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.moe import MoEMLP

H, F, E = 16, 32, 8
N = 32  # global tokens (b=8, s=4)


def build(mesh, layer):
    specs = layer.param_specs()

    def fwd(params, x):
        out, aux = layer.apply(params, x)
        return out, jax.lax.pmean(aux, "dp")

    fn = jax.jit(
        jax.shard_map(
            fwd, mesh=mesh,
            in_specs=(specs, P("dp")),
            out_specs=(P("dp"), P()),
        )
    )
    return fn, specs


def place(mesh, tree, specs):
    return jax.device_put(
        tree, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                           is_leaf=lambda x: isinstance(x, P))
    )


def test_ep_matches_dense():
    """ep=8-sharded MoE == the same params applied densely, when the
    capacity is large enough that nothing drops."""
    layer = MoEMLP(H, F, E, capacity_factor=float(E))  # no drops
    params = layer.init(jax.random.PRNGKey(0))
    x = 0.5 * jax.random.normal(jax.random.PRNGKey(1), (8, 4, H))

    # dense: dp=1 mesh (cp soaks up the devices)
    mesh = parallel_state.initialize_model_parallel(context_parallel_size_=8)
    try:
        fn, specs = build(mesh, layer)
        ref, ref_aux = fn(params, x)
        ref, ref_aux = np.asarray(ref), float(ref_aux)
    finally:
        parallel_state.destroy_model_parallel()

    # expert-parallel: dp=8, experts sharded across ranks
    mesh = parallel_state.initialize_model_parallel()
    try:
        fn, specs = build(mesh, layer)
        placed = place(mesh, params, specs)
        out, aux = fn(placed, x)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=1e-5)
        # aux loss is per-shard routing stats; just sanity it
        assert np.isfinite(float(aux))
    finally:
        parallel_state.destroy_model_parallel()


def test_capacity_drops_tokens():
    """With a tiny capacity most tokens get zero output (residual path)."""
    mesh = parallel_state.initialize_model_parallel(context_parallel_size_=8)
    try:
        layer = MoEMLP(H, F, E, capacity_factor=0.25)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, H))
        fn, specs = build(mesh, layer)
        out, _ = fn(params, x)
        flat = np.asarray(out).reshape(-1, H)
        zero_rows = np.sum(np.all(flat == 0, axis=-1))
        assert zero_rows > 0  # overflow tokens dropped
        assert zero_rows < flat.shape[0]  # but not all
    finally:
        parallel_state.destroy_model_parallel()


def test_moe_trains_and_grads_are_per_expert():
    """End-to-end: grads flow, expert grads differ across ep ranks, and
    a few SGD steps reduce the loss."""
    mesh = parallel_state.initialize_model_parallel()
    try:
        layer = MoEMLP(H, F, E, capacity_factor=8.0)
        params = layer.init(jax.random.PRNGKey(0))
        specs = layer.param_specs()
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, H))
        y = jax.random.normal(jax.random.PRNGKey(2), (8, 4, H))

        def loss_fn(params, x, y):
            out, aux = layer.apply(params, x)
            mse = jnp.mean((out - y) ** 2)
            return jax.lax.pmean(mse, "dp") + 0.01 * jax.lax.pmean(aux, "dp")

        step = jax.jit(
            jax.shard_map(
                lambda p, x, y: jax.value_and_grad(loss_fn)(p, x, y),
                mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=(P(), specs),
            )
        )
        placed = place(mesh, params, specs)
        losses = []
        for _ in range(200):
            loss, grads = step(placed, x, y)
            losses.append(float(loss))
            placed = jax.tree.map(lambda p, g: p - 1.0 * g, placed, grads)
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0] * 0.9
        # expert grads are ep-sharded arrays of global shape (E, ...)
        g_w1 = grads["w1"]
        assert g_w1.shape == (E, H, F)
    finally:
        parallel_state.destroy_model_parallel()

"""L1-tier analog: cross-product sweep of precision policies and loss
scaling over a real training loop, comparing kernel paths.

The reference's L1 tier sweeps opt_levels {O0..O3} x loss_scale
{none, 1, 128, dynamic} x keep_batchnorm, trains the same model with
extensions on and off, and compares the saved loss traces bitwise
(reference: tests/L1/common/run_test.sh:30-60, compare.py).  Here the
"extension on/off" pair is pallas vs XLA implementations, compared at
tolerance where fusion changes op order and exactly where achievable
(scaler math), per SURVEY.md §7's adaptation of the philosophy.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from apex_tpu import amp
from apex_tpu.ops.layer_norm import fused_layer_norm_affine
from apex_tpu.optimizers import FusedAdam

OPT_LEVELS = ["O0", "O1", "O2", "O3", "O4", "O5"]
LOSS_SCALES = [None, 1.0, 128.0, "dynamic"]


def init_model(key):
    k1, k2 = jax.random.split(key)
    return {
        "w1": 0.3 * jax.random.normal(k1, (8, 16)),
        "b1": jnp.zeros((16,)),
        "ln": {"scale": jnp.ones((16,)), "bias": jnp.zeros((16,))},
        "w2": 0.3 * jax.random.normal(k2, (16, 1)),
        "b2": jnp.zeros((1,)),
    }


def apply_model(p, x, ln_impl):
    h = jax.nn.relu(jnp.matmul(x, p["w1"].astype(x.dtype)) + p["b1"].astype(x.dtype))
    h = fused_layer_norm_affine(
        h, p["ln"]["scale"], p["ln"]["bias"], (16,), implementation=ln_impl
    )
    return jnp.matmul(h, p["w2"].astype(h.dtype)) + p["b2"].astype(h.dtype)


def train_trace(opt_level, loss_scale, ln_impl, steps=20):
    """Run a small train loop; returns the loss trace."""
    overrides = {}
    if loss_scale is not None:
        overrides["loss_scale"] = loss_scale
    mp = amp.initialize(opt_level=opt_level, **overrides)
    opt = FusedAdam(lr=1e-2)

    params = init_model(jax.random.PRNGKey(0))
    params, amp_state = mp.init(params)
    opt_state = opt.init(params)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
    y = jnp.sum(x[:, :2], axis=1, keepdims=True)

    @jax.jit
    def step(params, opt_state, amp_state, x, y):
        def loss_fn(p):
            h = apply_model(
                mp.policy.cast_to_compute(p),
                x.astype(mp.policy.compute_dtype or x.dtype),
                ln_impl,
            )
            loss = jnp.mean((h.astype(jnp.float32) - y) ** 2)
            return mp.scale_loss(amp_state, loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(params)
        grads, finite, new_amp = mp.unscale_and_adjust(amp_state, grads)
        new_params, new_opt = opt.step(
            opt_state, grads, params, grads_finite=finite
        )
        return new_params, new_opt, new_amp, loss

    trace = []
    for _ in range(steps):
        params, opt_state, amp_state, loss = step(
            params, opt_state, amp_state, x, y
        )
        trace.append(float(loss))
    return np.asarray(trace)


@pytest.mark.parametrize("opt_level", OPT_LEVELS)
@pytest.mark.parametrize("loss_scale", LOSS_SCALES)
def test_policy_by_scale_converges(opt_level, loss_scale):
    """Every (opt_level, loss_scale) cell trains and improves."""
    if opt_level in ("O0", "O4", "O5") and isinstance(loss_scale, float):
        pytest.skip("fp32/bf16 levels don't use loss scaling")
    trace = train_trace(opt_level, loss_scale, ln_impl="xla")
    assert np.all(np.isfinite(trace))
    assert trace[-1] < trace[0]


@pytest.mark.parametrize("opt_level", ["O0", "O2", "O5"])
def test_kernel_paths_agree(opt_level):
    """pallas(interpret) vs XLA layernorm paths give near-identical
    loss traces — the ext-on vs ext-off comparison."""
    a = train_trace(opt_level, None, ln_impl="xla")
    b = train_trace(opt_level, None, ln_impl="pallas")
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_o0_trace_is_bitwise_deterministic():
    """Exactness where achievable (reference asserts bitwise equality):
    two identical fp32 runs must agree bit-for-bit."""
    a = train_trace("O0", None, ln_impl="xla")
    b = train_trace("O0", None, ln_impl="xla")
    np.testing.assert_array_equal(a, b)

"""L1-tier analog: cross-product sweep of precision policies and loss
scaling over a real training loop, comparing kernel paths.

The reference's L1 tier sweeps opt_levels {O0..O3} x loss_scale
{none, 1, 128, dynamic} x keep_batchnorm, trains the same model with
extensions on and off, and compares the saved loss traces bitwise
(reference: tests/L1/common/run_test.sh:30-60, compare.py).  Here the
model is a small tensor-parallel **GPT** (not a toy MLP) on the dp=4 x
tp=2 virtual mesh, the policy reaches the model through one kwarg
(``GPTConfig(policy=...)``), and the "extension on/off" pair is pallas
vs XLA implementations, compared at tolerance where fusion changes op
order and exactly where achievable, per SURVEY.md §7's adaptation of the
philosophy.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow  # L1-style cross-product tier (reference: tests/L1)
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.amp import model_parallel_all_finite

OPT_LEVELS = ["O0", "O1", "O2", "O3", "O4", "O5"]
LOSS_SCALES = [None, 1.0, 128.0, "dynamic"]

VOCAB, LAYERS, HIDDEN, HEADS, SEQ, BATCH = 64, 2, 32, 2, 8, 8


@pytest.fixture(scope="module")
def mesh():
    m = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2
    )
    yield m
    parallel_state.destroy_model_parallel()


def _data():
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (BATCH, SEQ)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    return tokens, targets


def train_trace(mesh, opt_level, loss_scale, attn_impl="xla", steps=10):
    """Train a policy-driven GPT; returns the loss trace.

    The policy reaches the model via ``GPTConfig(policy=...)`` — the
    single-kwarg O0..O5 switch (reference UX: amp.initialize and forget,
    apex/amp/_initialize.py:145-265).
    """
    overrides = {}
    if loss_scale is not None:
        overrides["loss_scale"] = loss_scale
    mp = amp.initialize(opt_level=opt_level, **overrides)

    cfg = GPTConfig(
        vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
        num_attention_heads=HEADS, max_position_embeddings=SEQ,
        policy=mp.policy, remat=False, attention_impl=attn_impl,
    )
    model = GPTModel(cfg)
    # the policy reached the model: params carry its dtype (norms fp32
    # when it says so), and the train loop derives scaler + masters
    opt = FusedAdam(lr=1e-2, master_weights=mp.policy.master_weights)

    params = model.init(jax.random.PRNGKey(0))
    specs = model.param_specs()
    amp_state = mp.init()
    opt_state = opt.init(params)
    state_specs = {
        k: (jax.tree.map(lambda _: P(), v) if k == "step"
            else jax.tree.map(
                lambda s: s, specs, is_leaf=lambda x: isinstance(x, P)))
        for k, v in opt_state.items()
    }
    tokens, targets = _data()

    def step(params, opt_state, amp_state, tokens, targets):
        def loss_fn(p):
            loss = model.loss(p, tokens, targets)
            return mp.scale_loss(amp_state, loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(params)
        # dp average + tp consensus for tp-replicated params (their grads
        # are identical across tp ranks; pmean re-establishes invariance)
        flat_specs = jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        flat_grads = jax.tree.leaves(grads)

        def sync(g, s):
            g = jax.lax.pmean(g, "dp")
            names = [n for e in s if e
                     for n in ((e,) if isinstance(e, str) else e)]
            if "tp" not in names:
                g = jax.lax.pmean(g, "tp")
            return g

        grads = jax.tree.unflatten(
            jax.tree.structure(grads),
            [sync(g, s) for g, s in zip(flat_grads, flat_specs)],
        )
        # inf consensus across the model-parallel axes (the reference's
        # MP GradScaler found_inf all-reduce) happens inside the adjust
        grads, finite, new_amp = mp.unscale_and_adjust(
            amp_state, grads, finite_reduce=model_parallel_all_finite
        )
        new_params, new_opt = opt.step(
            opt_state, grads, params, grads_finite=finite
        )
        return new_params, new_opt, new_amp, jax.lax.pmean(loss, "dp")

    amp_specs = jax.tree.map(lambda _: P(), amp_state)
    sharded = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(specs, state_specs, amp_specs, P("dp"), P("dp")),
        out_specs=(specs, state_specs, amp_specs, P()),
    ))
    placed = jax.device_put(
        params,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    trace = []
    for _ in range(steps):
        placed, opt_state, amp_state, loss = sharded(
            placed, opt_state, amp_state, tokens, targets
        )
        trace.append(float(loss))
    return np.asarray(trace), placed


@pytest.mark.parametrize("opt_level", OPT_LEVELS)
@pytest.mark.parametrize("loss_scale", LOSS_SCALES)
def test_policy_by_scale_converges(mesh, opt_level, loss_scale):
    """Every (opt_level, loss_scale) cell trains the GPT and improves."""
    if opt_level in ("O0", "O4", "O5") and isinstance(loss_scale, float):
        pytest.skip("fp32/bf16 levels don't use loss scaling")
    trace, _ = train_trace(mesh, opt_level, loss_scale)
    assert np.all(np.isfinite(trace))
    assert trace[-1] < trace[0]


def test_policy_drives_model_dtypes(mesh):
    """One kwarg flips the whole model: O2 → fp16 params with fp32
    norms, masters in the optimizer; O5 → bf16 params, fp32 norms."""
    for level, low in (("O2", jnp.float16), ("O5", jnp.bfloat16)):
        mp = amp.initialize(opt_level=level)
        cfg = GPTConfig(
            vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
            num_attention_heads=HEADS, max_position_embeddings=SEQ,
            policy=mp.policy, remat=False,
        )
        params = GPTModel(cfg).init(jax.random.PRNGKey(0))
        assert params["embedding"]["weight"].dtype == low
        assert params["layers"]["ln1"]["scale"].dtype == jnp.float32
        assert mp.policy.master_weights


@pytest.mark.parametrize("opt_level", ["O0", "O2", "O5"])
def test_kernel_paths_agree(mesh, opt_level):
    """pallas(interpret) vs XLA attention paths give near-identical loss
    traces — the ext-on vs ext-off comparison."""
    a, _ = train_trace(mesh, opt_level, None, attn_impl="xla", steps=6)
    b, _ = train_trace(mesh, opt_level, None, attn_impl="pallas", steps=6)
    np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)


def test_o0_trace_is_bitwise_deterministic(mesh):
    """Exactness where achievable (reference asserts bitwise equality):
    two identical fp32 runs must agree bit-for-bit."""
    a, _ = train_trace(mesh, "O0", None)
    b, _ = train_trace(mesh, "O0", None)
    np.testing.assert_array_equal(a, b)

"""L1-tier analog: cross-product sweep of precision policies and loss
scaling over a real training loop, comparing kernel paths.

The reference's L1 tier sweeps opt_levels {O0..O3} x loss_scale
{none, 1, 128, dynamic} x keep_batchnorm, trains the same model with
extensions on and off, and compares the saved loss traces bitwise
(reference: tests/L1/common/run_test.sh:30-60, compare.py).  Here the
model is a small tensor-parallel **GPT** (not a toy MLP) on the dp=4 x
tp=2 virtual mesh, the policy reaches the model through one kwarg
(``GPTConfig(policy=...)``), and the "extension on/off" pair is pallas
vs XLA implementations, compared at tolerance where fusion changes op
order and exactly where achievable, per SURVEY.md §7's adaptation of the
philosophy.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow  # L1-style cross-product tier (reference: tests/L1)
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.amp import model_parallel_all_finite
from apex_tpu.transformer.parallel_state import spec_axis_names

OPT_LEVELS = ["O0", "O1", "O2", "O3", "O4", "O5"]
LOSS_SCALES = [None, 1.0, 128.0, "dynamic"]

# Default tier: a representative subset that still trains every opt
# level at least once and every loss-scale mode at least once (the
# full 6x4 product re-trains the same GPT 18 times and blew the
# 20-minute single-core budget for the whole slow tier).  Set
# APEX_TPU_FULL_CROSS_PRODUCT=1 to sweep the complete product.
DEFAULT_CELLS = [
    ("O0", None),
    ("O1", None), ("O1", "dynamic"),
    ("O2", 1.0), ("O2", 128.0), ("O2", "dynamic"),
    ("O3", 128.0),
    ("O4", None),
    ("O5", "dynamic"),
]
CONVERGENCE_CELLS = (
    [(o, s) for o in OPT_LEVELS for s in LOSS_SCALES]
    if os.environ.get("APEX_TPU_FULL_CROSS_PRODUCT")
    else DEFAULT_CELLS
)

VOCAB, LAYERS, HIDDEN, HEADS, SEQ, BATCH = 64, 2, 32, 2, 8, 8


@pytest.fixture(scope="module")
def mesh():
    m = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2
    )
    yield m
    parallel_state.destroy_model_parallel()


def _data():
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (BATCH, SEQ)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    return tokens, targets


def train_trace(mesh, opt_level, loss_scale, attn_impl="xla", steps=10):
    """Train a policy-driven GPT; returns the loss trace.

    The policy reaches the model via ``GPTConfig(policy=...)`` — the
    single-kwarg O0..O5 switch (reference UX: amp.initialize and forget,
    apex/amp/_initialize.py:145-265).
    """
    overrides = {}
    if loss_scale is not None:
        overrides["loss_scale"] = loss_scale
    mp = amp.initialize(opt_level=opt_level, **overrides)

    cfg = GPTConfig(
        vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
        num_attention_heads=HEADS, max_position_embeddings=SEQ,
        policy=mp.policy, remat=False, attention_impl=attn_impl,
    )
    model = GPTModel(cfg)
    # the policy reached the model: params carry its dtype (norms fp32
    # when it says so), and the train loop derives scaler + masters
    opt = FusedAdam(lr=1e-2, master_weights=mp.policy.master_weights)

    params = model.init(jax.random.PRNGKey(0))
    specs = model.param_specs()
    amp_state = mp.init()
    opt_state = opt.init(params)
    state_specs = {
        k: (jax.tree.map(lambda _: P(), v) if k == "step"
            else jax.tree.map(
                lambda s: s, specs, is_leaf=lambda x: isinstance(x, P)))
        for k, v in opt_state.items()
    }
    tokens, targets = _data()

    def step(params, opt_state, amp_state, tokens, targets):
        def loss_fn(p):
            loss = model.loss(p, tokens, targets)
            return mp.scale_loss(amp_state, loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(params)
        # dp average + tp consensus for tp-replicated params (their grads
        # are identical across tp ranks; pmean re-establishes invariance)
        flat_specs = jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        flat_grads = jax.tree.leaves(grads)

        def sync(g, s):
            g = jax.lax.pmean(g, "dp")
            if "tp" not in spec_axis_names(s):
                g = jax.lax.pmean(g, "tp")
            return g

        grads = jax.tree.unflatten(
            jax.tree.structure(grads),
            [sync(g, s) for g, s in zip(flat_grads, flat_specs)],
        )
        # inf consensus across the model-parallel axes (the reference's
        # MP GradScaler found_inf all-reduce) happens inside the adjust
        grads, finite, new_amp = mp.unscale_and_adjust(
            amp_state, grads, finite_reduce=model_parallel_all_finite
        )
        new_params, new_opt = opt.step(
            opt_state, grads, params, grads_finite=finite
        )
        # global grad norm of the unscaled grads: the second trace the
        # reference's compare.py checks in (reference: tests/L1/common/
        # compare.py:1-30 — loss AND grad-norm drift both fail the run).
        # tp-sharded leaves hold disjoint shards, so their square-sums
        # psum over tp; tp-replicated leaves must not be double-counted
        sq = jnp.asarray(0.0, jnp.float32)
        for g, s in zip(jax.tree.leaves(grads), flat_specs):
            leaf_sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
            if "tp" in spec_axis_names(s):
                leaf_sq = jax.lax.psum(leaf_sq, "tp")
            sq = sq + leaf_sq
        gnorm = jnp.sqrt(sq)
        return (new_params, new_opt, new_amp,
                jax.lax.pmean(loss, "dp"), gnorm)

    amp_specs = jax.tree.map(lambda _: P(), amp_state)
    sharded = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(specs, state_specs, amp_specs, P("dp"), P("dp")),
        out_specs=(specs, state_specs, amp_specs, P(), P()),
    ))
    placed = jax.device_put(
        params,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    trace, gnorms = [], []
    for _ in range(steps):
        placed, opt_state, amp_state, loss, gnorm = sharded(
            placed, opt_state, amp_state, tokens, targets
        )
        trace.append(float(loss))
        gnorms.append(float(gnorm))
    return np.asarray(trace), np.asarray(gnorms), placed


@pytest.mark.parametrize("opt_level,loss_scale", CONVERGENCE_CELLS)
def test_policy_by_scale_converges(mesh, opt_level, loss_scale):
    """Every (opt_level, loss_scale) cell trains the GPT and improves
    (representative default subset; APEX_TPU_FULL_CROSS_PRODUCT=1 for
    the complete 6x4 sweep)."""
    if opt_level in ("O0", "O4", "O5") and isinstance(loss_scale, float):
        pytest.skip("fp32/bf16 levels don't use loss scaling")
    trace, _, _ = train_trace(mesh, opt_level, loss_scale)
    assert np.all(np.isfinite(trace))
    assert trace[-1] < trace[0]


def test_policy_drives_model_dtypes(mesh):
    """One kwarg flips the whole model: O2 → fp16 params with fp32
    norms, masters in the optimizer; O5 → bf16 params, fp32 norms."""
    for level, low in (("O2", jnp.float16), ("O5", jnp.bfloat16)):
        mp = amp.initialize(opt_level=level)
        cfg = GPTConfig(
            vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
            num_attention_heads=HEADS, max_position_embeddings=SEQ,
            policy=mp.policy, remat=False,
        )
        params = GPTModel(cfg).init(jax.random.PRNGKey(0))
        assert params["embedding"]["weight"].dtype == low
        assert params["layers"]["ln1"]["scale"].dtype == jnp.float32
        assert mp.policy.master_weights


@pytest.mark.parametrize("opt_level", ["O0", "O2", "O5"])
def test_kernel_paths_agree(mesh, opt_level):
    """pallas(interpret) vs XLA attention paths give near-identical loss
    traces — the ext-on vs ext-off comparison."""
    a, _, _ = train_trace(mesh, opt_level, None, attn_impl="xla", steps=6)
    b, _, _ = train_trace(mesh, opt_level, None, attn_impl="pallas", steps=6)
    np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)


def test_o0_trace_is_bitwise_deterministic(mesh):
    """Exactness where achievable (reference asserts bitwise equality):
    two identical fp32 runs must agree bit-for-bit."""
    a, _, _ = train_trace(mesh, "O0", None)
    b, _, _ = train_trace(mesh, "O0", None)
    np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------- golden tier
# Checked-in numeric baselines (reference: tests/L1/common/compare.py:1-30
# compares fresh loss/grad-norm traces against *stored* files, catching
# cross-version drift that in-process A/B sweeps cannot see).  Regenerate
# deliberately after an intentional numeric change with:
#
#     APEX_TPU_REGEN_GOLDEN=1 python -m pytest tests/test_cross_product.py \
#         -k golden -q   # then commit tests/golden/cross_product_traces.json

GOLDEN_PATH = os.path.join(
    os.path.dirname(__file__), "golden", "cross_product_traces.json",
)
GOLDEN_CELLS = [
    ("O0", None), ("O1", "dynamic"), ("O2", 128.0),
    ("O3", None), ("O4", None), ("O5", "dynamic"),
]
# fp32 is near-bitwise on one platform; reduced-precision levels get the
# tolerance fusion/reassociation is entitled to across XLA versions
GOLDEN_TOL = {"O0": (1e-5, 1e-7)}
GOLDEN_DEFAULT_TOL = (5e-3, 5e-4)


def _golden_key(opt_level, loss_scale):
    return f"{opt_level}|{loss_scale}"


def test_golden_baseline_traces(mesh):
    """Loss + grad-norm traces match the committed baselines; numeric
    drift between rounds/versions fails here, not in production."""
    import json

    fresh = {}
    for opt_level, loss_scale in GOLDEN_CELLS:
        loss_t, gnorm_t, _ = train_trace(mesh, opt_level, loss_scale)
        fresh[_golden_key(opt_level, loss_scale)] = {
            "loss": [float(x) for x in loss_t],
            "grad_norm": [float(x) for x in gnorm_t],
        }

    if os.environ.get("APEX_TPU_REGEN_GOLDEN"):
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as f:
            json.dump(fresh, f, indent=1, sort_keys=True)
        pytest.skip(f"regenerated {GOLDEN_PATH}; commit it")

    assert os.path.exists(GOLDEN_PATH), (
        f"golden baseline file missing: {GOLDEN_PATH} — run with "
        "APEX_TPU_REGEN_GOLDEN=1 and commit the result"
    )
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    for key, traces in fresh.items():
        assert key in golden, f"golden cell {key} missing — regenerate"
        rtol, atol = GOLDEN_TOL.get(key.split("|")[0], GOLDEN_DEFAULT_TOL)
        for name in ("loss", "grad_norm"):
            np.testing.assert_allclose(
                traces[name], golden[key][name], rtol=rtol, atol=atol,
                err_msg=(
                    f"{name} trace drifted for {key}: intentional numeric "
                    "changes must regenerate the golden file (see module "
                    "docstring), unintentional ones are a regression"
                ),
            )


def test_golden_pipeline_trace(mesh):
    """Schedule-numerics golden: a fixed pp=2 GPT's 10-step loss trace
    through pipeline_1f1b_grads must match the committed baseline —
    catches silent drift in the compiled schedule itself (the
    cross-product cells above only cover the sequential path)."""
    import json

    path = os.path.join(os.path.dirname(GOLDEN_PATH),
                        "pipeline_1f1b_trace.json")
    # needs its own pp mesh: tear down the module fixture's, and
    # restore it in the finally so later/reordered tests in this
    # module still see initialized parallel state
    parallel_state.destroy_model_parallel()
    m2 = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2)
    try:
        cfg = GPTConfig(
            vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
            num_attention_heads=HEADS, max_position_embeddings=SEQ,
            compute_dtype=jnp.float32, remat=False, attention_impl="xla",
        )
        model = GPTModel(cfg)
        specs = model.pipeline_param_specs()
        params = model.init(jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-2)
        opt_state = opt.init(params)
        from apex_tpu.transformer.tensor_parallel.layers import (
            state_specs_like,
        )

        opt_specs = state_specs_like(specs, opt_state)
        tokens, targets = _data()

        def stepf(p, s, t, y):
            loss, grads = model.pipeline_1f1b_grads(p, t, y, 2)
            p, s = opt.step(s, grads, p)
            return p, s, loss

        jstep = jax.jit(jax.shard_map(
            stepf, mesh=m2,
            in_specs=(specs, opt_specs, P("dp"), P("dp")),
            out_specs=(specs, opt_specs, P()),
        ))
        place = lambda t, sp: jax.device_put(
            t, jax.tree.map(lambda s: NamedSharding(m2, s), sp,
                            is_leaf=lambda x: isinstance(x, P)))
        p, s = place(params, specs), place(opt_state, opt_specs)
        trace = []
        for _ in range(10):
            p, s, loss = jstep(p, s, tokens, targets)
            trace.append(float(loss))

        if os.environ.get("APEX_TPU_REGEN_GOLDEN"):
            with open(path, "w") as f:
                json.dump({"loss": trace}, f, indent=1)
            pytest.skip(f"regenerated {path}; commit it")
        assert os.path.exists(path), (
            f"golden file missing: {path} — regenerate with "
            "APEX_TPU_REGEN_GOLDEN=1")
        with open(path) as f:
            golden = json.load(f)
        np.testing.assert_allclose(
            trace, golden["loss"], rtol=1e-5, atol=1e-7,
            err_msg="pipeline_1f1b numeric drift (see module docstring)",
        )
    finally:
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2)

"""Runtime telemetry subsystem tests: async scalar harvesting (the
dispatch-spy proof that the default flush cadence performs ZERO
per-step blocking device→host transfers in a GPT training loop),
MetricsLogger sinks/meters, StepStats rates, the event bus and its
subsystem wiring (guard / watchdog / checkpoint / autoresume /
Reducer comm buckets), TraceTrigger, log_util validation, and
tools/metrics_report."""

import json
import logging
import math
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.telemetry import events
from apex_tpu.telemetry import metrics as metrics_mod
from apex_tpu.telemetry.events import ring_wire_bytes
from apex_tpu.telemetry.metrics import (
    MetricsLogger,
    StepStats,
    device_peak_flops,
    transformer_flops_per_token,
)
from apex_tpu.telemetry.spans import PHASES, TraceTrigger, phase


class CapturingSink:
    def __init__(self):
        self.evs = []

    def event(self, kind, **fields):
        self.evs.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.evs]

    def of(self, kind):
        return [f for k, f in self.evs if k == kind]


@pytest.fixture
def sink():
    cap = CapturingSink()
    events.add_sink(cap)
    try:
        yield cap
    finally:
        events.remove_sink(cap)


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# --------------------------------------------------------------- event bus
class TestEventBus:
    def test_emit_without_sinks_is_noop(self):
        events.emit("whatever", x=1)  # must not raise

    def test_sink_receives_and_scoped_removal(self):
        cap = CapturingSink()
        with events.sink(cap):
            events.emit("a", x=1)
        events.emit("b", x=2)  # after removal
        assert cap.kinds() == ["a"]

    def test_broken_sink_never_breaks_emit(self, sink):
        class Broken:
            def event(self, kind, **f):
                raise RuntimeError("boom")

        with events.sink(Broken()):
            events.emit("a")  # must not raise
        assert sink.kinds() == ["a"]  # healthy sink still got it

    def test_non_sink_rejected(self):
        with pytest.raises(TypeError):
            events.add_sink(object())

    def test_double_add_single_delivery(self, sink):
        events.add_sink(sink)  # second add is a no-op
        events.emit("once")
        assert sink.kinds() == ["once"]

    def test_ring_wire_bytes_model(self):
        # the comm_audit docstring formulas, byte for byte
        assert ring_wire_bytes("all-reduce", 4, 100) == 150.0
        assert ring_wire_bytes("reduce-scatter", 4, 100) == 75.0
        assert ring_wire_bytes("all-to-all", 4, 100) == 75.0
        assert ring_wire_bytes("all-gather", 4, 0, result_bytes=100) == 75.0
        assert ring_wire_bytes("collective-permute", 4, 100) == 100.0
        assert ring_wire_bytes("all-reduce", 1, 100) == 0.0

    def test_ring_model_matches_comm_audit(self):
        import importlib.util

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "comm_audit", os.path.join(root, "tools", "comm_audit.py"))
        ca = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(ca)
        rec = {"op": "all-reduce", "operand_bytes": 1024,
               "result_bytes": 1024,
               "replica_groups": [[0, 1], [2, 3]]}
        assert ca._wire_bytes(rec) == ring_wire_bytes(
            "all-reduce", 2, 1024, result_bytes=1024)


# ----------------------------------------------------------- MetricsLogger
class TestMetricsLogger:
    def test_jsonl_step_records_and_cadence(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        tlm = MetricsLogger(jsonl_path=p, console=False, flush_every=4)
        for i in range(10):
            tlm.log_scalars(i, loss=float(i))
        # two full cadence windows flushed, 2 records pending
        recs = read_jsonl(p)
        assert len([r for r in recs if r["kind"] == "step"]) == 8
        tlm.close()  # drains the rest
        recs = read_jsonl(p)
        steps = [r for r in recs if r["kind"] == "step"]
        assert [r["step"] for r in steps] == list(range(10))
        assert steps[-1]["loss"] == 9.0
        assert tlm.last == {"loss": 9.0}
        assert tlm.last_step == 9

    def test_device_scalars_resolve_batched(self, tmp_path, monkeypatch):
        calls = []
        real = metrics_mod._device_get
        monkeypatch.setattr(metrics_mod, "_device_get",
                            lambda h: (calls.append(len(h)), real(h))[1])
        tlm = MetricsLogger(jsonl_path=str(tmp_path / "m.jsonl"),
                            console=False, flush_every=5)
        for i in range(10):
            tlm.log_scalars(i, loss=jnp.float32(i), lr=jnp.float32(0.1))
        tlm.close()
        # ONE device_get per flush window, each carrying the whole
        # window's scalars (5 steps x 2 scalars)
        assert calls == [10, 10]
        assert tlm.n_resolves == 2

    def test_flush_every_one_is_synchronous(self, tmp_path):
        tlm = MetricsLogger(jsonl_path=str(tmp_path / "m.jsonl"),
                            console=False, flush_every=1)
        tlm.log_scalars(0, loss=jnp.float32(1.5))
        assert tlm.last == {"loss": 1.5}  # resolved immediately

    def test_meters_counters_gauges_timings(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        tlm = MetricsLogger(jsonl_path=p, console=False, flush_every=100)
        tlm.counter("saves")
        tlm.counter("saves", 2)
        tlm.gauge("scale", 128.0)
        tlm.gauge("gnorm", jnp.float32(0.5))  # device gauge
        with tlm.timing("data"):
            pass
        tlm.log_scalars(0, loss=1.0)
        tlm.close()
        meters = [r for r in read_jsonl(p) if r["kind"] == "meters"]
        assert len(meters) == 1
        assert meters[0]["counters"] == {"saves": 3}
        assert meters[0]["gauges"]["scale"] == 128.0
        assert meters[0]["gauges"]["gnorm"] == 0.5
        assert meters[0]["timings_ms"]["data"] >= 0

    def test_event_written_immediately(self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        tlm = MetricsLogger(jsonl_path=p, console=False, flush_every=100)
        tlm.event("checkpoint_save", path="/x", duration_s=0.1)
        recs = read_jsonl(p)  # before any flush
        assert recs[0]["kind"] == "event"
        assert recs[0]["event"] == "checkpoint_save"
        tlm.close()

    def test_attach_events_routes_bus_and_close_deregisters(
            self, tmp_path):
        p = str(tmp_path / "m.jsonl")
        tlm = MetricsLogger(jsonl_path=p, console=False).attach_events()
        try:
            events.emit("guard_warn", step=3)
        finally:
            tlm.close()
        # close() removed the sink: later bus traffic must not land in
        # the dead logger's file (the exception-path leak the trainers
        # rely on close() to prevent)
        events.emit("guard_warn", step=4)
        assert not events.have_sinks()
        recs = read_jsonl(p)
        assert len(recs) == 1
        assert recs[0]["event"] == "guard_warn" and recs[0]["step"] == 3

    def test_console_line(self):
        lines = []
        tlm = MetricsLogger(console=True, flush_every=2,
                            print_fn=lines.append)
        tlm.log_scalars(0, loss=1.25)
        tlm.log_scalars(1, loss=1.5)
        assert lines and "step 1" in lines[0] and "1.5000" in lines[0]
        tlm.close()

    def test_validation(self):
        with pytest.raises(ValueError):
            MetricsLogger(flush_every=0)

    def test_overhead_accounting_excludes_resolve_wait(self, tmp_path):
        tlm = MetricsLogger(jsonl_path=str(tmp_path / "m.jsonl"),
                            console=False, flush_every=2)
        tlm.log_scalars(0, loss=jnp.float32(1.0))
        tlm.log_scalars(1, loss=jnp.float32(2.0))
        tlm.close()
        assert tlm.overhead_s >= 0
        assert tlm.resolve_wait_s >= 0


# --------------------------------------------------------------- StepStats
class TestStepStats:
    def test_rates_with_fake_clock(self):
        t = [0.0]
        stats = StepStats(tokens_per_step=100, flops_per_token=10,
                          peak_flops=1e4, time_fn=lambda: t[0])
        stats.begin()
        t[0] = 1.0
        stats.tick(10)
        iv = stats.interval()
        assert iv["ms_per_step"] == pytest.approx(100.0)
        assert iv["tokens_per_sec"] == pytest.approx(1000.0)
        # mfu = tps * flops_per_token / peak = 1000*10/1e4
        assert iv["mfu"] == pytest.approx(1.0)
        # a second interval with no new ticks is empty
        assert stats.interval() == {}
        t[0] = 2.0
        stats.tick(5)
        iv2 = stats.interval()
        assert iv2["ms_per_step"] == pytest.approx(200.0)
        s = stats.summary()
        assert s["timed_steps"] == 15
        assert s["ms_per_step"] == pytest.approx(2000.0 / 15)

    def test_begin_excludes_first_step(self):
        t = [0.0]
        stats = StepStats(tokens_per_step=1, time_fn=lambda: t[0])
        t[0] = 5.0  # "compile" happened before begin
        stats.begin()
        t[0] = 6.0
        stats.tick()
        assert stats.summary()["ms_per_step"] == pytest.approx(1000.0)

    def test_no_ticks_summary(self):
        stats = StepStats()
        assert stats.summary() == {"timed_steps": 0}
        assert stats.interval() == {}

    def test_flop_model_and_peak_table(self):
        # 6N + 12*L*h*s — the bench/scale_mfu numerator
        assert transformer_flops_per_token(1000, 2, 8, 16) == \
            6 * 1000 + 12 * 2 * 8 * 16
        # CPU devices have no peak entry: MFU omitted, not fabricated
        assert device_peak_flops(jax.devices()[0]) is None

        class FakeDev:
            device_kind = "TPU v5e"

        assert device_peak_flops(FakeDev()) == 197e12


# --------------------------------------- the dispatch-spy GPT-loop proof
class BlockingSpyScalar:
    """Wraps a device scalar; any blocking host conversion outside the
    sanctioned batched resolve is recorded.  Registered as a virtual
    jax.Array subclass so MetricsLogger treats it as a device value."""

    def __init__(self, arr, counter):
        self._arr = arr
        self._counter = counter

    def __float__(self):
        self._counter["blocking"] += 1
        return float(self._arr)

    def __array__(self, *a, **k):
        self._counter["blocking"] += 1
        return np.asarray(self._arr)

    def __bool__(self):
        self._counter["blocking"] += 1
        return bool(self._arr)


jax.Array.register(BlockingSpyScalar)


class TestDispatchSpyGPTLoop:
    """The acceptance-criteria test: at the default flush cadence the
    GPT training loop performs ZERO per-step blocking device→host
    transfers — scalars resolve only inside the flush's batched
    device_get, once per cadence window."""

    @pytest.fixture(scope="class")
    def gpt_loop(self):
        from apex_tpu._compat import shard_map
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.transformer import parallel_state
        from apex_tpu.transformer.tensor_parallel.layers import (
            state_specs_like,
        )
        from jax.sharding import NamedSharding

        mesh = parallel_state.initialize_model_parallel()
        try:
            cfg = GPTConfig(
                vocab_size=64, num_layers=1, hidden_size=32,
                num_attention_heads=4, max_position_embeddings=16,
                compute_dtype=jnp.float32, remat=False,
                attention_impl="xla",
            )
            model = GPTModel(cfg)
            params = model.init(jax.random.PRNGKey(0))
            specs = model.param_specs()
            opt = FusedAdam(lr=1e-3)
            opt_state = opt.init(params)
            opt_specs = state_specs_like(specs, opt_state)

            def train_step(p, s, tokens, targets):
                with phase("fwd_bwd"):
                    loss, grads = jax.value_and_grad(model.loss)(
                        p, tokens, targets)
                with phase("grad_sync"):
                    grads = jax.tree.map(
                        lambda g: jax.lax.pmean(g, "dp"), grads)
                with phase("optimizer"):
                    p, s = opt.step(s, grads, p)
                return p, s, loss

            step = jax.jit(shard_map(
                train_step, mesh=mesh,
                in_specs=(specs, opt_specs, P("dp"), P("dp")),
                out_specs=(specs, opt_specs, P()),
            ))
            place = lambda tree, sp: jax.device_put(
                tree, jax.tree.map(
                    lambda s_: NamedSharding(mesh, s_), sp,
                    is_leaf=lambda x: isinstance(x, P)))
            dp = mesh.shape["dp"]
            tokens = jax.random.randint(
                jax.random.PRNGKey(1), (dp, 16), 0, 64)
            targets = jnp.roll(tokens, -1, axis=1)
            yield (place(params, specs), place(opt_state, opt_specs),
                   step, tokens, targets)
        finally:
            parallel_state.destroy_model_parallel()

    def _run(self, gpt_loop, tmp_path, monkeypatch, steps, flush_every):
        p, s, step, tokens, targets = gpt_loop
        counter = {"blocking": 0, "resolves": 0}
        real = metrics_mod._device_get

        def spy_get(handles):
            counter["resolves"] += 1
            return real([h._arr if isinstance(h, BlockingSpyScalar)
                         else h for h in handles])

        monkeypatch.setattr(metrics_mod, "_device_get", spy_get)
        tlm = MetricsLogger(jsonl_path=str(tmp_path / "m.jsonl"),
                            console=False, flush_every=flush_every)
        loss = None
        for i in range(steps):
            p, s, loss = step(p, s, tokens, targets)
            tlm.log_scalars(i, loss=BlockingSpyScalar(loss, counter))
        tlm.close()
        return counter, tlm, loss

    def test_default_cadence_zero_per_step_blocking_transfers(
            self, gpt_loop, tmp_path, monkeypatch):
        STEPS = 20
        counter, tlm, loss = self._run(
            gpt_loop, tmp_path, monkeypatch, STEPS, flush_every=10)
        # the proof: NO wrapped scalar was ever converted outside the
        # batched resolve, and the batched resolve ran once per cadence
        # window — not once per step
        assert counter["blocking"] == 0
        assert counter["resolves"] == math.ceil(STEPS / 10)
        # and the values still landed, exact
        recs = read_jsonl(str(tmp_path / "m.jsonl"))
        steps = [r for r in recs if r["kind"] == "step"]
        assert len(steps) == STEPS
        assert steps[-1]["loss"] == pytest.approx(float(loss))

    def test_cadence_one_reproduces_per_step_sync(
            self, gpt_loop, tmp_path, monkeypatch):
        # control: flush_every=1 is the seed's synchronous behaviour —
        # one resolve per step (the spy DETECTS what cadence removes)
        STEPS = 6
        counter, _, _ = self._run(
            gpt_loop, tmp_path, monkeypatch, STEPS, flush_every=1)
        assert counter["resolves"] == STEPS


# ------------------------------------------------------------ phase spans
class TestPhases:
    def test_phase_names_hlo(self):
        def f(x):
            with phase("fwd_bwd"):
                return jnp.sin(x) + 1

        lowered = jax.jit(f).lower(jnp.ones(4))
        try:  # newer jax: scope names in the lowering's debug info
            text = lowered.as_text(debug_info=True)
        except TypeError:  # 0.4.x: in the compiled HLO metadata
            text = lowered.compile().as_text()
        assert "tlm.fwd_bwd" in text

    def test_phases_nest_and_cost_nothing_outside_jit(self):
        with phase("data"), phase("checkpoint"):
            pass
        assert "grad_sync" in PHASES


# ----------------------------------------------------------- TraceTrigger
class TestTraceTrigger:
    def test_touch_file_capture_and_rearm(self, tmp_path):
        tdir = str(tmp_path / "traces")
        trig = TraceTrigger(trace_dir=tdir, steps=2, poll_every=1)
        f = jax.jit(lambda x: x * 2)
        assert not trig.poll(0)  # nothing armed
        open(trig.trigger_file, "w").close()  # arm
        assert trig.poll(1)  # capture opens
        assert not os.path.exists(trig.trigger_file)  # consumed
        jax.block_until_ready(f(jnp.ones(8)))
        assert trig.poll(2)  # window step 1
        jax.block_until_ready(f(jnp.ones(8)))
        assert not trig.poll(3)  # window closed
        assert trig.captures == 1
        out = os.path.join(tdir, "step1")
        assert os.path.isdir(out) and os.listdir(out)
        # re-touch re-arms a second capture
        open(trig.trigger_file, "w").close()
        assert trig.poll(4)
        trig.close()
        assert trig.captures == 2

    def test_touch_file_dir_override(self, tmp_path):
        tdir = str(tmp_path / "traces")
        other = str(tmp_path / "elsewhere")
        trig = TraceTrigger(trace_dir=tdir, steps=1, poll_every=1)
        with open(trig.trigger_file, "w") as f:
            f.write(other + "\n")
        assert trig.poll(7)
        trig.close()
        assert os.path.isdir(os.path.join(other, "step7"))

    def test_env_arming_one_shot(self, tmp_path, monkeypatch, sink):
        monkeypatch.setenv("APEX_TPU_TRACE_DIR",
                           str(tmp_path / "envtrace"))
        trig = TraceTrigger(steps=1)
        assert trig.poll(0)  # armed by env at startup
        assert not trig.poll(1)
        assert not trig.poll(2)  # one-shot: no re-arm
        assert trig.captures == 1
        assert "trace_start" in [k for k, _ in sink.evs]
        assert "trace_captured" in [k for k, _ in sink.evs]

    def test_validation(self):
        with pytest.raises(ValueError):
            TraceTrigger(poll_every=0)
        with pytest.raises(ValueError):
            TraceTrigger(steps=0)


# -------------------------------------------------------- subsystem wiring
class TestSubsystemEvents:
    def test_guard_warn_and_diverged_events(self, sink):
        from apex_tpu.resilience import DivergenceError, StepGuard

        g = StepGuard(warn_after=1, rollback_after=2, raise_after=2)
        g.observe(False, step=5)
        assert sink.of("guard_warn")[0]["step"] == 5
        with pytest.raises(DivergenceError):
            g.observe(False, step=6)
        assert sink.of("guard_diverged")[0]["consecutive_bad"] == 2

    def test_guard_rollback_event(self, sink, tmp_path):
        from apex_tpu.resilience import StepGuard
        from apex_tpu.utils.autoresume import AutoResume

        ar = AutoResume(str(tmp_path), interval_steps=1)
        ar.maybe_save(1, {"x": np.float32(1.0)})
        g = StepGuard(autoresume=ar, warn_after=1, rollback_after=2,
                      raise_after=5)
        g.observe(False, step=10)
        v = g.observe(False, step=11)
        assert v.action == "rollback"
        ev = sink.of("guard_rollback")[0]
        assert ev["restored_step"] == 1 and ev["restored"] is True

    def test_checkpoint_save_verify_restore_events(self, sink, tmp_path):
        from apex_tpu import checkpoint

        path = str(tmp_path / "ck")
        checkpoint.save(path, {"w": jnp.arange(8.0)})
        ev = sink.of("checkpoint_save")[0]
        assert ev["path"] == path and ev["bytes"] == 32
        assert ev["duration_s"] >= 0
        assert checkpoint.verify(path) == []
        ev = sink.of("checkpoint_verify")[0]
        assert ev["ok"] is True and ev["bad_files"] == []
        checkpoint.restore(path, verify_integrity=True)
        ev = sink.of("checkpoint_restore")[0]
        assert ev["verified"] is True

    def test_checkpoint_corrupt_fallback_event(self, sink, tmp_path):
        from apex_tpu import checkpoint

        good = {"w": np.arange(4, dtype=np.float32)}
        checkpoint.save_step(str(tmp_path), 1, good)
        checkpoint.save_step(str(tmp_path), 2, good)
        blob = os.path.join(str(tmp_path), "step_2", "data.bin")
        with open(blob, "r+b") as f:
            f.write(b"\xff" * 4)  # corrupt the newer step
        tree, step = checkpoint.restore_latest_valid(str(tmp_path))
        assert step == 1
        ev = sink.of("checkpoint_corrupt_fallback")[0]
        assert ev["step"] == 2

    def test_autoresume_gc_and_resume_events(self, sink, tmp_path):
        from apex_tpu.utils.autoresume import AutoResume

        ar = AutoResume(str(tmp_path), interval_steps=1, keep=1)
        ar.maybe_save(1, {"x": np.float32(1.0)})
        ar.maybe_save(2, {"x": np.float32(2.0)})  # GCs step 1
        assert sink.of("autoresume_gc")[0]["step"] == 1
        _, step = ar.resume()
        assert step == 2
        assert sink.of("autoresume_resume")[0]["step"] == 2

    def test_watchdog_heartbeat_file_and_stall_event(
            self, sink, tmp_path):
        import io
        import time as _time

        from apex_tpu.resilience import Watchdog, read_heartbeat

        hb = str(tmp_path / "hb.json")
        wd = Watchdog(deadline_s=0.1, poll_s=0.02, heartbeat_file=hb,
                      stream=io.StringIO())
        with wd:
            wd.beat(step=7)
            rec = read_heartbeat(hb)
            assert rec is not None
            assert rec["step"] == 7 and rec["age_s"] >= 0
            assert rec["pid"] == os.getpid()
            deadline = _time.monotonic() + 5.0
            while wd.stall_count == 0 and _time.monotonic() < deadline:
                _time.sleep(0.02)
        assert wd.stall_count >= 1
        ev = sink.of("watchdog_stall")[0]
        assert ev["deadline_s"] == 0.1 and ev["will_abort"] is False

    def test_read_heartbeat_absent(self, tmp_path):
        from apex_tpu.resilience import read_heartbeat

        assert read_heartbeat(str(tmp_path / "nope.json")) is None
        assert read_heartbeat(None) is None  # no env configured

    def test_reducer_comm_bucket_events_int8(self, sink):
        from apex_tpu._compat import shard_map
        from apex_tpu.ops.quantization import CompressionConfig
        from apex_tpu.parallel import hierarchical_data_parallel_mesh
        from apex_tpu.parallel.distributed import Reducer
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            parallel_state.destroy_model_parallel()
        mesh = hierarchical_data_parallel_mesh(ici_size=4)
        red = Reducer(axis_name=("dcn", "ici"), overlap_grad_sync=True,
                      bucket_bytes=256,
                      compression=CompressionConfig(block_size=64))

        def step(xs):
            acc = red.init(xs)
            acc = red.accumulate(acc, xs)
            g, _ = red.reduce(acc)
            return g

        x = jax.random.normal(jax.random.PRNGKey(0), (8, 128))
        jax.jit(shard_map(step, mesh=mesh, in_specs=(P(("dcn", "ici")),),
                          out_specs=P(("dcn", "ici"))))(x)
        evs = sink.of("comm_bucket")
        assert evs, "Reducer emitted no comm_bucket events"
        ev = evs[0]
        assert ev["where"] == "reducer"
        assert ev["dcn_size"] == 2 and ev["ici_size"] == 4
        assert ev["compression"] == "int8"
        # per-device leaf is (1,128): 128 fp32 elements = 512B in ONE
        # bucket (buckets group whole leaves; an oversized leaf gets
        # its own bucket rather than being split)
        assert ev["elements"] == 128 and ev["bytes"] == 512
        # RS/AG legs ride ici full-width over the padded buffer; the
        # dcn AR leg is quantized: 128/4=32-elem chunk padded to block
        # 64 -> 64 int8 values + one fp32 scale
        assert ev["rs_ici_wire_bytes"] == round(
            ring_wire_bytes("reduce-scatter", 4, 512))
        assert ev["ag_ici_wire_bytes"] == round(
            ring_wire_bytes("all-gather", 4, 512, result_bytes=512))
        assert ev["ar_dcn_wire_bytes"] == round(
            ring_wire_bytes("all-reduce", 2, 64 + 4))

    def test_ddp_bucketed_comm_events_and_silence_without_sink(self):
        from apex_tpu._compat import shard_map
        from apex_tpu.parallel import hierarchical_data_parallel_mesh
        from apex_tpu.parallel.distributed import all_reduce_gradients
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            parallel_state.destroy_model_parallel()
        mesh = hierarchical_data_parallel_mesh(ici_size=4)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 32))

        def reduce(g):
            return all_reduce_gradients(g, ("dcn", "ici"),
                                        overlap_grad_sync=True,
                                        bucket_bytes=4096)

        # no sink: traces fine, emits nothing, result correct
        out = jax.jit(shard_map(
            reduce, mesh=mesh, in_specs=(P(("dcn", "ici")),),
            out_specs=P(("dcn", "ici"))))(x)
        ref = np.broadcast_to(np.mean(np.asarray(x), 0, keepdims=True),
                              x.shape)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6,
                                   atol=1e-6)
        cap = CapturingSink()
        with events.sink(cap):
            jax.jit(shard_map(
                lambda g: all_reduce_gradients(
                    g, ("dcn", "ici"), overlap_grad_sync=True,
                    bucket_bytes=64),
                mesh=mesh, in_specs=(P(("dcn", "ici")),),
                out_specs=P(("dcn", "ici"))))(x)
        evs = cap.of("comm_bucket")
        assert evs and evs[0]["where"] == "all_reduce_gradients"
        assert evs[0]["compression"] == "none"


# ----------------------------------------------------- log_util satellite
class TestLogUtil:
    def test_null_handler_installed(self):
        from apex_tpu.transformer.log_util import get_transformer_logger

        get_transformer_logger("somemodule.py")
        root = logging.getLogger("apex_tpu.transformer")
        assert any(isinstance(h, logging.NullHandler)
                   for h in root.handlers)

    def test_set_logging_level_accepts_int_and_str(self):
        from apex_tpu.transformer.log_util import set_logging_level

        root = logging.getLogger("apex_tpu.transformer")
        old = root.level
        try:
            set_logging_level(logging.DEBUG)
            assert root.level == logging.DEBUG
            set_logging_level("warning")
            assert root.level == logging.WARNING
        finally:
            root.setLevel(old)

    @pytest.mark.parametrize("bad", [object(), 1.5, [], None, True,
                                     "VERBOSE"])
    def test_set_logging_level_rejects_garbage(self, bad):
        from apex_tpu.transformer.log_util import set_logging_level

        with pytest.raises((TypeError, ValueError)):
            set_logging_level(bad)


# ------------------------------------------------------ tools/metrics_report
class TestMetricsReport:
    def _write(self, tmp_path, records, junk=False):
        p = str(tmp_path / "run.jsonl")
        with open(p, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
            if junk:
                f.write('{"torn": \n')
        return p

    def _records(self):
        recs = []
        for i in range(6):
            recs.append({"t": 100.0 + i, "kind": "step", "step": i,
                         "run": "test", "loss": 5.0 - i})
        recs.append({"t": 103.0, "kind": "throughput", "step": 2,
                     "ms_per_step": 10.0, "tokens_per_sec": 1000.0,
                     "mfu": 0.4})
        recs.append({"t": 106.0, "kind": "throughput", "step": 5,
                     "ms_per_step": 8.0, "tokens_per_sec": 1250.0,
                     "mfu": 0.5})
        recs.append({"t": 104.0, "kind": "event",
                     "event": "checkpoint_save", "path": "/x",
                     "duration_s": 0.2})
        recs.append({"t": 105.0, "kind": "event", "event": "guard_warn",
                     "step": 4})
        recs.append({"t": 106.5, "kind": "meters", "step": 5,
                     "counters": {"saves": 1},
                     "timings_ms": {"data": 6.0}})
        return recs

    def test_summarize(self, tmp_path):
        from tools.metrics_report import load_records, summarize

        recs = load_records(self._write(tmp_path, self._records(),
                                        junk=True))
        s = summarize(recs)
        assert s["runs"] == ["test"]
        assert s["steps"]["count"] == 6
        assert s["scalars"]["loss"]["first"] == 5.0
        assert s["scalars"]["loss"]["last"] == 0.0
        assert s["value"] == 1250.0 and s["unit"] == "tokens/s"
        assert s["throughput"]["ms_per_step"]["best"] == 8.0  # min!
        assert s["throughput"]["mfu"]["final"] == 0.5
        assert s["events"]["counts"] == {"checkpoint_save": 1,
                                         "guard_warn": 1}
        assert s["events"]["timeline"][0]["t_rel_s"] == 4.0
        assert s["meters"]["host_phase_ms_per_step"]["data"] == 1.0

    def test_report_and_bench_compare(self, tmp_path, capsys):
        from tools.metrics_report import main

        p = self._write(tmp_path, self._records())
        bench = str(tmp_path / "BENCH.json")
        with open(bench, "w") as f:
            json.dump({"metric": "gpt_tp1_tokens_per_sec",
                       "value": 2500.0, "unit": "tokens/s"}, f)
        outj = str(tmp_path / "summary.json")
        assert main([p, "--bench", bench, "--json", outj]) == 0
        text = capsys.readouterr().out
        assert "throughput trajectory" in text
        assert "guard_warn" in text
        assert "0.5x" in text  # 1250 / 2500
        with open(outj) as f:
            s = json.load(f)
        assert s["vs_bench"]["run_vs_bench"] == 0.5

    def test_empty_file(self, tmp_path):
        from tools.metrics_report import main

        p = str(tmp_path / "empty.jsonl")
        open(p, "w").close()
        assert main([p]) == 1

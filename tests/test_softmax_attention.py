"""Fused softmax + flash attention vs analytic references.

Mirrors the reference's test style: fused path compared against a
composed naive implementation, values and gradients
(reference: tests/L0/run_transformer/test_fused_softmax.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import (
    flash_attention,
    mha_reference,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax


def naive_softmax(x, mask=None, scale=1.0, causal=False):
    x = x.astype(jnp.float32) * scale
    sq, sk = x.shape[-2:]
    if causal:
        tri = np.triu(np.ones((sq, sk), bool), k=1)
        x = jnp.where(jnp.asarray(tri), -10000.0, x)
    if mask is not None:
        x = jnp.where(mask, -10000.0, x)
    return jax.nn.softmax(x, axis=-1)


class TestScaledSoftmax:
    def test_matches_naive(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 8, 16))
        got = scaled_softmax(x, scale=0.5)
        want = naive_softmax(x, scale=0.5)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_causal(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 16, 16))
        got = scaled_upper_triang_masked_softmax(x, scale=2.0)
        want = naive_softmax(x, scale=2.0, causal=True)
        np.testing.assert_allclose(got, want, atol=1e-6)
        # strictly-upper entries ~0
        assert float(got[0, 0, 0, 1]) < 1e-4

    def test_padding_mask(self):
        key = jax.random.PRNGKey(2)
        x = jax.random.normal(key, (2, 4, 8, 12))
        mask = jax.random.bernoulli(key, 0.3, (2, 1, 8, 12))
        got = scaled_masked_softmax(x, mask, scale=1.5)
        want = naive_softmax(x, mask=mask, scale=1.5)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_gradient_matches_naive(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (2, 2, 8, 8))

        def loss_fused(x):
            return jnp.sum(
                scaled_upper_triang_masked_softmax(x, 1.7) ** 2
            )

        def loss_naive(x):
            return jnp.sum(naive_softmax(x, scale=1.7, causal=True) ** 2)

        g1 = jax.grad(loss_fused)(x)
        g2 = jax.grad(loss_naive)(x)
        np.testing.assert_allclose(g1, g2, atol=1e-5)

    def test_bf16_output_dtype(self):
        x = jax.random.normal(
            jax.random.PRNGKey(4), (1, 2, 8, 8)
        ).astype(jnp.bfloat16)
        y = scaled_softmax(x)
        assert y.dtype == jnp.bfloat16


class TestFusedScaleMaskSoftmax:
    def test_causal_module(self):
        m = FusedScaleMaskSoftmax(
            attn_mask_type=AttnMaskType.causal, scale=0.125
        )
        x = jax.random.normal(jax.random.PRNGKey(5), (2, 4, 16, 16))
        got = m(x.astype(jnp.bfloat16), None)
        want = naive_softmax(x.astype(jnp.bfloat16), scale=0.125,
                             causal=True)
        np.testing.assert_allclose(
            got.astype(jnp.float32), want, atol=1e-2
        )

    def test_padding_module_with_mask_func(self):
        m = FusedScaleMaskSoftmax(
            attn_mask_type=AttnMaskType.padding,
            mask_func=lambda s, mask: jnp.where(mask, -10000.0, s),
        )
        key = jax.random.PRNGKey(6)
        x = jax.random.normal(key, (2, 2, 8, 8))
        mask = jax.random.bernoulli(key, 0.2, (2, 1, 8, 8))
        got = m(x, mask)
        want = naive_softmax(x, mask=mask)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_causal_composes_with_padding_mask(self):
        m = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal)
        key = jax.random.PRNGKey(30)
        x = jax.random.normal(key, (2, 2, 8, 8))
        mask = jax.random.bernoulli(key, 0.3, (2, 1, 8, 8))
        got = m(x, mask)
        want = naive_softmax(x, mask=mask, causal=True)
        np.testing.assert_allclose(got, want, atol=1e-6)
        # the mask must actually matter
        assert not np.allclose(got, m(x, None))

    def test_flag_conflict(self):
        with pytest.raises(RuntimeError):
            FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)
        with pytest.raises(RuntimeError):
            FusedScaleMaskSoftmax(softmax_in_fp32=False, scale=2.0)


class TestPallasKernelsInterpreted:
    """Force implementation='pallas' on CPU — interpret mode runs the real
    kernel bodies, so the Pallas code paths have coverage off-TPU."""

    def test_softmax_kernel_body(self):
        x = jax.random.normal(jax.random.PRNGKey(20), (2, 16, 128))
        got = scaled_softmax(x, 0.7, implementation="pallas")
        want = scaled_softmax(x, 0.7, implementation="xla")
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_causal_softmax_kernel_body(self):
        x = jax.random.normal(jax.random.PRNGKey(21), (2, 16, 128))
        got = scaled_upper_triang_masked_softmax(
            x, 1.3, implementation="pallas"
        )
        want = scaled_upper_triang_masked_softmax(
            x, 1.3, implementation="xla"
        )
        np.testing.assert_allclose(got, want, atol=1e-6)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_kernels_fwd_bwd(self, causal):
        key = jax.random.PRNGKey(22)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (1, 2, 128, 128)
        q = jax.random.normal(kq, shape)
        k = jax.random.normal(kk, shape)
        v = jax.random.normal(kv, shape)

        def f_pallas(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=causal, block_q=64, block_k=64,
                    implementation="pallas",
                ) ** 2
            )

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

        v1, g1 = jax.value_and_grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        v2, g2 = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(v1, v2, rtol=1e-5)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_flash_kernel_unpadded_seq(self):
        # seq not a multiple of the block size exercises the pad+mask path
        key = jax.random.PRNGKey(23)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (1, 1, 100, 128))
        k = jax.random.normal(kk, (1, 1, 72, 128))
        v = jax.random.normal(kv, (1, 1, 72, 128))
        got = flash_attention(
            q, k, v, block_q=64, block_k=64, implementation="pallas"
        )
        want = mha_reference(q, k, v)
        np.testing.assert_allclose(got, want, atol=1e-5)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        key = jax.random.PRNGKey(7)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (2, 3, 32, 16)
        q = jax.random.normal(kq, shape)
        k = jax.random.normal(kk, shape)
        v = jax.random.normal(kv, shape)
        got = flash_attention(q, k, v, causal=causal)
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_gradients_match_reference(self):
        key = jax.random.PRNGKey(8)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (1, 2, 16, 8)
        q = jax.random.normal(kq, shape)
        k = jax.random.normal(kk, shape)
        v = jax.random.normal(kv, shape)

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_cross_attention_lengths(self):
        key = jax.random.PRNGKey(9)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (2, 2, 8, 16))
        k = jax.random.normal(kk, (2, 2, 24, 16))
        v = jax.random.normal(kv, (2, 2, 24, 16))
        got = flash_attention(q, k, v)
        want = mha_reference(q, k, v)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_bias_path(self):
        key = jax.random.PRNGKey(10)
        kq, kk, kv, kb = jax.random.split(key, 4)
        shape = (1, 2, 8, 8)
        q = jax.random.normal(kq, shape)
        k = jax.random.normal(kk, shape)
        v = jax.random.normal(kv, shape)
        bias = jax.random.normal(kb, (1, 2, 8, 8))
        got = flash_attention(q, k, v, bias=bias)
        want = mha_reference(q, k, v, bias=bias)
        np.testing.assert_allclose(got, want, atol=1e-5)

"""Fused softmax + flash attention vs analytic references.

Mirrors the reference's test style: fused path compared against a
composed naive implementation, values and gradients
(reference: tests/L0/run_transformer/test_fused_softmax.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import (
    flash_attention,
    mha_reference,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import FusedScaleMaskSoftmax


def naive_softmax(x, mask=None, scale=1.0, causal=False):
    x = x.astype(jnp.float32) * scale
    sq, sk = x.shape[-2:]
    if causal:
        tri = np.triu(np.ones((sq, sk), bool), k=1)
        x = jnp.where(jnp.asarray(tri), -10000.0, x)
    if mask is not None:
        x = jnp.where(mask, -10000.0, x)
    return jax.nn.softmax(x, axis=-1)


class TestScaledSoftmax:
    def test_matches_naive(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 8, 16))
        got = scaled_softmax(x, scale=0.5)
        want = naive_softmax(x, scale=0.5)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_causal(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 16, 16))
        got = scaled_upper_triang_masked_softmax(x, scale=2.0)
        want = naive_softmax(x, scale=2.0, causal=True)
        np.testing.assert_allclose(got, want, atol=1e-6)
        # strictly-upper entries ~0
        assert float(got[0, 0, 0, 1]) < 1e-4

    def test_padding_mask(self):
        key = jax.random.PRNGKey(2)
        x = jax.random.normal(key, (2, 4, 8, 12))
        mask = jax.random.bernoulli(key, 0.3, (2, 1, 8, 12))
        got = scaled_masked_softmax(x, mask, scale=1.5)
        want = naive_softmax(x, mask=mask, scale=1.5)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_gradient_matches_naive(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (2, 2, 8, 8))

        def loss_fused(x):
            return jnp.sum(
                scaled_upper_triang_masked_softmax(x, 1.7) ** 2
            )

        def loss_naive(x):
            return jnp.sum(naive_softmax(x, scale=1.7, causal=True) ** 2)

        g1 = jax.grad(loss_fused)(x)
        g2 = jax.grad(loss_naive)(x)
        np.testing.assert_allclose(g1, g2, atol=1e-5)

    def test_bf16_output_dtype(self):
        x = jax.random.normal(
            jax.random.PRNGKey(4), (1, 2, 8, 8)
        ).astype(jnp.bfloat16)
        y = scaled_softmax(x)
        assert y.dtype == jnp.bfloat16


class TestFusedScaleMaskSoftmax:
    def test_causal_module(self):
        m = FusedScaleMaskSoftmax(
            attn_mask_type=AttnMaskType.causal, scale=0.125
        )
        x = jax.random.normal(jax.random.PRNGKey(5), (2, 4, 16, 16))
        got = m(x.astype(jnp.bfloat16), None)
        want = naive_softmax(x.astype(jnp.bfloat16), scale=0.125,
                             causal=True)
        np.testing.assert_allclose(
            got.astype(jnp.float32), want, atol=1e-2
        )

    def test_padding_module_with_mask_func(self):
        m = FusedScaleMaskSoftmax(
            attn_mask_type=AttnMaskType.padding,
            mask_func=lambda s, mask: jnp.where(mask, -10000.0, s),
        )
        key = jax.random.PRNGKey(6)
        x = jax.random.normal(key, (2, 2, 8, 8))
        mask = jax.random.bernoulli(key, 0.2, (2, 1, 8, 8))
        got = m(x, mask)
        want = naive_softmax(x, mask=mask)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_causal_composes_with_padding_mask(self):
        m = FusedScaleMaskSoftmax(attn_mask_type=AttnMaskType.causal)
        key = jax.random.PRNGKey(30)
        x = jax.random.normal(key, (2, 2, 8, 8))
        mask = jax.random.bernoulli(key, 0.3, (2, 1, 8, 8))
        got = m(x, mask)
        want = naive_softmax(x, mask=mask, causal=True)
        np.testing.assert_allclose(got, want, atol=1e-6)
        # the mask must actually matter
        assert not np.allclose(got, m(x, None))

    def test_flag_conflict(self):
        with pytest.raises(RuntimeError):
            FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)
        with pytest.raises(RuntimeError):
            FusedScaleMaskSoftmax(softmax_in_fp32=False, scale=2.0)


class TestPallasKernelsInterpreted:
    """Force implementation='pallas' on CPU — interpret mode runs the real
    kernel bodies, so the Pallas code paths have coverage off-TPU."""

    def test_softmax_kernel_body(self):
        x = jax.random.normal(jax.random.PRNGKey(20), (2, 16, 128))
        got = scaled_softmax(x, 0.7, implementation="pallas")
        want = scaled_softmax(x, 0.7, implementation="xla")
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_causal_softmax_kernel_body(self):
        x = jax.random.normal(jax.random.PRNGKey(21), (2, 16, 128))
        got = scaled_upper_triang_masked_softmax(
            x, 1.3, implementation="pallas"
        )
        want = scaled_upper_triang_masked_softmax(
            x, 1.3, implementation="xla"
        )
        np.testing.assert_allclose(got, want, atol=1e-6)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_kernels_fwd_bwd(self, causal):
        key = jax.random.PRNGKey(22)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (1, 2, 128, 128)
        q = jax.random.normal(kq, shape)
        k = jax.random.normal(kk, shape)
        v = jax.random.normal(kv, shape)

        def f_pallas(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=causal, block_q=64, block_k=64,
                    implementation="pallas",
                ) ** 2
            )

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

        v1, g1 = jax.value_and_grad(f_pallas, argnums=(0, 1, 2))(q, k, v)
        v2, g2 = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(v1, v2, rtol=1e-5)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_flash_kernel_unpadded_seq(self):
        # seq not a multiple of the block size exercises the pad+mask path
        key = jax.random.PRNGKey(23)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (1, 1, 100, 128))
        k = jax.random.normal(kk, (1, 1, 72, 128))
        v = jax.random.normal(kv, (1, 1, 72, 128))
        got = flash_attention(
            q, k, v, block_q=64, block_k=64, implementation="pallas"
        )
        want = mha_reference(q, k, v)
        np.testing.assert_allclose(got, want, atol=1e-5)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        key = jax.random.PRNGKey(7)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (2, 3, 32, 16)
        q = jax.random.normal(kq, shape)
        k = jax.random.normal(kk, shape)
        v = jax.random.normal(kv, shape)
        got = flash_attention(q, k, v, causal=causal)
        want = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_gradients_match_reference(self):
        key = jax.random.PRNGKey(8)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (1, 2, 16, 8)
        q = jax.random.normal(kq, shape)
        k = jax.random.normal(kk, shape)
        v = jax.random.normal(kv, shape)

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_cross_attention_lengths(self):
        key = jax.random.PRNGKey(9)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (2, 2, 8, 16))
        k = jax.random.normal(kk, (2, 2, 24, 16))
        v = jax.random.normal(kv, (2, 2, 24, 16))
        got = flash_attention(q, k, v)
        want = mha_reference(q, k, v)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_bias_path(self):
        key = jax.random.PRNGKey(10)
        kq, kk, kv, kb = jax.random.split(key, 4)
        shape = (1, 2, 8, 8)
        q = jax.random.normal(kq, shape)
        k = jax.random.normal(kk, shape)
        v = jax.random.normal(kv, shape)
        bias = jax.random.normal(kb, (1, 2, 8, 8))
        got = flash_attention(q, k, v, bias=bias)
        want = mha_reference(q, k, v, bias=bias)
        np.testing.assert_allclose(got, want, atol=1e-5)


class TestFlashAttentionExtras:
    """New in-kernel capabilities: segment ids (varlen), differentiable
    additive bias, and counter-based dropout — each checked pallas-vs-xla
    in interpret mode (the two paths share the dropout hash, so dropout
    comparisons are exact, not statistical)."""

    def _qkv(self, key, shape):
        kq, kk, kv = jax.random.split(key, 3)
        return (jax.random.normal(kq, shape), jax.random.normal(kk, shape),
                jax.random.normal(kv, shape))

    @pytest.mark.parametrize("causal", [False, True])
    def test_segment_ids_match_reference(self, causal):
        q, k, v = self._qkv(jax.random.PRNGKey(30), (2, 2, 96, 128))
        # two packed sequences of 40 + 56 tokens per batch row
        seg = jnp.concatenate(
            [jnp.zeros((2, 40), jnp.int32), jnp.ones((2, 56), jnp.int32)],
            axis=1,
        )
        got = flash_attention(
            q, k, v, causal=causal, q_segment_ids=seg, kv_segment_ids=seg,
            block_q=64, block_k=64, implementation="pallas",
        )
        want = mha_reference(
            q, k, v, causal=causal, q_segment_ids=seg, kv_segment_ids=seg
        )
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_segment_ids_gradients(self):
        q, k, v = self._qkv(jax.random.PRNGKey(31), (1, 2, 64, 128))
        seg = (jnp.arange(64) // 24).astype(jnp.int32)[None, :]

        def f(impl):
            def loss(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, q_segment_ids=seg,
                    kv_segment_ids=seg, block_q=32, block_k=32,
                    implementation=impl,
                ) ** 2)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        for a, b in zip(f("pallas"), f("xla")):
            np.testing.assert_allclose(a, b, atol=1e-4)

    @pytest.mark.parametrize(
        "bias_shape", [(1, 1, 64, 64), (2, 1, 64, 64), (2, 2, 64, 64)]
    )
    def test_bias_broadcast_and_grad(self, bias_shape):
        q, k, v = self._qkv(jax.random.PRNGKey(32), (2, 2, 64, 128))
        bias = jax.random.normal(jax.random.PRNGKey(33), bias_shape)

        def loss(impl):
            def f(q, k, v, bias):
                return jnp.sum(flash_attention(
                    q, k, v, bias=bias, block_q=32, block_k=32,
                    implementation=impl,
                ) ** 2)
            return f

        got = flash_attention(q, k, v, bias=bias, block_q=32, block_k=32,
                              implementation="pallas")
        want = mha_reference(q, k, v, bias=bias)
        np.testing.assert_allclose(got, want, atol=1e-5)

        g1 = jax.grad(loss("pallas"), argnums=(0, 1, 2, 3))(q, k, v, bias)
        g2 = jax.grad(loss("xla"), argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(g1, g2):
            assert a.shape == b.shape
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_bias_with_causal_grad(self):
        q, k, v = self._qkv(jax.random.PRNGKey(34), (1, 2, 48, 128))
        bias = jax.random.normal(jax.random.PRNGKey(35), (1, 2, 48, 48))

        def loss(impl):
            def f(q, k, v, bias):
                return jnp.sum(flash_attention(
                    q, k, v, bias=bias, causal=True, block_q=16, block_k=16,
                    implementation=impl,
                ) ** 2)
            return f

        g1 = jax.grad(loss("pallas"), argnums=(0, 1, 2, 3))(q, k, v, bias)
        g2 = jax.grad(loss("xla"), argnums=(0, 1, 2, 3))(q, k, v, bias)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_dropout_exact_parity_and_rate(self):
        q, k, v = self._qkv(jax.random.PRNGKey(36), (2, 2, 64, 128))
        got = flash_attention(
            q, k, v, dropout_rate=0.3, dropout_seed=1234,
            block_q=32, block_k=32, implementation="pallas",
        )
        want = flash_attention(
            q, k, v, dropout_rate=0.3, dropout_seed=1234,
            implementation="xla",
        )
        # same hash, same seed → identical mask → near-identical values
        np.testing.assert_allclose(got, want, atol=1e-5)
        # deterministic given the seed
        again = flash_attention(
            q, k, v, dropout_rate=0.3, dropout_seed=1234,
            block_q=32, block_k=32, implementation="pallas",
        )
        np.testing.assert_allclose(got, again, atol=0)
        # different seed → different output
        other = flash_attention(
            q, k, v, dropout_rate=0.3, dropout_seed=99,
            block_q=32, block_k=32, implementation="pallas",
        )
        assert float(jnp.max(jnp.abs(got - other))) > 1e-3

    def test_dropout_mask_statistics(self):
        from apex_tpu.ops.attention import _keep_mask, _keep_threshold

        q_idx = jax.lax.broadcasted_iota(jnp.int32, (256, 256), 0)
        k_idx = jax.lax.broadcasted_iota(jnp.int32, (256, 256), 1)
        keep = _keep_mask(jnp.uint32(5), jnp.int32(3), q_idx, k_idx,
                          jnp.uint32(_keep_threshold(0.25)))
        frac = float(jnp.mean(keep.astype(jnp.float32)))
        assert abs(frac - 0.75) < 0.02

    def test_dropout_gradients_match_reference(self):
        q, k, v = self._qkv(jax.random.PRNGKey(37), (1, 2, 64, 128))

        def loss(impl):
            def f(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, dropout_rate=0.2, dropout_seed=7,
                    block_q=32, block_k=32, implementation=impl,
                ) ** 2)
            return f

        g1 = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4)

    def test_everything_composes(self):
        # segments + bias + dropout + causal + ragged seq in one call
        q, k, v = self._qkv(jax.random.PRNGKey(38), (2, 2, 50, 128))
        seg = (jnp.arange(50) // 20).astype(jnp.int32)[None, :].repeat(2, 0)
        bias = 0.1 * jax.random.normal(jax.random.PRNGKey(39), (2, 1, 50, 50))
        kwargs = dict(
            causal=True, bias=bias, q_segment_ids=seg, kv_segment_ids=seg,
            dropout_rate=0.1, dropout_seed=42,
        )
        got = flash_attention(q, k, v, block_q=16, block_k=16,
                              implementation="pallas", **kwargs)
        want = flash_attention(q, k, v, implementation="xla", **kwargs)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_large_uint32_seed(self):
        q, k, v = self._qkv(jax.random.PRNGKey(40), (1, 1, 32, 128))
        got = flash_attention(q, k, v, dropout_rate=0.2,
                              dropout_seed=0xDEADBEEF, block_q=16,
                              block_k=16, implementation="pallas")
        want = flash_attention(q, k, v, dropout_rate=0.2,
                               dropout_seed=0xDEADBEEF,
                               implementation="xla")
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_sub_4d_bias(self):
        q, k, v = self._qkv(jax.random.PRNGKey(41), (2, 2, 16, 128))
        bias = jax.random.normal(jax.random.PRNGKey(42), (16, 16))
        got = flash_attention(q, k, v, bias=bias, block_q=16, block_k=16,
                              implementation="pallas")
        want = mha_reference(q, k, v, bias=bias[None, None])
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_constant_mask_bias_skips_dbias(self):
        q, k, v = self._qkv(jax.random.PRNGKey(43), (1, 2, 32, 128))
        # keep the diagonal unmasked: a q row with NO live causal entry
        # is degenerate — the kernel's single-pass softmax and the
        # reference's spread-then-zero convention legitimately differ
        # there, and this test is about dbias skipping, not dead rows
        keep = jnp.logical_or(
            jax.random.bernoulli(jax.random.PRNGKey(44), 0.8, (1, 1, 32, 32)),
            jnp.eye(32, dtype=bool),
        )
        bias = jnp.where(keep, 0.0, -1e30)

        def loss(q, k, v, bias):
            return jnp.sum(flash_attention(
                q, k, v, bias=bias, bias_requires_grad=False,
                causal=True, block_q=16, block_k=16,
                implementation="pallas",
            ) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2, 3))(q, k, v, bias)
        # q/k/v grads match the XLA path; bias cotangent is hard zero
        def loss_ref(q, k, v):
            return jnp.sum(mha_reference(q, k, v, bias=bias, causal=True) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g[:3], gr):
            np.testing.assert_allclose(a, b, atol=1e-4)
        np.testing.assert_allclose(g[3], 0.0, atol=0)

    def test_explicit_pallas_raises_without_pallas(self, monkeypatch):
        from apex_tpu.ops import attention as attn_mod
        from apex_tpu.ops.common import KernelLoweringError

        q = k = v = jnp.ones((1, 1, 8, 8))
        monkeypatch.setattr(attn_mod, "pl", None)
        with pytest.raises(KernelLoweringError):
            attn_mod.flash_attention(q, k, v, implementation="pallas")
        # auto mode still degrades gracefully
        out = attn_mod.flash_attention(q, k, v)
        assert out.shape == (1, 1, 8, 8)


def test_masked_softmax_explicit_pallas_raises():
    """No silent degradation: the masked variant has no pallas kernel,
    so an explicit request errors instead of silently running XLA."""
    from apex_tpu.ops.common import KernelLoweringError

    x = jnp.zeros((1, 8, 8))
    mask = jnp.zeros((1, 8, 8), bool)
    with pytest.raises(KernelLoweringError):
        scaled_masked_softmax(x, mask, implementation="pallas")
    # auto + explicit xla still fine
    out = scaled_masked_softmax(x, mask)
    assert out.shape == (1, 8, 8)


class TestFp32DispatchWindow:
    """fp32 short-seq auto mode routes to XLA (measured window,
    KERNELS_TPU.json); bf16 and explicit requests are unaffected."""

    def _spy(self, monkeypatch):
        from apex_tpu.ops import attention as attn_mod
        from apex_tpu.utils import platform as plat

        calls = []

        def fake_pallas(q, k, v, *a, **kw):
            calls.append(q.dtype)
            return jnp.zeros(q.shape, q.dtype)

        from apex_tpu.ops import attention_mid as mid_mod

        monkeypatch.setattr(attn_mod, "_flash_attention_pallas", fake_pallas)
        # the mid tier is part of the pallas kernel family: these tests
        # pin the fp32-vs-kernel WINDOW, not which tier takes the shape
        # (tier routing has its own tests in test_attention_mid.py)
        monkeypatch.setattr(
            mid_mod, "_fmha_mid_pallas",
            lambda q, *a, **kw: fake_pallas(q, None, None))
        monkeypatch.setattr(plat, "_current_platform", lambda: "tpu")
        monkeypatch.delenv("APEX_TPU_DISABLE_PALLAS", raising=False)
        monkeypatch.delenv("APEX_TPU_STRICT_KERNELS", raising=False)
        monkeypatch.delenv("APEX_TPU_FMHA_MID_MAX_SEQ", raising=False)
        return attn_mod, calls

    def test_fp32_short_seq_auto_routes_to_xla(self, monkeypatch):
        attn_mod, calls = self._spy(monkeypatch)
        q = jnp.ones((1, 1, 8, 8), jnp.float32)
        attn_mod.flash_attention(q, q, q, implementation=None)
        assert calls == []  # window fired: no pallas attempt
        # inclusive boundary: seq == FLASH_FP32_XLA_MAX_SEQ also routes
        s = attn_mod.FLASH_FP32_XLA_MAX_SEQ
        qb = jnp.ones((1, 1, s, 8), jnp.float32)
        attn_mod.flash_attention(qb, qb, qb, implementation=None)
        assert calls == []

    def test_bf16_and_explicit_fp32_still_hit_pallas(self, monkeypatch):
        from apex_tpu.ops.attention_short import FMHA_SHORT_MAX_SEQ

        attn_mod, calls = self._spy(monkeypatch)
        # above the short-kernel window so the FLASH kernel is what
        # auto mode must pick (the short window has its own dispatch
        # tests in test_attention_short.py)
        s = FMHA_SHORT_MAX_SEQ + 128
        qb = jnp.ones((1, 1, s, 8), jnp.bfloat16)
        attn_mod.flash_attention(qb, qb, qb, implementation=None)
        assert len(calls) == 1  # bf16 auto stays on pallas
        qf = jnp.ones((1, 1, 8, 8), jnp.float32)
        attn_mod.flash_attention(qf, qf, qf, implementation="pallas")
        assert len(calls) == 2  # explicit request honored for fp32

    def test_fp32_long_seq_auto_stays_pallas(self, monkeypatch):
        attn_mod, calls = self._spy(monkeypatch)
        s = attn_mod.FLASH_FP32_XLA_MAX_SEQ + 128
        q = jnp.ones((1, 1, s, 8), jnp.float32)
        attn_mod.flash_attention(q, q, q, implementation=None)
        assert len(calls) == 1  # beyond the window: pallas


class TestFp32BlockClamp:
    """fp32 blocks are clamped to the 512*1024 area before the kernel is
    built: the bwd kernels hold ~4 (block_q, block_k) fp32 temporaries
    live, and 1024x1024 fp32 blocks measured 18.3 MB of scoped vmem
    against Mosaic's 16 MB stack limit (r5 sweep compile failure)."""

    def test_fp32_oversize_blocks_clamped(self):
        from apex_tpu.ops.attention import _clamp_blocks

        assert _clamp_blocks(jnp.float32, 1024, 1024) == (512, 1024)
        assert _clamp_blocks(jnp.float32, 2048, 1024) == (512, 1024)
        assert _clamp_blocks(jnp.float32, 512, 2048) == (512, 1024)
        # at or under the area: untouched
        assert _clamp_blocks(jnp.float32, 512, 1024) == (512, 1024)
        assert _clamp_blocks(jnp.float32, 256, 512) == (256, 512)

    def test_bf16_blocks_untouched(self):
        from apex_tpu.ops.attention import _clamp_blocks

        assert _clamp_blocks(jnp.bfloat16, 1024, 1024) == (1024, 1024)
        assert _clamp_blocks(jnp.bfloat16, 2048, 2048) == (2048, 2048)

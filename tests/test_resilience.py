"""Resilience subsystem tests: every fault-injection mode driven
through save/restore/resume/StepGuard/Watchdog.

Layout mirrors the subsystem: checkpoint integrity (checksums, verify,
truncation), corruption fallback (restore_latest_valid + AutoResume),
transient-I/O retry, SIGTERM handling, the StepGuard escalation ladder,
and the Watchdog stall detector.  All corruption is injected
deterministically via apex_tpu.resilience.faults — no test asserts a
recovery path it did not first break.
"""

import io
import json
import os
import signal
import time

import numpy as np
import jax.numpy as jnp
import pytest

from apex_tpu import checkpoint as ckpt
from apex_tpu.checkpoint import CheckpointCorruptError
from apex_tpu.resilience import (
    DivergenceError,
    RetryPolicy,
    StepGuard,
    Watchdog,
    faults,
    locate_nonfinite,
)
from apex_tpu.utils.autoresume import AutoResume


@pytest.fixture(autouse=True)
def _fast_io_retry(monkeypatch):
    """Keep backoff sleeps microscopic so retry tests run in ms."""
    monkeypatch.setenv("APEX_TPU_IO_RETRIES", "3")
    monkeypatch.setenv("APEX_TPU_IO_BACKOFF_BASE", "0.001")
    monkeypatch.setenv("APEX_TPU_IO_BACKOFF_MAX", "0.01")
    yield
    # drain (and discard) any failed async handles this test created so
    # they don't resurface in a later test's wait_pending_saves()
    try:
        ckpt.wait_pending_saves(timeout=30)
    except Exception:
        pass


def _tree(v=1.0):
    return {
        "params": {"w": jnp.full((16, 8), v, jnp.float32),
                   "b": jnp.ones((8,), jnp.bfloat16)},
        "step": jnp.int32(int(v)),
    }


def _save_steps(root, steps):
    for s in steps:
        ckpt.save_step(str(root), s, _tree(float(s)))


# ===================================================== checkpoint integrity
class TestIntegrity:
    def test_verify_clean_checkpoint_is_empty(self, tmp_path):
        ckpt.save(str(tmp_path / "c"), _tree())
        assert ckpt.verify(str(tmp_path / "c")) == []

    def test_manifest_records_chunked_checksums(self, tmp_path, monkeypatch):
        # tiny chunks force the multi-chunk streaming path
        monkeypatch.setenv("APEX_TPU_CKPT_CHUNK_BYTES", "64")
        path = str(tmp_path / "c")
        ckpt.save(path, _tree())
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        integ = manifest["integrity"]
        assert integ["algo"] == "crc32"
        assert integ["chunk_bytes"] == 64
        data_rec = integ["files"]["data.bin"]
        assert data_rec["nbytes"] == os.path.getsize(
            os.path.join(path, "data.bin"))
        assert len(data_rec["chunks"]) == -(-data_rec["nbytes"] // 64)
        assert "treedef.pkl" in integ["files"]
        assert ckpt.verify(path) == []

    def test_verify_flags_exactly_the_bitflipped_file(self, tmp_path):
        path = str(tmp_path / "c")
        ckpt.save(path, _tree())
        faults.flip_bit(os.path.join(path, "data.bin"),
                        byte_offset=17, bit=5)
        assert ckpt.verify(path) == ["data.bin"]

    def test_verify_flags_corrupt_treedef(self, tmp_path):
        path = str(tmp_path / "c")
        ckpt.save(path, _tree())
        faults.flip_bit(os.path.join(path, "treedef.pkl"), byte_offset=3)
        assert ckpt.verify(path) == ["treedef.pkl"]

    def test_verify_flags_missing_file(self, tmp_path):
        path = str(tmp_path / "c")
        ckpt.save(path, _tree())
        faults.remove_file(os.path.join(path, "treedef.pkl"))
        assert ckpt.verify(path) == ["treedef.pkl"]

    def test_verify_flags_unreadable_manifest(self, tmp_path):
        path = str(tmp_path / "c")
        ckpt.save(path, _tree())
        faults.truncate_file(os.path.join(path, "manifest.json"))
        assert ckpt.verify(path) == ["manifest.json"]

    def test_truncated_blob_raises_clear_corrupt_error(self, tmp_path):
        path = str(tmp_path / "c")
        ckpt.save(path, _tree())
        faults.truncate_file(os.path.join(path, "data.bin"))
        with pytest.raises(CheckpointCorruptError, match="truncated"):
            ckpt.restore(path)

    def test_bitflip_same_length_passes_length_check_fails_verify(
            self, tmp_path):
        """A flip keeps the byte length — only the checksum catches it;
        restore(verify_integrity=True) refuses to hand back garbage."""
        path = str(tmp_path / "c")
        ckpt.save(path, _tree())
        faults.flip_bit(os.path.join(path, "data.bin"), byte_offset=0)
        ckpt.restore(path)  # length check alone cannot see the flip
        with pytest.raises(CheckpointCorruptError, match="data.bin"):
            ckpt.restore(path, verify_integrity=True)

    def test_mangled_but_parseable_manifest_flagged_not_raised(
            self, tmp_path):
        """A bit flip inside a manifest key can survive json.load;
        verify must report the manifest, restore must raise
        CheckpointCorruptError, and the fallback walk must skip it —
        never a bare KeyError."""
        _save_steps(tmp_path, (1, 2))
        mpath = str(tmp_path / "step_2" / "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["lgaves"] = manifest.pop("leaves")  # flipped key byte
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        assert ckpt.verify(str(tmp_path / "step_2")) == ["manifest.json"]
        with pytest.raises(CheckpointCorruptError):
            ckpt.restore(str(tmp_path / "step_2"))
        state, step = AutoResume(str(tmp_path)).resume()
        assert step == 1

    def test_corrupt_treedef_raises_corrupt_error_and_falls_back(
            self, tmp_path):
        """pickle.loads on flipped treedef bytes raises arbitrary
        exception types (ValueError, KeyError, ...); restore must fold
        them all into CheckpointCorruptError so the fallback walk can
        skip the step — including on legacy checkpoints where no CRC
        catches the flip first."""
        _save_steps(tmp_path, (1, 2))
        path = str(tmp_path / "step_2")
        mpath = os.path.join(path, "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        del manifest["integrity"]  # legacy: verify can't see the flip
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        faults.flip_bit(os.path.join(path, "treedef.pkl"),
                        byte_offset=0, bit=1)
        with pytest.raises(CheckpointCorruptError, match="treedef"):
            ckpt.restore(path)
        _, step = AutoResume(str(tmp_path)).resume()
        assert step == 1

    def test_zero_chunk_bytes_manifest_flagged_not_raised(self, tmp_path):
        """integrity.chunk_bytes mangled to 0 must not leak a bare
        ValueError (range step 0) out of verify/restore/fallback."""
        _save_steps(tmp_path, (1, 2))
        mpath = str(tmp_path / "step_2" / "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["integrity"]["chunk_bytes"] = 0
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        # verify streams with read(0) → empty CRC replay mismatches the
        # recorded chunks: the payload files are flagged, nothing raises
        assert ckpt.verify(str(tmp_path / "step_2")) != []
        with pytest.raises(CheckpointCorruptError):
            ckpt.restore(str(tmp_path / "step_2"), verify_integrity=True)
        _, step = AutoResume(str(tmp_path)).resume()
        assert step == 1

    def test_verify_requires_integrity_coverage_of_payload_files(
            self, tmp_path):
        """A parseable manifest whose integrity section LOST its
        data.bin entry must read as corrupt, not clean — the blob would
        otherwise go unchecksummed and a bit flip would pass verify."""
        _save_steps(tmp_path, (1, 2))
        mpath = str(tmp_path / "step_2" / "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        del manifest["integrity"]["files"]["data.bin"]
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        assert ckpt.verify(str(tmp_path / "step_2")) == ["data.bin"]
        with pytest.raises(CheckpointCorruptError, match="data.bin"):
            ckpt.restore(str(tmp_path / "step_2"), verify_integrity=True)
        _, step = AutoResume(str(tmp_path)).resume()
        assert step == 1

    def test_legacy_manifest_without_integrity_section(self, tmp_path):
        """Pre-integrity checkpoints still verify (length/existence
        only) and still restore."""
        path = str(tmp_path / "c")
        ckpt.save(path, _tree(7.0))
        mpath = os.path.join(path, "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        del manifest["integrity"]
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        assert ckpt.verify(path) == []
        out = ckpt.restore(path)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]), 7.0)
        faults.truncate_file(os.path.join(path, "data.bin"))
        assert ckpt.verify(path) == ["data.bin"]


# ==================================================== corruption fallback
class TestFallback:
    def test_restore_latest_valid_walks_past_corruption(self, tmp_path):
        _save_steps(tmp_path, (1, 2, 3))
        faults.flip_bit(str(tmp_path / "step_3" / "data.bin"), 9)
        tree, step = ckpt.restore_latest_valid(str(tmp_path))
        assert step == 2
        np.testing.assert_array_equal(np.asarray(tree["params"]["w"]), 2.0)
        # second-newest also corrupt → keeps walking
        faults.truncate_file(str(tmp_path / "step_2" / "data.bin"))
        tree, step = ckpt.restore_latest_valid(str(tmp_path))
        assert step == 1

    def test_restore_latest_valid_none_when_all_corrupt(self, tmp_path):
        _save_steps(tmp_path, (1, 2))
        for s in (1, 2):
            faults.remove_file(str(tmp_path / f"step_{s}" / "data.bin"))
        tree, step = ckpt.restore_latest_valid(str(tmp_path))
        assert tree is None and step is None
        assert ckpt.restore_latest_valid(str(tmp_path / "nowhere")) == \
            (None, None)

    def test_latest_valid_step_skips_bad(self, tmp_path):
        _save_steps(tmp_path, (4, 8))
        assert ckpt.latest_valid_step(str(tmp_path)) == 8
        faults.flip_bit(str(tmp_path / "step_8" / "data.bin"), 2)
        assert ckpt.latest_valid_step(str(tmp_path)) == 4
        assert ckpt.latest_step(str(tmp_path)) == 8  # raw view unchanged

    def test_autoresume_falls_back_past_corrupt_newest(self, tmp_path):
        """Acceptance criterion: bit-flipped newest step → resume
        returns the previous valid step."""
        _save_steps(tmp_path, (5, 10, 15))
        faults.flip_bit(str(tmp_path / "step_15" / "data.bin"),
                        byte_offset=33, bit=7)
        state, step = AutoResume(str(tmp_path), keep=3).resume()
        assert step == 10
        np.testing.assert_array_equal(
            np.asarray(state["params"]["w"]), 10.0)

    def test_autoresume_falls_back_past_truncated_newest(self, tmp_path):
        _save_steps(tmp_path, (5, 10))
        faults.truncate_file(str(tmp_path / "step_10" / "data.bin"))
        state, step = AutoResume(str(tmp_path)).resume()
        assert step == 5

    def test_autoresume_fresh_when_only_husks(self, tmp_path):
        (tmp_path / "step_3.tmp").mkdir()
        state, step = AutoResume(str(tmp_path)).resume()
        assert state is None and step == 0


# ========================================================== retry on OSError
class TestRetry:
    def test_save_retries_transient_oserror_then_succeeds(self, tmp_path):
        path = str(tmp_path / "c")
        with faults.failing_writes(fail_first=2):
            ckpt.save(path, _tree(3.0))
        assert ckpt.verify(path) == []
        out = ckpt.restore(path)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]), 3.0)

    def test_save_async_retries_then_succeeds(self, tmp_path):
        path = str(tmp_path / "a")
        with faults.failing_writes(fail_first=1):
            h = ckpt.save_async(path, _tree(4.0))
            h.result(timeout=30)  # drain inside the patch's scope
        assert ckpt.verify(path) == []
        out = ckpt.restore(path)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]), 4.0)

    def test_save_retry_exhausted_raises(self, tmp_path):
        with faults.failing_writes(forever=True):
            with pytest.raises(faults.InjectedIOError):
                ckpt.save(str(tmp_path / "c"), _tree())
        assert not os.path.exists(str(tmp_path / "c"))

    def test_async_retry_exhausted_surfaces_at_result(self, tmp_path):
        with faults.failing_writes(forever=True):
            h = ckpt.save_async(str(tmp_path / "c"), _tree())
            with pytest.raises(faults.InjectedIOError):
                h.result(timeout=30)

    def test_save_retries_through_failed_rename(self, tmp_path):
        """The rename is the one step where a fault could lose the
        previous checkpoint (it was already rmtree'd): the retry must
        rebuild the tmp dir and land the rename on a later attempt."""
        path = str(tmp_path / "c")
        ckpt.save(path, _tree(1.0))
        with faults.failing_renames(fail_first=2) as count:
            ckpt.save(path, _tree(2.0))
        assert count[0] == 2
        assert ckpt.verify(path) == []
        out = ckpt.restore(path)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]), 2.0)

    def test_rename_retry_exhausted_raises(self, tmp_path):
        with faults.failing_renames(forever=True):
            with pytest.raises(faults.InjectedIOError):
                ckpt.save(str(tmp_path / "c"), _tree())

    def test_rename_exhausted_preserves_previous_checkpoint(self, tmp_path):
        """Overwrite-mode save parks the old checkpoint aside; if every
        rename attempt fails, the old checkpoint is restored — retry
        exhaustion must never leave a hole where a checkpoint was."""
        path = str(tmp_path / "c")
        ckpt.save(path, _tree(1.0))
        with faults.failing_renames(forever=True):
            with pytest.raises(faults.InjectedIOError):
                ckpt.save(path, _tree(2.0))
        assert ckpt.verify(path) == []
        out = ckpt.restore(path)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]), 1.0)
        assert not os.path.exists(path + ".old")  # parked copy renamed back

    def test_parked_old_checkpoint_from_crashed_attempt_is_recovered(
            self, tmp_path):
        """A prior attempt (or process) that died between parking the
        old checkpoint at .old and landing the new rename must not have
        its parked copy destroyed by the next attempt — it is the only
        surviving copy and gets renamed back into place."""
        path = str(tmp_path / "c")
        ckpt.save(path, _tree(1.0))
        os.rename(path, path + ".old")  # simulated crash window
        with faults.failing_renames(forever=True):
            with pytest.raises(faults.InjectedIOError):
                ckpt.save(path, _tree(2.0))
        assert ckpt.verify(path) == []
        out = ckpt.restore(path)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]), 1.0)

    def test_read_paths_heal_crash_between_park_and_rename(self, tmp_path):
        """SIGKILL between parking the old checkpoint at .old and the
        tmp→final rename strands the only complete copy at .old; the
        read paths (verify/restore) must recover it, not wait for the
        next save to the same path."""
        path = str(tmp_path / "c")
        ckpt.save(path, _tree(5.0))
        os.rename(path, path + ".old")  # the crash window, frozen
        assert ckpt.verify(path) == []  # healed on read
        out = ckpt.restore(path)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]), 5.0)
        assert not os.path.exists(path + ".old")

    def test_retry_only_matching_paths(self, tmp_path):
        """path_substr scopes injection: the other checkpoint's writes
        pass through untouched."""
        with faults.failing_writes(forever=True, path_substr="doomed"):
            ckpt.save(str(tmp_path / "fine"), _tree())
            with pytest.raises(faults.InjectedIOError):
                ckpt.save(str(tmp_path / "doomed"), _tree())
        assert ckpt.verify(str(tmp_path / "fine")) == []

    def test_retry_policy_env_and_bounds(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_IO_RETRIES", "5")
        monkeypatch.setenv("APEX_TPU_IO_BACKOFF_BASE", "0.25")
        monkeypatch.setenv("APEX_TPU_IO_BACKOFF_MAX", "1.0")
        p = RetryPolicy()
        assert p.retries == 5
        for attempt in range(1, 8):
            d = p.sleep_for(attempt)
            assert 0.0 <= d <= min(1.0, 0.25 * 2 ** (attempt - 1))
        with pytest.raises(ValueError):
            RetryPolicy(retries=-1)

    def test_retry_counts_attempts_and_gives_up(self):
        calls = []

        def flaky():
            calls.append(1)
            raise OSError("transient")

        p = RetryPolicy(retries=2, backoff_base=1e-4, backoff_max=1e-3)
        with pytest.raises(OSError):
            p.call(flaky)
        assert len(calls) == 3  # 1 try + 2 retries

    def test_retry_does_not_catch_programming_errors(self):
        calls = []

        def broken():
            calls.append(1)
            raise TypeError("bug, not weather")

        with pytest.raises(TypeError):
            RetryPolicy(retries=3, backoff_base=1e-4).call(broken)
        assert len(calls) == 1


# ============================================================ SIGTERM faults
class TestSigterm:
    def test_sigterm_mid_save_marks_termination_and_save_lands(
            self, tmp_path):
        prev = signal.getsignal(signal.SIGTERM)
        try:
            ar = AutoResume(str(tmp_path), interval_steps=1000,
                            install_sigterm_handler=True)
            with faults.sigterm_on_write(nth=1):
                assert ar.maybe_save(7, _tree(7.0), force=True)
            assert ar.termination_requested()
            # the interrupted save still completed and verifies
            state, step = AutoResume(str(tmp_path)).resume()
            assert step == 7
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_sigterm_chains_previously_installed_handler(self, tmp_path):
        prev = signal.getsignal(signal.SIGTERM)
        seen = []
        try:
            signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
            ar = AutoResume(str(tmp_path), install_sigterm_handler=True)
            os.kill(os.getpid(), signal.SIGTERM)
            assert ar.termination_requested()
            assert seen == [signal.SIGTERM]  # prior handler still ran
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_termination_save_happens_once_not_every_step(self, tmp_path):
        ar = AutoResume(str(tmp_path), interval_steps=1000, keep=2)
        ar.request_termination()
        assert ar.maybe_save(3, _tree(3.0))
        # flag consumed: later steps do NOT re-save / GC-churn …
        assert not ar.maybe_save(4, _tree(4.0))
        assert not ar.maybe_save(5, _tree(5.0))
        # … but the loop still sees the request and exits
        assert ar.termination_requested()
        # a fresh request re-arms exactly one more forced save
        ar.request_termination()
        assert ar.maybe_save(6, _tree(6.0))
        assert not ar.maybe_save(7, _tree(7.0))


# ============================================================== AutoResume
class TestAutoResumeValidation:
    def test_keep_must_be_at_least_one(self, tmp_path):
        with pytest.raises(ValueError, match="keep"):
            AutoResume(str(tmp_path), keep=0)
        with pytest.raises(ValueError, match="interval_steps"):
            AutoResume(str(tmp_path), interval_steps=0)

    def test_keep_one_never_deletes_what_it_just_wrote(self, tmp_path):
        ar = AutoResume(str(tmp_path), interval_steps=1, keep=1)
        for s in (1, 2, 3):
            ar.maybe_save(s, _tree(float(s)))
        assert sorted(os.listdir(str(tmp_path))) == ["step_3"]
        _, step = ar.resume()
        assert step == 3

    def test_gc_keeps_valid_checkpoint_over_corrupt_newer(self, tmp_path):
        """keep=1, step_8 valid, step_9/10 bit-flipped: resume falls
        back to 8, the next save overwrites step_9 — GC must keep that
        just-written valid checkpoint and remove the corrupt step_10,
        NOT the reverse (corrupt dirs never count toward ``keep``)."""
        _save_steps(tmp_path, (8, 9, 10))
        for s in (9, 10):
            faults.flip_bit(str(tmp_path / f"step_{s}" / "data.bin"), 5)
        ar = AutoResume(str(tmp_path), interval_steps=1, keep=1)
        _, step = ar.resume()
        assert step == 8
        assert ar.maybe_save(9, _tree(9.0))
        assert sorted(os.listdir(str(tmp_path))) == ["step_9"]
        assert ckpt.latest_valid_step(str(tmp_path)) == 9
        _, step = AutoResume(str(tmp_path)).resume()
        assert step == 9

    def test_gc_removes_corrupt_dirs_in_keep_window(self, tmp_path):
        """Corrupt dirs inside the newest-``keep`` window are deleted
        and their keep slots go to older valid checkpoints."""
        _save_steps(tmp_path, (8, 9, 10))
        for s in (9, 10):
            faults.truncate_file(str(tmp_path / f"step_{s}" / "data.bin"))
        ar = AutoResume(str(tmp_path), interval_steps=1, keep=2)
        _, step = ar.resume()
        assert step == 8
        assert ar.maybe_save(11, _tree(11.0))
        assert sorted(os.listdir(str(tmp_path))) == ["step_11", "step_8"]

    def test_gc_retains_checkpoint_on_transient_verify_error(
            self, tmp_path, monkeypatch):
        """A storage blip while GC verifies a dir must not condemn it:
        the dir stays on disk (uncounted), only genuinely corrupt or
        beyond-quota dirs are removed."""
        _save_steps(tmp_path, (8, 9))
        ar = AutoResume(str(tmp_path), interval_steps=1, keep=2)
        real_verify = ckpt.verify

        def flaky_verify(path, **kw):
            if path.endswith("step_9"):
                raise OSError("transient read error")
            return real_verify(path, **kw)

        monkeypatch.setattr(ckpt, "verify", flaky_verify)
        assert ar.maybe_save(10, _tree(10.0))
        names = sorted(os.listdir(str(tmp_path)))
        # step_9 is inside the keep window but could not be verified:
        # retained (uncounted), its keep slot going to valid step_8
        assert names == ["step_10", "step_8", "step_9"]


# ============================================================== StepGuard
class TestStepGuard:
    def test_escalation_warn_rollback_raise(self, tmp_path):
        """Acceptance criterion: scripted divergence triggers rollback
        after K consecutive nonfinite steps, then raises."""
        _save_steps(tmp_path, (1, 2))
        ar = AutoResume(str(tmp_path), keep=2)
        g = StepGuard(autoresume=ar, warn_after=2, rollback_after=3,
                      raise_after=5)
        assert g.observe(False).action == "ok"      # 1 bad: below warn
        assert g.observe(False).action == "warn"    # 2
        v = g.observe(False)                        # 3: rollback
        assert v.action == "rollback"
        assert v.restored_step == 2
        np.testing.assert_array_equal(
            np.asarray(v.restored_state["params"]["w"]), 2.0)
        assert g.observe(False).action == "warn"    # 4: already rolled back
        with pytest.raises(DivergenceError, match="5 consecutive"):
            g.observe(False)                        # 5: raise

    def test_finite_step_resets_counter_and_rearms_rollback(self, tmp_path):
        _save_steps(tmp_path, (1,))
        ar = AutoResume(str(tmp_path))
        g = StepGuard(autoresume=ar, warn_after=1, rollback_after=2,
                      raise_after=10)
        g.observe(False)
        assert g.observe(False).action == "rollback"
        assert g.observe(True).action == "ok"
        assert g.consecutive_bad == 0
        g.observe(False)
        assert g.observe(False).action == "rollback"  # new episode

    def test_equal_rollback_and_raise_thresholds_still_roll_back(
            self, tmp_path):
        """rollback_after == raise_after is valid config: the rollback
        gets its chance first, the raise fires on the next bad step."""
        _save_steps(tmp_path, (1,))
        g = StepGuard(autoresume=AutoResume(str(tmp_path)),
                      warn_after=1, rollback_after=3, raise_after=3)
        g.observe(False)
        g.observe(False)
        assert g.observe(False).action == "rollback"
        with pytest.raises(DivergenceError):
            g.observe(False)

    def test_rollback_discards_newer_step_dirs(self, tmp_path):
        """Rollback must be durable: step dirs newer than the restored
        step are removed, so a crash right after rollback resumes from
        the rollback point instead of a stale newer checkpoint."""
        _save_steps(tmp_path, (2, 4))
        faults.flip_bit(str(tmp_path / "step_4" / "data.bin"), 3)
        ar = AutoResume(str(tmp_path), keep=3)
        g = StepGuard(autoresume=ar, warn_after=1, rollback_after=1,
                      raise_after=3)
        v = g.observe(False)
        assert v.action == "rollback" and v.restored_step == 2
        assert not os.path.exists(str(tmp_path / "step_4"))
        _, step = AutoResume(str(tmp_path)).resume()
        assert step == 2

    def test_rollback_skips_checksum_valid_diverged_checkpoint(
            self, tmp_path):
        """A divergence that outlives a save interval leaves
        checksum-valid NaN snapshots on disk; rollback must walk past
        them (and remove them) instead of resuming into the diverged
        state."""
        ckpt.save_step(str(tmp_path), 2, _tree(2.0))
        ckpt.save_step(str(tmp_path), 4, faults.poison_tree(_tree(4.0)))
        assert ckpt.verify(str(tmp_path / "step_4")) == []  # checksums ok
        ar = AutoResume(str(tmp_path), keep=3)
        g = StepGuard(autoresume=ar, warn_after=1, rollback_after=1,
                      raise_after=3)
        v = g.observe(False)
        assert v.action == "rollback"
        assert v.restored_step == 2
        assert np.isfinite(
            np.asarray(v.restored_state["params"]["w"])).all()
        assert not os.path.exists(str(tmp_path / "step_4"))
        _, step = AutoResume(str(tmp_path)).resume()
        assert step == 2

    def test_rollback_quarantines_rather_than_deletes(self, tmp_path):
        """When every checkpoint on disk is checksum-valid-but-NaN,
        rollback must not erase the training history: each is renamed
        to step_<N>.discarded (invisible to resume, preserved for
        forensics) and the verdict carries state=None."""
        for s in (2, 4):
            ckpt.save_step(str(tmp_path), s,
                           faults.poison_tree(_tree(float(s))))
        ar = AutoResume(str(tmp_path), keep=3)
        g = StepGuard(autoresume=ar, warn_after=1, rollback_after=1,
                      raise_after=3)
        v = g.observe(False)
        assert v.action == "rollback" and v.restored_state is None
        assert sorted(os.listdir(str(tmp_path))) == \
            ["step_2.discarded", "step_4.discarded"]
        assert AutoResume(str(tmp_path)).resume() == (None, 0)

    def test_rollback_terminates_when_discard_has_no_effect(self, tmp_path):
        """If discarding a poisoned checkpoint silently fails (e.g. no
        delete permission), resume hands the same step back — the walk
        must bail out with that state instead of looping forever."""
        root = str(tmp_path)
        ckpt.save_step(root, 2, faults.poison_tree(_tree(2.0)))

        class StuckAutoResume:
            def resume(self, target=None):
                return ckpt.restore_latest_valid(root, target=target)

            def discard_step(self, step):
                pass  # broken: the dir never actually goes away

            def discard_steps_after(self, step):
                pass

        g = StepGuard(autoresume=StuckAutoResume(), warn_after=1,
                      rollback_after=1, raise_after=3)
        v = g.observe(False)  # must terminate
        assert v.action == "rollback" and v.restored_step == 2

    def test_rollback_skipped_without_autoresume(self):
        g = StepGuard(warn_after=1, rollback_after=2, raise_after=4)
        assert g.observe(False).action == "warn"
        assert g.observe(False).action == "warn"  # no AR → no rollback
        g.observe(False)
        with pytest.raises(DivergenceError):
            g.observe(False)

    def test_scale_floor_alarm(self):
        from apex_tpu.amp import LossScaler

        scaler = LossScaler(min_loss_scale=128.0, init_scale=128.0)
        state = scaler.init()
        g = StepGuard(scaler=scaler, warn_after=100, rollback_after=100,
                      raise_after=200)
        v = g.observe(False, scaler_state=state)
        assert v.at_scale_floor
        assert v.action == "warn"  # pinned scale alarms before warn_after

    def test_no_floor_alarm_at_healthy_scale(self):
        from apex_tpu.amp import LossScaler

        scaler = LossScaler()
        state = scaler.init()  # 2**16, floor 1.0
        g = StepGuard(scaler=scaler, warn_after=100, rollback_after=100,
                      raise_after=200)
        v = g.observe(False, scaler_state=state)
        assert not v.at_scale_floor and v.action == "ok"

    def test_threshold_ordering_validated(self):
        with pytest.raises(ValueError):
            StepGuard(warn_after=5, rollback_after=3, raise_after=10)
        with pytest.raises(ValueError):
            StepGuard(warn_after=0)

    def test_nan_localization_names_the_leaf(self):
        grads = {"layer0": {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))},
                 "layer1": {"w": jnp.ones((2, 2))}}
        bad = faults.poison_tree(grads, leaf_index=2, element=1)
        located = locate_nonfinite(bad)
        assert len(located) == 1
        assert "layer1" in located[0] and "w" in located[0]
        assert "nan x1/4" in located[0]

    def test_localization_sees_bfloat16_leaves(self):
        """bf16 is the common TPU gradient dtype; localization (and the
        poison harness) must treat it as floating even though bare
        numpy does not."""
        grads = {"wq": jnp.ones((4,), jnp.bfloat16)}
        bad = faults.poison_tree(grads, element=2)
        (entry,) = locate_nonfinite(bad)
        assert "wq" in entry and "nan x1/4" in entry

    def test_localization_distinguishes_inf(self):
        bad = faults.poison_tree({"g": jnp.zeros((8,))},
                                 value=float("inf"), element=3)
        (entry,) = locate_nonfinite(bad)
        assert "inf" in entry and "nan" not in entry

    def test_divergence_error_carries_localization(self, tmp_path):
        g = StepGuard(warn_after=1, rollback_after=1, raise_after=2)
        grads = faults.poison_tree({"wq": jnp.ones((3,))})
        g.observe(False, grads=grads)
        with pytest.raises(DivergenceError, match="wq"):
            g.observe(False, grads=grads)


class TestPoisonTree:
    def test_poisons_exactly_one_element(self):
        tree = {"a": jnp.zeros((4,)), "n": jnp.arange(3)}  # n: int, skipped
        out = faults.poison_tree(tree, leaf_index=0, element=2)
        a = np.asarray(out["a"])
        assert np.isnan(a[2]) and np.isfinite(a[[0, 1, 3]]).all()
        np.testing.assert_array_equal(np.asarray(out["n"]), [0, 1, 2])

    def test_rejects_treeless_or_out_of_range(self):
        with pytest.raises(ValueError, match="no floating"):
            faults.poison_tree({"i": jnp.arange(3)})
        with pytest.raises(ValueError, match="out of range"):
            faults.poison_tree({"a": jnp.zeros(2)}, leaf_index=5)


# =============================================================== Watchdog
class TestWatchdog:
    def test_stall_dumps_stacks_and_fires_callback(self):
        buf = io.StringIO()
        hits = []
        with Watchdog(deadline_s=0.15, poll_s=0.02, stream=buf,
                      on_stall=lambda e, t: hits.append((e, t))) as wd:
            time.sleep(0.5)  # no beat → stall
        assert wd.stall_count == 1  # one dump per episode, not per poll
        assert hits and hits[0][0] >= 0.15
        dump = buf.getvalue()
        assert "watchdog stack dump" in dump
        assert "apex-tpu-watchdog" in dump  # all threads, incl. itself

    def test_beats_prevent_stall(self):
        buf = io.StringIO()
        with Watchdog(deadline_s=0.2, poll_s=0.02, stream=buf) as wd:
            for _ in range(10):
                time.sleep(0.04)
                wd.beat()
        assert wd.stall_count == 0
        assert buf.getvalue() == ""

    def test_beat_after_stall_rearms(self):
        buf = io.StringIO()
        with Watchdog(deadline_s=0.12, poll_s=0.02, stream=buf) as wd:
            time.sleep(0.3)   # episode 1
            wd.beat()
            time.sleep(0.3)   # episode 2
        assert wd.stall_count == 2

    def test_callback_failure_does_not_kill_watchdog(self):
        buf = io.StringIO()

        def bad_callback(elapsed, text):
            raise RuntimeError("observer bug")

        with Watchdog(deadline_s=0.1, poll_s=0.02, stream=buf,
                      on_stall=bad_callback) as wd:
            time.sleep(0.25)
            wd.beat()
            time.sleep(0.25)
        assert wd.stall_count == 2  # survived the broken callback

    def test_lifecycle_validation(self):
        with pytest.raises(ValueError):
            Watchdog(deadline_s=0.0)
        wd = Watchdog(deadline_s=10.0)
        wd.start()
        with pytest.raises(RuntimeError, match="already running"):
            wd.start()
        wd.stop()
        wd.stop()  # idempotent
        wd.start()  # restartable
        wd.stop()


# ================================================= end-to-end divergence run
def test_scripted_divergence_training_loop(tmp_path):
    """A toy loop: healthy steps checkpoint, then gradients go NaN;
    StepGuard warns, rolls the state back to the last good checkpoint,
    and finally raises when divergence persists."""
    from apex_tpu.amp import LossScaler

    scaler = LossScaler(init_scale=2.0 ** 8)
    sstate = scaler.init()
    ar = AutoResume(str(tmp_path), interval_steps=2, keep=2)
    guard = StepGuard(scaler=scaler, autoresume=ar, warn_after=2,
                      rollback_after=3, raise_after=6)

    state = {"params": {"w": jnp.zeros((4,))}, "step": jnp.int32(0)}
    rolled_back_to = None
    with pytest.raises(DivergenceError):
        for step in range(1, 20):
            diverged = step > 6
            grads = {"w": jnp.full((4,), float("nan") if diverged
                                   else 0.1)}
            grads, finite = scaler.unscale(sstate, grads)
            sstate = scaler.adjust(sstate, finite)
            if bool(finite):
                state = {"params": {"w": state["params"]["w"]
                                    - 0.1 * grads["w"]},
                         "step": jnp.int32(step)}
            ar.maybe_save(step, state)
            verdict = guard.observe(finite, step=step,
                                    scaler_state=sstate, grads=grads)
            if verdict.action == "rollback":
                state = verdict.restored_state
                rolled_back_to = verdict.restored_step
    # the interval save at step 8 checkpointed the (skip-step-protected)
    # step-6 state, so rollback lands there and the params are the last
    # finite ones
    assert rolled_back_to == 8
    assert int(np.asarray(state["step"])) == 6
    assert np.isfinite(np.asarray(state["params"]["w"])).all()

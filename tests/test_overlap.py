"""Overlapped bucketed gradient sync on the 8-device virtual mesh.

Covers the bucket-assembly invariants (every leaf exactly once,
reverse-layer order, size targets), the bit-identity guarantees
(bucketed single-shot reduce vs unbucketed at ``compression=None``;
pipelined loop vs the per-microbatch reference, and vs the deferred
seed path at K=1), int8+error-feedback parity within the PR 3
tolerance, the bucketed residual state's checkpoint round-trip, a GPT
accumulation-loop numerics test against the unbucketed seed path, and
the scheduled-HLO overlap audit (async start/done pair counting +
dataflow overlappability).
"""

import importlib.util
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.quantization import CompressionConfig
from apex_tpu.parallel import (
    GradientBuckets,
    all_reduce_gradients,
    data_parallel_mesh,
    hierarchical_data_parallel_mesh,
)
from apex_tpu.parallel.distributed import (
    Reducer,
    comm_state_specs,
    init_comm_state,
)
from apex_tpu.parallel.overlap import (
    bucket_comm_state,
    is_bucketed_residuals,
)

try:  # jax >= 0.6 spelling
    _shard_map = jax.shard_map
    _SM_KW = {"check_vma": False}
except AttributeError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _SM_KW = {"check_rep": False}


def smap(f, mesh, in_specs, out_specs):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **_SM_KW)


DCN, ICI = 2, 4
AXES = ("dcn", "ici")


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests require 8 virtual devices"
    return hierarchical_data_parallel_mesh(ici_size=ICI)


@pytest.fixture(scope="module")
def flat_mesh():
    return data_parallel_mesh()


def _grads(key=5):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    return {"w": jax.random.normal(ks[0], (8, 33, 7)),
            "b": jax.random.normal(ks[1], (8, 9)),
            "h": jax.random.normal(ks[2], (8, 129)).astype(jnp.bfloat16)}


# ---------------------------------------------------------------- assembly


class TestBucketAssembly:
    def test_every_leaf_exactly_once(self):
        shapes = [(5, 7), (3,), (64,), (2, 2), (100,)]
        dtypes = [jnp.float32] * 5
        plan = GradientBuckets.from_shapes(shapes, dtypes, 256)
        seen = sorted(i for b in plan.buckets for i in b.leaf_ids)
        assert seen == list(range(5))
        sizes = {i: 1 for i in range(5)}
        for b in plan.buckets:
            for i, s in zip(b.leaf_ids, b.sizes):
                expected = int(np.prod(shapes[i]))
                assert s == expected
                sizes.pop(i, None)

    def test_reverse_layer_order(self):
        """Concatenating the bucket order must give exactly the
        REVERSED tree order — the backward-ready order the reference
        discovers its buckets in."""
        shapes = [(4,)] * 6
        plan = GradientBuckets.from_shapes(
            shapes, [jnp.float32] * 6, 2 * 4 * 4)
        flat = [i for b in plan.buckets for i in b.leaf_ids]
        assert flat == [5, 4, 3, 2, 1, 0]

    def test_size_target_closes_buckets(self):
        # 6 leaves of 16 bytes each, target 40 bytes -> 2 per bucket
        plan = GradientBuckets.from_shapes(
            [(4,)] * 6, [jnp.float32] * 6, 40)
        assert [len(b.leaf_ids) for b in plan.buckets] == [2, 2, 2]
        for b in plan.buckets:
            assert b.size * 4 <= 40

    def test_oversized_leaf_gets_own_bucket(self):
        plan = GradientBuckets.from_shapes(
            [(4,), (1000,), (4,)], [jnp.float32] * 3, 64)
        by_len = [b.leaf_ids for b in plan.buckets]
        assert (1,) in by_len  # the big leaf rides alone

    def test_dtype_never_mixes(self):
        plan = GradientBuckets.from_shapes(
            [(4,), (4,), (4,)],
            [jnp.float32, jnp.bfloat16, jnp.bfloat16],
            1 << 20,
        )
        for b in plan.buckets:
            assert len({str(b.dtype)}) == 1
        # bf16 leaves (ids 2,1) share; the f32 leaf is separate
        assert [b.leaf_ids for b in plan.buckets] == [(2, 1), (0,)]

    def test_forced_dtype_merges_everything(self):
        plan = GradientBuckets.for_tree(
            {"a": jnp.ones((4,), jnp.bfloat16),
             "b": jnp.ones((4,), jnp.float32)},
            bucket_bytes=1 << 20, dtype=jnp.float32)
        assert len(plan.buckets) == 1

    def test_pack_unpack_roundtrip_bit_exact(self):
        grads = _grads()
        leaves = jax.tree.leaves(grads)
        plan = GradientBuckets.for_tree(grads, bucket_bytes=300)
        back = plan.unpack(plan.pack(leaves), leaves)
        for a, b in zip(leaves, back):
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32))
            assert a.dtype == b.dtype

    def test_validation(self):
        with pytest.raises(ValueError, match="bucket_bytes"):
            GradientBuckets.from_shapes([(4,)], [jnp.float32], 0)
        with pytest.raises(ValueError, match="exactly once"):
            GradientBuckets(
                GradientBuckets.from_shapes(
                    [(4,)], [jnp.float32], 64).buckets, 2)
        plan = GradientBuckets.from_shapes([(4,)], [jnp.float32], 64)
        with pytest.raises(ValueError, match="leaves"):
            plan.pack([jnp.ones(4), jnp.ones(4)])

    def test_zero_element_and_scalar_leaves(self, mesh):
        """A zero-element leaf must occupy 0 buffer slots (not 1) so
        unpack offsets stay aligned, and a scalar occupies exactly 1;
        the bucketed reduce stays bit-identical with both present."""
        grads = {"a": jnp.ones((3,)) * 2.0,
                 "s": jnp.float32(5.0),
                 "z": jnp.zeros((0,))}
        leaves = jax.tree.leaves(grads)
        plan = GradientBuckets.for_tree(grads, bucket_bytes=1 << 20)
        assert sum(b.size for b in plan.buckets) == 4
        back = plan.unpack(plan.pack(leaves), leaves)
        for a, b in zip(leaves, back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the BUCKETED reduce handles zero-element leaves (the seed
        # per-leaf hierarchical path cannot — psum_scatter rejects
        # empty operands), so compare against the analytic mean
        g8 = {"a": jax.random.normal(jax.random.PRNGKey(3), (8, 3)),
              "s": jax.random.normal(jax.random.PRNGKey(4), (8,)),
              "z": jnp.zeros((8, 0)),
              # bf16 + empty: forms an entirely-empty bucket (dtype
              # split), exercising the zero-size-bucket pass-through
              "y": jnp.zeros((8, 0), jnp.bfloat16)}
        spec = jax.tree.map(lambda _: P(AXES), g8)
        bucketed = jax.jit(smap(
            lambda g: all_reduce_gradients(
                g, AXES, overlap_grad_sync=True, bucket_bytes=8),
            mesh, (spec,), spec))(g8)
        assert bucketed["z"].shape == (8, 0)
        assert bucketed["y"].dtype == jnp.bfloat16
        for k in ("a", "s"):
            ref = np.broadcast_to(
                np.mean(np.asarray(g8[k]), axis=0, keepdims=True),
                g8[k].shape)
            np.testing.assert_allclose(
                np.asarray(bucketed[k]), ref, rtol=1e-6, atol=1e-7)

    def test_model_axis_union(self):
        from jax.sharding import Mesh

        devs = np.asarray(jax.devices()).reshape(2, 2, 2)
        mesh3 = Mesh(devs, ("dcn", "ici", "pp"))
        params = {"stack": jnp.zeros((2, 40)), "norm": jnp.zeros((24,))}
        pspecs = {"stack": P("pp"), "norm": P()}
        plan = GradientBuckets.for_tree(
            params, bucket_bytes=1 << 20, param_specs=pspecs,
            mesh=mesh3)
        (b,) = plan.buckets
        assert b.model_axes == ("pp",)
        # the pp-sharded leaf is sized PER DEVICE: (2//2, 40) = 40
        assert dict(zip(b.leaf_ids, b.sizes)) == {0: 24, 1: 40}


# ------------------------------------------------------------ bit identity


class TestBitIdentity:
    def test_bucketed_reduce_bit_identical_hierarchical(self, mesh):
        grads = _grads()
        spec = jax.tree.map(lambda _: P(AXES), grads)
        plain = jax.jit(smap(
            lambda g: all_reduce_gradients(g, AXES),
            mesh, (spec,), spec))(grads)
        for bb in (64, 300, 1 << 20):
            bucketed = jax.jit(smap(
                lambda g: all_reduce_gradients(
                    g, AXES, overlap_grad_sync=True, bucket_bytes=bb),
                mesh, (spec,), spec))(grads)
            for k in grads:
                np.testing.assert_array_equal(
                    np.asarray(plain[k], np.float32),
                    np.asarray(bucketed[k], np.float32))

    def test_bucketed_reduce_bit_identical_flat_axis(self, flat_mesh):
        grads = _grads()
        spec = jax.tree.map(lambda _: P("dp"), grads)
        plain = jax.jit(smap(
            lambda g: all_reduce_gradients(g, "dp"),
            flat_mesh, (spec,), spec))(grads)
        bucketed = jax.jit(smap(
            lambda g: all_reduce_gradients(
                g, "dp", overlap_grad_sync=True, bucket_bytes=256),
            flat_mesh, (spec,), spec))(grads)
        for k in grads:
            np.testing.assert_array_equal(
                np.asarray(plain[k], np.float32),
                np.asarray(bucketed[k], np.float32))

    def test_pipelined_k1_bit_identical_to_seed(self, mesh):
        def run(red):
            def step(x):
                acc = red.init(x)
                acc = red.accumulate(acc, x)
                g, _ = red.reduce(acc)
                return g

            return jax.jit(smap(step, mesh, (P(AXES),), P(AXES)))(
                jax.random.normal(jax.random.PRNGKey(7), (8, 57)))

        seed = run(Reducer(axis_name=AXES))
        over = run(Reducer(axis_name=AXES, overlap_grad_sync=True,
                           bucket_bytes=64))
        np.testing.assert_array_equal(np.asarray(seed), np.asarray(over))

    def test_pipelined_matches_per_microbatch_reference(self, mesh):
        """The pipelined loop's documented semantics — Σ_k psum(g_k),
        then the deferred path's exact scaling ops — reproduced inline
        and compared BIT-exactly."""
        x = jax.random.normal(jax.random.PRNGKey(8), (8, 100))

        def overlapped(xs):
            red = Reducer(axis_name=AXES, overlap_grad_sync=True,
                          bucket_bytes=160)
            acc = red.init(xs)
            for k in range(3):
                acc = red.accumulate(acc, (k + 1.0) * xs)
            g, _ = red.reduce(acc)
            return g

        def reference(xs):
            tot = None
            for k in range(3):
                r = all_reduce_gradients(
                    (k + 1.0) * xs, AXES, gradient_average=False)
                tot = r if tot is None else tot + r
            return tot / 8.0 / 3.0

        go = jax.jit(smap(overlapped, mesh, (P(AXES),), P(AXES)))(x)
        gr = jax.jit(smap(reference, mesh, (P(AXES),), P(AXES)))(x)
        np.testing.assert_array_equal(np.asarray(go), np.asarray(gr))

    def test_pipelined_close_to_deferred_k3(self, mesh):
        """Different summation order, same mean: the pipelined result
        tracks the deferred one to fp32 reduction-order noise."""
        x = jax.random.normal(jax.random.PRNGKey(9), (8, 210))

        def run(red):
            def step(xs):
                acc = red.init(xs)
                for k in range(3):
                    acc = red.accumulate(acc, (1.0 + 0.1 * k) * xs)
                g, _ = red.reduce(acc)
                return g

            return jax.jit(smap(step, mesh, (P(AXES),), P(AXES)))(x)

        deferred = run(Reducer(axis_name=AXES))
        pipelined = run(Reducer(axis_name=AXES, overlap_grad_sync=True,
                                bucket_bytes=256))
        np.testing.assert_allclose(
            np.asarray(pipelined), np.asarray(deferred),
            rtol=1e-6, atol=1e-6)

    def test_pipelined_scan_matches_python_loop(self, mesh):
        """After priming with one accumulate the state structure is
        stable, so the rest of the loop can be a lax.scan carry — and
        produces bit-identical results to the unrolled loop."""
        gs = jax.random.normal(jax.random.PRNGKey(10), (4, 8, 90))

        def python_loop(gs):
            red = Reducer(axis_name=AXES, overlap_grad_sync=True,
                          bucket_bytes=128)
            acc = red.init(gs[0])
            for k in range(4):
                acc = red.accumulate(acc, gs[k])
            g, _ = red.reduce(acc)
            return g

        def scan_loop(gs):
            red = Reducer(axis_name=AXES, overlap_grad_sync=True,
                          bucket_bytes=128)
            acc = red.init(gs[0])
            acc = red.accumulate(acc, gs[0])  # prime: adds "pending"
            acc, _ = jax.lax.scan(
                lambda st, g: (red.accumulate(st, g), None),
                acc, gs[1:])
            g, _ = red.reduce(acc)
            return g

        spec = P(None, AXES)
        a = jax.jit(smap(python_loop, mesh, (spec,), P(AXES)))(gs)
        b = jax.jit(smap(scan_loop, mesh, (spec,), P(AXES)))(gs)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_predivide_and_raw_sum_semantics(self, mesh):
        x = jax.random.normal(jax.random.PRNGKey(11), (8, 40))

        def run(**kw):
            red = Reducer(axis_name=AXES, overlap_grad_sync=True,
                          bucket_bytes=128, **kw)

            def step(xs):
                acc = red.init(xs)
                acc = red.accumulate(acc, xs)
                acc = red.accumulate(acc, xs)
                g, _ = red.reduce(acc)
                return g

            return np.asarray(jax.jit(smap(
                step, mesh, (P(AXES),), P(AXES)))(x))

        mean_ref = np.broadcast_to(
            np.mean(np.asarray(x), axis=0, keepdims=True), x.shape)
        np.testing.assert_allclose(
            run(gradient_predivide_factor=4.0), mean_ref,
            rtol=1e-5, atol=1e-6)
        # raw sum over world x K
        np.testing.assert_allclose(
            run(gradient_average=False),
            np.broadcast_to(
                2.0 * np.sum(np.asarray(x), axis=0, keepdims=True),
                x.shape),
            rtol=1e-5, atol=1e-5)
        # reference scaling: mean over world, SUM over microbatches
        np.testing.assert_allclose(
            run(average_over_microbatches=False), 2.0 * mean_ref,
            rtol=1e-5, atol=1e-6)


# --------------------------------------------------------- compression


class TestBucketedCompression:
    def test_bucketed_int8_ef_tracks_exact_mean(self, mesh):
        grads = {"w": _grads()["w"], "b": _grads()["b"]}
        spec = jax.tree.map(lambda _: P(AXES), grads)
        local = jax.tree.map(
            lambda g: jnp.zeros((1,) + g.shape[1:], g.dtype), grads)
        cfg = CompressionConfig(block_size=64)
        state = init_comm_state(local, AXES, cfg, mesh=mesh,
                                bucket_bytes=300)
        assert is_bucketed_residuals(state["residuals"])
        cspecs = comm_state_specs(state, AXES)
        step = jax.jit(smap(
            lambda g, st: all_reduce_gradients(
                g, AXES, compression=cfg, comm_state=st,
                overlap_grad_sync=True, bucket_bytes=300),
            mesh, (spec, cspecs), (spec, cspecs)))
        out, state = step(grads, state)
        assert int(state["step"]) == 1
        for k in grads:
            ref = np.broadcast_to(
                np.mean(np.asarray(grads[k]), axis=0, keepdims=True),
                grads[k].shape)
            amax = np.max(np.abs(ref))
            assert np.max(np.abs(np.asarray(out[k]) - ref)) \
                < 0.05 * amax
        # a second step consumes and refreshes the bucketed residuals
        out2, state = step(grads, state)
        assert int(state["step"]) == 2
        assert any(
            float(jnp.sum(jnp.abs(l))) > 0
            for l in jax.tree.leaves(
                jax.device_get(state)["residuals"])
        )

    def test_pipelined_int8_ef_parity_with_exact(self, mesh):
        """int8+EF through the PIPELINED loop tracks the exact
        pipelined reduce within the PR 3 tolerance."""
        x = jax.random.normal(jax.random.PRNGKey(12), (8, 300))

        def run(comp):
            red = Reducer(axis_name=AXES, overlap_grad_sync=True,
                          bucket_bytes=256, compression=comp)

            def step(xs):
                acc = red.init(xs)
                for k in range(2):
                    acc = red.accumulate(acc, xs)
                g, fresh = red.reduce(acc)
                resid = jnp.float32(0.0)
                if "comm" in fresh:
                    resid = sum(
                        jnp.sum(jnp.abs(l)) for l in
                        jax.tree.leaves(fresh["comm"]["residuals"]))
                return g, resid

            return jax.jit(smap(
                step, mesh, (P(AXES),), (P(AXES), P())))(x)

        exact, _ = run(None)
        quant, resid = run(CompressionConfig(block_size=64))
        amax = np.max(np.abs(np.asarray(exact)))
        np.testing.assert_allclose(
            np.asarray(quant), np.asarray(exact), atol=3e-2 * amax)
        # residuals persisted in the fresh state for the next cycle
        assert float(resid) > 0.0

    def test_mismatched_bucketed_state_raises(self, mesh):
        grads = {"w": jnp.ones((8, 64))}
        spec = {"w": P(AXES)}
        cfg = CompressionConfig(block_size=4)
        # state sized for HALF the local leaf the reduce will see
        local = {"w": jnp.zeros((1, 32))}
        state = init_comm_state(local, AXES, cfg, mesh=mesh,
                                bucket_bytes=1 << 20)
        cspecs = comm_state_specs(state, AXES)
        with pytest.raises(ValueError, match="bucket"):
            jax.jit(smap(
                lambda g, st: all_reduce_gradients(
                    g, AXES, compression=cfg, comm_state=st,
                    overlap_grad_sync=True, bucket_bytes=1 << 20),
                mesh, (spec, cspecs), (spec, cspecs)))(grads, state)

    def test_bucketed_state_without_overlap_raises(self, mesh):
        cfg = CompressionConfig(block_size=16)
        local = {"w": jnp.zeros((1, 64))}
        state = init_comm_state(local, AXES, cfg, mesh=mesh,
                                bucket_bytes=64)
        with pytest.raises(ValueError, match="overlap_grad_sync"):
            all_reduce_gradients(
                {"w": jnp.ones((8, 64))}, AXES, compression=cfg,
                comm_state=state)

    def test_leaf_state_with_overlap_raises(self, mesh):
        cfg = CompressionConfig(block_size=16)
        local = {"w": jnp.zeros((1, 64))}
        state = init_comm_state(local, AXES, cfg, mesh=mesh)
        with pytest.raises(ValueError, match="BUCKETED"):
            all_reduce_gradients(
                {"w": jnp.ones((8, 64))}, AXES, compression=cfg,
                comm_state=state, overlap_grad_sync=True)

    def test_bucketed_specs_with_model_axes(self):
        from jax.sharding import Mesh

        devs = np.asarray(jax.devices()).reshape(2, 2, 2)
        mesh3 = Mesh(devs, ("dcn", "ici", "pp"))
        params = {"stack": jnp.zeros((2, 40)), "norm": jnp.zeros((24,))}
        pspecs = {"stack": P("pp"), "norm": P()}
        cfg = CompressionConfig(block_size=16)
        plan = GradientBuckets.for_tree(
            params, bucket_bytes=1 << 20, param_specs=pspecs,
            mesh=mesh3)
        state = init_comm_state(params, AXES, cfg, mesh=mesh3,
                                param_specs=pspecs, buckets=plan)
        specs = comm_state_specs(state, AXES, buckets=plan)
        (name,) = state["residuals"].keys()
        assert specs["residuals"][name]["push"] == \
            P(("dcn", "ici", "pp"))
        # bucket holds 64 local elems -> chunk 32 over ici=2 -> padded
        # to dcn*block = 32; x (2 dcn x 2 ici x 2 pp) positions
        assert state["residuals"][name]["push"].shape == (8 * 32,)

    def test_ddp_remembers_bucket_plan_for_specs(self):
        """DistributedDataParallel must hand its own bucket plan to
        comm_state_specs — otherwise model-sharded bucketed residuals
        get replicated-over-model-axes specs and mis-shard."""
        from jax.sharding import Mesh

        from apex_tpu.parallel.distributed import (
            DistributedDataParallel,
        )

        devs = np.asarray(jax.devices()).reshape(2, 2, 2)
        mesh3 = Mesh(devs, ("dcn", "ici", "pp"))
        params = {"stack": jnp.zeros((2, 40)), "norm": jnp.zeros((24,))}
        pspecs = {"stack": P("pp"), "norm": P()}
        ddp = DistributedDataParallel(
            axis_name=AXES, compression=CompressionConfig(block_size=16),
            overlap_grad_sync=True, bucket_bytes=1 << 20)
        state = ddp.init_comm_state(params, mesh=mesh3,
                                    param_specs=pspecs)
        specs = ddp.comm_state_specs(state)
        (name,) = state["residuals"].keys()
        assert specs["residuals"][name]["push"] == \
            P(("dcn", "ici", "pp"))


# ------------------------------------------------------- checkpointing


class TestCheckpointRoundTrip:
    def test_bucketed_residuals_round_trip(self, mesh, tmp_path):
        """Save the bucketed comm state mid-run, restore, and the
        resumed reduce must be BIT-identical to the uninterrupted
        one — the same guarantee PR 3 gave per-leaf residuals."""
        from apex_tpu import checkpoint

        grads = {"w": _grads()["w"]}
        spec = {"w": P(AXES)}
        local = {"w": jnp.zeros((1,) + grads["w"].shape[1:])}
        cfg = CompressionConfig(block_size=64)
        state0 = init_comm_state(local, AXES, cfg, mesh=mesh,
                                 bucket_bytes=256)
        cspecs = comm_state_specs(state0, AXES)
        step = jax.jit(smap(
            lambda g, st: all_reduce_gradients(
                g, AXES, compression=cfg, comm_state=st,
                overlap_grad_sync=True, bucket_bytes=256),
            mesh, (spec, cspecs), (spec, cspecs)))

        # uninterrupted: 3 steps
        st = state0
        for _ in range(2):
            _, st = step(grads, st)
        out_ref, st_ref = step(grads, st)

        # interrupted: 2 steps, checkpoint, restore, third step
        st = state0
        for _ in range(2):
            _, st = step(grads, st)
        path = os.path.join(str(tmp_path), "comm")
        checkpoint.save(path, jax.device_get(st))
        restored = checkpoint.restore(path, target=jax.device_get(st))
        out_res, st_res = step(grads, restored)
        np.testing.assert_array_equal(
            np.asarray(out_ref["w"]), np.asarray(out_res["w"]))
        for a, b in zip(jax.tree.leaves(jax.device_get(st_ref)),
                        jax.tree.leaves(jax.device_get(st_res))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------------- GPT


VOCAB, LAYERS, HIDDEN, HEADS, SEQ = 64, 2, 32, 4, 8


@pytest.fixture(scope="module")
def gpt_mesh():
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        data_parallel_ici_size_=ICI)
    yield mesh
    parallel_state.destroy_model_parallel()


def test_gpt_accumulation_loop_matches_seed_path(gpt_mesh):
    """The pipelined accumulate-and-reduce loop on a real GPT fwd/bwd
    tracks the unbucketed deferred seed path: same microbatch stream,
    grads equal to fp32 reduction-order noise, and a short training
    run's loss curve indistinguishable at 1e-4."""
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import parallel_state

    cfg = GPTConfig(
        vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
        num_attention_heads=HEADS, max_position_embeddings=SEQ,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    )
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    specs = model.param_specs()
    data_axes = parallel_state.data_parallel_axis_names()
    rng = np.random.default_rng(0)
    micro = [jnp.asarray(rng.integers(0, VOCAB, (8, SEQ)), jnp.int32)
             for _ in range(2)]

    def make_step(red):
        from apex_tpu.transformer.tensor_parallel.layers import (
            state_specs_like,
        )

        opt = FusedAdam(lr=1e-2)
        opt_state = opt.init(params)
        opt_specs = state_specs_like(specs, opt_state)

        def step(p, s, t0, g0, t1, g1):
            acc = red.init(p)
            losses = []
            for tok, tgt in ((t0, g0), (t1, g1)):
                loss, grads = jax.value_and_grad(model.loss)(
                    p, tok, tgt)
                losses.append(jax.lax.pmean(loss, data_axes))
                acc = red.accumulate(acc, grads)
            grads, _ = red.reduce(acc)
            p, s = opt.step(s, grads, p)
            return p, s, (losses[0] + losses[1]) / 2.0, grads

        dspec = P(data_axes)
        jstep = jax.jit(smap(
            step, gpt_mesh,
            (specs, opt_specs, dspec, dspec, dspec, dspec),
            (specs, opt_specs, P(), specs)))
        return jstep, opt_state

    def train(red, steps=4):
        jstep, opt_state = make_step(red)
        p, s = params, opt_state
        losses, last_grads = [], None
        for i in range(steps):
            tok = micro[i % 2]
            tgt = jnp.roll(tok, -1, axis=1)
            tok2 = micro[(i + 1) % 2]
            tgt2 = jnp.roll(tok2, -1, axis=1)
            p, s, loss, last_grads = jstep(p, s, tok, tgt, tok2, tgt2)
            losses.append(float(loss))
        return losses, last_grads

    seed_losses, seed_grads = train(Reducer(axis_name=data_axes))
    over_losses, over_grads = train(Reducer(
        axis_name=data_axes, overlap_grad_sync=True,
        bucket_bytes=16 * 1024))
    np.testing.assert_allclose(over_losses, seed_losses, atol=1e-4)
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(jax.device_get(seed_grads)),
        jax.tree_util.tree_leaves_with_path(jax.device_get(over_grads)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
            err_msg=str(path))


# ----------------------------------------------------------- audit tool


def _load_comm_audit():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "comm_audit", os.path.join(root, "tools", "comm_audit.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_ASYNC_HLO = """\
HloModule test, is_scheduled=true

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[256,256], p1: f32[4096]) -> (f32[4096], f32[256,256]) {
  %p0 = f32[256,256]{1,0} parameter(0)
  %p1 = f32[4096]{0} parameter(1)
  %ars = f32[4096]{0} all-reduce-start(f32[4096]{0} %p1), replica_groups={{0,4},{1,5},{2,6},{3,7}}, use_global_device_ids=true, to_apply=%add
  %dot = f32[256,256]{1,0} dot(f32[256,256]{1,0} %p0, f32[256,256]{1,0} %p0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %ard = f32[4096]{0} all-reduce-done(f32[4096]{0} %ars)
  ROOT %t = (f32[4096]{0}, f32[256,256]{1,0}) tuple(f32[4096]{0} %ard, f32[256,256]{1,0} %dot)
}
"""

_SYNC_HLO = """\
HloModule test2, is_scheduled=true

ENTRY %main (p0: f32[256,256], p1: f32[4096]) -> (f32[4096], f32[256,256]) {
  %p0 = f32[256,256]{1,0} parameter(0)
  %p1 = f32[4096]{0} parameter(1)
  %dot = f32[256,256]{1,0} dot(f32[256,256]{1,0} %p0, f32[256,256]{1,0} %p0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %ar = f32[4096]{0} all-reduce(f32[4096]{0} %p1), replica_groups={{0,1,2,3},{4,5,6,7}}, use_global_device_ids=true, to_apply=%add
  %use = f32[4096]{0} add(f32[4096]{0} %ar, f32[4096]{0} %ar)
  ROOT %t = (f32[4096]{0}, f32[256,256]{1,0}) tuple(f32[4096]{0} %use, f32[256,256]{1,0} %dot)
}
"""


class TestOverlapAudit:
    def test_async_pair_counted_with_window_compute(self, mesh):
        ca = _load_comm_audit()
        records, summary = ca.analyze_overlap(_ASYNC_HLO, mesh)
        assert summary["n_collectives"] == 1
        assert summary["n_async_pairs"] == 1
        (rec,) = records
        assert rec["async_pair"] and rec["op"] == "all-reduce"
        assert rec["axis"] == "dcn"  # groups span the dcn axis
        assert rec["independent_compute_s"] > 0  # the dot in the window
        assert rec["overlappable"]

    def test_sync_collective_independent_compute(self, mesh):
        ca = _load_comm_audit()
        records, summary = ca.analyze_overlap(_SYNC_HLO, mesh)
        assert summary["n_async_pairs"] == 0
        (rec,) = records
        assert rec["axis"] == "ici"  # groups stay inside each slice
        # the dot neither feeds nor consumes the all-reduce
        assert rec["overlappable"]
        assert rec["independent_compute_s"] > 0

    def test_descendants_and_ancestors_excluded(self, mesh):
        ca = _load_comm_audit()
        # make the dot CONSUME the reduce: no independent compute left
        hlo = _SYNC_HLO.replace(
            "dot(f32[256,256]{1,0} %p0, f32[256,256]{1,0} %p0)",
            "dot(f32[256,256]{1,0} %p0, f32[256,256]{1,0} %dep)",
        ).replace(
            "%p1 = f32[4096]{0} parameter(1)",
            "%p1 = f32[4096]{0} parameter(1)\n"
            "  %dep = f32[256,256]{1,0} bitcast(f32[4096]{0} %ar)",
        )
        records, _ = ca.analyze_overlap(hlo, mesh)
        (rec,) = records
        assert not rec["overlappable"]

    def test_compiled_pipelined_loop_fully_overlappable(self, mesh):
        """The real thing: compile the 2-microbatch pipelined loop and
        every grad collective must have independent compute; the
        deferred loop must have strictly less of it in total."""
        ca = _load_comm_audit()
        txt, m = ca.compile_grad_sync_loop(
            True, None, ici_size=ICI, bucket_bytes=48 * 1024,
            num_micro=2)
        _, over = ca.analyze_overlap(txt, m)
        assert over["n_collectives"] > 0
        assert over["overlappable_frac"] == 1.0
        txt_d, m_d = ca.compile_grad_sync_loop(
            False, None, ici_size=ICI, bucket_bytes=48 * 1024,
            num_micro=2)
        _, deferred = ca.analyze_overlap(txt_d, m_d)
        assert over["independent_compute_ms"] > \
            deferred["independent_compute_ms"]

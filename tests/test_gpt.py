"""GPT model tests: tp-sharded forward/loss/grad vs dense math, on the
8-device virtual CPU mesh (SURVEY.md §4 philosophy — smallest real mesh,
analytic/dense-reference expectations; mirrors the reference's
run_megatron_gpt_pipeline.py end-to-end tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.transformer import parallel_state


def small_config(**kw):
    base = dict(
        vocab_size=64,
        num_layers=2,
        hidden_size=32,
        num_attention_heads=4,
        max_position_embeddings=16,
        compute_dtype=jnp.float32,
        remat=False,
        attention_impl="xla",
    )
    base.update(kw)
    return GPTConfig(**base)


def build(mesh, model):
    """jit(shard_map(loss)) + matching param placement."""
    specs = model.param_specs()

    def loss_fn(params, tokens, targets):
        return model.loss(params, tokens, targets)

    sharded = jax.jit(
        jax.shard_map(
            loss_fn,
            mesh=mesh,
            in_specs=(specs, P("dp"), P("dp")),
            out_specs=P(),
        )
    )
    return sharded, specs


def test_gpt_loss_tp_invariant():
    """The same logical params give (numerically) the same loss on a
    tp=1 and a tp=4 mesh."""
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 12), 0, 64)
    targets = jax.random.randint(jax.random.PRNGKey(2), (8, 12), 0, 64)
    losses = {}
    for tp in (1, 4):
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp
        )
        try:
            model = GPTModel(small_config())
            params = model.init(jax.random.PRNGKey(0))
            sharded, specs = build(mesh, model)
            placed = jax.device_put(
                params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                     is_leaf=lambda x: isinstance(x, P))
            )
            losses[tp] = float(sharded(placed, tokens, targets))
            assert np.isfinite(losses[tp])
        finally:
            parallel_state.destroy_model_parallel()
    np.testing.assert_allclose(losses[4], losses[1], rtol=2e-4)


def test_gpt_grads_finite_and_remat_matches():
    mesh = parallel_state.initialize_model_parallel(tensor_model_parallel_size_=2)
    try:
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 64)
        targets = jax.random.randint(jax.random.PRNGKey(2), (4, 8), 0, 64)
        losses = {}
        grads = {}
        for remat in (False, True):
            model = GPTModel(small_config(remat=remat))
            params = model.init(jax.random.PRNGKey(0))
            specs = model.param_specs()
            grad_fn = jax.jit(
                jax.shard_map(
                    jax.value_and_grad(lambda p, t, y: model.loss(p, t, y)),
                    mesh=mesh,
                    in_specs=(specs, P("dp"), P("dp")),
                    out_specs=(P(), specs),
                )
            )
            placed = jax.device_put(
                params,
                jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P)),
            )
            loss, g = grad_fn(placed, tokens, targets)
            losses[remat] = float(loss)
            grads[remat] = g
            flat = jax.tree.leaves(g)
            assert all(np.all(np.isfinite(np.asarray(x))) for x in flat)
        np.testing.assert_allclose(losses[False], losses[True], rtol=1e-6)
        for a, b in zip(jax.tree.leaves(grads[False]), jax.tree.leaves(grads[True])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                       atol=1e-6)
    finally:
        parallel_state.destroy_model_parallel()


def test_gpt_pipeline_matches_non_pipeline():
    """pp=2 x tp=2 x dp=2 pipeline loss+grads == single-mesh loss+grads."""
    from apex_tpu.transformer.pipeline_parallel import sync_replicated_grads

    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 8), 0, 64)
    targets = jax.random.randint(jax.random.PRNGKey(2), (8, 8), 0, 64)

    # dense reference: tp=1 pp=1 mesh
    mesh = parallel_state.initialize_model_parallel()
    try:
        model = GPTModel(small_config())
        params = model.init(jax.random.PRNGKey(0))
        sharded, specs = build(mesh, model)
        grad_fn = jax.jit(
            jax.shard_map(
                jax.value_and_grad(lambda p, t, y: model.loss(p, t, y)),
                mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=(P(), specs),
            )
        )
        ref_loss, ref_grads = grad_fn(params, tokens, targets)
        ref_loss = float(ref_loss)
        ref_grads = jax.device_get(ref_grads)
    finally:
        parallel_state.destroy_model_parallel()

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2
    )
    try:
        model = GPTModel(small_config())
        params = model.init(jax.random.PRNGKey(0))
        specs = model.pipeline_param_specs()

        def pp_loss_and_grad(params, tokens, targets):
            loss, grads = jax.value_and_grad(model.pipeline_loss)(
                params, tokens, targets, 2
            )
            grads = sync_replicated_grads(grads, specs)
            return loss, grads

        grad_fn = jax.jit(
            jax.shard_map(
                pp_loss_and_grad,
                mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=(P(), specs),
            )
        )
        placed = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P))
        )
        loss, grads = grad_fn(placed, tokens, targets)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=2e-4)
        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(grads)),
            jax.tree_util.tree_leaves_with_path(ref_grads),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5,
                err_msg=str(ka),
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_gpt_moe_trains():
    """MoE-GPT: tp=2 x dp=4(ep), 4 experts — loss decreases, expert
    grads stay per-expert (dp-sharded)."""
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2
    )
    try:
        model = GPTModel(small_config(
            num_experts=4, moe_capacity_factor=4.0
        ))
        params = model.init(jax.random.PRNGKey(0))
        specs = model.param_specs()
        assert "moe" in jax.tree_util.tree_structure(
            specs["layers"]
        ).__repr__() or "moe" in specs["layers"]
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 12), 0, 64)
        targets = jax.random.randint(jax.random.PRNGKey(2), (8, 12), 0, 64)

        grad_fn = jax.jit(
            jax.shard_map(
                jax.value_and_grad(lambda p, t, y: model.loss(p, t, y)),
                mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=(P(), specs),
            )
        )
        placed = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P))
        )
        first = None
        for _ in range(40):
            loss, grads = grad_fn(placed, tokens, targets)
            if first is None:
                first = float(loss)
            placed = jax.tree.map(lambda p, g: p - 0.1 * g, placed, grads)
        assert np.isfinite(float(loss))
        assert float(loss) < first
        # expert weights stacked (L, E, h, f), experts sharded over dp
        w1 = placed["layers"]["moe"]["w1"]
        assert w1.shape[1] == 4
    finally:
        parallel_state.destroy_model_parallel()


def test_gpt_dropout_rng_paths():
    mesh = parallel_state.initialize_model_parallel(tensor_model_parallel_size_=2)
    try:
        model = GPTModel(
            small_config(hidden_dropout=0.1, attention_dropout=0.1)
        )
        params = model.init(jax.random.PRNGKey(0))
        specs = model.param_specs()
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 64)

        def fwd(params, tokens, rng):
            return model.apply(params, tokens, rng)

        sharded = jax.jit(
            jax.shard_map(
                fwd,
                mesh=mesh,
                in_specs=(specs, P("dp"), P()),
                out_specs=P("dp", None, "tp"),
            )
        )
        placed = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P))
        )
        a = sharded(placed, tokens, jax.random.PRNGKey(3))
        b = sharded(placed, tokens, jax.random.PRNGKey(4))
        assert not np.allclose(np.asarray(a), np.asarray(b))
    finally:
        parallel_state.destroy_model_parallel()


def test_gpt_1f1b_matches_gpipe_pipeline():
    """GPT fwd+bwd through the true 1F1B schedule == jax.grad of the
    GPipe-style pipeline, loss and grads, on the pp=2 x tp=2 x dp=2 mesh."""
    from apex_tpu.transformer.pipeline_parallel import sync_replicated_grads

    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 8), 0, 64)
    targets = jax.random.randint(jax.random.PRNGKey(2), (8, 8), 0, 64)
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2
    )
    try:
        model = GPTModel(small_config())
        params = model.init(jax.random.PRNGKey(0))
        specs = model.pipeline_param_specs()
        placed = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P))
        )

        def gpipe(params, tokens, targets):
            loss, grads = jax.value_and_grad(model.pipeline_loss)(
                params, tokens, targets, 4
            )
            grads = sync_replicated_grads(grads, specs)
            grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, "dp"), grads
            )
            return loss, grads

        def fb_1f1b(params, tokens, targets):
            return model.pipeline_1f1b_grads(params, tokens, targets, 4)

        outs = {}
        for name, fn in (("gpipe", gpipe), ("1f1b", fb_1f1b)):
            f = jax.jit(jax.shard_map(
                fn, mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=(P(), specs),
            ))
            outs[name] = f(placed, tokens, targets)
        (l_ref, g_ref), (l_new, g_new) = outs["gpipe"], outs["1f1b"]
        np.testing.assert_allclose(float(l_new), float(l_ref), rtol=1e-5)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_new),
            jax.tree_util.tree_leaves_with_path(g_ref),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6,
                err_msg=str(path),
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_gpt_interleaved_1f1b_matches_gpipe_pipeline():
    """GPT fwd+bwd through the interleaved 1F1B schedule (V=2 chunks per
    rank, dispatched by get_forward_backward_func) == jax.grad of the
    GPipe-style pipeline, loss and grads, on the pp=2 x tp=2 x dp=2 mesh
    (reference: fwd_bwd_pipelining_with_interleaving.py:22-308)."""
    from apex_tpu.transformer.pipeline_parallel import sync_replicated_grads

    V = 2
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 8), 0, 64)
    targets = jax.random.randint(jax.random.PRNGKey(2), (8, 8), 0, 64)
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2
    )
    try:
        model = GPTModel(small_config(num_layers=4))
        params = model.init(jax.random.PRNGKey(0))
        specs = model.pipeline_param_specs()
        placed = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P))
        )
        chunk_specs = model.pipeline_param_specs(V)
        chunked = model.pipeline_chunk_params(params, V)
        placed_chunks = jax.device_put(
            chunked,
            jax.tree.map(lambda s: NamedSharding(mesh, s), chunk_specs,
                         is_leaf=lambda x: isinstance(x, P)),
        )

        def gpipe(params, tokens, targets):
            loss, grads = jax.value_and_grad(model.pipeline_loss)(
                params, tokens, targets, 4
            )
            grads = sync_replicated_grads(grads, specs)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            return loss, grads

        def fb_il(params, tokens, targets):
            return model.pipeline_1f1b_grads(
                params, tokens, targets, 4, num_model_chunks=V
            )

        ref = jax.jit(jax.shard_map(
            gpipe, mesh=mesh,
            in_specs=(specs, P("dp"), P("dp")), out_specs=(P(), specs),
        ))(placed, tokens, targets)
        got = jax.jit(jax.shard_map(
            fb_il, mesh=mesh,
            in_specs=(chunk_specs, P("dp"), P("dp")),
            out_specs=(P(), chunk_specs),
        ))(placed_chunks, tokens, targets)

        np.testing.assert_allclose(float(got[0]), float(ref[0]), rtol=1e-5)
        # chunked grads reshape back to the stacked (L, ...) layout
        g_ref, g_new = ref[1], got[1]
        g_new = {
            **g_new,
            "layers": jax.tree.map(
                lambda x: x.reshape(-1, *x.shape[3:]), g_new["layers"]
            ),
        }
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_new),
            jax.tree_util.tree_leaves_with_path(g_ref),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6,
                err_msg=str(path),
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_measured_optimal_defaults_pinned():
    """The bench flagship inherits GPTConfig's defaults, so an
    accidental default change silently regresses the headline capture.
    Pin the measured-optimal set (PROFILE_r03 exp 1, PROFILE_r05):
    any deliberate re-tune must update this test WITH fresh chip
    evidence."""
    cfg = GPTConfig()
    assert cfg.remat is True
    assert cfg.remat_policy == "dots_with_no_batch_dims_saveable"
    assert cfg.fused_ce is None  # auto by logits size (PROFILE_r05)
    assert cfg.fused_ce_chunk == 8192
    assert cfg.attention_impl is None  # auto -> pallas on TPU
    assert cfg.position_embedding == "learned"  # reference parity

    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        FUSED_CE_AUTO_BYTES,
    )

    # flagship (8192 tokens x 32768 vocab = 1.07 GB) must stay on the
    # measured-faster two-step side of the auto rule
    assert 8192 * 32768 * 4 <= FUSED_CE_AUTO_BYTES

"""bench.py TPU-probe fail-fast: the probe loop's own budget
(APEX_TPU_BENCH_PROBE_BUDGET) and the same-boot failure cache in
BENCH_WATCH.json (BENCH_r05 burned 1500 s probing an unreachable TPU
before the CPU fallback started)."""

import importlib
import json
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


@pytest.fixture
def watch_path(tmp_path, monkeypatch):
    p = str(tmp_path / "BENCH_WATCH.json")
    monkeypatch.setattr(bench, "BENCH_WATCH_PATH", p)
    monkeypatch.setattr(bench, "_boot_id", lambda: "boot-a")
    monkeypatch.setattr(bench, "PROBE_CACHE_S", 3600)
    return p


def test_probe_budget_default_well_under_old_burn():
    # the r05 gate lost ~1500 s to the probe loop; the new default cap
    # must sit well under that (and stay env-tunable)
    assert bench.PROBE_BUDGET <= 900


def test_probe_budget_env_override(monkeypatch):
    monkeypatch.setenv("APEX_TPU_BENCH_PROBE_BUDGET", "42")
    try:
        importlib.reload(bench)
        assert bench.PROBE_BUDGET == 42
    finally:
        monkeypatch.delenv("APEX_TPU_BENCH_PROBE_BUDGET")
        importlib.reload(bench)


def test_failure_cache_round_trip(watch_path):
    assert bench._cached_probe_failure() is None
    bench._set_probe_failure(
        {"boot_id": "boot-a", "at": time.time(), "attempts": 3})
    rec = bench._cached_probe_failure()
    assert rec is not None and rec["attempts"] == 3
    bench._set_probe_failure(None)
    assert bench._cached_probe_failure() is None


def test_failure_cache_ignores_other_boot(watch_path):
    bench._set_probe_failure(
        {"boot_id": "boot-OLD", "at": time.time(), "attempts": 1})
    assert bench._cached_probe_failure() is None


def test_failure_cache_expires(watch_path):
    bench._set_probe_failure(
        {"boot_id": "boot-a", "at": time.time() - 7200, "attempts": 1})
    assert bench._cached_probe_failure() is None  # older than cache_s


def test_cache_disabled_by_env_zero(watch_path, monkeypatch):
    bench._set_probe_failure(
        {"boot_id": "boot-a", "at": time.time(), "attempts": 1})
    monkeypatch.setattr(bench, "PROBE_CACHE_S", 0)
    # tpu_watch's post-contact bench run sets the env to 0 so a stale
    # record cannot make it skip its own probe
    assert bench._cached_probe_failure() is None


def test_cache_merge_preserves_capture_record(watch_path):
    # tpu_watch's capture record must survive the failure cache writes
    with open(watch_path, "w") as f:
        json.dump({"captured": True, "result": {"value": 1.0}}, f)
    bench._set_probe_failure(
        {"boot_id": "boot-a", "at": time.time(), "attempts": 2})
    with open(watch_path) as f:
        d = json.load(f)
    assert d["captured"] is True and "probe_failure" in d
    bench._set_probe_failure(None)
    with open(watch_path) as f:
        d = json.load(f)
    assert d["captured"] is True and "probe_failure" not in d


def test_corrupt_watch_file_is_tolerated(watch_path):
    with open(watch_path, "w") as f:
        f.write("{not json")
    assert bench._cached_probe_failure() is None
    bench._set_probe_failure(
        {"boot_id": "boot-a", "at": time.time(), "attempts": 1})
    assert bench._cached_probe_failure() is not None

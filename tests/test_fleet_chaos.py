"""Serving fault-tolerance tier: the durable request journal, replica
health monitoring, deadlines + hedged re-routing, brownout degradation
and the serving fault seams.

The load-bearing claims, each pinned here:

- :class:`RequestJournal` is write-ahead (an admission is on disk
  before serving starts), CRC-checked per record, and atomic-append —
  :func:`recover_journal` survives torn tails, flipped bits and lost
  delta records (a gap FREEZES the stream at the consistent prefix,
  it never stitches across a hole);
- a full restart — new batchers, new router, journal replayed —
  resumes every in-flight request token-identically and keeps every
  completed stream, with zero new jit cache entries;
- a pump that raises is a counted replica fault; enough consecutive
  faults (or one stalled pump past ``pump_timeout_s``) quarantine the
  replica and its work migrates with zero losses, token-identically;
  a single transient fault does NOT quarantine;
- impossible deadlines are rejected at admission with the distinct
  ``deadline_unmeetable`` reason; a missed deadline retries (re-armed,
  token-identical) or terminates with a stream that is a committed
  PREFIX of the reference — never garbage;
- a hedged duplicate resolves first-commit-wins with the stream
  token-identical either way, and loses cleanly when the primary
  lands first;
- the brownout ladder escalates under queue pressure (speculation
  off -> chunk throttle -> shed the batch class), de-escalates with
  hysteresis, and never changes a token — the levers are scheduling
  only;
- ``ContinuousBatcher.cancel()`` is safe mid-speculation-window:
  survivors' streams are untouched, pages are released, the slot is
  reusable (the regression test speculation's cancel path rides on);
- every pump heartbeat carries the replica's name so
  ``tools/tpu_watch.py`` can name a stalled replica.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from apex_tpu.fleet import (
    BrownoutPolicy,
    FleetPolicy,
    FleetRouter,
    Replica,
    RequestJournal,
    RequestLog,
    SLOClass,
    recover_journal,
)
from apex_tpu.resilience import faults
from apex_tpu.serving.kv_cache import (
    KVCacheConfig,
    PagedKVCache,
    init_pools,
)
from apex_tpu.serving.serve import ContinuousBatcher, Request


# ---------------------------------------------------------------------------
# journal: pure host, no model
# ---------------------------------------------------------------------------


def _admit(log, journal, uid, *, plen=4, new=6, seed=7, slo="interactive",
           deadline=None):
    e = log.admit(Request(uid=uid, prompt=list(range(1, plen + 1)),
                          max_new_tokens=new, seed=seed),
                  slo=slo, replica="r0", t_arrive=1.0)
    if deadline is not None:
        e.deadline_rel = deadline
    journal.admit(e)
    return e


class TestJournal:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        log, j = RequestLog(), RequestJournal(path)
        _admit(log, j, "a", deadline=2.5)
        _admit(log, j, "b", seed=None, slo="batch")
        log.record_progress("r0", {"a": [5, 6]}, now=2.0)
        j.sync(log)
        log.record_progress("r0", {"a": [5, 6, 7]}, now=3.0)
        log.complete("b", [9], "eos", now=3.0)
        j.sync(log)
        j.close()
        rec = recover_journal(path)
        assert rec.corrupt == 0 and rec.gapped == 0
        a, b = rec.entries["a"], rec.entries["b"]
        assert a["request"].prompt == [1, 2, 3, 4]
        assert a["request"].max_new_tokens == 6
        assert a["request"].seed == 7
        assert a["slo"] == "interactive" and a["deadline_s"] == 2.5
        assert a["emitted"] == [5, 6, 7] and not a["done"]
        assert b["request"].seed is None
        assert b["done"] and b["reason"] == "eos" and b["emitted"] == [9]
        assert list(rec.inflight) == ["a"]
        assert list(rec.completed) == ["b"]

    def test_write_ahead_admit_lands_immediately(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        log, j = RequestLog(), RequestJournal(path)
        _admit(log, j, "a")
        # no sync, no close: the admit must already be durable
        rec = recover_journal(path)
        assert list(rec.entries) == ["a"]

    def test_sync_batches_one_append_per_step(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        log, j = RequestJournal(path), None
        log, j = RequestLog(), RequestJournal(path)
        for uid in ("a", "b", "c"):
            _admit(log, j, uid)
        appends0 = j.stats["appends"]
        log.record_progress("r0", {"a": [1], "b": [2], "c": [3]}, now=2.0)
        j.sync(log)
        assert j.stats["appends"] == appends0 + 1   # 3 deltas, ONE write
        assert j.stats["records"] >= 6
        assert j.stats["write_s"] >= 0.0

    def test_crc_flip_detected(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        log, j = RequestLog(), RequestJournal(path)
        _admit(log, j, "a")
        _admit(log, j, "b")
        j.close()
        lines = open(path, "rb").read().splitlines(keepends=True)
        # tamper a payload byte of the FIRST record, CRC untouched
        tampered = lines[0].replace(b'"budget":6', b'"budget":7')
        assert tampered != lines[0]
        with open(path, "wb") as f:
            f.writelines([tampered] + lines[1:])
        rec = recover_journal(path)
        assert rec.corrupt == 1
        assert list(rec.entries) == ["b"]          # the clean record

    def test_torn_tail_skipped(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        log, j = RequestLog(), RequestJournal(path)
        _admit(log, j, "a")
        log.record_progress("r0", {"a": [5]}, now=2.0)
        j.sync(log)
        j.close()
        size = os.path.getsize(path)
        os.truncate(path, size - 7)                # tear the last line
        rec = recover_journal(path)
        assert rec.corrupt == 1
        assert rec.entries["a"]["emitted"] == []   # frozen pre-tear

    def test_gap_freezes_at_consistent_prefix(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        log, j = RequestLog(), RequestJournal(path)
        _admit(log, j, "a")
        log.record_progress("r0", {"a": [5, 6]}, now=2.0)
        j.sync(log)
        log.record_progress("r0", {"a": [5, 6, 7, 8]}, now=3.0)
        j.sync(log)
        j.close()
        lines = open(path, "rb").read().splitlines(keepends=True)
        assert len(lines) == 3
        with open(path, "wb") as f:                # drop the 1st delta
            f.writelines([lines[0], lines[2]])
        rec = recover_journal(path)
        assert rec.gapped == 1
        # off=2 disagrees with the empty accumulated stream: frozen at
        # the admit-level prefix, NOT stitched as [7, 8]
        assert rec.entries["a"]["emitted"] == []
        assert not rec.entries["a"]["done"]

    def test_unjournalable_uid_rejected(self, tmp_path):
        log = RequestLog()
        j = RequestJournal(str(tmp_path / "j.jsonl"))
        e = log.admit(Request(uid=("t", 1), prompt=[1, 2],
                              max_new_tokens=2, seed=1),
                      slo="interactive", replica="r0", t_arrive=0.0)
        with pytest.raises(ValueError, match="uids must be str or int"):
            j.admit(e)

    def test_missing_file_recovers_empty(self, tmp_path):
        rec = recover_journal(str(tmp_path / "nope.jsonl"))
        assert rec.entries == {} and rec.records == 0

    def test_prime_appends_only_new_tokens(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        log, j = RequestLog(), RequestJournal(path)
        _admit(log, j, "a")
        log.record_progress("r0", {"a": [5, 6]}, now=2.0)
        j.sync(log)
        j.close()
        # "restart": a fresh journal on the SAME path, cursor primed
        log2 = RequestLog()
        e2 = log2.admit(Request(uid="a", prompt=[1, 2, 3, 4],
                                max_new_tokens=6, seed=7),
                        slo="interactive", replica="r0", t_arrive=9.0)
        e2.emitted = [5, 6]
        j2 = RequestJournal(path)
        j2.prime(log2)
        log2.record_progress("r0", {"a": [5, 6, 7]}, now=10.0)
        j2.sync(log2)
        j2.close()
        rec = recover_journal(path)
        assert rec.corrupt == 0 and rec.gapped == 0
        assert rec.entries["a"]["emitted"] == [5, 6, 7]


# ---------------------------------------------------------------------------
# policy validation: pure host
# ---------------------------------------------------------------------------


class TestFaultPolicyValidation:
    def test_slo_deadline_fields(self):
        with pytest.raises(ValueError, match="deadline_s"):
            SLOClass("x", deadline_s=0)
        with pytest.raises(ValueError, match="max_retries"):
            SLOClass("x", max_retries=-1)
        with pytest.raises(ValueError, match="hedge_after_s"):
            SLOClass("x", hedge_after_s=0)

    def test_fleet_policy_fields(self):
        with pytest.raises(ValueError, match="step_floor_s"):
            FleetPolicy(step_floor_s=-1)
        with pytest.raises(ValueError, match="pump_timeout_s"):
            FleetPolicy(pump_timeout_s=0)
        with pytest.raises(ValueError, match="max_replica_faults"):
            FleetPolicy(max_replica_faults=0)

    def test_brownout_ladder_shape(self):
        BrownoutPolicy()                            # defaults are valid
        with pytest.raises(ValueError, match="3 rungs"):
            BrownoutPolicy(page_frac=(0.3, 0.1))
        with pytest.raises(ValueError, match="non-increasing"):
            BrownoutPolicy(page_frac=(0.1, 0.2, 0.05))
        with pytest.raises(ValueError, match="non-decreasing"):
            BrownoutPolicy(queue_depth=(8, 4, 16))
        with pytest.raises(ValueError, match="chunk_throttle"):
            BrownoutPolicy(chunk_throttle=1)
        with pytest.raises(ValueError, match="recover_margin"):
            BrownoutPolicy(recover_margin=1.0)


# ---------------------------------------------------------------------------
# the tiny-GPT fleet under injected faults
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def chaos_setup():
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:1])
    model = GPTModel(GPTConfig(
        vocab_size=64, num_layers=2, hidden_size=32,
        num_attention_heads=4, max_position_embeddings=64,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))
    params = model.init(jax.random.PRNGKey(5))
    page, new, maxp = 4, 6, 24
    pps = -(-(maxp + new) // page)
    ccfg = KVCacheConfig(
        num_layers=2, num_heads=4, head_dim=8,
        num_pages=1 + 4 * pps, page_size=page, max_seqs=2,
        pages_per_seq=pps, dtype=jnp.float32)
    fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=maxp,
                           prefill_chunk=4)
    yield mesh, model, params, ccfg, fns, maxp
    parallel_state.destroy_model_parallel()


def _replicas(ccfg, fns, maxp, n=2):
    return [
        Replica(f"r{i}", ContinuousBatcher(
            fns.prefill, fns.decode, PagedKVCache(ccfg),
            init_pools(ccfg), max_prompt_len=maxp, harvest_every=2,
            chunk_fn=fns.chunk, prefill_chunk=4, prefix_cache=True))
        for i in range(n)
    ]


def _req(uid, prompt, new=4, seed=None):
    return Request(uid=uid, prompt=prompt, max_new_tokens=new,
                   seed=seed)


def _some_reqs(n=6, new=5, seed0=None, rng_seed=31):
    rng = np.random.RandomState(rng_seed)
    return [
        _req(f"u{i}",
             [int(t) for t in rng.randint(1, 64, (5 + (i % 3) * 3,))],
             new=new, seed=None if seed0 is None else seed0 + i)
        for i in range(n)
    ]


def _reference(ccfg, fns, maxp, reqs):
    router = FleetRouter(_replicas(ccfg, fns, maxp))
    for r in reqs:
        assert router.submit(r)
    router.drain()
    return {u: c.tokens for u, c in router.completions.items()}


class TestHealthMonitoring:
    def test_repeated_faults_quarantine_and_migrate(self, chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        reqs = _some_reqs()
        ref = _reference(ccfg, fns, maxp, reqs)
        router = FleetRouter(
            _replicas(ccfg, fns, maxp),
            FleetPolicy(max_replica_faults=2))
        for r in reqs:
            assert router.submit(r)
        r0 = router.replicas[0]
        with faults.nonfinite_logits(r0.batcher, nth=2, forever=True):
            router.drain()
        assert r0.quarantined == "faults"
        assert not r0.alive
        assert r0.consecutive_faults >= 2
        assert "FloatingPointError" in r0.last_error
        assert router.stats["quarantined"] == 1
        assert router.stats["replica_faults"] >= 2
        assert len(router.completions) == len(reqs)   # zero lost
        for uid, toks in ref.items():
            assert router.completions[uid].tokens == toks, uid

    def test_single_transient_fault_heals(self, chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        reqs = _some_reqs()
        ref = _reference(ccfg, fns, maxp, reqs)
        router = FleetRouter(
            _replicas(ccfg, fns, maxp),
            FleetPolicy(max_replica_faults=3))
        for r in reqs:
            assert router.submit(r)
        r0 = router.replicas[0]
        with faults.failing_windows(r0.batcher, nth=1, count=1):
            router.drain()
        assert r0.alive and r0.quarantined is None
        assert r0.faults == 1
        assert r0.consecutive_faults == 0       # reset by the recovery
        assert router.stats["quarantined"] == 0
        for uid, toks in ref.items():
            assert router.completions[uid].tokens == toks, uid

    def test_stalled_pump_quarantined(self, chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        reqs = _some_reqs()
        ref = _reference(ccfg, fns, maxp, reqs)
        router = FleetRouter(
            _replicas(ccfg, fns, maxp),
            FleetPolicy(pump_timeout_s=0.05))
        for r in reqs:
            assert router.submit(r)
        r0 = router.replicas[0]
        with faults.stalled_pump(r0.batcher, stall_s=0.2):
            router.drain()
        assert r0.quarantined == "stall"
        assert len(router.completions) == len(reqs)
        for uid, toks in ref.items():
            assert router.completions[uid].tokens == toks, uid

    def test_heartbeat_names_the_replica(self, chaos_setup, tmp_path,
                                         monkeypatch):
        from apex_tpu.resilience.watchdog import Watchdog

        import tools.tpu_watch as tpu_watch

        mesh, model, params, ccfg, fns, maxp = chaos_setup
        hb = str(tmp_path / "heartbeat.json")
        wd = Watchdog(deadline_s=600, heartbeat_file=hb)
        router = FleetRouter(_replicas(ccfg, fns, maxp, n=1),
                             watchdog=wd)
        router.submit(_req("a", [1, 2, 3], new=3))
        wd._last_hb_write = 0.0                 # defeat the throttle
        router.step()
        rec = json.load(open(hb))
        assert rec["replica"] == "r0"
        assert "serving_step" in rec and "live_slots" in rec
        monkeypatch.setenv("APEX_TPU_HEARTBEAT_FILE", hb)
        note = tpu_watch.heartbeat_note()
        assert "replica r0" in note and "live slots" in note
        router.drain()


class TestDeadlines:
    def test_unmeetable_deadline_rejected_at_admission(self,
                                                       chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        policy = FleetPolicy(
            classes=(SLOClass("interactive", 0, deadline_s=30.0),
                     SLOClass("batch", 1)),
            step_floor_s=1.0)
        router = FleetRouter(_replicas(ccfg, fns, maxp), policy)
        # 8-token prompt = 2 chunks; +6 tokens -> 7 steps >= 7s floor
        assert not router.submit(_req("tight", [1] * 8, new=6),
                                 deadline_s=3.0)
        assert router.rejected["tight"] == "deadline_unmeetable"
        # the same request with the class's 30 s deadline admits
        assert router.submit(_req("ok", [1] * 8, new=6))
        router.drain()
        assert "ok" in router.completions

    def test_miss_retries_token_identical(self, chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        # 6 requests onto 4 fleet slots: the overflow queues past its
        # deadline, so misses are guaranteed
        reqs = _some_reqs(n=6, new=6)
        ref = _reference(ccfg, fns, maxp, reqs)
        clk = [0.0]
        policy = FleetPolicy(classes=(
            SLOClass("interactive", 0, deadline_s=2.0, max_retries=50),
            SLOClass("batch", 1)))
        router = FleetRouter(_replicas(ccfg, fns, maxp), policy,
                             clock=lambda: clk[0])
        for r in reqs:
            assert router.submit(r)
        while router.pending:
            router.step()
            clk[0] += 1.0
            assert clk[0] < 300, "deadline retries livelocked"
        assert router.stats["deadline_misses"] >= 1
        assert router.stats["deadline_retries"] >= 1
        assert len(router.completions) == len(reqs)
        for uid, toks in ref.items():
            c = router.completions[uid]
            assert c.reason != "deadline"
            assert c.tokens == toks, uid

    def test_miss_without_retries_terminates_with_prefix(self,
                                                         chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        reqs = _some_reqs(n=6, new=6)
        ref = _reference(ccfg, fns, maxp, reqs)
        clk = [0.0]
        policy = FleetPolicy(classes=(
            SLOClass("interactive", 0, deadline_s=3.0, max_retries=0),
            SLOClass("batch", 1)))
        router = FleetRouter(_replicas(ccfg, fns, maxp), policy,
                             clock=lambda: clk[0])
        for r in reqs:
            assert router.submit(r)
        while router.pending:
            router.step()
            clk[0] += 1.0
            assert clk[0] < 100
        dead = [u for u, c in router.completions.items()
                if c.reason == "deadline"]
        assert dead, "no deadline ever fired — the test proved nothing"
        assert router.stats["deadline_misses"] == len(dead)
        for uid, c in router.completions.items():
            full = ref[uid]
            # terminal-deadline streams are COMMITTED PREFIXES of the
            # reference — cut off, never corrupted
            assert c.tokens == full[:len(c.tokens)], uid
            if c.reason != "deadline":
                assert c.tokens == full, uid


class TestHedging:
    def test_hedge_wins_when_primary_is_stuck(self, chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        reqs = _some_reqs(n=2, new=5, seed0=400)
        ref = _reference(ccfg, fns, maxp, reqs)
        clk = [0.0]
        policy = FleetPolicy(
            classes=(SLOClass("interactive", 0, hedge_after_s=3.0),
                     SLOClass("batch", 1)),
            max_replica_faults=10_000)      # fault forever, no quarantine
        router = FleetRouter(_replicas(ccfg, fns, maxp), policy,
                             clock=lambda: clk[0])
        for r in reqs:
            assert router.submit(r)
        r0 = router.replicas[0]
        # every window on r0 raises: its requests make no progress, so
        # after hedge_after_s each spawns a duplicate on r1 and the
        # duplicate commits first
        with faults.failing_windows(r0.batcher, nth=1, count=10_000):
            while router.pending:
                router.step()
                clk[0] += 1.0
                assert clk[0] < 200, "hedged fleet livelocked"
        stuck = [u for u in ref
                 if router.log.get(u).replica == "r1"
                 and router.completions[u].hedged]
        assert router.stats["hedge_wins"] >= 1
        assert stuck, "no hedge ever won"
        for uid, toks in ref.items():
            assert router.completions[uid].tokens == toks, uid

    def test_hedge_loses_cleanly_when_primary_lands(self, chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        reqs = _some_reqs(n=2, new=8, seed0=500)
        ref = _reference(ccfg, fns, maxp, reqs)
        clk = [0.0]
        policy = FleetPolicy(
            classes=(SLOClass("interactive", 0, hedge_after_s=1.0),
                     SLOClass("batch", 1)))
        router = FleetRouter(_replicas(ccfg, fns, maxp), policy,
                             clock=lambda: clk[0])
        for r in reqs:
            assert router.submit(r)
        while router.pending:
            router.step()
            clk[0] += 1.0
            assert clk[0] < 200
        assert router.stats["hedges"] >= 1
        assert router.stats["hedge_losses"] >= 1
        assert not router._hedges                # no hedge left live
        for uid, toks in ref.items():
            assert router.completions[uid].tokens == toks, uid
        # the losers' slots and pages were actually released — every
        # page is either free or held (refcount 1) by the prefix index
        for r in router.replicas:
            assert r.batcher.live_slots == 0
            cache = r.batcher.cache
            assert (cache.allocator.num_free + cache.prefix_index_size
                    == cache.config.num_pages - 1)


class TestBrownout:
    def test_ladder_up_down_sheds_batch_and_keeps_tokens(self,
                                                         chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        reqs = _some_reqs(n=6, new=4)
        ref = _reference(ccfg, fns, maxp, reqs)
        bp = BrownoutPolicy(page_frac=(0.0, 0.0, 0.0),
                            queue_depth=(2, 3, 4),
                            chunk_throttle=2, recover_margin=1.5)
        router = FleetRouter(_replicas(ccfg, fns, maxp, n=1),
                             FleetPolicy(brownout=bp))
        for r in reqs:
            assert router.submit(r)
        router.step()                           # qd=6 >= 4: level 3
        assert router.brownout_level == 3
        b = router.replicas[0].batcher
        assert b.speculation_enabled is False
        assert b.chunk_throttle == 2
        # level 3 sheds the LOWEST-priority class at admission
        assert not router.submit(_req("shed", [1, 2, 3], new=2),
                                 "batch")
        assert router.rejected["shed"] == "brownout"
        # interactive still admits under the same pressure
        assert router.submit(_req("keep", [1, 2, 4], new=2),
                             "interactive")
        router.drain()
        # pressure cleared: the ladder walked back down (hysteresis
        # releases one rung per step; the drain has plenty)
        assert router.brownout_level < 3
        assert b.speculation_enabled or router.brownout_level >= 1
        assert router.stats["brownout_transitions"] >= 2
        # the levers are scheduling-only: every admitted stream is
        # token-identical to the no-brownout reference
        for uid, toks in ref.items():
            assert router.completions[uid].tokens == toks, uid
        assert "keep" in router.completions

    def test_page_pressure_rung_via_exhaust_pool(self, chaos_setup):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        bp = BrownoutPolicy(page_frac=(0.9, 0.05, 0.01),
                            queue_depth=(10_000,) * 3)
        router = FleetRouter(_replicas(ccfg, fns, maxp, n=1),
                             FleetPolicy(brownout=bp))
        cache = router.replicas[0].batcher.cache
        with faults.exhaust_pool(cache, leave_free=1):
            router.step()
            assert router.brownout_level >= 1
        # pages returned; de-escalation needs the recover margin, one
        # rung per step
        for _ in range(4):
            router.step()
        assert router.brownout_level == 0


class TestJournalRestart:
    def test_restart_resumes_token_identical(self, chaos_setup,
                                             tmp_path):
        mesh, model, params, ccfg, fns, maxp = chaos_setup
        path = str(tmp_path / "journal.jsonl")
        # mixed greedy + seeded-looking uids; greedy fns so identity is
        # exact (seeded identity is pinned at the dryrun tier)
        reqs = _some_reqs(n=5, new=6)
        ref = _reference(ccfg, fns, maxp, reqs)
        router = FleetRouter(_replicas(ccfg, fns, maxp),
                             journal=RequestJournal(path))
        for r in reqs:
            assert router.submit(r)
        for _ in range(4):                      # serve PARTWAY, then die
            router.step()
        done_before = dict(router.completions)
        assert router.pending > 0, "nothing in flight at the kill point"
        # ---- the process is gone.  A new one recovers from disk:
        rec = recover_journal(path)
        assert rec.corrupt == 0
        router2 = FleetRouter(_replicas(ccfg, fns, maxp),
                              journal=RequestJournal(path))
        out = router2.resume_from_journal(rec)
        assert out["resumed"] + out["completed"] == len(reqs)
        assert out["resumed"] >= 1
        router2.drain()
        assert len(router2.completions) == len(reqs)     # zero lost
        for uid, toks in ref.items():
            assert router2.completions[uid].tokens == toks, uid
        # completed-before-death streams came back from the journal
        for uid, c in done_before.items():
            assert router2.completions[uid].tokens == c.tokens
            assert router2.completions[uid].replica == "<journal>"
        # and the SAME journal path journals the rest: a second
        # recovery sees every stream complete
        rec2 = recover_journal(path)
        assert rec2.corrupt == 0 and rec2.gapped == 0
        for uid, toks in ref.items():
            assert rec2.entries[uid]["done"], uid
            assert rec2.entries[uid]["emitted"] == toks, uid


# ---------------------------------------------------------------------------
# cancel mid-speculation-window (regression for the hedge/deadline
# cancel path)
# ---------------------------------------------------------------------------


class TestCancelMidSpeculation:
    def test_cancel_mid_window_is_safe(self):
        from apex_tpu.models import GPTConfig, GPTModel
        from apex_tpu.serving.speculate import NGramDraftSource
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel(
            devices=jax.devices()[:1])
        try:
            model = GPTModel(GPTConfig(
                vocab_size=64, num_layers=2, hidden_size=32,
                num_attention_heads=4, max_position_embeddings=64,
                compute_dtype=jnp.float32, remat=False,
                attention_impl="xla"))
            params = model.init(jax.random.PRNGKey(0))
            PAGE, NEW, K, maxp = 4, 8, 3, 12
            pps = -(-(maxp + NEW) // PAGE)
            ccfg = KVCacheConfig(
                num_layers=2, num_heads=4, head_dim=8,
                num_pages=1 + 2 * pps, page_size=PAGE, max_seqs=2,
                pages_per_seq=pps, dtype=jnp.float32)
            fns = model.decode_fns(params, mesh, ccfg,
                                   max_prompt_len=maxp, speculate_k=K)
            # repetitive prompts so drafts actually accept (the cancel
            # must land while multi-token windows are in flight)
            rng = np.random.RandomState(3)
            prompts = []
            for n in (12, 11, 10):
                pat = rng.randint(1, 64, (4,))
                prompts.append([int(t) for t in np.tile(pat, 3)[:n]])
            reqs = [Request(uid=f"s{i}", prompt=list(p),
                            max_new_tokens=NEW)
                    for i, p in enumerate(prompts)]

            def batcher():
                return ContinuousBatcher(
                    fns.prefill, fns.decode, PagedKVCache(ccfg),
                    init_pools(ccfg), max_prompt_len=maxp,
                    harvest_every=3, spec_fn=fns.spec, speculate_k=K,
                    draft_source=NGramDraftSource(K))

            ref = {u: c.tokens
                   for u, c in batcher().run(list(reqs)).items()}

            b = batcher()
            import collections
            q = collections.deque(reqs)
            b.pump(q)                       # s0+s1 admitted, mid-stream
            assert b.live_slots == 2
            got = b.cancel("s0")
            # the victim's harvested tokens are a committed prefix
            assert got is not None
            assert got == ref["s0"][:len(got)]
            assert b.cancel("s0") is None   # idempotent: already gone
            while b.live_slots or q:
                b.pump(q)
            assert "s0" not in b.completions
            # survivors (including s2, admitted into the FREED slot)
            # are token-identical to the uncancelled reference
            assert b.completions["s1"].tokens == ref["s1"]
            assert b.completions["s2"].tokens == ref["s2"]
            # every page came back (shared prefix pages excepted: none
            # here — no prefix cache)
            assert (b.cache.allocator.num_free
                    == ccfg.num_pages - 1)
        finally:
            parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# metrics report: the fault/recovery section
# ---------------------------------------------------------------------------


class TestFaultReportSection:
    def test_summarize_faults(self):
        from tools.metrics_report import format_report, summarize

        records = [
            {"kind": "event", "t": 1.0, "event": "replica_fault",
             "replica": "r0", "consecutive": 1, "error": "boom"},
            {"kind": "event", "t": 1.1, "event": "replica_fault",
             "replica": "r0", "consecutive": 2, "error": "boom"},
            {"kind": "event", "t": 1.2, "event": "replica_quarantined",
             "replica": "r0", "cause": "faults"},
            {"kind": "event", "t": 1.3, "event": "request_migrated",
             "uid": "a", "replica": "r1"},
            {"kind": "event", "t": 1.4, "event": "request_migrated",
             "uid": "b", "replica": "r1", "cause": "deadline"},
            {"kind": "event", "t": 1.5, "event": "deadline_miss",
             "uid": "b", "slo": "interactive", "retry": True},
            {"kind": "event", "t": 1.6, "event": "deadline_miss",
             "uid": "c", "slo": "interactive", "retry": False},
            {"kind": "event", "t": 1.7, "event": "hedge_spawn",
             "uid": "d", "replica": "r1", "primary": "r0"},
            {"kind": "event", "t": 1.8, "event": "hedge_win",
             "uid": "d", "replica": "r1"},
            {"kind": "event", "t": 1.9, "event": "brownout",
             "from_level": 0, "to_level": 2, "free_page_frac": 0.04,
             "queue_depth": 9},
            {"kind": "event", "t": 2.0, "event": "journal_replayed",
             "resumed": 3, "completed": 2, "corrupt": 1, "gapped": 0},
            {"kind": "event", "t": 2.1, "event": "trace_request",
             "uid": "b", "slo": "interactive", "reason": "eos"},
            {"kind": "event", "t": 2.2, "event": "trace_request",
             "uid": "c", "slo": "interactive", "reason": "deadline"},
        ]
        s = summarize(records)
        ft = s["faults"]
        assert ft["replica_faults"]["count"] == 2
        assert ft["replica_faults"]["by_replica"] == {"r0": 2}
        assert ft["quarantined"] == [{"replica": "r0",
                                      "cause": "faults"}]
        assert ft["migrations"]["by_cause"] == {
            "replica_dead": 1, "deadline": 1}
        assert ft["deadline_misses"] == {"count": 2, "retried": 1,
                                         "terminal": 1}
        assert ft["hedging"] == {"spawned": 1, "wins": 1, "losses": 0}
        assert ft["brownout"]["max_level"] == 2
        assert ft["journal_replays"][0]["resumed"] == 3
        att = ft["slo_attainment"]["interactive"]
        assert att == {"n": 2, "deadline_missed": 1,
                       "attainment": 0.5}
        text = format_report(s)
        assert "fault / recovery summary:" in text
        assert "quarantined: r0(faults)" in text
        assert "slo attainment 50.0%" in text
        # the timeline keeps the new fields
        tl = {e["event"]: e for e in s["events"]["timeline"]}
        assert tl["brownout"]["to_level"] == 2
        assert tl["replica_quarantined"]["cause"] == "faults"

    def test_load_gen_counts_deadline_and_hedge(self):
        from tools.load_gen import summarize_trace

        recs = [
            {"uid": "a", "slo": "interactive", "reason": "eos",
             "new_tokens": 3},
            {"uid": "b", "slo": "interactive", "reason": "deadline",
             "new_tokens": 1},
            {"uid": "c", "slo": "batch", "reason": "eos",
             "new_tokens": 2, "hedged": True},
        ]
        s = summarize_trace(recs)
        assert s["deadline_missed"] == 1
        assert s["hedged"] == 1
        assert s["completed"] == 3

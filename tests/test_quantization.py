"""Compressed-collectives parity suite on the 8-device virtual mesh.

Covers the quantized hierarchical gradient collectives end to end:
block-wise int8 quantize/dequantize numerics, ``compression=None``
bit-identity with the uncompressed hierarchical psum, int8 accuracy
with and without error feedback, the DDP/Reducer/ZeRO threading, a GPT
short-training run whose int8+error-feedback loss curve must track the
fp32-comms baseline within documented tolerance, and the residual
state's round-trip through the checkpoint layer.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.quantization import (
    CompressionConfig,
    as_compression_config,
    comm_residual_sizes,
    dequantize_blockwise,
    init_residual,
    quantize_blockwise,
)
from apex_tpu.parallel import (
    all_reduce_gradients,
    hierarchical_data_parallel_mesh,
)
from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    comm_state_specs,
    init_comm_state,
)

try:  # jax >= 0.6 spelling
    _shard_map = jax.shard_map
    _SM_KW = {"check_vma": False}
except AttributeError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _SM_KW = {"check_rep": False}


def smap(f, mesh, in_specs, out_specs):
    """Replication checking is off on BOTH spellings: every test here
    reduces explicitly (the DDP.value_and_grad convention), so the
    autodiff-inserted psum the checker enables is never relied on."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **_SM_KW)


DCN, ICI = 2, 4
AXES = ("dcn", "ici")


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests require 8 virtual devices"
    return hierarchical_data_parallel_mesh(ici_size=ICI)


# ---------------------------------------------------------------- numerics


class TestQuantizeBlockwise:
    def test_roundtrip_error_bounded_per_block(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1024,)) * 10.0
        q, s = quantize_blockwise(x, 64)
        assert q.dtype == jnp.int8 and s.dtype == jnp.float32
        assert s.shape == (16,)
        back = dequantize_blockwise(q, s, 64)
        err = np.abs(np.asarray(x - back)).reshape(16, 64)
        # nearest rounding: error <= scale/2 per block
        bound = np.asarray(s)[:, None] / 2 + 1e-7
        assert np.all(err <= bound)

    def test_partial_block_and_shape_preserved(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (7, 13))  # 91 elems
        q, s = quantize_blockwise(x, 32)
        assert q.shape == x.shape
        assert s.shape == (3,)  # ceil(91/32)
        back = dequantize_blockwise(q, s, 32)
        assert back.shape == x.shape
        amax = float(jnp.max(jnp.abs(x)))
        assert float(jnp.max(jnp.abs(x - back))) <= amax / 127

    def test_bf16_in_out(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (256,), jnp.bfloat16)
        q, s = quantize_blockwise(x, 128)
        back = dequantize_blockwise(q, s, 128, dtype=jnp.bfloat16)
        assert back.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(x, jnp.float32), np.asarray(back, jnp.float32),
            atol=float(jnp.max(jnp.abs(x))) / 100,
        )

    def test_zero_block_exact(self):
        x = jnp.zeros((128,))
        q, s = quantize_blockwise(x, 64)
        assert np.all(np.asarray(q) == 0)
        np.testing.assert_array_equal(
            np.asarray(dequantize_blockwise(q, s, 64)), 0.0
        )

    def test_deterministic_rounding_is_deterministic(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (512,))
        q1, s1 = quantize_blockwise(x, 64)
        q2, s2 = quantize_blockwise(x, 64)
        np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))

    def test_stochastic_rounding_unbiased(self):
        x = jax.random.normal(jax.random.PRNGKey(4), (256,))
        outs = []
        for i in range(64):
            q, s = quantize_blockwise(
                x, 64, "stochastic", jax.random.PRNGKey(i)
            )
            outs.append(np.asarray(dequantize_blockwise(q, s, 64)))
        single_err = np.max(np.abs(outs[0] - np.asarray(x)))
        mean_err = np.max(np.abs(np.mean(outs, axis=0) - np.asarray(x)))
        # the average over keys converges on the true value — the
        # defining property deterministic rounding lacks
        assert mean_err < single_err / 3

    def test_stochastic_requires_key(self):
        with pytest.raises(ValueError, match="PRNG key"):
            quantize_blockwise(jnp.ones((8,)), 8, "stochastic")

    def test_config_validation(self):
        assert as_compression_config(None) is None
        cfg = as_compression_config("int8")
        assert cfg.block_size == 256 and cfg.error_feedback
        assert as_compression_config(cfg) is cfg
        with pytest.raises(ValueError, match="method"):
            CompressionConfig(method="fp4")
        with pytest.raises(ValueError, match="rounding"):
            CompressionConfig(rounding="up")
        with pytest.raises(ValueError, match="block_size"):
            CompressionConfig(block_size=0)
        with pytest.raises(ValueError, match="compression must be"):
            as_compression_config(8)

    def test_residual_sizes(self):
        padded, shard = comm_residual_sizes(100, 2, 64)
        assert padded == 128 and shard == 64
        res = init_residual(100, 2, 64)
        assert res["push"].shape == (128,)
        assert res["pull"].shape == (64,)


# ------------------------------------------------------ hierarchical reduce


def _grads(key=5):
    ks = jax.random.split(jax.random.PRNGKey(key), 2)
    return {"w": jax.random.normal(ks[0], (8, 13, 7)),
            "b": jax.random.normal(ks[1], (8, 5))}


def _seed_hierarchical_mean(g, ici=ICI):
    """The pre-compression hierarchical psum, inlined verbatim from the
    seed (RS(ici) -> AR(dcn) -> AG(ici), then /world): the bit-identity
    reference for compression=None."""
    def one(g):
        n = g.size
        flat = g.reshape(-1)
        pad = (-n) % ici
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        chunk = jax.lax.psum_scatter(flat, "ici", tiled=True)
        chunk = jax.lax.psum(chunk, "dcn")
        out = jax.lax.all_gather(chunk, "ici", axis=0, tiled=True)
        if pad:
            out = out[:n]
        return (out.reshape(g.shape) / (DCN * ICI)).astype(g.dtype)

    return jax.tree.map(one, g)


class TestCompressedAllReduce:
    def test_compression_none_bit_identical_to_seed(self, mesh):
        grads = _grads()
        spec = jax.tree.map(lambda _: P(AXES), grads)
        ours = jax.jit(smap(
            lambda g: all_reduce_gradients(g, AXES),
            mesh, (spec,), spec))(grads)
        seed = jax.jit(smap(
            _seed_hierarchical_mean, mesh, (spec,), spec))(grads)
        for k in grads:
            np.testing.assert_array_equal(
                np.asarray(ours[k]), np.asarray(seed[k]))

    def test_int8_stateless_tracks_exact_mean(self, mesh):
        grads = _grads()
        spec = jax.tree.map(lambda _: P(AXES), grads)
        cfg = CompressionConfig(block_size=64, error_feedback=False)
        out = jax.jit(smap(
            lambda g: all_reduce_gradients(g, AXES, compression=cfg),
            mesh, (spec,), spec))(grads)
        for k in grads:
            ref = np.broadcast_to(
                np.mean(np.asarray(grads[k]), axis=0, keepdims=True),
                grads[k].shape)
            amax = np.max(np.abs(ref))
            assert np.max(np.abs(np.asarray(out[k]) - ref)) < 0.05 * amax

    def test_output_dtype_untouched(self, mesh):
        grads = {"w": jnp.ones((8, 16), jnp.bfloat16)}
        spec = {"w": P(AXES)}
        cfg = CompressionConfig(error_feedback=False)
        out = jax.jit(smap(
            lambda g: all_reduce_gradients(g, AXES, compression=cfg),
            mesh, (spec,), spec))(grads)
        assert out["w"].dtype == jnp.bfloat16

    def test_error_feedback_improves_time_average(self, mesh):
        grads = _grads()
        # per-device grad shapes (what the reduce sees inside shard_map)
        local = jax.tree.map(
            lambda g: jnp.zeros((1,) + g.shape[1:]), grads)
        spec = jax.tree.map(lambda _: P(AXES), grads)
        cfg = CompressionConfig(block_size=64)
        state = init_comm_state(local, AXES, cfg, mesh=mesh)
        cspecs = comm_state_specs(state, AXES)
        step = jax.jit(smap(
            lambda g, st: all_reduce_gradients(
                g, AXES, compression=cfg, comm_state=st),
            mesh, (spec, cspecs), (spec, cspecs)))
        outs = []
        for _ in range(20):
            out, state = step(grads, state)
            outs.append(np.asarray(out["w"]))
        assert int(state["step"]) == 20
        ref = np.broadcast_to(
            np.mean(np.asarray(grads["w"]), axis=0, keepdims=True),
            grads["w"].shape)
        single = np.max(np.abs(outs[0] - ref))
        averaged = np.max(np.abs(np.mean(outs, axis=0) - ref))
        # the residual compensates the rounding bias over steps
        assert averaged < single / 3

    def test_stochastic_rounding_in_collective(self, mesh):
        grads = _grads()
        local = jax.tree.map(
            lambda g: jnp.zeros((1,) + g.shape[1:]), grads)
        spec = jax.tree.map(lambda _: P(AXES), grads)
        cfg = CompressionConfig(block_size=64, rounding="stochastic",
                                error_feedback=False)
        # stochastic without a step source would re-roll the SAME
        # dither forever (a fixed bias): stateless use is refused
        with pytest.raises(ValueError, match="comm state"):
            all_reduce_gradients(grads, AXES, compression=cfg)
        state = init_comm_state(local, AXES, cfg, mesh=mesh)
        cspecs = comm_state_specs(state, AXES)
        step = jax.jit(smap(
            lambda g, st: all_reduce_gradients(
                g, AXES, compression=cfg, comm_state=st),
            mesh, (spec, cspecs), (spec, cspecs)))
        out1, state = step(grads, state)
        out2, state = step(grads, state)
        ref = np.broadcast_to(
            np.mean(np.asarray(grads["w"]), axis=0, keepdims=True),
            grads["w"].shape)
        amax = np.max(np.abs(ref))
        for out in (out1, out2):
            assert np.max(np.abs(np.asarray(out["w"]) - ref)) < 0.1 * amax
        # the step counter advanced the key: fresh dither each step
        assert np.any(np.asarray(out1["w"]) != np.asarray(out2["w"]))
        # EF off: residuals pass through untouched (zeros)
        assert all(
            float(jnp.sum(jnp.abs(l))) == 0.0
            for l in jax.tree.leaves(
                jax.device_get(state)["residuals"])
        )

    def test_model_axis_sharded_residual_specs(self, mesh):
        """pp/tp-sharded params carry per-model-axis-position residuals:
        the specs must declare them varying there and the global buffer
        must hold every copy (review finding repro)."""
        import numpy as _np

        devs = _np.asarray(jax.devices()).reshape(2, 2, 2)
        from jax.sharding import Mesh

        mesh3 = Mesh(devs, ("dcn", "ici", "pp"))
        # one pp-sharded leaf, one replicated leaf
        params = {"stack": jnp.zeros((2, 40)), "norm": jnp.zeros((24,))}
        pspecs = {"stack": P("pp"), "norm": P()}
        cfg = CompressionConfig(block_size=16)
        state = init_comm_state(params, AXES, cfg, mesh=mesh3,
                                param_specs=pspecs)
        cspecs = comm_state_specs(state, AXES, param_specs=pspecs)
        assert cspecs["residuals"]["stack"]["push"] == \
            P(("dcn", "ici", "pp"))
        assert cspecs["residuals"]["norm"]["push"] == P(("dcn", "ici"))
        # pp-sharded leaf: local rows = 40 elems -> chunk 20 -> padded
        # 32 per device, x (2 dcn x 2 ici x 2 pp) positions globally
        assert state["residuals"]["stack"]["push"].shape == (8 * 32,)
        # replicated leaf: 24 -> chunk 12 -> padded 32, x (dcn x ici)
        assert state["residuals"]["norm"]["push"].shape == (4 * 32,)

        def step(g, st):
            return all_reduce_gradients(
                g, AXES, compression=cfg, comm_state=st)

        # per-device grads mirror the param locals: stack (1, 40) per
        # (dcn, ici, pp) position, norm (24,) varying over data only
        gspecs = {"stack": P(("dcn", "ici", "pp")),
                  "norm": P(("dcn", "ici"))}
        grads = {"stack": jax.random.normal(jax.random.PRNGKey(9),
                                            (8, 40)),
                 "norm": jax.random.normal(jax.random.PRNGKey(10),
                                           (192,))}
        out, new_state = jax.jit(smap(
            step, mesh3, (gspecs, cspecs), (gspecs, cspecs)))(
            grads, state)
        assert int(new_state["step"]) == 1
        for k in out:
            assert np.all(np.isfinite(np.asarray(out[k])))

    def test_validation_errors(self):
        with pytest.raises(ValueError, match="hierarchical"):
            all_reduce_gradients({}, "dp", compression="int8")
        with pytest.raises(ValueError, match="comm state"):
            all_reduce_gradients({}, AXES, compression="int8")
        with pytest.raises(ValueError, match="without compression"):
            all_reduce_gradients({}, AXES, comm_state={"residuals": {},
                                                       "step": 0})
        with pytest.raises(ValueError, match="hierarchical"):
            DistributedDataParallel(axis_name="dp", compression="int8")
        with pytest.raises(ValueError, match="hierarchical"):
            Reducer(axis_name="dp", compression="int8")

    def test_ddp_call_threads_state(self, mesh):
        grads = _grads()
        local = jax.tree.map(
            lambda g: jnp.zeros((1,) + g.shape[1:]), grads)
        spec = jax.tree.map(lambda _: P(AXES), grads)
        ddp = DistributedDataParallel(axis_name=AXES, compression="int8")
        state = ddp.init_comm_state(local, mesh=mesh)
        cspecs = ddp.comm_state_specs(state)
        step = jax.jit(smap(ddp, mesh, (spec, cspecs), (spec, cspecs)))
        out, state = step(grads, state)
        assert int(state["step"]) == 1
        ref = np.broadcast_to(
            np.mean(np.asarray(grads["w"]), axis=0, keepdims=True),
            grads["w"].shape)
        np.testing.assert_allclose(np.asarray(out["w"]), ref, atol=0.05)

    def test_reducer_compressed_accumulate_reduce(self, mesh):
        red = Reducer(axis_name=AXES, compression="int8")
        exact = Reducer(axis_name=AXES)

        def run(reducer):
            def step(x):
                acc = reducer.init(x[0])
                acc = reducer.accumulate(acc, x[0])
                acc = reducer.accumulate(acc, 2.0 * x[0])
                g, _ = reducer.reduce(acc)
                return g

            return jax.jit(smap(
                step, mesh, (P(AXES),), P(AXES)))(
                jax.random.normal(jax.random.PRNGKey(7), (8, 24)))

        g_c = run(red)
        g_e = run(exact)
        amax = np.max(np.abs(np.asarray(g_e)))
        np.testing.assert_allclose(
            np.asarray(g_c), np.asarray(g_e), atol=0.05 * amax)

    def test_reducer_comm_state_persists_across_cycles(self, mesh):
        red = Reducer(axis_name=AXES, compression="int8")

        def step(x):
            acc = red.init(x[0])
            acc = red.accumulate(acc, x[0])
            _, fresh = red.reduce(acc)
            # the accumulator resets, the residual does not
            zeroed = sum(jnp.sum(jnp.abs(l))
                         for l in jax.tree.leaves(fresh["sum"]))
            resid = sum(jnp.sum(jnp.abs(l)) for l in jax.tree.leaves(
                fresh["comm"]["residuals"]))
            count = fresh["comm"]["step"].astype(jnp.float32)
            return jax.lax.pmax(
                jnp.stack([zeroed, resid, count]), AXES)

        out = np.asarray(jax.jit(smap(
            step, mesh, (P(AXES),), P()))(
            jax.random.normal(jax.random.PRNGKey(8), (8, 40)) * 3.0))
        assert out[0] == 0.0
        assert out[1] > 0.0  # a real residual carried over
        assert int(out[2]) == 1


# ---------------------------------------------------------------- ZeRO


def _zero_params_grads():
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    params = {"a": jax.random.normal(ks[0], (37, 5)),
              "b": jax.random.normal(ks[1], (16,))}
    grads = jax.tree.map(
        lambda p: jax.random.normal(ks[2], p.shape), params)
    return params, grads


def _run_zero(mesh, opt, params, grads, steps=3):
    pspec = jax.tree.map(lambda _: P(), params)
    ss = opt.state_specs()
    init = jax.jit(smap(opt.init, mesh, (pspec,), ss))
    stepf = jax.jit(smap(lambda s, g, p: opt.step(s, g, p),
                         mesh, (ss, pspec, pspec), (pspec, ss)))
    st = init(params)
    p = params
    for _ in range(steps):
        p, st = stepf(st, grads, p)
    return p, st


class TestZeroCompressed:
    def test_adam_int8_tracks_uncompressed(self, mesh):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        params, grads = _zero_params_grads()
        exact, st_e = _run_zero(mesh, DistributedFusedAdam(
            lr=1e-2, weight_decay=0.01, axis_name=AXES), params, grads)
        comp, st_c = _run_zero(mesh, DistributedFusedAdam(
            lr=1e-2, weight_decay=0.01, axis_name=AXES,
            compression="int8"), params, grads)
        assert "comm" not in st_e and "comm" in st_c
        for a, b in zip(jax.tree.leaves(exact), jax.tree.leaves(comp)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-2)

    def test_lamb_int8_tracks_uncompressed(self, mesh):
        from apex_tpu.contrib.optimizers import DistributedFusedLAMB

        params, grads = _zero_params_grads()
        exact, _ = _run_zero(mesh, DistributedFusedLAMB(
            lr=1e-2, weight_decay=0.01, max_grad_norm=0.05,
            axis_name=AXES), params, grads)
        comp, _ = _run_zero(mesh, DistributedFusedLAMB(
            lr=1e-2, weight_decay=0.01, max_grad_norm=0.05,
            axis_name=AXES, compression="int8"), params, grads)
        for a, b in zip(jax.tree.leaves(exact), jax.tree.leaves(comp)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-2)

    def test_compression_requires_hierarchy(self):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        with pytest.raises(ValueError, match="hierarchical"):
            DistributedFusedAdam(axis_name="dp", compression="int8")

    def test_comm_state_specs_cover_both_axes(self, mesh):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam

        opt = DistributedFusedAdam(axis_name=AXES, compression="int8")
        specs = opt.state_specs()
        assert specs["comm"]["push"] == P(("dcn", "ici"))
        assert specs["comm"]["pull"] == P(("dcn", "ici"))


# ------------------------------------------------- GPT training parity


VOCAB, LAYERS, HIDDEN, HEADS, SEQ = 64, 2, 32, 4, 8

# documented tolerance for the acceptance criterion: int8 + error
# feedback must track the fp32-comms loss curve within this absolute
# gap at every one of the 8 short-training steps (measured headroom on
# the virtual mesh is ~10x tighter)
GPT_LOSS_ATOL = 3e-2


@pytest.fixture(scope="module")
def gpt_mesh():
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        data_parallel_ici_size_=ICI)
    yield mesh
    parallel_state.destroy_model_parallel()


def _gpt_setup():
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    cfg = GPTConfig(
        vocab_size=VOCAB, num_layers=LAYERS, hidden_size=HIDDEN,
        num_attention_heads=HEADS, max_position_embeddings=SEQ,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    )
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, VOCAB, (8, SEQ)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    return model, params, opt, tokens, targets


def _gpt_step_fn(mesh, model, opt, comp):
    from apex_tpu.transformer import parallel_state

    data_axes = parallel_state.data_parallel_axis_names()
    use_comm = comp is not None and comp.error_feedback

    def step(p, s, comm, tok, tgt):
        loss, grads = jax.value_and_grad(model.loss)(p, tok, tgt)
        loss = jax.lax.pmean(loss, data_axes)
        if comp is None:
            grads = all_reduce_gradients(grads, data_axes)
        elif use_comm:
            grads, comm = all_reduce_gradients(
                grads, data_axes, compression=comp, comm_state=comm)
        else:
            grads = all_reduce_gradients(
                grads, data_axes, compression=comp)
        p, s = opt.step(s, grads, p)
        return p, s, comm, loss

    return step, data_axes


def _train_gpt(mesh, comp, steps=8, resume_via_checkpoint_at=None,
               tmp_path=None):
    from apex_tpu.transformer.tensor_parallel.layers import (
        state_specs_like,
    )

    model, params, opt, tokens, targets = _gpt_setup()
    specs = model.param_specs()
    opt_state = opt.init(params)
    opt_specs = state_specs_like(specs, opt_state)
    step, data_axes = _gpt_step_fn(mesh, model, opt, comp)
    use_comm = comp is not None and comp.error_feedback
    if use_comm:
        comm = init_comm_state(params, data_axes, comp, mesh=mesh)
        cspecs = comm_state_specs(comm, data_axes)
    else:
        comm, cspecs = {}, {}
    dspec = P(data_axes)
    jstep = jax.jit(smap(
        step, mesh,
        (specs, opt_specs, cspecs, dspec, dspec),
        (specs, opt_specs, cspecs, P()),
    ))
    p, s = params, opt_state
    trace = []
    for i in range(steps):
        p, s, comm, loss = jstep(p, s, comm, tokens, targets)
        trace.append(float(loss))
        if resume_via_checkpoint_at is not None \
                and i == resume_via_checkpoint_at:
            # full save/restore round trip mid-run, residuals included
            from apex_tpu import checkpoint

            path = str(tmp_path / "ck")
            state = {"params": jax.device_get(p),
                     "opt": jax.device_get(s),
                     "comm": jax.device_get(comm)}
            checkpoint.save(path, state)
            restored = checkpoint.restore(path, target=state,
                                          verify_integrity=True)
            p = restored["params"]
            s = restored["opt"]
            comm = restored["comm"]
    return np.asarray(trace)


class TestGPTTrainingParity:
    def test_int8_error_feedback_matches_fp32_comms(self, gpt_mesh):
        base = _train_gpt(gpt_mesh, None)
        comp = _train_gpt(gpt_mesh, CompressionConfig())
        assert np.all(np.isfinite(base)) and base[-1] < base[0]
        np.testing.assert_allclose(comp, base, atol=GPT_LOSS_ATOL)

    def test_residual_state_roundtrips_through_checkpoint(
            self, gpt_mesh, tmp_path):
        uninterrupted = _train_gpt(gpt_mesh, CompressionConfig())
        resumed = _train_gpt(gpt_mesh, CompressionConfig(),
                             resume_via_checkpoint_at=3,
                             tmp_path=tmp_path)
        # deterministic rounding + full state capture -> bit-identical
        np.testing.assert_array_equal(uninterrupted, resumed)

    def test_data_parallel_helpers(self, gpt_mesh):
        from apex_tpu.transformer import parallel_state

        assert parallel_state.data_parallel_axis_names() == AXES
        assert parallel_state.hierarchical_data_parallel_axes() == AXES
        assert parallel_state.get_data_parallel_world_size() == 8
        assert gpt_mesh.shape["dp"] == 1

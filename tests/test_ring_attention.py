"""Ring attention tests: cp-sharded exact attention vs dense reference."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.ops.attention import mha_reference
from apex_tpu.ops.ring_attention import ring_attention
from apex_tpu.transformer import parallel_state

B, H, S, D = 2, 4, 32, 16  # global seq 32 → 8 per rank on cp=4


@pytest.fixture
def mesh():
    m = parallel_state.initialize_model_parallel(context_parallel_size_=4)
    yield m
    parallel_state.destroy_model_parallel()


def qkv(key):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, H, S, D)) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(mesh, causal):
    q, k, v = qkv(jax.random.PRNGKey(0))
    ref = mha_reference(q, k, v, causal=causal)

    fn = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, causal=causal),
            mesh=mesh,
            in_specs=(P(None, None, "cp"),) * 3,
            out_specs=P(None, None, "cp"),
        )
    )
    out = fn(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


@pytest.mark.parametrize("block_k", [2, 4, 8])
def test_ring_inner_chunking_matches(mesh, block_k):
    """The block_k inner K walk must not change the math."""
    q, k, v = qkv(jax.random.PRNGKey(3))
    ref = mha_reference(q, k, v, causal=True)
    fn = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring_attention(
                q, k, v, causal=True, block_k=block_k
            ),
            mesh=mesh,
            in_specs=(P(None, None, "cp"),) * 3,
            out_specs=P(None, None, "cp"),
        )
    )
    np.testing.assert_allclose(
        np.asarray(fn(q, k, v)), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


@pytest.mark.parametrize("remat", [False, True])
def test_ring_grads_match_dense(mesh, remat):
    q, k, v = qkv(jax.random.PRNGKey(1))

    def ring_loss(q, k, v):
        out = ring_attention(q, k, v, causal=True, remat=remat)
        return jnp.sum(out**2)

    def dense_loss(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    ring_grad = jax.jit(
        jax.shard_map(
            jax.grad(ring_loss, argnums=(0, 1, 2)),
            mesh=mesh,
            in_specs=(P(None, None, "cp"),) * 3,
            out_specs=(P(None, None, "cp"),) * 3,
        )
    )(q, k, v)
    dense_grad = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(ring_grad, dense_grad):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_gpt_context_parallel_matches_dense(mesh):
    """GPT with the sequence sharded over cp == dense GPT loss+grads."""
    cfg = dict(
        vocab_size=64, num_layers=2, hidden_size=32, num_attention_heads=4,
        max_position_embeddings=32, compute_dtype=jnp.float32, remat=False,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    targets = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 64)

    dense_model = GPTModel(GPTConfig(**cfg, attention_impl="xla"))
    params = dense_model.init(jax.random.PRNGKey(0))
    specs = dense_model.param_specs()

    # dense reference on the same mesh (batch over dp, full seq)
    ref_fn = jax.jit(
        jax.shard_map(
            jax.value_and_grad(lambda p, t, y: dense_model.loss(p, t, y)),
            mesh=mesh,
            in_specs=(specs, P("dp"), P("dp")),
            out_specs=(P(), specs),
        )
    )
    ref_loss, ref_grads = ref_fn(params, tokens, targets)

    cp_model = GPTModel(GPTConfig(**cfg, context_parallel=True))

    def cp_loss(p, t, y):
        return cp_model.loss(p, t, y)

    cp_fn = jax.jit(
        jax.shard_map(
            jax.value_and_grad(cp_loss),
            mesh=mesh,
            in_specs=(specs, P("dp", "cp"), P("dp", "cp")),
            out_specs=(P(), specs),
        )
    )
    loss, grads = cp_fn(params, tokens, targets)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for (ka, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(jax.device_get(grads)),
        jax.tree_util.tree_leaves_with_path(jax.device_get(ref_grads)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5,
            err_msg=str(ka),
        )

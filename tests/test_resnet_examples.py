"""ResNet model + examples smoke tests (the reference's L1 tier runs its
examples as tests; same idea at unit scale, SURVEY.md §4)."""

import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models.resnet import ResNet, ResNetConfig
from apex_tpu.transformer import parallel_state


def small_resnet(depth=18, sync_bn_axis=None):
    return ResNet(ResNetConfig(
        depth=depth, num_classes=10, width=8,
        compute_dtype=jnp.float32, sync_bn_axis=sync_bn_axis,
    ))


class TestResNet:
    def test_forward_shapes_and_stats_update(self):
        model = small_resnet()
        params, stats = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits, new_stats = model.apply(params, stats, x, training=True)
        assert logits.shape == (2, 10)
        assert np.all(np.isfinite(np.asarray(logits)))
        assert not np.allclose(
            np.asarray(new_stats["bn_stem"]["mean"]),
            np.asarray(stats["bn_stem"]["mean"]),
        )

    def test_eval_uses_running_stats(self):
        model = small_resnet()
        params, stats = model.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits1, s1 = model.apply(params, stats, x, training=False)
        logits2, s2 = model.apply(params, stats, x, training=False)
        np.testing.assert_array_equal(np.asarray(logits1), np.asarray(logits2))
        # eval must not touch running stats
        for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(stats)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_resnet50_builds(self):
        model = ResNet(ResNetConfig(depth=50, num_classes=10, width=8,
                                    compute_dtype=jnp.float32,
                                    sync_bn_axis=None))
        params, stats = model.init(jax.random.PRNGKey(0))
        n_params = sum(int(jnp.size(l)) for l in jax.tree.leaves(params))
        assert n_params > 1e5
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3))
        logits, _ = model.apply(params, stats, x)
        assert logits.shape == (1, 10)

    def test_sync_bn_matches_single_device(self):
        """dp=8-sharded batch with SyncBN == whole batch on one device."""
        mesh = parallel_state.initialize_model_parallel()
        try:
            model_sync = small_resnet(sync_bn_axis="dp")
            model_local = small_resnet(sync_bn_axis=None)
            params, stats = model_local.init(jax.random.PRNGKey(0))
            x = jax.random.normal(jax.random.PRNGKey(1), (16, 16, 16, 3))
            ref_logits, _ = model_local.apply(params, stats, x, training=True)

            from apex_tpu._compat import shard_map

            pspec = jax.tree.map(lambda _: P(), params)
            sspec = jax.tree.map(lambda _: P(), stats)
            fn = jax.jit(
                shard_map(
                    lambda p, s, x: model_sync.apply(p, s, x, training=True),
                    mesh=mesh,
                    in_specs=(pspec, sspec, P("dp")),
                    out_specs=(P("dp"), sspec),
                )
            )
            logits, _ = fn(params, stats, x)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(ref_logits), rtol=5e-3,
                atol=5e-4,
            )
        finally:
            parallel_state.destroy_model_parallel()


@pytest.mark.parametrize(
    "script,args",
    [
        ("examples/simple_distributed.py", []),
        ("examples/dcgan_amp.py", ["--steps", "10", "--batch", "16"]),
        ("examples/imagenet_amp.py",
         ["--depth", "18", "--batch-size", "1", "--image-size", "32",
          "--epochs", "1", "--steps-per-epoch", "2", "--eval-steps", "1",
          "--num-classes", "10"]),
        ("examples/gpt_pretrain.py",
         ["--tp", "2", "--pp", "2", "--num-micro", "2", "--vocab", "64",
          "--layers", "2", "--hidden", "32", "--heads", "4",
          "--seq", "16", "--micro-batch", "1", "--steps", "3"]),
        ("examples/gpt_pretrain.py",
         ["--pp", "2", "--num-micro", "2", "--vocab", "64",
          "--layers", "2", "--hidden", "32", "--heads", "4",
          "--seq", "16", "--micro-batch", "1", "--steps", "3",
          "--zero", "--opt-level", "O2"]),
        ("examples/gpt_pretrain.py",
         ["--pp", "2", "--num-micro", "2", "--vocab", "64",
          "--layers", "2", "--hidden", "32", "--heads", "4",
          "--seq", "16", "--micro-batch", "1", "--steps", "3",
          "--zero", "--num-experts", "8"]),
        ("examples/gpt_pretrain.py",
         ["--vocab", "64", "--layers", "2", "--hidden", "32",
          "--heads", "4", "--seq", "16", "--micro-batch", "1",
          "--steps", "3", "--num-experts", "8"]),
        ("examples/gpt_pretrain.py",
         ["--vocab", "64", "--layers", "2", "--hidden", "32",
          "--heads", "4", "--seq", "16", "--micro-batch", "1",
          "--steps", "3", "--num-experts", "8", "--opt-level", "O2"]),
        ("examples/bert_finetune.py",
         ["--tp", "2", "--vocab", "64", "--layers", "1",
          "--hidden", "32", "--heads", "2", "--seq", "16",
          "--batch", "1", "--steps", "3", "--eval-batches", "1"]),
    ],
)
def test_example_runs(script, args):
    import os

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = "."
    out = subprocess.run(
        [sys.executable, script] + args,
        capture_output=True, text=True, timeout=500, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]


@pytest.mark.slow
def test_imagenet_trainer_checkpoint_roundtrip(tmp_path):
    """The flagship trainer's save/resume through apex_tpu.checkpoint
    round-trips the FULL training state bitwise (reference: the
    main_amp.py checkpoint dict — params + optimizer + epoch +
    best_prec1 — restored exactly by --resume)."""
    import importlib.util
    import os

    from apex_tpu import checkpoint
    from apex_tpu.transformer import parallel_state

    spec = importlib.util.spec_from_file_location(
        "imagenet_amp", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "examples", "imagenet_amp.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    ckdir = str(tmp_path / "ck")
    base = ["--depth", "18", "--batch-size", "1", "--image-size", "32",
            "--steps-per-epoch", "2", "--eval-steps", "1",
            "--num-classes", "10", "--checkpoint-dir", ckdir]
    try:
        out1 = mod.main(base + ["--epochs", "1"])
    finally:
        parallel_state.destroy_model_parallel()

    def assert_tree_equal(a, b, what):
        # tree_map fails loudly on structure mismatch (zip would
        # silently truncate)
        jax.tree.map(
            lambda x, y: np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=what),
            a, b,
        )

    # the epoch-0 checkpoint holds exactly the state main() returned
    saved = checkpoint.restore_step(ckdir, step=0)
    for key in ("params", "opt_state", "bn_stats"):
        assert_tree_equal(saved[key], out1[key], key)
    assert int(saved["epoch"]) == 0
    assert float(saved["best_prec1"]) == out1["best_prec1"]

    # --resume with epochs=1 restores and immediately returns: the
    # returned state must be the checkpoint, bitwise
    try:
        out2 = mod.main(base + ["--epochs", "1", "--resume"])
    finally:
        parallel_state.destroy_model_parallel()
    assert_tree_equal(out1["params"], out2["params"], "resume params")
    assert out2["best_prec1"] == out1["best_prec1"]

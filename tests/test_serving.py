"""Serving stack: allocator invariants, cache writes, fused sampling,
and continuous batching end-to-end on the tiny GPT.

The load-bearing claims, each pinned here:

- the page allocator never double-books, reuses freed pages, and
  reserves page 0 (unallocated table entries must stay addressable);
  shared pages survive until their LAST holder frees them (refcounts);
- cache writes round-trip (fp exactly, int8 within the block-scale
  band) and idle writes land on the null page; a copy-on-write tail
  page is bitwise-isolated from its source;
- greedy sampling is BIT-identical to argmax (the dryrun's
  generation-parity gate rests on this);
- the continuous-batching driver sustains admit/retire across >= 3
  request generations with ragged (EOS) finishes, produces
  per-request output identical to the single-request reference, and
  NEVER recompiles the decode step (compile-counting spy);
- chunked prefill is token-identical to the monolithic path under
  slot churn, a prefix-cache hit's logits are BIT-identical to a cold
  admission, chunk counts / hit patterns add zero jit entries, and a
  seeded request's sampled stream is reproducible regardless of
  admission order or slot assignment;
- cancel(uid) frees the slot without recording a Completion (the uid
  re-serves from scratch) and the load gauges the fleet router scores
  by reach the metrics jsonl.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.serving.kv_cache import (
    CacheOutOfPages,
    KVCacheConfig,
    PageAllocator,
    PagedKVCache,
    copy_pages,
    init_pools,
    write_targets,
    write_tokens,
)
from apex_tpu.serving.sampling import greedy, sample


class TestPageAllocator:
    def test_page_zero_reserved(self):
        a = PageAllocator(8)
        assert a.num_free == 7
        got = a.alloc(7)
        assert 0 not in got
        assert sorted(got) == list(range(1, 8))

    def test_alloc_is_all_or_nothing(self):
        a = PageAllocator(8)
        a.alloc(5)
        before = a.num_free
        with pytest.raises(CacheOutOfPages):
            a.alloc(3)
        assert a.num_free == before        # failed alloc leaked nothing

    def test_reuse_after_free(self):
        a = PageAllocator(4)
        p1 = a.alloc(3)
        a.free(p1)
        p2 = a.alloc(3)
        assert sorted(p1) == sorted(p2)    # the pool is fully reusable

    def test_lifo_reuse(self):
        a = PageAllocator(16)
        pages = a.alloc(4)
        a.free(pages)
        assert a.alloc(1) == [pages[-1]]   # hottest page comes back first

    def test_double_free_rejected(self):
        a = PageAllocator(4)
        p = a.alloc(1)
        a.free(p)
        with pytest.raises(ValueError, match="not allocated"):
            a.free(p)
        with pytest.raises(ValueError, match="null page"):
            a.free([0])

    def test_share_keeps_page_allocated_until_last_free(self):
        a = PageAllocator(4)
        p = a.alloc(1)
        a.share(p)                          # rc 2
        a.free(p)                           # rc 1: still allocated
        assert a.refcount(p[0]) == 1
        assert a.num_free == 2              # not back on the free list
        a.free(p)                           # rc 0: now free
        assert a.refcount(p[0]) == 0
        assert a.num_free == 3

    def test_double_share_needs_double_free(self):
        a = PageAllocator(4)
        p = a.alloc(1)
        a.share(p)
        a.share(p)                          # rc 3
        for want in (2, 1):
            a.free(p)
            assert a.refcount(p[0]) == want
        a.free(p)
        with pytest.raises(ValueError, match="not allocated"):
            a.free(p)                       # the classic double free

    def test_share_unallocated_or_freed_rejected(self):
        a = PageAllocator(4)
        with pytest.raises(ValueError, match="cannot share"):
            a.share([1])
        p = a.alloc(1)
        a.free(p)
        with pytest.raises(ValueError, match="cannot share"):
            a.share(p)

    def test_free_while_shared_preserves_other_holder(self):
        """Slot A retires while slot B still reads the shared page: the
        page must stay allocated and B's later free releases it."""
        a = PageAllocator(8)
        shared = a.alloc(2)
        a.share(shared)                     # B's reference
        a.free(shared)                      # A retires
        assert all(a.refcount(p) == 1 for p in shared)
        got = a.alloc(5)                    # the pool can't hand them out
        assert not (set(got) & set(shared))
        a.free(shared)                      # B retires
        assert a.num_free == 2

    def test_fragmentation_interleave_conserves_pool(self):
        """Interleaved alloc/free of ragged sizes: the free count is
        always pool-1 minus live pages and nothing is ever lost —
        paging has no external fragmentation by construction."""
        a = PageAllocator(32)
        live = []
        rng = np.random.RandomState(0)
        for step in range(50):
            if live and (rng.rand() < 0.5 or a.num_free < 5):
                a.free(live.pop(rng.randint(len(live))))
            else:
                live.append(a.alloc(int(rng.randint(1, 5))))
            n_live = sum(len(p) for p in live)
            assert a.num_free == 31 - n_live, step
        for p in live:
            a.free(p)
        assert a.num_free == 31


class TestPagedKVCache:
    def cfg(self, **kw):
        base = dict(num_layers=1, num_heads=2, head_dim=8,
                    num_pages=16, page_size=4, max_seqs=3,
                    pages_per_seq=4, dtype=jnp.float32)
        base.update(kw)
        return KVCacheConfig(**base)

    def test_admit_allocates_exactly_and_retire_returns(self):
        c = PagedKVCache(self.cfg())
        c.admit(0, 9)                       # ceil(9/4) = 3 pages
        assert c.allocator.num_free == 15 - 3
        row = c.page_table[0]
        assert (row[:3] > 0).all() and (row[3:] == 0).all()
        c.retire(0)
        assert c.allocator.num_free == 15
        assert (c.page_table[0] == 0).all()

    def test_double_admit_and_overlength_rejected(self):
        c = PagedKVCache(self.cfg())
        c.admit(1, 4)
        with pytest.raises(ValueError, match="already admitted"):
            c.admit(1, 4)
        with pytest.raises(ValueError, match="exceeds the slot bound"):
            c.admit(2, 17)                  # > 4*4

    def test_backpressure_has_no_side_effects(self):
        c = PagedKVCache(self.cfg(num_pages=6))
        c.admit(0, 16)                      # 4 of 5 free pages
        before = (c.allocator.num_free, c.page_table.copy())
        with pytest.raises(CacheOutOfPages):
            c.admit(1, 9)
        assert c.allocator.num_free == before[0]
        assert (c.page_table == before[1]).all()

    def test_config_validation(self):
        with pytest.raises(ValueError, match="null page"):
            self.cfg(num_pages=1)
        with pytest.raises(ValueError, match="int8"):
            self.cfg(kv_dtype=jnp.float16)
        assert self.cfg(kv_dtype=jnp.int8).quantized


class TestPrefixIndex:
    def cfg(self, **kw):
        base = dict(num_layers=1, num_heads=2, head_dim=8,
                    num_pages=32, page_size=4, max_seqs=4,
                    pages_per_seq=6, dtype=jnp.float32)
        base.update(kw)
        return KVCacheConfig(**base)

    def test_cold_admission_matches_nothing(self):
        c = PagedKVCache(self.cfg())
        res = c.admit(0, 12, prompt_tokens=[1, 2, 3, 4, 5, 6, 7, 8])
        assert res.matched_tokens == 0 and res.shared_pages == 0
        assert res.copied_page is None

    def test_register_then_hit_shares_full_pages(self):
        c = PagedKVCache(self.cfg())
        prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]     # 2 full pages + 2
        c.admit(0, 14, prompt_tokens=prompt)
        assert c.register_prefix(0, prompt) == 2
        pages0 = list(c.page_table[0][:2])
        res = c.admit(1, 14, prompt_tokens=prompt)
        assert res.matched_tokens == 8 and res.shared_pages == 2
        assert res.copied_page is None
        assert list(c.page_table[1][:2]) == pages0    # same phys pages
        # shared pages survive BOTH retirements (the index holds them)
        c.retire(0)
        c.retire(1)
        assert all(c.allocator.refcount(p) == 1 for p in pages0)
        # ... and a later admission still hits
        res = c.admit(2, 14, prompt_tokens=prompt)
        assert res.matched_tokens == 8

    def test_last_token_never_matched_cow_instead(self):
        """A whole-prompt full-page match caps at plen - 1: the last
        page is COPIED (its final token must be recomputed for
        logits), the rest shared."""
        c = PagedKVCache(self.cfg())
        prompt = [5, 6, 7, 8, 1, 2, 3, 4]            # exactly 2 pages
        c.admit(0, 12, prompt_tokens=prompt)
        c.register_prefix(0, prompt)
        res = c.admit(1, 12, prompt_tokens=prompt)
        assert res.matched_tokens == 7               # plen - 1
        assert res.shared_pages == 1
        src, dst = res.copied_page
        assert src == c.page_table[0][1]
        assert dst == c.page_table[1][1]
        assert src != dst

    def test_prefix_of_registered_prompt_hits(self):
        c = PagedKVCache(self.cfg())
        long = list(range(1, 17))                    # 4 full pages
        c.admit(0, 20, prompt_tokens=long)
        c.register_prefix(0, long)
        res = c.admit(1, 14, prompt_tokens=long[:10])
        assert res.matched_tokens == 8 and res.shared_pages == 2

    def test_divergent_prompt_stops_at_divergence(self):
        c = PagedKVCache(self.cfg())
        a = [1, 2, 3, 4, 5, 6, 7, 8]
        c.admit(0, 12, prompt_tokens=a)
        c.register_prefix(0, a)
        b = [1, 2, 3, 4, 9, 9, 9, 9, 1, 1]           # page 1 differs
        res = c.admit(1, 14, prompt_tokens=b)
        assert res.matched_tokens == 4 and res.shared_pages == 1

    def test_eviction_is_refcount_gc(self):
        """When an admission runs short, index-only pages are evicted
        leaf-first; pages a live slot still shares are untouchable."""
        c = PagedKVCache(self.cfg(num_pages=8, pages_per_seq=7))  # 7 free
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        c.admit(0, 8, prompt_tokens=prompt)           # 2 pages
        c.register_prefix(0, prompt)
        c.retire(0)                                   # index-held only
        assert c.prefix_index_size == 2
        assert c.allocator.num_free == 5
        # needs 7 pages -> evicts both cached pages
        c.admit(1, 25)
        assert c.prefix_index_size == 0
        c.retire(1)
        # now pin the pages with a LIVE sharer: eviction cannot free
        c.admit(0, 8, prompt_tokens=prompt)
        c.register_prefix(0, prompt)
        with pytest.raises(CacheOutOfPages):
            c.admit(1, 25)                            # 2 live + 2... short
        assert c.prefix_index_size == 2               # nothing evicted

    def test_failed_hit_admission_unshares(self):
        c = PagedKVCache(self.cfg(num_pages=6, pages_per_seq=6))
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        c.admit(0, 8, prompt_tokens=prompt)
        c.register_prefix(0, prompt)
        rc_before = [c.allocator.refcount(p) for p in c.page_table[0][:2]]
        with pytest.raises(CacheOutOfPages):
            # matches 2 pages but the 4 fresh pages don't fit (3 free)
            c.admit(1, 24, prompt_tokens=prompt + [9, 9])
        assert [c.allocator.refcount(p)
                for p in c.page_table[0][:2]] == rc_before

    def test_cow_source_protected_from_eviction_and_reuse(self):
        """The CoW source is referenced by the admitting slot until it
        retires: eviction pressure can neither free it (backpressure
        instead) nor re-issue it as one of the same admission's fresh
        pages (which would alias the pending device copy)."""
        # success case: enough room — the source must not alias fresh
        c = PagedKVCache(self.cfg(num_pages=5, pages_per_seq=3))
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        c.admit(0, 8, prompt_tokens=prompt)
        c.register_prefix(0, prompt)
        c.retire(0)
        res = c.admit(1, 12, prompt_tokens=prompt)
        src, dst = res.copied_page
        assert src not in list(c.page_table[1])
        assert c.allocator.refcount(src) == 2    # index + slot's ref
        c.retire(1)
        assert c.allocator.refcount(src) == 1    # index only again
        # pressure case: the only evictable candidate IS the source —
        # the admission must backpressure, not corrupt
        c2 = PagedKVCache(self.cfg(num_pages=4, pages_per_seq=3))
        c2.admit(0, 8, prompt_tokens=prompt)
        c2.register_prefix(0, prompt)
        c2.retire(0)
        rc_before = {p: c2.allocator.refcount(p)
                     for e in c2._prefix.values() for p in [e["page"]]}
        with pytest.raises(CacheOutOfPages):
            c2.admit(1, 12, prompt_tokens=prompt)
        assert c2.prefix_index_size == 2         # nothing evicted
        for p, rc in rc_before.items():
            assert c2.allocator.refcount(p) == rc

    def test_cow_tail_isolation_bitwise(self):
        """Writes into the CoW destination page never leak into the
        shared source page (and the copy itself is bit-exact)."""
        cfg = self.cfg()
        pools = init_pools(cfg)
        rng = jax.random.PRNGKey(3)
        k_new = jax.random.normal(rng, (4, 2, 8))
        layer0 = jax.tree.map(lambda x: x[0], pools)
        layer0 = write_tokens(
            layer0, k_new, k_new, jnp.full((4,), 5, jnp.int32),
            jnp.arange(4, dtype=jnp.int32))
        pools = jax.tree.map(lambda full, l0: full.at[0].set(l0),
                             pools, layer0)
        copied = jax.jit(copy_pages)(
            pools, jnp.asarray([5], jnp.int32),
            jnp.asarray([7], jnp.int32))
        np.testing.assert_array_equal(
            np.asarray(copied["k"][0, 7]), np.asarray(pools["k"][0, 5]))
        # overwrite one token in the copy; the source must not move
        src_before = np.asarray(copied["k"][0, 5]).copy()
        l0 = jax.tree.map(lambda x: x[0], copied)
        l0 = write_tokens(
            l0, k_new[:1] * 100.0, k_new[:1] * 100.0,
            jnp.asarray([7], jnp.int32), jnp.asarray([3], jnp.int32))
        np.testing.assert_array_equal(np.asarray(l0["k"][5]),
                                      src_before)
        assert not np.array_equal(np.asarray(l0["k"][7]),
                                  np.asarray(copied["k"][0, 7]))


class TestWrites:
    def test_fp_write_round_trip(self):
        cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=8,
                            num_pages=8, page_size=4, max_seqs=1,
                            pages_per_seq=3, dtype=jnp.float32)
        pools = jax.tree.map(lambda x: x[0], init_pools(cfg))  # layer 0
        row = jnp.array([5, 2, 7], jnp.int32)
        n = 10                                     # partial last page
        k_new = jax.random.normal(jax.random.PRNGKey(0), (n, 2, 8))
        v_new = jax.random.normal(jax.random.PRNGKey(1), (n, 2, 8))
        pos = jnp.arange(n, dtype=jnp.int32)
        wp, wo = write_targets(row, pos, pos < n, cfg.page_size)
        pools = write_tokens(pools, k_new, v_new, wp, wo)
        # read back through the page table
        got = jnp.moveaxis(pools["k"][row], 2, 1).reshape(-1, 2, 8)[:n]
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(k_new))

    def test_int8_write_round_trip_band(self):
        from apex_tpu.ops.quantization import dequantize_rows

        cfg = KVCacheConfig(num_layers=1, num_heads=2, head_dim=16,
                            num_pages=8, page_size=4, max_seqs=1,
                            pages_per_seq=2, dtype=jnp.float32,
                            kv_dtype=jnp.int8, kv_block=8)
        pools = jax.tree.map(lambda x: x[0], init_pools(cfg))
        row = jnp.array([3, 1], jnp.int32)
        n = 6
        k_new = jax.random.normal(jax.random.PRNGKey(2), (n, 2, 16))
        pos = jnp.arange(n, dtype=jnp.int32)
        wp, wo = write_targets(row, pos, pos < n, cfg.page_size)
        pools = write_tokens(pools, k_new, k_new, wp, wo,
                             quantized=True, kv_block=8)
        vals = jnp.moveaxis(pools["k"][row], 2, 1).reshape(-1, 2, 16)[:n]
        scales = jnp.moveaxis(
            pools["k_scales"][row], 2, 1).reshape(-1, 2, 2)[:n]
        deq = dequantize_rows(vals.reshape(n * 2, 16).astype(jnp.float32),
                              scales.reshape(n * 2, 2), 8)
        err = np.max(np.abs(np.asarray(deq).reshape(n, 2, 16)
                            - np.asarray(k_new)))
        # per-block amax/127 rounding bound for unit-normal data
        assert err < 4.0 / 127.0, err

    def test_invalid_positions_hit_null_page(self):
        row = jnp.array([5, 6], jnp.int32)
        pos = jnp.arange(8, dtype=jnp.int32)
        wp, wo = write_targets(row, pos, pos < 3, page_size=4)
        assert (np.asarray(wp)[3:] == 0).all()
        assert (np.asarray(wo)[3:] == 0).all()
        assert (np.asarray(wp)[:3] == 5).all()


class TestSampling:
    def test_greedy_is_argmax_bitwise(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (7, 33))
        np.testing.assert_array_equal(
            np.asarray(greedy(logits)),
            np.asarray(jnp.argmax(logits, axis=-1).astype(jnp.int32)))
        # temperature=0 routes THROUGH greedy: same bits, key ignored
        np.testing.assert_array_equal(
            np.asarray(sample(logits, None, temperature=0.0)),
            np.asarray(greedy(logits)))

    def test_temperature_needs_key(self):
        with pytest.raises(ValueError, match="PRNG key"):
            sample(jnp.zeros((1, 4)), None, temperature=1.0)

    def test_top_k_restricts_support(self):
        logits = jnp.array([[3.0, 2.9, 2.8, -1.0, -2.0, -3.0]])
        top3 = {0, 1, 2}
        for i in range(40):
            t = int(sample(logits, jax.random.PRNGKey(i),
                           temperature=1.0, top_k=3)[0])
            assert t in top3, (i, t)

    def test_top_p_keeps_nucleus_only(self):
        # one token holds ~0.95 mass: any top_p <= 0.9 is greedy
        logits = jnp.array([[8.0, 2.0, 1.0, 0.0]])
        for i in range(20):
            t = int(sample(logits, jax.random.PRNGKey(i),
                           temperature=1.0, top_p=0.9)[0])
            assert t == 0, (i, t)
        # top_p=1.0 leaves the support alone — other tokens reachable
        seen = {int(sample(logits * 0.0, jax.random.PRNGKey(i),
                           temperature=1.0, top_p=1.0)[0])
                for i in range(60)}
        assert len(seen) > 1

    def test_validation(self):
        with pytest.raises(ValueError, match="temperature"):
            sample(jnp.zeros((1, 4)), temperature=-1.0)
        with pytest.raises(ValueError, match="top_k"):
            sample(jnp.zeros((1, 4)), jax.random.PRNGKey(0),
                   temperature=1.0, top_k=0)
        with pytest.raises(ValueError, match="top_p"):
            sample(jnp.zeros((1, 4)), jax.random.PRNGKey(0),
                   temperature=1.0, top_p=0.0)


# ---------------------------------------------------------------------------
# Continuous batching end-to-end (tiny GPT)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gpt_setup():
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:1])
    yield from _gpt_setup_body(mesh)
    # leave global parallel state the way later test modules expect it
    parallel_state.destroy_model_parallel()


def _gpt_setup_body(mesh):
    from apex_tpu.models import GPTConfig, GPTModel
    model = GPTModel(GPTConfig(
        vocab_size=64, num_layers=2, hidden_size=32,
        num_attention_heads=4, max_position_embeddings=64,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(3)
    prompts = rng.randint(1, 64, (6, 10)).astype(np.int32)
    plens = np.array([10, 8, 6, 4, 9, 5], np.int32)
    for i in range(6):
        prompts[i, plens[i]:] = 0
    new = 12
    ref = model.generate_reference(params, prompts, plens, new,
                                   mesh=mesh)
    yield mesh, model, params, prompts, plens, new, ref


from apex_tpu.serving.serve import ContinuousBatcher, Request  # noqa: E402


def _serve(gpt_setup, n_req, max_seqs, harvest_every, eos_id=None,
           logger=None, kv_dtype=None):
    mesh, model, params, prompts, plens, new, ref = gpt_setup
    page = 4
    pps = -(-(10 + new) // page)
    ccfg = KVCacheConfig(
        num_layers=2, num_heads=4, head_dim=8,
        num_pages=1 + max_seqs * pps, page_size=page,
        max_seqs=max_seqs, pages_per_seq=pps, dtype=jnp.float32,
        kv_dtype=kv_dtype, kv_block=8)
    fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=10,
                           eos_id=eos_id)
    batcher = ContinuousBatcher(
        fns.prefill, fns.decode, PagedKVCache(ccfg), init_pools(ccfg),
        max_prompt_len=10, harvest_every=harvest_every, eos_id=eos_id,
        logger=logger)
    reqs = [
        Request(uid=i,
                prompt=[int(t) for t in prompts[i, : plens[i]]],
                max_new_tokens=new)
        for i in range(n_req)
    ]
    return batcher, fns, batcher.run(reqs)


class TestContinuousBatching:
    def test_three_generations_ragged_finishes_no_recompile(
            self, gpt_setup):
        """6 requests through 2 slots = 3 admit/retire generations; an
        eos_id chosen to finish some requests mid-window makes the
        finish steps ragged; every completion must match the
        single-request reference and the decode step must not
        recompile after the first generation."""
        mesh, model, params, prompts, plens, new, ref = gpt_setup
        # pick an eos that actually appears mid-generation for SOME
        # requests (and not at all for others) — ragged by construction
        flat = [t for i in range(6) for t in map(int, ref[i])]
        eos = max(set(flat), key=flat.count)
        batcher, fns, comps = _serve(
            gpt_setup, n_req=6, max_seqs=2, harvest_every=3,
            eos_id=eos)
        assert len(comps) == 6
        reasons = {c.reason for c in comps.values()}
        finishes = {len(c.tokens) for c in comps.values()}
        assert "eos" in reasons                      # some finished early
        assert len(finishes) > 1                     # ... raggedly
        for i in range(6):
            want = list(map(int, ref[i]))
            if eos in want:
                want = want[: want.index(eos) + 1]
                assert comps[i].reason == "eos"
            else:
                assert comps[i].reason == "budget"
            assert comps[i].tokens == want, i
        # compile-count spy: generations 2 and 3 added ZERO entries
        # beyond generation 1's (the one-time uncommitted-vs-resident
        # pair); run a FOURTH generation to be sure
        from apex_tpu.serving.serve import Request

        size = fns.decode_jit._cache_size()
        assert size <= 2, size
        again = batcher.run([
            Request(uid="again", prompt=[1, 2, 3], max_new_tokens=4)
        ])
        assert len(again["again"].tokens) <= 4
        assert fns.decode_jit._cache_size() == size
        assert fns.prefill_jit._cache_size() <= 2

    def test_matches_reference_exactly_all_budget(self, gpt_setup):
        mesh, model, params, prompts, plens, new, ref = gpt_setup
        _, _, comps = _serve(gpt_setup, n_req=4, max_seqs=4,
                             harvest_every=5)
        for i in range(4):
            assert comps[i].tokens == list(map(int, ref[i])), i

    def test_int8_kv_generates_full_budget(self, gpt_setup):
        _, _, comps = _serve(gpt_setup, n_req=2, max_seqs=2,
                             harvest_every=4, kv_dtype=jnp.int8)
        for i in range(2):
            assert len(comps[i].tokens) == 12
            assert comps[i].reason == "budget"

    def test_backpressure_serializes_then_completes(self, gpt_setup):
        """A pool with room for ONE sequence still serves 3 requests —
        admissions wait for pages instead of failing."""
        from apex_tpu.serving.serve import ContinuousBatcher, Request

        mesh, model, params, prompts, plens, new, ref = gpt_setup
        page = 4
        pps = -(-(10 + new) // page)
        ccfg = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8,
            num_pages=1 + pps, page_size=page, max_seqs=2,
            pages_per_seq=pps, dtype=jnp.float32)
        fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=10)
        batcher = ContinuousBatcher(
            fns.prefill, fns.decode, PagedKVCache(ccfg),
            init_pools(ccfg), max_prompt_len=10, harvest_every=4)
        comps = batcher.run([
            Request(uid=i, prompt=[int(t) for t in
                                   prompts[i, : plens[i]]],
                    max_new_tokens=new)
            for i in range(3)
        ])
        for i in range(3):
            assert comps[i].tokens == list(map(int, ref[i])), i

    def test_impossible_request_raises_not_hangs(self, gpt_setup):
        from apex_tpu.serving.serve import ContinuousBatcher, Request

        mesh, model, params, prompts, plens, new, ref = gpt_setup
        ccfg = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8,
            num_pages=2, page_size=4, max_seqs=1,
            pages_per_seq=6, dtype=jnp.float32)
        fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=10)
        batcher = ContinuousBatcher(
            fns.prefill, fns.decode, PagedKVCache(ccfg),
            init_pools(ccfg), max_prompt_len=10)
        with pytest.raises(CacheOutOfPages, match="no slot"):
            batcher.run([Request(uid=0, prompt=[1, 2, 3, 4, 5],
                                 max_new_tokens=8)])

    def test_serving_telemetry_reaches_metrics_report(
            self, gpt_setup, tmp_path):
        from apex_tpu.telemetry.metrics import MetricsLogger

        jsonl = str(tmp_path / "serve.jsonl")
        logger = MetricsLogger(jsonl_path=jsonl, console=False)
        _, _, comps = _serve(gpt_setup, n_req=3, max_seqs=2,
                             harvest_every=4, logger=logger)
        logger.close()

        import tools.metrics_report as mr

        records = mr.load_records(jsonl)
        summary = mr.summarize(records)
        sv = summary["serving"]
        assert sv["requests"]["completed"] == 3
        assert sv["requests"]["by_reason"] == {"budget": 3}
        assert sv["prefill_spans"] == 3
        assert sv["decode_windows"], sv
        assert "decode_tokens_per_sec" in sv
        assert "inter_token_latency_ms" in sv
        assert set(sv["inter_token_latency_ms"]) >= {"p50", "p90",
                                                     "p99"}
        assert "ttft_s" in sv and sv["ttft_s"]["p50"] >= 0
        # the formatted report renders the section without crashing
        text = mr.format_report(summary)
        assert "serving summary" in text
        assert "time-to-first-token" in text

    def test_cancel_releases_slot_and_uid_is_reservable(
            self, gpt_setup, tmp_path):
        """cancel(uid) mid-flight: returns the HARVESTED prefix of the
        stream (a prefix of the reference — harvest is the commit
        point), frees the slot for new admissions, records no
        Completion (the uid can be re-served from scratch), and emits
        a ``request_cancelled`` event."""
        import collections

        from apex_tpu.telemetry.metrics import MetricsLogger

        mesh, model, params, prompts, plens, new, ref = gpt_setup
        jsonl = str(tmp_path / "cancel.jsonl")
        logger = MetricsLogger(jsonl_path=jsonl, console=False)
        page = 4
        pps = -(-(10 + new) // page)
        ccfg = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8,
            num_pages=1 + 2 * pps, page_size=page, max_seqs=2,
            pages_per_seq=pps, dtype=jnp.float32)
        fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=10)
        b = ContinuousBatcher(
            fns.prefill, fns.decode, PagedKVCache(ccfg),
            init_pools(ccfg), max_prompt_len=10, harvest_every=2,
            logger=logger)
        reqs = [
            Request(uid=i,
                    prompt=[int(t) for t in prompts[i, : plens[i]]],
                    max_new_tokens=new)
            for i in range(2)
        ]
        q = collections.deque(reqs)
        b.pump(q)                       # admit both, one harvest window
        assert b.live_slots == 2
        free_before = b.cache.allocator.num_free
        got = b.cancel(0)
        want0 = list(map(int, ref[0]))
        assert got and got == want0[: len(got)]
        assert b.cancel("never-admitted") is None
        assert b.live_slots == 1
        assert b.cache.allocator.num_free > free_before
        assert 0 not in b.completions   # cancelled, not completed
        # the uid is free again: re-serve it from scratch to the full
        # reference while request 1 keeps decoding undisturbed
        q2 = collections.deque([reqs[0]])
        while b.pump(q2):
            pass
        assert b.completions[0].tokens == want0
        assert b.completions[1].tokens == list(map(int, ref[1]))
        logger.close()

        import tools.metrics_report as mr

        cancels = [r for r in mr.load_records(jsonl)
                   if r.get("event") == "request_cancelled"]
        assert len(cancels) == 1
        assert cancels[0]["uid"] == 0
        assert cancels[0]["new_tokens"] == len(got)

    def test_load_gauges_reach_metrics_jsonl(self, gpt_setup,
                                             tmp_path):
        """The serving load gauges (pages_free / pages_shared /
        live_slots / queue_depth) — the quantities the fleet router
        scores replicas by — land in the jsonl meters stream and the
        report summary."""
        from apex_tpu.telemetry.metrics import MetricsLogger

        jsonl = str(tmp_path / "gauges.jsonl")
        logger = MetricsLogger(jsonl_path=jsonl, console=False)
        _serve(gpt_setup, n_req=3, max_seqs=2, harvest_every=4,
               logger=logger)
        logger.close()

        import tools.metrics_report as mr

        summary = mr.summarize(mr.load_records(jsonl))
        gauges = summary["meters"]["gauges"]
        assert {"pages_free", "pages_shared", "live_slots",
                "queue_depth"} <= set(gauges)
        assert all(v >= 0 for v in gauges.values())

    def test_request_validation(self):
        from apex_tpu.serving.serve import Request

        with pytest.raises(ValueError, match="max_new_tokens"):
            Request(uid=0, prompt=[1], max_new_tokens=0)
        with pytest.raises(ValueError, match="prompt"):
            Request(uid=0, prompt=[], max_new_tokens=1)


def _chunked_setup(gpt_setup, chunk, *, prefix=False, temperature=0.0,
                   slots=2, logger=None, new=12):
    """decode_fns + batcher wired for chunked prefill on the tiny GPT."""
    mesh, model, params, prompts, plens, _new, ref = gpt_setup
    page = 4
    pps = -(-(10 + new) // page)
    ccfg = KVCacheConfig(
        num_layers=2, num_heads=4, head_dim=8,
        num_pages=1 + (slots + 4) * pps, page_size=page,
        max_seqs=slots, pages_per_seq=pps, dtype=jnp.float32)
    fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=10,
                           temperature=temperature,
                           top_k=(20 if temperature else None),
                           prefill_chunk=chunk)
    batcher = ContinuousBatcher(
        fns.prefill, fns.decode, PagedKVCache(ccfg), init_pools(ccfg),
        max_prompt_len=10, harvest_every=3, chunk_fn=fns.chunk,
        prefill_chunk=chunk, prefix_cache=prefix, logger=logger)
    return fns, batcher


class TestChunkedPrefillServing:
    def test_chunked_matches_monolithic_and_reference_under_churn(
            self, gpt_setup):
        """6 requests through 2 slots, varying prompt lengths (1 to 3
        chunks each): the chunked scheduler's greedy output must equal
        BOTH the monolithic path's and the full-recompute reference,
        with and without the prefix cache."""
        mesh, model, params, prompts, plens, new, ref = gpt_setup
        for prefix in (False, True):
            fns, batcher = _chunked_setup(gpt_setup, chunk=4,
                                          prefix=prefix)
            comps = batcher.run([
                Request(uid=i,
                        prompt=[int(t) for t in prompts[i, : plens[i]]],
                        max_new_tokens=new)
                for i in range(6)
            ])
            for i in range(6):
                assert comps[i].tokens == list(map(int, ref[i])), \
                    (prefix, i)

    def test_prefix_hit_logits_bit_identical_to_cold(self, gpt_setup):
        """Same prompt admitted cold, then as a hit (and twice more
        through the copy-on-write whole-prompt-match path): the
        last-prompt-token logits must agree BITWISE — shared pages
        hold the same bits a cold prefill would write."""
        mesh, model, params, prompts, plens, new, ref = gpt_setup
        fns, batcher = _chunked_setup(gpt_setup, chunk=4, prefix=True)
        prompt = [int(t) for t in prompts[0, :10]]

        def logits_of(uid, pr):
            batcher.run([Request(uid=uid, prompt=pr,
                                 max_new_tokens=new)])
            return np.asarray(
                jax.device_get(batcher.last_prefill_logits))

        cold = logits_of("cold", prompt)
        hit = logits_of("hit", prompt)
        np.testing.assert_array_equal(cold, hit)
        assert batcher.prefix_stats["hits"] == 1
        assert batcher.prefix_stats["shared_pages"] == 2
        # whole-prompt full-page match -> CoW tail; the cold baseline
        # comes from a FRESH batcher — on the shared one prompt[:8]
        # already prefix-matches, so both sides would take the CoW
        # path and a deterministic copy bug could hide
        fns2, fresh = _chunked_setup(gpt_setup, chunk=4, prefix=True)
        fresh.run([Request(uid="cc", prompt=prompt[:8],
                           max_new_tokens=new)])
        assert fresh.prefix_stats["hits"] == 0       # genuinely cold
        cow_cold = np.asarray(
            jax.device_get(fresh.last_prefill_logits))
        cow_hit = logits_of("ch", prompt[:8])
        np.testing.assert_array_equal(cow_cold, cow_hit)
        assert batcher.prefix_stats["copied_pages"] >= 1
        assert (fresh.completions["cc"].tokens
                == batcher.completions["ch"].tokens)

    def test_zero_new_jit_entries_across_chunk_counts_and_hits(
            self, gpt_setup):
        """The compile-count spy for the chunk path: prompts of 1, 2
        and 3 chunks, cold and hit admissions, a CoW admission — all
        reuse the same compiled chunk/decode steps."""
        mesh, model, params, prompts, plens, new, ref = gpt_setup
        fns, batcher = _chunked_setup(gpt_setup, chunk=4, prefix=True)
        p0 = [int(t) for t in prompts[0, :10]]
        batcher.run([Request(uid=0, prompt=p0, max_new_tokens=new)])
        chunk_size = int(fns.chunk_jit._cache_size())
        decode_size = int(fns.decode_jit._cache_size())
        assert chunk_size <= 2, chunk_size
        batcher.run([
            Request(uid=1, prompt=p0[:3], max_new_tokens=4),   # 1 chunk
            Request(uid=2, prompt=p0[:7], max_new_tokens=4),   # 2 chunks
            Request(uid=3, prompt=p0, max_new_tokens=new),     # full hit
            Request(uid=4, prompt=p0[:8], max_new_tokens=4),   # CoW hit
        ])
        assert int(fns.chunk_jit._cache_size()) == chunk_size
        assert int(fns.decode_jit._cache_size()) == decode_size
        assert batcher.prefix_stats["hits"] >= 2

    def test_seeded_requests_reproducible_across_order_and_slots(
            self, gpt_setup):
        """A seeded request samples the same stream no matter the
        admission order, slot assignment, scheduler mode or server
        key (test-pinned satellite contract)."""
        mesh, model, params, prompts, plens, new, ref = gpt_setup

        def serve(order, chunk, server_seed):
            if chunk is None:
                page = 4
                pps = -(-(10 + new) // page)
                ccfg = KVCacheConfig(
                    num_layers=2, num_heads=4, head_dim=8,
                    num_pages=1 + 2 * pps, page_size=page, max_seqs=2,
                    pages_per_seq=pps, dtype=jnp.float32)
                fns = model.decode_fns(
                    params, mesh, ccfg, max_prompt_len=10,
                    temperature=0.7, top_k=20)
                batcher = ContinuousBatcher(
                    fns.prefill, fns.decode, PagedKVCache(ccfg),
                    init_pools(ccfg), max_prompt_len=10,
                    harvest_every=3,
                    key=jax.random.PRNGKey(server_seed))
            else:
                fns, batcher = _chunked_setup(
                    gpt_setup, chunk=chunk, temperature=0.7)
            reqs = [Request(uid=i,
                            prompt=[int(t) for t in
                                    prompts[i, : plens[i]]],
                            max_new_tokens=new, seed=100 + i)
                    for i in order]
            return batcher.run(reqs)

        a = serve([0, 1, 2], None, 0)
        b = serve([2, 1, 0], None, 7)       # order + server key moved
        c = serve([1, 2, 0], 4, 0)          # chunked scheduler
        for i in range(3):
            assert a[i].tokens == b[i].tokens, i
            assert a[i].tokens == c[i].tokens, i
        # and an unseeded request does NOT promise this
        assert len(a[0].tokens) > 0

    def test_chunked_telemetry_reaches_metrics_report(
            self, gpt_setup, tmp_path):
        from apex_tpu.telemetry.metrics import MetricsLogger

        mesh, model, params, prompts, plens, new, ref = gpt_setup
        jsonl = str(tmp_path / "chunked.jsonl")
        logger = MetricsLogger(jsonl_path=jsonl, console=False)
        fns, batcher = _chunked_setup(gpt_setup, chunk=4, prefix=True,
                                      logger=logger)
        p0 = [int(t) for t in prompts[0, :10]]
        # sequential: "b" admits after "a" registered the prefix (two
        # identical prompts admitted CONCURRENTLY both miss — the
        # first has not finished prefilling when the second matches)
        batcher.run([Request(uid="a", prompt=p0, max_new_tokens=new)])
        batcher.run([Request(uid="b", prompt=p0, max_new_tokens=new)])
        logger.close()

        import tools.metrics_report as mr

        summary = mr.summarize(mr.load_records(jsonl))
        sv = summary["serving"]
        assert sv["prefill_chunks"]["count"] == batcher.prefill_chunks
        px = sv["prefix_cache"]
        assert px["admissions"] == 2 and px["hits"] == 1
        assert px["hit_rate"] == 0.5
        assert px["pages_shared"] == 2
        assert px["prefill_tokens_skipped"] == 8
        text = mr.format_report(summary)
        assert "prefix cache" in text
        assert "chunk-granularity admission" in text

    def test_chunked_rope_model_matches_reference(self, gpt_setup):
        """The chunk step's rope rows come from the same cached table
        decode uses — a rotary (Llama-style) model must be chunk/
        monolithic/reference token-identical too."""
        from apex_tpu.models import GPTConfig, GPTModel

        mesh, *_ = gpt_setup
        model = GPTModel(GPTConfig(
            vocab_size=64, num_layers=2, hidden_size=32,
            num_attention_heads=4, max_position_embeddings=64,
            position_embedding="rope", normalization="rmsnorm",
            compute_dtype=jnp.float32, remat=False,
            attention_impl="xla"))
        params = model.init(jax.random.PRNGKey(2))
        rng = np.random.RandomState(9)
        prompts = rng.randint(1, 64, (3, 9)).astype(np.int32)
        plens = np.array([9, 6, 4], np.int32)
        for i in range(3):
            prompts[i, plens[i]:] = 0
        ref = model.generate_reference(params, prompts, plens, 8,
                                       mesh=mesh)
        got = model.generate(params, prompts, plens, 8, mesh=mesh,
                             page_size=4, max_seqs=2, harvest_every=3,
                             prefill_chunk=4, prefix_cache=True)
        for i in range(3):
            assert got[i] == list(map(int, ref[i])), i

    def test_batcher_validation(self, gpt_setup):
        mesh, model, params, prompts, plens, new, ref = gpt_setup
        page = 4
        pps = -(-(10 + new) // page)
        ccfg = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8,
            num_pages=1 + 2 * pps, page_size=page, max_seqs=2,
            pages_per_seq=pps, dtype=jnp.float32)
        fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=10,
                               prefill_chunk=4)
        kw = dict(cache=PagedKVCache(ccfg), pools=init_pools(ccfg))
        with pytest.raises(ValueError, match="BOTH chunk_fn"):
            ContinuousBatcher(fns.prefill, fns.decode, kw["cache"],
                              kw["pools"], max_prompt_len=10,
                              chunk_fn=fns.chunk)
        with pytest.raises(ValueError, match="prefill_chunk mismatch"):
            ContinuousBatcher(fns.prefill, fns.decode, kw["cache"],
                              kw["pools"], max_prompt_len=10,
                              chunk_fn=fns.chunk, prefill_chunk=8)
        with pytest.raises(ValueError, match="prefix_cache requires"):
            ContinuousBatcher(fns.prefill, fns.decode, kw["cache"],
                              kw["pools"], max_prompt_len=10,
                              prefix_cache=True)
        with pytest.raises(ValueError, match="prefill_chunk must be"):
            model.decode_fns(params, mesh, ccfg, max_prompt_len=10,
                             prefill_chunk=0)
        # past the kernel's per-program row budget: fail at build
        # time, not with a VMEM lowering error at serve time
        from apex_tpu.ops.attention_decode import FMHA_DECODE_MAX_ROWS

        with pytest.raises(ValueError, match="row budget"):
            model.decode_fns(params, mesh, ccfg, max_prompt_len=10,
                             prefill_chunk=FMHA_DECODE_MAX_ROWS + 1)

    def test_decode_fns_rejects_mismatched_cache(self, gpt_setup):
        mesh, model, params, *_ = gpt_setup
        bad = KVCacheConfig(num_layers=2, num_heads=8, head_dim=8,
                            num_pages=4, page_size=4, max_seqs=1,
                            pages_per_seq=2)
        with pytest.raises(ValueError, match="does not match"):
            model.decode_fns(params, mesh, bad, max_prompt_len=8)

    def test_decode_fns_rejects_learned_overflow(self, gpt_setup):
        mesh, model, params, *_ = gpt_setup
        big = KVCacheConfig(num_layers=2, num_heads=4, head_dim=8,
                            num_pages=64, page_size=32, max_seqs=1,
                            pages_per_seq=4)   # 128 > 64 positions
        with pytest.raises(ValueError, match="learned table"):
            model.decode_fns(params, mesh, big, max_prompt_len=8)

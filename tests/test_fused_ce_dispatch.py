"""Unit tests for the fused-CE auto-dispatch adopted after the r05
profile: ``fused_ce=None`` picks the two-step path below
``FUSED_CE_AUTO_BYTES`` of materialized logits and the fused
online-logsumexp scan above it (transformer/tensor_parallel/
cross_entropy.py), threaded through ``models/gpt.py``.
"""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import cross_entropy as ce


class TestAutoRule:
    def test_boundary_exact_bytes_takes_two_step(self, monkeypatch):
        # the rule is STRICTLY greater-than: logits of exactly the
        # threshold size stay on the faster two-step path
        monkeypatch.setattr(ce, "FUSED_CE_AUTO_BYTES", 4096)
        assert ce.fused_ce_auto(32, 32) is False      # 32*32*4 == 4096

    def test_boundary_one_element_over_takes_fused(self, monkeypatch):
        monkeypatch.setattr(ce, "FUSED_CE_AUTO_BYTES", 4096)
        assert ce.fused_ce_auto(32, 33) is True       # 4224 > 4096

    def test_flagship_residual_takes_two_step(self):
        # the r05-adopted decision at the flagship config: the 1.07 GB
        # (8192 tokens x 32768 vocab) fp32 residual sits under the
        # 2 GiB default and runs the measured-faster two-step path
        assert ce.fused_ce_auto(8192, 32768) is False

    def test_just_over_default_takes_fused(self):
        assert ce.fused_ce_auto(8192, (2 << 30) // (8192 * 4) + 1) is True

    def test_env_override_round_trip(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_FUSED_CE_BYTES", "1024")
        try:
            importlib.reload(ce)
            assert ce.FUSED_CE_AUTO_BYTES == 1024
            assert ce.fused_ce_auto(16, 16) is False  # 1024 == 1024
            assert ce.fused_ce_auto(16, 17) is True
        finally:
            monkeypatch.delenv("APEX_TPU_FUSED_CE_BYTES")
            importlib.reload(ce)
        assert ce.FUSED_CE_AUTO_BYTES == 2 << 30


class TestGPTDispatch:
    """``GPTConfig(fused_ce=None)`` must route through the auto rule —
    spied at the two cross_entropy entry points the dispatcher picks
    between."""

    @pytest.fixture
    def mesh(self):
        m = parallel_state.initialize_model_parallel()
        yield m
        parallel_state.destroy_model_parallel()

    def _loss(self, mesh, model, calls, monkeypatch):
        fused_orig = ce.vocab_parallel_cross_entropy_from_hidden
        twostep_orig = ce.vocab_parallel_cross_entropy

        def spy_fused(*a, **kw):
            calls.append("fused")
            return fused_orig(*a, **kw)

        def spy_twostep(*a, **kw):
            calls.append("two_step")
            return twostep_orig(*a, **kw)

        monkeypatch.setattr(
            ce, "vocab_parallel_cross_entropy_from_hidden", spy_fused)
        monkeypatch.setattr(
            ce, "vocab_parallel_cross_entropy", spy_twostep)
        specs = model.param_specs()
        params = model.init(jax.random.PRNGKey(0))
        params = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P)))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
        fn = jax.jit(shard_map(
            model.loss, mesh=mesh,
            in_specs=(specs, P("dp"), P("dp")), out_specs=P(),
        ))
        return float(jax.device_get(
            fn(params, tokens, jnp.roll(tokens, -1, axis=1))))

    def _model(self, fused_ce=None):
        return GPTModel(GPTConfig(
            vocab_size=64, num_layers=1, hidden_size=32,
            num_attention_heads=2, max_position_embeddings=16,
            compute_dtype=jnp.float32, remat=False, attention_impl="xla",
            fused_ce=fused_ce,
        ))

    def test_auto_small_logits_two_step(self, mesh, monkeypatch):
        calls = []
        loss = self._loss(mesh, self._model(fused_ce=None), calls,
                          monkeypatch)
        # 32 tokens x 64 vocab sits far under the threshold
        assert "two_step" in calls and "fused" not in calls
        assert np.isfinite(loss)

    def test_auto_above_threshold_fused(self, mesh, monkeypatch):
        monkeypatch.setattr(ce, "FUSED_CE_AUTO_BYTES", 1)
        calls = []
        loss = self._loss(mesh, self._model(fused_ce=None), calls,
                          monkeypatch)
        assert "fused" in calls and "two_step" not in calls
        assert np.isfinite(loss)

    def test_forced_paths_ignore_threshold(self, mesh, monkeypatch):
        # fused_ce=True / False must win over any threshold setting
        monkeypatch.setattr(ce, "FUSED_CE_AUTO_BYTES", 1)
        calls = []
        self._loss(mesh, self._model(fused_ce=False), calls, monkeypatch)
        assert "two_step" in calls and "fused" not in calls
        monkeypatch.setattr(ce, "FUSED_CE_AUTO_BYTES", 2 << 30)
        calls = []
        self._loss(mesh, self._model(fused_ce=True), calls, monkeypatch)
        assert "fused" in calls and "two_step" not in calls

    def test_auto_matches_forced_numerics(self, mesh, monkeypatch):
        calls = []
        auto = self._loss(mesh, self._model(fused_ce=None), calls,
                          monkeypatch)
        forced = self._loss(mesh, self._model(fused_ce=False), calls,
                            monkeypatch)
        assert auto == pytest.approx(forced, rel=1e-6)

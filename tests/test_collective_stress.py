"""Adversarial collective + buffer-donation stress on the 8-device mesh.

The XLA-era analog of the reference's DDP race-condition test
(reference: tests/distributed/DDP/ddp_race_condition_test.py:37-60),
which hammers overlapping NCCL all-reduces against concurrent buffer
writes and asserts the result is still exact.  Under XLA there are no
streams to race, but the equivalent hazard class is real: buffer
DONATION aliases inputs to outputs, and a miscompiled collective
schedule reading a donated buffer after reuse would corrupt values
non-deterministically.  These tests drive donated carries through
psum / ppermute / psum_scatter / all_gather at deliberately irregular
(non-tile-aligned, mutually prime) sizes and mixed dtypes in a loop,
and assert bitwise run-to-run determinism plus exact analytic values.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

pytestmark = pytest.mark.slow

from apex_tpu.transformer import parallel_state


@pytest.fixture(scope="module")
def mesh():
    m = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2, context_parallel_size_=2
    )
    yield m
    parallel_state.destroy_model_parallel()


# irregular, mutually prime sizes: no tile alignment, forcing padded
# collective layouts where an aliasing bug would show
SHAPES = [(3, 5), (7,), (127, 3), (1, 13), (61,)]
DTYPES = [jnp.float32, jnp.bfloat16, jnp.float32, jnp.int32, jnp.float32]


def _carry():
    ks = jax.random.split(jax.random.PRNGKey(0), len(SHAPES))
    leaves = []
    for k, shape, dt in zip(ks, SHAPES, DTYPES):
        if jnp.issubdtype(dt, jnp.integer):
            leaves.append(jax.random.randint(k, shape, -100, 100, dt))
        else:
            leaves.append(jax.random.normal(k, shape).astype(dt))
    return leaves


def _stress_step(carry, seed):
    """One tick: every leaf rides a different collective pattern, all
    feeding back into the donated carry."""
    out = []
    for i, x in enumerate(carry):
        if i % 3 == 0:
            # ring shift over pp then mean over dp — ppermute writes
            # into a buffer the donated input may alias
            pp = jax.lax.axis_size("pp")
            perm = [(s, (s + 1) % pp) for s in range(pp)]
            x = jax.lax.ppermute(x, "pp", perm)
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = jax.lax.pmean(x, "dp")
        elif i % 3 == 1:
            x = jax.lax.psum(x, "cp") // jax.lax.axis_size("cp") \
                if jnp.issubdtype(x.dtype, jnp.integer) \
                else jax.lax.psum(x, "cp") / jax.lax.axis_size("cp")
        else:
            # scatter+gather round trip at a non-divisible size: pad to
            # the axis size, scatter, gather, slice back
            n = jax.lax.axis_size("dp")
            flat = x.reshape(-1).astype(jnp.float32)
            pad = (-flat.shape[0]) % n
            padded = jnp.pad(flat, (0, pad))
            scat = jax.lax.psum_scatter(padded, "dp", tiled=True)
            gath = jax.lax.all_gather(scat, "dp", tiled=True)
            x = gath[: flat.shape[0]].reshape(x.shape).astype(x.dtype) / n
        if jnp.issubdtype(x.dtype, jnp.floating):
            # data-dependent but deterministic perturbation
            x = x + jnp.cos(x * (1.0 + seed)).astype(x.dtype) * 1e-3
        out.append(x)
    return out


def _run(mesh, steps, donate):
    reps = [P() for _ in SHAPES]
    step = jax.shard_map(
        _stress_step, mesh=mesh, in_specs=(reps, P()), out_specs=reps,
        check_vma=False,
    )
    jstep = jax.jit(step, donate_argnums=(0,) if donate else ())
    carry = jax.device_put(
        _carry(),
        [NamedSharding(mesh, P()) for _ in SHAPES],
    )
    trace = []
    for t in range(steps):
        carry = jstep(carry, jnp.float32(t % 7))
        trace.append([np.asarray(x).copy() for x in carry])
    return trace


def test_donated_collective_loop_bitwise_deterministic(mesh):
    """Two identical 20-step loops with donated carries agree bit-for-bit
    at every step — donation must never let a collective read a reused
    buffer."""
    a = _run(mesh, 20, donate=True)
    b = _run(mesh, 20, donate=True)
    for t, (xs, ys) in enumerate(zip(a, b)):
        for i, (x, y) in enumerate(zip(xs, ys)):
            np.testing.assert_array_equal(
                x, y, err_msg=f"step {t} leaf {i} diverged across runs"
            )


def test_donation_matches_no_donation(mesh):
    """Donated and non-donated executions of the same program are
    bitwise identical — aliasing is an optimization, never a semantic."""
    a = _run(mesh, 10, donate=True)
    b = _run(mesh, 10, donate=False)
    for t, (xs, ys) in enumerate(zip(a, b)):
        for i, (x, y) in enumerate(zip(xs, ys)):
            np.testing.assert_array_equal(
                x, y, err_msg=f"step {t} leaf {i}: donation changed values"
            )


def test_collective_values_exact(mesh):
    """One tick against analytic expectations: replicated inputs mean to
    themselves under pmean/psum-div, ppermute of replicated data is
    identity, scatter+gather round-trips exactly."""
    reps = [P() for _ in SHAPES]
    step = jax.shard_map(
        _stress_step, mesh=mesh, in_specs=(reps, P()), out_specs=reps,
        check_vma=False,
    )
    carry = _carry()
    out = jax.jit(step)(carry, jnp.float32(0.0))
    for i, (x0, x1) in enumerate(zip(carry, out)):
        x0 = np.asarray(jnp.asarray(x0).astype(jnp.float32)) \
            if i != 3 else np.asarray(x0)
        # every pattern is an exact identity on replicated inputs
        # (ppermute full rotation, psum/size, scatter+gather/size)
        base = x0.astype(np.float32)
        x1 = np.asarray(jnp.asarray(x1).astype(jnp.float32))
        if np.issubdtype(np.asarray(carry[i]).dtype, np.floating) or \
                str(np.asarray(carry[i]).dtype) == "bfloat16":
            expect = base + np.cos(base) * 1e-3
            # bf16 leaves round the cos chain at bf16 precision
            tol = 2e-2 if i == 1 else 1e-6
            np.testing.assert_allclose(
                x1, expect, rtol=tol, atol=tol, err_msg=f"leaf {i}"
            )
        else:
            np.testing.assert_array_equal(x1, x0, err_msg=f"leaf {i}")

"""Quantized weight pools: int4 packing, the in-tile dequant matmul,
the checkpoint-load conversion seam, and width threading through
``decode_fns``.

The load-bearing claims, each pinned here:

- ``pack_int4``/``unpack_int4`` round-trip every nibble exactly for
  random shapes, and the halves layout is pinned bit-for-bit (packed
  column ``c`` = column ``c`` LOW nibble, column ``c + n/2`` HIGH) —
  the kernel's single-concat unpack depends on that exact pairing;
- the strict block validation names the offending leaf: odd int4
  blocks, rows that 2*block does not tile, and non-dividing int8
  blocks all raise actionable errors instead of silently padding;
- ``dequant_matmul`` (Pallas, interpreted on CPU) is bit-identical to
  the XLA fallback and to dequantize-then-dot for both widths, with
  leading batch dims flattened and the block size recoverable from the
  scales' shape alone;
- ZeRO-3 checkpoint -> ``unshard_params(transform=quantize)`` produces
  BIT-identical pools to quantizing the replicated weights directly
  (the quantize-at-load seam: the rebuild is exact, quantization is a
  pure function of the weight bits);
- ``decode_fns`` converts once and stamps the width: a pre-quantized
  tree is accepted (the fleet's share-don't-copy seam) and generates
  token-identically to the quantize-inside path, a mismatched declared
  width raises, and the quantized pool streams fewer bytes than fp32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu._compat import shard_map
from apex_tpu.ops.dequant_matmul import (
    dequant_matmul,
    dequant_matmul_reference,
    dequantize_weight,
    quantize_weight,
    weight_pool_block,
    weight_pool_dtype,
)
from apex_tpu.ops.quantization import (
    dequantize_rows_int4,
    pack_int4,
    quantize_rows,
    quantize_rows_int4,
    unpack_int4,
)


class TestInt4Pack:
    def test_round_trip_property(self):
        """Exact nibble round trip over random shapes — every value in
        [-8, 7] must survive pack -> unpack bit-for-bit."""
        rng = np.random.RandomState(0)
        for rows, n in [(1, 2), (3, 8), (5, 64), (7, 130), (16, 256)]:
            q = rng.randint(-8, 8, (rows, n)).astype(np.int8)
            packed = np.asarray(pack_int4(jnp.asarray(q)))
            assert packed.shape == (rows, n // 2)
            assert packed.dtype == np.int8
            np.testing.assert_array_equal(
                np.asarray(unpack_int4(jnp.asarray(packed))), q)

    def test_halves_layout_pinned(self):
        """Packed column c = column c (LOW) | column c + n/2 (HIGH) —
        the layout the kernel's single-concat unpack assumes."""
        q = jnp.asarray([[1, -2, 3, -4]], jnp.int8)
        packed = np.asarray(pack_int4(q)).astype(np.int32) & 0xFF
        lo = ((packed & 0xF) ^ 8) - 8
        hi = (((packed >> 4) & 0xF) ^ 8) - 8
        np.testing.assert_array_equal(lo, [[1, -2]])
        np.testing.assert_array_equal(hi, [[3, -4]])

    def test_odd_row_length_rejected(self):
        with pytest.raises(ValueError, match="even row length"):
            pack_int4(jnp.zeros((2, 5), jnp.int8))

    def test_quantize_rows_int4_band(self):
        """Each dequantized element stays within half a quantization
        step (amax/7/2) of its source, per block."""
        rng = np.random.RandomState(1)
        x = rng.randn(6, 64).astype(np.float32)
        bs = 16
        packed, scales = quantize_rows_int4(jnp.asarray(x), bs)
        back = np.asarray(dequantize_rows_int4(packed, scales, bs))
        amax = np.abs(x.reshape(6, -1, bs)).max(axis=2)
        tol = (amax / 7.0 / 2.0 + 1e-7)[:, :, None]
        assert (np.abs((back - x).reshape(6, -1, bs)) <= tol).all()

    def test_strict_block_errors_name_the_leaf(self):
        x = jnp.zeros((2, 96), jnp.float32)
        with pytest.raises(ValueError, match="must be even"):
            quantize_rows_int4(x, 3, leaf="layers/qkv.weight")
        # 96 % (2*32) != 0: a nibble half would straddle a block
        with pytest.raises(ValueError, match="layers/qkv.weight"):
            quantize_rows_int4(x, 32, leaf="layers/qkv.weight")
        with pytest.raises(ValueError, match="layers/fc1.weight"):
            quantize_rows(x, 36, leaf="layers/fc1.weight")
        # without a leaf the legacy padding contract stands
        v, s = quantize_rows(x, 36)
        assert v.shape == (2, 96)


class TestDequantMatmul:
    @pytest.mark.parametrize("weight_dtype", ["int8", "int4"])
    def test_pallas_matches_xla_and_reference(self, weight_dtype):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(4, 32).astype(np.float32))
        w = jnp.asarray(rng.randn(32, 64).astype(np.float32))
        wq = quantize_weight(w, weight_dtype, 16)
        qv = wq["q8"] if weight_dtype == "int8" else wq["q4"]
        ref = dequant_matmul_reference(
            x, qv, wq["scales"], weight_dtype=weight_dtype,
            block_size=16)
        for impl in ("pallas", "xla"):
            out = dequant_matmul(
                x, qv, wq["scales"], weight_dtype=weight_dtype,
                implementation=impl)
            np.testing.assert_array_equal(
                np.asarray(out), np.asarray(ref))

    def test_leading_batch_dims_flattened(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(2, 3, 32).astype(np.float32))
        w = jnp.asarray(rng.randn(32, 64).astype(np.float32))
        wq = quantize_weight(w, "int8", 16)
        out = dequant_matmul(x, wq["q8"], wq["scales"],
                             weight_dtype="int8")
        assert out.shape == (2, 3, 64)
        flat = dequant_matmul(x.reshape(6, 32), wq["q8"], wq["scales"],
                              weight_dtype="int8")
        np.testing.assert_array_equal(
            np.asarray(out).reshape(6, 64), np.asarray(flat))

    def test_block_size_recovered_from_scales(self):
        w = jnp.asarray(np.random.RandomState(4)
                        .randn(32, 64).astype(np.float32))
        wq = quantize_weight(w, "int4", 16)
        assert weight_pool_dtype(wq) == "int4"
        assert weight_pool_block(wq) == 16
        wq8 = quantize_weight(w, "int8", 32)
        assert weight_pool_dtype(wq8) == "int8"
        assert weight_pool_block(wq8) == 32

    def test_dequantize_weight_round_trip_band(self):
        rng = np.random.RandomState(5)
        w = rng.randn(32, 64).astype(np.float32)
        wq = quantize_weight(jnp.asarray(w), "int8", 16)
        back = np.asarray(dequantize_weight(wq))
        amax = np.abs(w.reshape(32, -1, 16)).max(axis=2)
        tol = (amax / 127.0 / 2.0 + 1e-7)[:, :, None]
        assert (np.abs((back - w).reshape(32, -1, 16)) <= tol).all()

    def test_validation_errors(self):
        x = jnp.zeros((4, 32), jnp.float32)
        w = jnp.asarray(np.random.RandomState(6)
                        .randn(32, 64).astype(np.float32))
        wq = quantize_weight(w, "int8", 16)
        with pytest.raises(ValueError, match="weight_dtype"):
            dequant_matmul(x, wq["q8"], wq["scales"],
                           weight_dtype="fp8")
        with pytest.raises(ValueError):
            dequant_matmul(jnp.zeros((4, 16), jnp.float32), wq["q8"],
                           wq["scales"], weight_dtype="int8")
        with pytest.raises(ValueError):
            dequant_matmul(x, wq["q8"], wq["scales"],
                           weight_dtype="int8", block_size=24)


# ---------------------------------------------------------------------------
# The quantize-at-load seam: ZeRO-3 checkpoint -> unshard -> pools
# ---------------------------------------------------------------------------


def _tiny_gpt():
    from apex_tpu.models import GPTConfig, GPTModel

    model = GPTModel(GPTConfig(
        vocab_size=64, num_layers=2, hidden_size=32,
        num_attention_heads=4, max_position_embeddings=64,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))
    return model, model.init(jax.random.PRNGKey(0))


class TestUnshardQuantizeSeam:
    def test_unshard_transform_bit_identical_to_direct(self):
        """quantize(unshard(shard(params))) == quantize(params) for
        both widths — the full-width tree never needs to exist on
        device to build the serving pools from a ZeRO-3 checkpoint."""
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        from apex_tpu.models.gpt import quantize_gpt_weights
        from apex_tpu.transformer import parallel_state

        if parallel_state.model_parallel_is_initialized():
            parallel_state.destroy_model_parallel()
        mesh = parallel_state.initialize_model_parallel()
        try:
            model, params = _tiny_gpt()
            opt = DistributedFusedAdam(lr=1e-2, shard_params=True,
                                       bucket_bytes=4096)
            opt.build_layout(params, mesh=mesh)
            pspec = jax.tree.map(lambda _: P(), params)
            shards = jax.jit(shard_map(
                opt.init_shards, mesh=mesh, in_specs=(pspec,),
                out_specs=opt.shard_spec()))(params)
            ckpt = np.asarray(jax.device_get(shards))
            for wd in ("int8", "int4"):
                pools = opt.unshard_params(
                    ckpt,
                    transform=lambda p: quantize_gpt_weights(
                        p, wd, 16))
                direct = quantize_gpt_weights(params, wd, 16)
                jax.tree.map(
                    lambda a, b: np.testing.assert_array_equal(
                        np.asarray(a), np.asarray(b)),
                    pools, direct)
        finally:
            parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# Width threading through decode_fns (single-device serving mesh)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serve_setup():
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:1])
    model, params = _tiny_gpt()
    rng = np.random.RandomState(7)
    prompts = rng.randint(1, 64, (4, 10)).astype(np.int32)
    plens = np.array([10, 8, 6, 9], np.int32)
    yield mesh, model, params, prompts, plens
    parallel_state.destroy_model_parallel()


def _run_batcher(serve_setup, fns_src, weight_dtype=None, new=8):
    from apex_tpu.serving.kv_cache import (
        KVCacheConfig, PagedKVCache, init_pools,
    )
    from apex_tpu.serving.serve import ContinuousBatcher, Request

    mesh, model, params, prompts, plens = serve_setup
    page = 4
    pps = -(-(10 + new) // page)
    ccfg = KVCacheConfig(
        num_layers=2, num_heads=4, head_dim=8,
        num_pages=1 + 2 * pps, page_size=page, max_seqs=2,
        pages_per_seq=pps, dtype=jnp.float32)
    fns = model.decode_fns(fns_src, mesh, ccfg, max_prompt_len=10,
                           weight_dtype=weight_dtype, weight_block=16)
    batcher = ContinuousBatcher(
        fns.prefill, fns.decode, PagedKVCache(ccfg), init_pools(ccfg),
        max_prompt_len=10, harvest_every=4)
    comps = batcher.run([
        Request(uid=i, prompt=[int(t) for t in prompts[i, :plens[i]]],
                max_new_tokens=new)
        for i in range(4)])
    return fns, comps


class TestDecodeFnsWidths:
    def test_convert_once_and_stamp(self, serve_setup):
        _, _, params, _, _ = serve_setup
        fp_bytes = int(sum(x.nbytes for x in jax.tree.leaves(params)))
        fns, comps = _run_batcher(serve_setup, params,
                                  weight_dtype="int8")
        assert fns.weight_dtype == "int8"
        assert 0 < fns.weight_stream_bytes < fp_bytes
        assert all(len(comps[i].tokens) == 8 for i in range(4))

    def test_prequantized_pool_shared_not_requantized(self, serve_setup):
        """The fleet seam: a pre-quantized tree with a MATCHING
        declared width is accepted as-is and generates exactly what
        the quantize-inside path generates."""
        from apex_tpu.models.gpt import quantize_gpt_weights

        _, _, params, _, _ = serve_setup
        qp = quantize_gpt_weights(params, "int8", 16)
        _, inside = _run_batcher(serve_setup, params,
                                 weight_dtype="int8")
        fns, shared = _run_batcher(serve_setup, qp,
                                   weight_dtype="int8")
        assert fns.weight_dtype == "int8"
        for i in range(4):
            assert shared[i].tokens == inside[i].tokens
        # declaring nothing infers the width from the structure
        fns2, inferred = _run_batcher(serve_setup, qp)
        assert fns2.weight_dtype == "int8"
        for i in range(4):
            assert inferred[i].tokens == inside[i].tokens

    def test_mismatched_width_rejected(self, serve_setup):
        from apex_tpu.models.gpt import quantize_gpt_weights

        _, _, params, _, _ = serve_setup
        qp = quantize_gpt_weights(params, "int8", 16)
        with pytest.raises(ValueError, match="int8"):
            _run_batcher(serve_setup, qp, weight_dtype="int4")

    def test_int4_band_wider_but_bounded(self, serve_setup):
        """int4 weights still complete generation; its logits ride a
        wider band (gated in the dryrun, not re-measured here)."""
        fns, comps = _run_batcher(serve_setup, serve_setup[2],
                                  weight_dtype="int4")
        assert fns.weight_dtype == "int4"
        assert all(len(comps[i].tokens) == 8 for i in range(4))

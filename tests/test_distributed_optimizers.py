"""ZeRO-style distributed optimizer tests: sharded step == unsharded step.

Philosophy (SURVEY.md §4): the reference tests DistributedFusedAdam
against the unsharded optimizer in a single process
(tests/L0/run_optimizers/test_dist_adam.py); here the dp=8 sharded path
runs on the virtual mesh and must match FusedAdam/FusedLAMB exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.transformer import parallel_state


@pytest.fixture
def mesh():
    m = parallel_state.initialize_model_parallel()
    yield m
    parallel_state.destroy_model_parallel()


def make_params_grads(key):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    params = {
        "w": jax.random.normal(k1, (13, 7)),   # deliberately odd sizes:
        "b": jax.random.normal(k2, (5,)),      # exercises flat padding
    }
    grads = {
        "w": 0.1 * jax.random.normal(k3, (13, 7)),
        "b": 0.1 * jax.random.normal(k4, (5,)),
    }
    return params, grads


def run_sharded(mesh, opt, params, grads, steps=3):
    """Run `steps` sharded optimizer steps with identical grads per rank."""
    state_specs = opt.state_specs()
    pspec = jax.tree.map(lambda _: P(), params)

    def init_fn(params):
        return opt.init(params)

    init = jax.jit(
        jax.shard_map(
            init_fn, mesh=mesh, in_specs=(pspec,), out_specs=state_specs
        )
    )
    state = init(params)

    def step_fn(state, grads, params):
        return opt.step(state, grads, params)

    step = jax.jit(
        jax.shard_map(
            step_fn,
            mesh=mesh,
            in_specs=(state_specs, pspec, pspec),
            out_specs=(pspec, state_specs),
        )
    )
    for _ in range(steps):
        params, state = step(state, grads, params)
    return params, state


class TestDistributedFusedAdam:
    def test_matches_unsharded(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(0))
        dopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01)
        sharded_params, state = run_sharded(mesh, dopt, params, grads)

        ref_opt = FusedAdam(lr=1e-2, weight_decay=0.01)
        ref_state = ref_opt.init(params)
        ref_params = params
        for _ in range(3):
            ref_params, ref_state = ref_opt.step(ref_state, grads, ref_params)

        for a, b in zip(
            jax.tree.leaves(sharded_params), jax.tree.leaves(ref_params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            )

    def test_state_is_sharded(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(0))
        dopt = DistributedFusedAdam(lr=1e-2)
        _, state = run_sharded(mesh, dopt, params, grads, steps=1)
        total = 13 * 7 + 5  # = 96, divisible by 8 → shard = 12
        assert state["exp_avg"].shape == (total,)
        # each device holds only its 1/8 shard
        shard_shapes = {
            s.data.shape for s in state["exp_avg"].addressable_shards
        }
        assert shard_shapes == {(total // 8,)}

    def test_skip_step_on_overflow(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(0))
        dopt = DistributedFusedAdam(lr=1e-2)
        state_specs = dopt.state_specs()
        pspec = jax.tree.map(lambda _: P(), params)
        init = jax.jit(
            jax.shard_map(
                dopt.init, mesh=mesh, in_specs=(pspec,),
                out_specs=state_specs,
            )
        )
        state = init(params)

        def step_fn(state, grads, params, finite):
            return dopt.step(state, grads, params, grads_finite=finite)

        step = jax.jit(
            jax.shard_map(
                step_fn,
                mesh=mesh,
                in_specs=(state_specs, pspec, pspec, P()),
                out_specs=(pspec, state_specs),
            )
        )
        new_params, new_state = step(
            state, grads, params, jnp.array(False)
        )
        for a, b in zip(
            jax.tree.leaves(new_params), jax.tree.leaves(params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(new_state["step"]) == 0


class TestDistributedFusedLAMB:
    @pytest.mark.parametrize("adam_w_mode", [True, False])
    @pytest.mark.parametrize("use_nvlamb", [False, True])
    def test_matches_unsharded(self, mesh, use_nvlamb, adam_w_mode):
        params, grads = make_params_grads(jax.random.PRNGKey(1))
        kw = dict(
            lr=1e-2, weight_decay=0.01, max_grad_norm=0.05,
            use_nvlamb=use_nvlamb, adam_w_mode=adam_w_mode,
        )
        dopt = DistributedFusedLAMB(**kw)
        sharded_params, _ = run_sharded(mesh, dopt, params, grads)

        ref_opt = FusedLAMB(**kw)
        ref_state = ref_opt.init(params)
        ref_params = params
        for _ in range(3):
            ref_params, ref_state = ref_opt.step(ref_state, grads, ref_params)

        for a, b in zip(
            jax.tree.leaves(sharded_params), jax.tree.leaves(ref_params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            )

    def test_bf16_params_roundtrip(self, mesh):
        """bf16 model params with fp32 sharded masters: the gathered
        params come back in bf16 while masters stay fp32."""
        params, grads = make_params_grads(jax.random.PRNGKey(2))
        params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
        grads = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)
        dopt = DistributedFusedLAMB(lr=1e-2)
        new_params, state = run_sharded(mesh, dopt, params, grads, steps=1)
        assert all(
            l.dtype == jnp.bfloat16 for l in jax.tree.leaves(new_params)
        )
        assert state["master"].dtype == jnp.float32

"""ZeRO-style distributed optimizer tests: sharded step == unsharded step.

Philosophy (SURVEY.md §4): the reference tests DistributedFusedAdam
against the unsharded optimizer in a single process
(tests/L0/run_optimizers/test_dist_adam.py); here the dp=8 sharded path
runs on the virtual mesh and must match FusedAdam/FusedLAMB exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.transformer import parallel_state


@pytest.fixture
def mesh():
    m = parallel_state.initialize_model_parallel()
    yield m
    parallel_state.destroy_model_parallel()


def make_params_grads(key):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    params = {
        "w": jax.random.normal(k1, (13, 7)),   # deliberately odd sizes:
        "b": jax.random.normal(k2, (5,)),      # exercises flat padding
    }
    grads = {
        "w": 0.1 * jax.random.normal(k3, (13, 7)),
        "b": 0.1 * jax.random.normal(k4, (5,)),
    }
    return params, grads


def run_sharded(mesh, opt, params, grads, steps=3):
    """Run `steps` sharded optimizer steps with identical grads per rank."""
    state_specs = opt.state_specs()
    pspec = jax.tree.map(lambda _: P(), params)

    def init_fn(params):
        return opt.init(params)

    init = jax.jit(
        jax.shard_map(
            init_fn, mesh=mesh, in_specs=(pspec,), out_specs=state_specs
        )
    )
    state = init(params)

    def step_fn(state, grads, params):
        return opt.step(state, grads, params)

    step = jax.jit(
        jax.shard_map(
            step_fn,
            mesh=mesh,
            in_specs=(state_specs, pspec, pspec),
            out_specs=(pspec, state_specs),
        )
    )
    for _ in range(steps):
        params, state = step(state, grads, params)
    return params, state


class TestDistributedFusedAdam:
    def test_matches_unsharded(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(0))
        dopt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01)
        sharded_params, state = run_sharded(mesh, dopt, params, grads)

        ref_opt = FusedAdam(lr=1e-2, weight_decay=0.01)
        ref_state = ref_opt.init(params)
        ref_params = params
        for _ in range(3):
            ref_params, ref_state = ref_opt.step(ref_state, grads, ref_params)

        for a, b in zip(
            jax.tree.leaves(sharded_params), jax.tree.leaves(ref_params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            )

    def test_state_is_sharded(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(0))
        dopt = DistributedFusedAdam(lr=1e-2)
        _, state = run_sharded(mesh, dopt, params, grads, steps=1)
        total = 13 * 7 + 5  # = 96, divisible by 8 → shard = 12
        assert state["exp_avg"].shape == (total,)
        # each device holds only its 1/8 shard
        shard_shapes = {
            s.data.shape for s in state["exp_avg"].addressable_shards
        }
        assert shard_shapes == {(total // 8,)}

    def test_skip_step_on_overflow(self, mesh):
        params, grads = make_params_grads(jax.random.PRNGKey(0))
        dopt = DistributedFusedAdam(lr=1e-2)
        state_specs = dopt.state_specs()
        pspec = jax.tree.map(lambda _: P(), params)
        init = jax.jit(
            jax.shard_map(
                dopt.init, mesh=mesh, in_specs=(pspec,),
                out_specs=state_specs,
            )
        )
        state = init(params)

        def step_fn(state, grads, params, finite):
            return dopt.step(state, grads, params, grads_finite=finite)

        step = jax.jit(
            jax.shard_map(
                step_fn,
                mesh=mesh,
                in_specs=(state_specs, pspec, pspec, P()),
                out_specs=(pspec, state_specs),
            )
        )
        new_params, new_state = step(
            state, grads, params, jnp.array(False)
        )
        for a, b in zip(
            jax.tree.leaves(new_params), jax.tree.leaves(params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(new_state["step"]) == 0


class TestDistributedFusedLAMB:
    @pytest.mark.parametrize("adam_w_mode", [True, False])
    @pytest.mark.parametrize("use_nvlamb", [False, True])
    def test_matches_unsharded(self, mesh, use_nvlamb, adam_w_mode):
        params, grads = make_params_grads(jax.random.PRNGKey(1))
        kw = dict(
            lr=1e-2, weight_decay=0.01, max_grad_norm=0.05,
            use_nvlamb=use_nvlamb, adam_w_mode=adam_w_mode,
        )
        dopt = DistributedFusedLAMB(**kw)
        sharded_params, _ = run_sharded(mesh, dopt, params, grads)

        ref_opt = FusedLAMB(**kw)
        ref_state = ref_opt.init(params)
        ref_params = params
        for _ in range(3):
            ref_params, ref_state = ref_opt.step(ref_state, grads, ref_params)

        for a, b in zip(
            jax.tree.leaves(sharded_params), jax.tree.leaves(ref_params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            )

    def test_bf16_params_roundtrip(self, mesh):
        """bf16 model params with fp32 sharded masters: the gathered
        params come back in bf16 while masters stay fp32."""
        params, grads = make_params_grads(jax.random.PRNGKey(2))
        params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
        grads = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)
        dopt = DistributedFusedLAMB(lr=1e-2)
        new_params, state = run_sharded(mesh, dopt, params, grads, steps=1)
        assert all(
            l.dtype == jnp.bfloat16 for l in jax.tree.leaves(new_params)
        )
        assert state["master"].dtype == jnp.float32


class TestHierarchicalCollectives:
    """Two-level DCN/ICI data parallelism == flat dp, bit for bit in the
    math (reference: distributed_fused_adam.py:106-160 intra-group RS +
    inter-group AR)."""

    def _flat_vs_hier(self, make_opt, steps=3):
        from apex_tpu.parallel import hierarchical_data_parallel_mesh

        params, grads = make_params_grads(jax.random.PRNGKey(5))
        # flat dp=8
        flat_mesh = parallel_state.initialize_model_parallel()
        try:
            opt = make_opt("dp")
            flat_params, _ = run_sharded(flat_mesh, opt, params, grads,
                                         steps=steps)
        finally:
            parallel_state.destroy_model_parallel()

        # hierarchical (dcn=2, ici=4)
        mesh = hierarchical_data_parallel_mesh(ici_size=4)
        hopt = make_opt(("dcn", "ici"))
        state_specs = hopt.state_specs()
        pspec = jax.tree.map(lambda _: P(), params)
        init = jax.jit(jax.shard_map(
            lambda p: hopt.init(p), mesh=mesh, in_specs=(pspec,),
            out_specs=state_specs,
        ))
        stepf = jax.jit(jax.shard_map(
            lambda s, g, p: hopt.step(s, g, p), mesh=mesh,
            in_specs=(state_specs, pspec, pspec),
            out_specs=(pspec, state_specs),
        ))
        state = init(params)
        hp = params
        for _ in range(steps):
            hp, state = stepf(state, grads, hp)
        return flat_params, hp

    def test_hier_adam_matches_flat(self):
        a, b = self._flat_vs_hier(
            lambda ax: DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                            axis_name=ax)
        )
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7
            )

    def test_hier_lamb_matches_flat(self):
        a, b = self._flat_vs_hier(
            lambda ax: DistributedFusedLAMB(lr=1e-2, weight_decay=0.01,
                                            max_grad_norm=0.05, axis_name=ax)
        )
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-7
            )

    def test_hier_ddp_allreduce_matches_flat(self):
        from apex_tpu.parallel import (
            all_reduce_gradients,
            hierarchical_data_parallel_mesh,
        )

        mesh = hierarchical_data_parallel_mesh(ici_size=4)
        grads = {"w": jax.random.normal(jax.random.PRNGKey(6), (8, 13, 7)),
                 "b": jax.random.normal(jax.random.PRNGKey(7), (8, 5))}

        def hier(g):
            return all_reduce_gradients(g, axis_name=("dcn", "ici"))

        out = jax.jit(jax.shard_map(
            hier, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(("dcn", "ici")), grads),),
            out_specs=jax.tree.map(lambda _: P(("dcn", "ici")), grads),
        ))(grads)
        # hierarchical RS/AR/AG mean == plain mean over the global batch
        for k in grads:
            want = np.broadcast_to(
                np.mean(np.asarray(grads[k]), axis=0, keepdims=True),
                grads[k].shape,
            )
            np.testing.assert_allclose(
                np.asarray(out[k]), want, rtol=1e-6, atol=1e-7
            )


class TestCompressedAllGather:
    """Opt-in lossy param all-gather (reference: distributed_fused_adam's
    e5m2-compressed allgather): masters stay exact, gathered params carry
    quantization commensurate with the chosen format."""

    @pytest.mark.parametrize("fmt,tol", [("bf16", 2e-2), ("e5m2", 0.25)])
    def test_quantized_gather_tracks_exact(self, mesh, fmt, tol):
        params, grads = make_params_grads(jax.random.PRNGKey(9))
        exact = DistributedFusedAdam(lr=1e-2)
        comp = DistributedFusedAdam(lr=1e-2, compressed_allgather=fmt)
        p_exact, s_exact = run_sharded(mesh, exact, params, grads, steps=2)
        p_comp, s_comp = run_sharded(mesh, comp, params, grads, steps=2)
        # masters identical: compression only touches the gather payload
        np.testing.assert_allclose(
            np.asarray(s_exact["master"]), np.asarray(s_comp["master"]),
            atol=0,
        )
        for a, b in zip(jax.tree.leaves(p_exact), jax.tree.leaves(p_comp)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=tol, atol=tol
            )

    def test_bad_format_rejected(self):
        with pytest.raises(ValueError):
            DistributedFusedAdam(lr=1e-2, compressed_allgather="int4")


class TestDataAxisShardedLeaves:
    """MoE composition: expert weights ride "dp" as the ep axis, so they
    are data-axis-SHARDED — the flat RS/AG path would sum unrelated
    expert shards.  With param_specs, DistributedFusedAdam updates them
    rank-locally (their grads are already complete on the owner)."""

    def test_moe_expert_leaves_update_locally(self, mesh):
        H, E_local = 6, 2
        specs = {"dense": P(), "experts": P("dp", None, None)}
        k = jax.random.PRNGKey(0)
        dense = jax.random.normal(k, (H, H))
        # per-rank DISTINCT expert shards: global (8*E_local, H, H)
        experts = jax.random.normal(jax.random.fold_in(k, 1),
                                    (8 * E_local, H, H))
        dense_grads_per_rank = 0.1 * jax.random.normal(
            jax.random.fold_in(k, 2), (8, H, H))
        expert_grads = 0.1 * jax.random.normal(
            jax.random.fold_in(k, 3), (8 * E_local, H, H))

        opt = DistributedFusedAdam(lr=1e-2, weight_decay=0.01,
                                   param_specs=specs)
        sspecs = opt.state_specs()
        pspec = specs

        def init_fn(p):
            return opt.init(p)

        init = jax.jit(jax.shard_map(
            init_fn, mesh=mesh, in_specs=(pspec,), out_specs=sspecs))

        params = {"dense": dense, "experts": experts}
        # dense grads are handed in stacked (8, H, H) and sharded over
        # dp, so each rank sees a DIFFERENT (1, H, H) slice — squeezed
        # to (H, H) inside; the RS path must average them
        grads = {"dense": dense_grads_per_rank,
                 "experts": expert_grads}

        def step_squeeze(state, grads, params):
            g = {"dense": grads["dense"][0], "experts": grads["experts"]}
            return opt.step(state, g, params)

        step = jax.jit(jax.shard_map(
            step_squeeze, mesh=mesh,
            in_specs=(sspecs,
                      {"dense": P("dp"), "experts": P("dp")},
                      pspec),
            out_specs=(pspec, sspecs),
        ))
        state = init(params)
        new_params, new_state = step(state, grads, params)

        # reference: dense uses the dp-MEAN of the per-rank grads (the
        # RS path averages); under the raw convention the expert grads
        # (the all_to_all SUM) are likewise divided by world — both
        # plain AdamW
        ref_opt = FusedAdam(lr=1e-2, weight_decay=0.01,
                            master_weights=True)
        ref_state = ref_opt.init(params)
        ref_grads = {"dense": jnp.mean(dense_grads_per_rank, axis=0),
                     "experts": expert_grads / 8.0}
        ref_params, _ = ref_opt.step(ref_state, ref_grads, params)
        np.testing.assert_allclose(
            np.asarray(new_params["dense"]),
            np.asarray(ref_params["dense"]), rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(new_params["experts"]),
            np.asarray(ref_params["experts"]), rtol=1e-6, atol=1e-7)

        # prenormalized convention: expert grads pass through unscaled
        step_pre = jax.jit(jax.shard_map(
            lambda st, g, pr: opt.step(
                st, {"dense": g["dense"][0], "experts": g["experts"]},
                pr, local_grads_prenormalized=True),
            mesh=mesh,
            in_specs=(sspecs, {"dense": P("dp"), "experts": P("dp")},
                      pspec),
            out_specs=(pspec, sspecs),
        ))
        state2 = init(params)
        pre_params, _ = step_pre(state2, grads, params)
        ref_grads_pre = {"dense": jnp.mean(dense_grads_per_rank, axis=0),
                         "experts": expert_grads}
        ref_state2 = ref_opt.init(params)
        ref_pre, _ = ref_opt.step(ref_state2, ref_grads_pre, params)
        np.testing.assert_allclose(
            np.asarray(pre_params["experts"]),
            np.asarray(ref_pre["experts"]), rtol=1e-6, atol=1e-7)

    def test_lamb_rejects_data_sharded_leaves(self):
        # fail-fast: at CONSTRUCTION, not at step-trace time
        with pytest.raises(NotImplementedError):
            DistributedFusedLAMB(lr=1e-2,
                                 param_specs={"w": P(), "e": P("dp")})

    def test_hierarchical_rejects_data_sharded_leaves(self):
        with pytest.raises(NotImplementedError):
            DistributedFusedAdam(
                lr=1e-2, axis_name=("dcn", "ici"),
                param_specs={"w": P(), "e": P("ici")})

"""T5 encoder-decoder model tests on the 8-device virtual CPU mesh.

Covers the reference's ModelType.encoder_and_decoder capability
(apex/transformer/pipeline_parallel/schedules/common.py:18-108 +
pipeline_model_parallel_split_rank): tp-invariance of the enc-dec loss,
grads, and the compiled encoder-decoder pipeline schedule vs the
sequential computation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models import T5Config, T5Model
from apex_tpu.transformer import parallel_state

VOCAB = 64


def small_config(**kw):
    base = dict(
        vocab_size=VOCAB,
        num_encoder_layers=2,
        num_decoder_layers=2,
        hidden_size=32,
        num_attention_heads=4,
        max_position_embeddings=16,
        compute_dtype=jnp.float32,
        remat=False,
        attention_impl="xla",
    )
    base.update(kw)
    return T5Config(**base)


def _place(mesh, params, specs):
    return jax.device_put(
        params,
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
    )


def _data(b=8, s_enc=12, s_dec=10):
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    return (
        jax.random.randint(ks[0], (b, s_enc), 0, VOCAB),
        jax.random.randint(ks[1], (b, s_dec), 0, VOCAB),
        jax.random.randint(ks[2], (b, s_dec), 0, VOCAB),
    )


def test_t5_loss_tp_invariant():
    enc, dec, tgt = _data()
    losses = {}
    for tp in (1, 4):
        mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp
        )
        try:
            model = T5Model(small_config())
            params = model.init(jax.random.PRNGKey(0))
            specs = model.param_specs()
            loss = jax.jit(
                jax.shard_map(
                    model.loss, mesh=mesh,
                    in_specs=(specs, P("dp"), P("dp"), P("dp")),
                    out_specs=P(),
                )
            )
            losses[tp] = float(loss(_place(mesh, params, specs), enc, dec, tgt))
            assert np.isfinite(losses[tp])
        finally:
            parallel_state.destroy_model_parallel()
    np.testing.assert_allclose(losses[4], losses[1], rtol=2e-4)


def test_t5_grads_finite():
    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2
    )
    try:
        enc, dec, tgt = _data(b=4)
        model = T5Model(small_config(remat=True))
        params = model.init(jax.random.PRNGKey(0))
        specs = model.param_specs()
        grad_fn = jax.jit(
            jax.shard_map(
                jax.value_and_grad(model.loss), mesh=mesh,
                in_specs=(specs, P("dp"), P("dp"), P("dp")),
                out_specs=(P(), specs),
            )
        )
        loss, grads = grad_fn(_place(mesh, params, specs), enc, dec, tgt)
        assert np.isfinite(float(loss))
        finite = jax.tree.map(
            lambda g: bool(jnp.all(jnp.isfinite(g))), grads
        )
        assert all(jax.tree.leaves(finite))
        # encoder cross-attention weights are dead by design: zero grad
        enc_cross = grads["enc_layers"]["cross_q"]["weight"]
        np.testing.assert_allclose(np.asarray(enc_cross), 0.0)
        # decoder cross-attention weights are live
        dec_cross = np.asarray(grads["dec_layers"]["cross_q"]["weight"])
        assert np.abs(dec_cross).max() > 0
    finally:
        parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("remat", [False, True])
@pytest.mark.parametrize("fused", [True, False])
def test_t5_pipeline_matches_sequential(remat, fused):
    """pp=4 (2 encoder + 2 decoder stages) enc-dec pipeline == the
    sequential loss, values and grads — both the one-body-per-tick
    fused schedule (default) and the two-stream fallback."""
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        pipeline_model_parallel_split_rank_=2,
    )
    try:
        enc, dec, tgt = _data(b=8)
        model = T5Model(small_config(remat=remat, fused_pipeline=fused))
        params = model.init(jax.random.PRNGKey(0))

        # sequential reference on the dp-only view of the same mesh
        seq_specs = model.param_specs()
        seq_loss = jax.jit(
            jax.shard_map(
                model.loss, mesh=mesh,
                in_specs=(seq_specs, P("dp"), P("dp"), P("dp")),
                out_specs=P(),
            )
        )
        expected = float(
            seq_loss(_place(mesh, params, seq_specs), enc, dec, tgt)
        )

        pp_params = model.pipeline_params(params)
        pp_specs = model.pipeline_param_specs()

        def pp_loss(p, e, d, t):
            return model.pipeline_loss(p, e, d, t, num_microbatches=4)

        grad_fn = jax.jit(
            jax.shard_map(
                jax.value_and_grad(pp_loss), mesh=mesh,
                in_specs=(pp_specs, P("dp"), P("dp"), P("dp")),
                out_specs=(P(), pp_specs),
            )
        )
        loss, grads = grad_fn(_place(mesh, pp_params, pp_specs), enc, dec, tgt)
        np.testing.assert_allclose(float(loss), expected, rtol=2e-5)

        # grads parity against the sequential path on one probe leaf
        seq_grad = jax.jit(
            jax.shard_map(
                jax.grad(model.loss), mesh=mesh,
                in_specs=(seq_specs, P("dp"), P("dp"), P("dp")),
                out_specs=seq_specs,
            )
        )
        g_seq = seq_grad(_place(mesh, params, seq_specs), enc, dec, tgt)
        g_seq_layers = jax.tree.map(
            lambda e_, d_: jnp.concatenate([e_, d_], axis=0),
            g_seq["enc_layers"], g_seq["dec_layers"],
        )
        np.testing.assert_allclose(
            np.asarray(grads["layers"]["fc1"]["weight"]),
            np.asarray(g_seq_layers["fc1"]["weight"]),
            rtol=5e-4, atol=5e-6,
        )
        np.testing.assert_allclose(
            np.asarray(grads["embedding"]["weight"]),
            np.asarray(g_seq["embedding"]["weight"]),
            rtol=5e-4, atol=5e-6,
        )
    finally:
        parallel_state.destroy_model_parallel()


def test_t5_policy_driven():
    """A Policy kwarg switches dtypes, as for GPT/BERT."""
    from apex_tpu.amp import get_policy

    cfg = small_config(policy=get_policy("O5"))
    assert cfg.params_dtype == get_policy("O5").param_dtype
    assert cfg.compute_dtype == get_policy("O5").compute_dtype
    mesh = parallel_state.initialize_model_parallel()
    try:
        model = T5Model(cfg)
        params = model.init(jax.random.PRNGKey(0))
        assert params["enc_layers"]["fc1"]["weight"].dtype == cfg.params_dtype
    finally:
        parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("fused", [True, False])
def test_t5_pipeline_grads_matches_gpipe(fused):
    """T5 fwd+bwd through the dispatched enc-dec schedule ==
    jax.grad of pipeline_loss (+ shared-param sync + dp pmean) — both
    the fused default and the two-stream fallback."""
    from apex_tpu.transformer.pipeline_parallel import sync_replicated_grads

    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2,
        pipeline_model_parallel_split_rank_=1,
    )
    try:
        cfg = small_config(fused_pipeline=fused)
        model = T5Model(cfg)
        params = model.pipeline_params(model.init(jax.random.PRNGKey(0)))
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        enc = jax.random.randint(ks[0], (8, 8), 0, cfg.vocab_size)
        dec = jax.random.randint(ks[1], (8, 8), 0, cfg.vocab_size)
        tgt = jax.random.randint(ks[2], (8, 8), 0, cfg.vocab_size)

        specs = model.pipeline_param_specs()
        placed = jax.device_put(
            params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P)))

        def gpipe(p, e, d, t):
            loss, grads = jax.value_and_grad(
                lambda pp_: model.pipeline_loss(pp_, e, d, t, 2)
            )(p)
            grads = sync_replicated_grads(grads, specs)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            return loss, grads

        ref = jax.jit(jax.shard_map(
            gpipe, mesh=mesh,
            in_specs=(specs,) + (P("dp"),) * 3, out_specs=(P(), specs),
        ))(placed, enc, dec, tgt)

        got = jax.jit(jax.shard_map(
            lambda p, e, d, t: model.pipeline_grads(p, e, d, t, 2),
            mesh=mesh,
            in_specs=(specs,) + (P("dp"),) * 3, out_specs=(P(), specs),
        ))(placed, enc, dec, tgt)

        np.testing.assert_allclose(float(got[0]), float(ref[0]), rtol=1e-5)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(got[1])),
            jax.tree_util.tree_leaves_with_path(jax.device_get(ref[1])),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6,
                err_msg=str(path),
            )
    finally:
        parallel_state.destroy_model_parallel()

"""Disaggregated fleet: page-level KV handoff, replica roles, and the
host-RAM offload tier.

The load-bearing claims, each pinned here:

- :func:`export_pages`/:func:`import_pages` round-trip KV bytes
  BIT-identically across pools for every page dtype family (fp32,
  bf16, int8+scales), through arbitrary physical page ids on both
  sides — page CONTENT is what moves, physical layout is private to
  each pool;
- export leaves shared/CoW refcounts intact on the source and the
  destination's prefix index adopts the moved pages under their
  original hashes;
- a prefill→decode handoff is token-identical to a unified run for
  greedy, seeded AND speculative serving (the absolute-position
  sampling-key schedule — the same argument failover replay stands
  on), and completions record ``handoffs``;
- a mid-handoff staged packet is charged to the DESTINATION's load
  score only — the source released the slot at export (the
  double-count fix);
- the :class:`HostOffloadPool` tier catches index-only prefix pages at
  eviction and faults them back bit-identically under real eviction
  pressure — a resumed session's stream matches a recompute reference
  exactly;
- a prefill replica dying mid-handoff loses nothing: in-flight work
  migrates (journaled), every stream completes token-identical to an
  unkilled reference, and full-process death recovers through
  ``recover_journal`` the same way.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from apex_tpu.fleet import FleetPolicy, FleetRouter, Replica
from apex_tpu.fleet.journal import RequestJournal, recover_journal
from apex_tpu.serving.kv_cache import (
    KVCacheConfig,
    PagedKVCache,
    HostOffloadPool,
    export_pages,
    import_pages,
    init_pools,
    prompt_page_hashes,
    staged_nbytes,
)
from apex_tpu.serving.serve import ContinuousBatcher, Request
from apex_tpu.serving.speculate import NGramDraftSource


# ---------------------------------------------------------------------------
# export/import round-trip: pure kv_cache, no model
# ---------------------------------------------------------------------------


def _cfg(**kw):
    base = dict(num_layers=2, num_heads=2, head_dim=8, num_pages=12,
                page_size=4, max_seqs=2, pages_per_seq=4,
                dtype=jnp.float32)
    base.update(kw)
    return KVCacheConfig(**base)


def _fill(pools, seed):
    """Deterministic non-trivial content in every pool buffer."""
    rng = np.random.RandomState(seed)
    out = {}
    for k, v in pools.items():
        if jnp.issubdtype(v.dtype, jnp.integer):
            data = rng.randint(-127, 128, v.shape)
        else:
            data = rng.randn(*v.shape)
        out[k] = jnp.asarray(data, v.dtype)
    return out


class TestExportImportRoundTrip:
    @pytest.mark.parametrize("dtype,kv_dtype", [
        (jnp.float32, None),
        (jnp.bfloat16, None),
        (jnp.float32, jnp.int8),
    ], ids=["fp32", "bf16", "int8"])
    def test_bit_identical_across_shuffled_physical_pages(
            self, dtype, kv_dtype):
        cfg = _cfg(dtype=dtype, kv_dtype=kv_dtype)
        src = _fill(init_pools(cfg), seed=1)
        dst = init_pools(cfg)
        # arbitrary, non-contiguous, differently-ordered page ids on
        # each side: content moves, physical layout does not
        src_pages = [7, 2, 9, 4]
        dst_pages = [1, 10, 3, 6]
        staged = export_pages(src, src_pages)
        if kv_dtype is not None:
            # int8 pools move quantized: int8 values + fp32 scales
            assert set(staged) == {"k", "v", "k_scales", "v_scales"}
            assert staged["k"].dtype == np.int8
            assert staged["k_scales"].dtype == np.float32
        assert staged_nbytes(staged) == sum(
            v.nbytes for v in staged.values())
        dst = import_pages(dst, staged, dst_pages)
        for k in src:
            a = np.asarray(src[k][:, src_pages])
            b = np.asarray(dst[k][:, dst_pages])
            assert a.tobytes() == b.tobytes(), f"pool {k!r} not bitwise"

    def test_untouched_destination_pages_stay_untouched(self):
        cfg = _cfg()
        src = _fill(init_pools(cfg), seed=2)
        dst = _fill(init_pools(cfg), seed=3)
        before = {k: np.asarray(v).copy() for k, v in dst.items()}
        dst = import_pages(dst, export_pages(src, [5]), [8])
        others = [p for p in range(cfg.num_pages) if p != 8]
        for k in dst:
            assert np.asarray(dst[k][:, others]).tobytes() == \
                before[k][:, others].tobytes()

    def test_export_is_read_only_on_source(self):
        cfg = _cfg()
        src = _fill(init_pools(cfg), seed=4)
        before = {k: np.asarray(v).copy() for k, v in src.items()}
        export_pages(src, [1, 2, 3])
        for k in src:
            assert np.asarray(src[k]).tobytes() == before[k].tobytes()


class TestHostOffloadPool:
    def _staged(self, cfg, page, seed):
        return export_pages(_fill(init_pools(cfg), seed), [page])

    def test_lru_eviction_drops_coldest(self):
        cfg = _cfg()
        pool = HostOffloadPool(max_pages=2)
        pool.put(b"a", None, self._staged(cfg, 1, 1))
        pool.put(b"b", b"a", self._staged(cfg, 2, 2))
        pool.put(b"c", b"b", self._staged(cfg, 3, 3))   # evicts "a"
        assert b"a" not in pool and len(pool) == 2
        assert pool.stats["lru_evicted"] == 1

    def test_put_refreshes_recency(self):
        cfg = _cfg()
        pool = HostOffloadPool(max_pages=2)
        pool.put(b"a", None, self._staged(cfg, 1, 1))
        pool.put(b"b", b"a", self._staged(cfg, 2, 2))
        pool.put(b"a", None, self._staged(cfg, 1, 1))   # re-warm "a"
        pool.put(b"c", b"b", self._staged(cfg, 3, 3))   # evicts "b"
        assert b"a" in pool and b"b" not in pool

    def test_take_is_move_semantics(self):
        cfg = _cfg()
        pool = HostOffloadPool(max_pages=4)
        staged = self._staged(cfg, 5, 5)
        pool.put(b"h", b"p", staged)
        entry = pool.take(b"h")
        assert entry["parent"] == b"p"
        assert entry["data"]["k"].tobytes() == staged["k"].tobytes()
        assert b"h" not in pool and pool.take(b"h") is None
        assert pool.stats["hits"] == 1 and pool.stats["misses"] == 1


class TestAdoptPrefixPage:
    def test_adopt_guards_and_links_parent(self):
        cfg = _cfg()
        cache = PagedKVCache(cfg)
        p0 = cache.adopt_prefix_page(b"h0", None)
        p1 = cache.adopt_prefix_page(b"h1", b"h0")
        assert p0 != p1 and cache.prefix_index_size == 2
        with pytest.raises(ValueError, match="already"):
            cache.adopt_prefix_page(b"h0", None)
        with pytest.raises(ValueError, match="parent"):
            cache.adopt_prefix_page(b"h2", b"missing")


# ---------------------------------------------------------------------------
# the tiny-GPT disaggregated fleet
# ---------------------------------------------------------------------------

PAGE, NEW, MAXP = 4, 5, 24


@pytest.fixture(scope="module")
def disagg_setup():
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        devices=jax.devices()[:1])
    model = GPTModel(GPTConfig(
        vocab_size=64, num_layers=2, hidden_size=32,
        num_attention_heads=4, max_position_embeddings=64,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))
    params = model.init(jax.random.PRNGKey(5))
    pps = -(-(MAXP + NEW) // PAGE)
    ccfg = KVCacheConfig(
        num_layers=2, num_heads=4, head_dim=8,
        num_pages=1 + 4 * pps, page_size=PAGE, max_seqs=2,
        pages_per_seq=pps, dtype=jnp.float32)
    fns = model.decode_fns(params, mesh, ccfg, max_prompt_len=MAXP,
                           prefill_chunk=4)
    sfns = model.decode_fns(params, mesh, ccfg, max_prompt_len=MAXP,
                            prefill_chunk=4, speculate_k=3)
    yield mesh, model, params, ccfg, fns, sfns
    parallel_state.destroy_model_parallel()


def _replicas(ccfg, fns, n=2, spec=False):
    kw = (dict(spec_fn=fns.spec, speculate_k=3,
               draft_source=NGramDraftSource(3)) if spec else {})
    return [
        Replica(f"r{i}", ContinuousBatcher(
            fns.prefill, fns.decode, PagedKVCache(ccfg),
            init_pools(ccfg), max_prompt_len=MAXP, harvest_every=2,
            chunk_fn=fns.chunk, prefill_chunk=4, prefix_cache=True,
            **kw))
        for i in range(n)
    ]


def _reqs(seeded):
    # repetitive prompts so the n-gram drafter gets real acceptance in
    # the speculative variant; identity must hold regardless
    rng = np.random.RandomState(11)
    reqs = []
    for i, plen in enumerate([12, 9, 11, 12]):
        pat = rng.randint(1, 64, (4,))
        prompt = [int(t) for t in np.tile(pat, 4)[:plen]]
        reqs.append(Request(
            uid=f"u{i}", prompt=prompt, max_new_tokens=NEW,
            seed=100 + i if seeded else None))
    return reqs


def _streams(router):
    return {u: c.tokens for u, c in sorted(router.completions.items())}


class TestRoleValidation:
    def test_unknown_role_rejected(self):
        with pytest.raises(ValueError, match="unknown replica role"):
            Replica("x", object(), role="verify")
        with pytest.raises(ValueError, match="roles"):
            FleetPolicy(roles=("prefill", "verify"))

    def test_one_sided_fleets_rejected(self):
        with pytest.raises(ValueError, match="decode"):
            FleetPolicy(roles=("prefill", "prefill"))
        with pytest.raises(ValueError, match="prefill"):
            FleetPolicy(roles=("decode", "decode"))

    def test_roles_length_must_match_fleet(self, disagg_setup):
        mesh, model, params, ccfg, fns, sfns = disagg_setup
        with pytest.raises(ValueError, match="roles"):
            FleetRouter(_replicas(ccfg, fns, n=2),
                        FleetPolicy(roles=("prefill", "decode",
                                           "unified")))


class TestHandoffIdentity:
    @pytest.mark.parametrize("seeded", [False, True],
                             ids=["greedy", "seeded"])
    def test_disagg_matches_unified(self, disagg_setup, seeded):
        mesh, model, params, ccfg, fns, sfns = disagg_setup

        def run(roles):
            router = FleetRouter(
                _replicas(ccfg, fns),
                FleetPolicy(roles=roles))
            for r in _reqs(seeded):
                assert router.submit(r)
            router.drain()
            return router

        ref = run(None)
        dis = run(("prefill", "decode"))
        assert _streams(dis) == _streams(ref)
        assert dis.stats["handoffs"] >= len(_reqs(seeded))
        assert dis.stats["handoff_pages"] > 0
        assert dis.stats["handoff_bytes"] > 0
        for c in dis.completions.values():
            assert c.handoffs >= 1
            assert c.replays == 0          # pages moved, no recompute
        for c in ref.completions.values():
            assert c.handoffs == 0

    def test_disagg_matches_unified_speculative(self, disagg_setup):
        mesh, model, params, ccfg, fns, sfns = disagg_setup

        def run(roles):
            router = FleetRouter(
                _replicas(ccfg, sfns, spec=True),
                FleetPolicy(roles=roles))
            for r in _reqs(seeded=False):
                assert router.submit(r)
            router.drain()
            return router

        ref = run(None)
        dis = run(("prefill", "decode"))
        assert _streams(dis) == _streams(ref)
        assert dis.stats["handoffs"] >= 1

    def test_prefill_replica_never_decodes(self, disagg_setup):
        mesh, model, params, ccfg, fns, sfns = disagg_setup
        router = FleetRouter(_replicas(ccfg, fns),
                             FleetPolicy(roles=("prefill", "decode")))
        pre, dec = router.replicas
        assert pre.batcher.decode_enabled is False
        assert dec.batcher.decode_enabled is True
        for r in _reqs(seeded=False):
            assert router.submit(r)
        router.drain()
        # every completion was held by the decode replica at the end,
        # and the prefill replica ran no decode steps of its own
        assert all(router.log.get(u).replica == "r1"
                   for u in router.completions)
        assert pre.batcher.steps == 0
        assert dec.batcher.steps > 0


class TestNoDoubleCount:
    def test_staged_packet_charges_destination_only(self, disagg_setup):
        mesh, model, params, ccfg, fns, sfns = disagg_setup
        router = FleetRouter(_replicas(ccfg, fns),
                             FleetPolicy(roles=("prefill", "decode")))
        pre, dec = router.replicas
        # hold the import: packets stage but cannot land
        real_import = dec.batcher.import_request
        dec.batcher.import_request = lambda pk: False
        assert router.submit(_reqs(seeded=False)[0])
        for _ in range(40):
            router.step()
            if router._handoffs:
                break
        assert len(router._handoffs) == 1
        pk = router._handoffs[0]
        assert pk["src"] == "r0" and pk["dst"] == "r1"
        # the source released the slot at export; only the destination
        # carries the request, via the in-flight-inbound load term
        assert pre.batcher.live_slots == 0
        assert router._inbound("r1") == 1 and router._inbound("r0") == 0
        # the packet is worth exactly one slot of load on the
        # destination and nothing on the source (other load terms —
        # free pages, queues — are per-replica and unaffected)
        with_pk = router._load(dec), router._load(pre)
        staged, router._handoffs = router._handoffs, []
        without = router._load(dec), router._load(pre)
        router._handoffs = staged
        assert with_pk[0] - without[0] == pytest.approx(
            router.policy.w_slots)
        assert with_pk[1] == without[1]
        # release the import: the packet lands and the stream finishes
        dec.batcher.import_request = real_import
        router.drain()
        assert router.completions["u0"].handoffs == 1

    def test_staging_bounded_by_destination_slots(self, disagg_setup):
        mesh, model, params, ccfg, fns, sfns = disagg_setup
        router = FleetRouter(_replicas(ccfg, fns),
                             FleetPolicy(roles=("prefill", "decode")))
        dec = router.replicas[1]
        dec.batcher.import_request = lambda pk: False
        for r in _reqs(seeded=False):
            assert router.submit(r)
        for _ in range(60):
            router.step()
        max_seqs = dec.batcher.cache.config.max_seqs
        assert 0 < len(router._handoffs) <= max_seqs


class TestOffloadTier:
    def test_offload_faultin_bit_identical_under_pressure(
            self, disagg_setup):
        mesh, model, params, ccfg, fns, sfns = disagg_setup
        # a pool too small to hold three prompts' prefix pages: serving
        # C must evict A's index-only pages — into the offload tier
        tight = KVCacheConfig(
            num_layers=2, num_heads=4, head_dim=8, num_pages=9,
            page_size=PAGE, max_seqs=2, pages_per_seq=4,
            dtype=jnp.float32)
        tfns = model.decode_fns(params, mesh, tight,
                                max_prompt_len=MAXP, prefill_chunk=4)

        def batcher(off):
            return ContinuousBatcher(
                tfns.prefill, tfns.decode, PagedKVCache(tight),
                init_pools(tight), max_prompt_len=MAXP,
                harvest_every=2, chunk_fn=tfns.chunk, prefill_chunk=4,
                prefix_cache=True, offload=off)

        pA = list(range(1, 13))
        off = HostOffloadPool(max_pages=16)
        b = batcher(off)
        r1 = b.run([Request(uid="a1", prompt=pA, max_new_tokens=4,
                            seed=3)])["a1"]
        # churn: two more 12-token prompts push A's pages out
        b.run([Request(uid="b1", prompt=list(range(30, 42)),
                       max_new_tokens=4, seed=4)])
        b.run([Request(uid="c1", prompt=list(range(50, 62)),
                       max_new_tokens=4, seed=5)])
        assert off.stats["offloaded"] > 0
        assert off.stats["bytes_in"] > 0
        r2 = b.run([Request(uid="a2", prompt=pA, max_new_tokens=4,
                            seed=3)])["a2"]
        assert off.stats["faulted"] > 0
        assert off.stats["bytes_out"] > 0
        # the resumed stream must match BOTH the original serve and a
        # cold recompute on a fresh batcher — bit-identical fault-in
        ref = batcher(None).run(
            [Request(uid="a2", prompt=pA, max_new_tokens=4,
                     seed=3)])["a2"]
        assert r2.tokens == ref.tokens == r1.tokens

    def test_offload_requires_prefix_cache(self, disagg_setup):
        mesh, model, params, ccfg, fns, sfns = disagg_setup
        with pytest.raises(ValueError, match="prefix_cache"):
            ContinuousBatcher(
                fns.prefill, fns.decode, PagedKVCache(ccfg),
                init_pools(ccfg), max_prompt_len=MAXP,
                offload=HostOffloadPool(max_pages=4))


class TestMidHandoffKill:
    def test_prefill_dies_zero_loss_token_identical(
            self, disagg_setup, tmp_path):
        mesh, model, params, ccfg, fns, sfns = disagg_setup
        reqs = _reqs(seeded=True)

        def run(fail):
            jr = RequestJournal(str(tmp_path / f"j_{fail}.jsonl"))
            router = FleetRouter(
                _replicas(ccfg, fns),
                FleetPolicy(roles=("prefill", "decode")),
                journal=jr)
            if fail:
                router.replicas[0].fail_after(2)
            for r in reqs:
                assert router.submit(r)
            router.drain()
            jr.close()
            return router

        ref = run(fail=False)
        drill = run(fail=True)
        assert not drill.replicas[0].alive
        # zero loss: every uid completed, streams token-identical
        assert sorted(drill.completions) == sorted(
            r.uid for r in reqs)
        assert _streams(drill) == _streams(ref)
        # the survivor (decode role) finished everything — role
        # fallback or migration, but never a dropped request
        assert drill.log.pending() == 0

    def test_process_death_recovers_via_journal(self, disagg_setup,
                                                tmp_path):
        mesh, model, params, ccfg, fns, sfns = disagg_setup
        path = str(tmp_path / "crash.jsonl")
        reqs = _reqs(seeded=True)

        ref = FleetRouter(_replicas(ccfg, fns),
                          FleetPolicy(roles=("prefill", "decode")))
        for r in reqs:
            assert ref.submit(r)
        ref.drain()

        jr = RequestJournal(path)
        router = FleetRouter(
            _replicas(ccfg, fns),
            FleetPolicy(roles=("prefill", "decode")), journal=jr)
        for r in reqs:
            assert router.submit(r)
        # a few steps: handoffs happen, nothing finishes draining —
        # then the "process" dies with packets possibly in flight
        for _ in range(6):
            router.step()
        jr.close()
        del router

        recovery = recover_journal(path)
        assert len(recovery.entries) == len(reqs)
        jr2 = RequestJournal(path)
        restarted = FleetRouter(
            _replicas(ccfg, fns),
            FleetPolicy(roles=("prefill", "decode")), journal=jr2)
        out = restarted.resume_from_journal(recovery)
        assert out["corrupt"] == 0
        restarted.drain()
        jr2.close()
        assert _streams(restarted) == _streams(ref)

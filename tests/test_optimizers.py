"""Fused optimizer tests vs torch.optim reference math
(reference analog: tests/L0/run_optimizers/test_fused_optimizer.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.optimizers import (
    LARC,
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedMixedPrecisionLamb,
    FusedNovoGrad,
    FusedSGD,
)


def _torch_params(np_params):
    out = []
    for p in np_params:
        t = torch.tensor(p, dtype=torch.float32, requires_grad=True)
        out.append(t)
    return out


def _run_jax(opt, np_params, np_grads_seq, lr=None):
    params = {f"p{i}": jnp.asarray(p) for i, p in enumerate(np_params)}
    state = opt.init(params)
    step = jax.jit(lambda s, g, p: opt.step(s, g, p))
    for np_grads in np_grads_seq:
        grads = {f"p{i}": jnp.asarray(g) for i, g in enumerate(np_grads)}
        params, state = step(state, grads, params)
    return [np.asarray(params[f"p{i}"]) for i in range(len(np_params))]


def _run_torch(topt_ctor, np_params, np_grads_seq):
    tparams = _torch_params(np_params)
    topt = topt_ctor(tparams)
    for np_grads in np_grads_seq:
        for t, g in zip(tparams, np_grads):
            t.grad = torch.tensor(g, dtype=torch.float32)
        topt.step()
    return [t.detach().numpy() for t in tparams]


def _random_problem(seed=0, steps=5):
    rng = np.random.RandomState(seed)
    np_params = [
        rng.randn(7, 5).astype(np.float32),
        rng.randn(11).astype(np.float32),
    ]
    grads_seq = [
        [rng.randn(*p.shape).astype(np.float32) for p in np_params]
        for _ in range(steps)
    ]
    return np_params, grads_seq


class TestFusedAdam:
    @pytest.mark.parametrize("adam_w_mode", [True, False])
    @pytest.mark.parametrize("weight_decay", [0.0, 0.1])
    def test_vs_torch(self, adam_w_mode, weight_decay):
        np_params, grads_seq = _random_problem()
        ours = _run_jax(
            FusedAdam(
                lr=1e-2, weight_decay=weight_decay, adam_w_mode=adam_w_mode
            ),
            np_params,
            grads_seq,
        )
        ctor = (
            (lambda ps: torch.optim.AdamW(ps, lr=1e-2, weight_decay=weight_decay))
            if adam_w_mode
            else (lambda ps: torch.optim.Adam(ps, lr=1e-2, weight_decay=weight_decay))
        )
        theirs = _run_torch(ctor, np_params, grads_seq)
        for a, b in zip(ours, theirs):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)

    def test_skip_step_on_overflow(self):
        opt = FusedAdam(lr=0.1)
        params = {"w": jnp.ones((3,))}
        state = opt.init(params)
        grads = {"w": jnp.full((3,), jnp.nan)}
        new_params, new_state = opt.step(
            state, grads, params, grads_finite=jnp.bool_(False)
        )
        np.testing.assert_allclose(new_params["w"], 1.0)
        assert int(new_state["step"]) == 0

    def test_master_weights_precision(self):
        # bf16 params with fp32 masters should track fp32 training closely
        opt_master = FusedAdam(lr=1e-2, master_weights=True)
        opt_plain = FusedAdam(lr=1e-2)
        rng = np.random.RandomState(1)
        w0 = rng.randn(64).astype(np.float32)
        gseq = [rng.randn(64).astype(np.float32) * 0.01 for _ in range(50)]

        pm = {"w": jnp.asarray(w0, jnp.bfloat16)}
        sm = opt_master.init(pm)
        pf = {"w": jnp.asarray(w0)}
        sf = opt_plain.init(pf)
        for g in gseq:
            pm, sm = opt_master.step(sm, {"w": jnp.asarray(g, jnp.bfloat16)}, pm)
            pf, sf = opt_plain.step(sf, {"w": jnp.asarray(g)}, pf)
        master = np.asarray(sm["master"]["w"])
        full = np.asarray(pf["w"])
        # the master starts from bf16-quantized weights, so that rounding is
        # the noise floor; what master weights buy is *no accumulating drift*
        # beyond it even though the model copy and grads are bf16
        init_err = np.max(np.abs(np.asarray(jnp.asarray(w0, jnp.bfloat16), np.float32) - w0))
        assert np.max(np.abs(master - full)) < init_err + 5e-3


class TestFusedSGD:
    @pytest.mark.parametrize("momentum,nesterov,wd", [
        (0.0, False, 0.0),
        (0.9, False, 0.0),
        (0.9, True, 0.0),
        (0.9, False, 0.05),
    ])
    def test_vs_torch(self, momentum, nesterov, wd):
        np_params, grads_seq = _random_problem(seed=2)
        ours = _run_jax(
            FusedSGD(lr=0.05, momentum=momentum, nesterov=nesterov,
                     weight_decay=wd),
            np_params,
            grads_seq,
        )
        theirs = _run_torch(
            lambda ps: torch.optim.SGD(
                ps, lr=0.05, momentum=momentum, nesterov=nesterov,
                weight_decay=wd,
            ),
            np_params,
            grads_seq,
        )
        for a, b in zip(ours, theirs):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)


class TestFusedAdagrad:
    def test_vs_torch(self):
        np_params, grads_seq = _random_problem(seed=3)
        ours = _run_jax(FusedAdagrad(lr=0.05, eps=1e-10), np_params, grads_seq)
        theirs = _run_torch(
            lambda ps: torch.optim.Adagrad(ps, lr=0.05, eps=1e-10),
            np_params,
            grads_seq,
        )
        for a, b in zip(ours, theirs):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)


class TestFusedLAMB:
    def test_decreases_loss(self):
        # analytic fixture: quadratic loss, LAMB should descend
        # note: LAMB's trust ratio makes steps proportional to ||p||, so a
        # near-zero init moves slowly by design — start from a nonzero point
        opt = FusedLAMB(lr=0.1, weight_decay=0.01)
        target = jnp.asarray(np.linspace(-1, 1, 32).astype(np.float32))
        params = {"w": jnp.full((32,), 0.5)}
        state = opt.init(params)

        def loss_fn(p):
            return jnp.sum(jnp.square(p["w"] - target))

        losses = []
        for _ in range(60):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, state = opt.step(state, grads, params)
            losses.append(float(loss))
        assert losses[-1] < 0.2 * losses[0]

    def test_trust_ratio_scales_update(self):
        # with weight_decay>0 the update magnitude is ~ lr * ||p|| per layer
        opt = FusedLAMB(lr=0.1, weight_decay=0.01, max_grad_norm=None)
        rng = np.random.RandomState(0)
        big = rng.randn(16).astype(np.float32) * 100.0
        small = rng.randn(16).astype(np.float32) * 0.01
        params = {"big": jnp.asarray(big), "small": jnp.asarray(small)}
        g = {"big": jnp.asarray(rng.randn(16).astype(np.float32)),
             "small": jnp.asarray(rng.randn(16).astype(np.float32))}
        state = opt.init(params)
        new_params, _ = opt.step(state, g, params)
        delta_big = np.linalg.norm(np.asarray(new_params["big"]) - big)
        delta_small = np.linalg.norm(np.asarray(new_params["small"]) - small)
        norm_big = np.linalg.norm(big)
        norm_small = np.linalg.norm(small)
        # both deltas should be ≈ lr * ||p||
        assert abs(delta_big / norm_big - 0.1) < 0.02
        assert abs(delta_small / norm_small - 0.1) < 0.02

    def test_grad_clipping(self):
        opt = FusedLAMB(lr=0.01, max_grad_norm=1.0)
        params = {"w": jnp.ones((4,))}
        state = opt.init(params)
        huge = {"w": jnp.full((4,), 1e6)}
        new_params, _ = opt.step(state, huge, params)
        assert np.all(np.isfinite(np.asarray(new_params["w"])))

    @pytest.mark.parametrize("use_nvlamb", [False, True])
    @pytest.mark.parametrize("adam_w_mode,wd", [
        (True, 0.1),
        (False, 0.1),
        (True, 0.0),   # wd=0: trust ratio applies only under nvlamb
    ])
    def test_decay_modes_vs_numpy(self, adam_w_mode, wd, use_nvlamb):
        # NumPy transliteration of the reference kernel's two decay modes
        # (multi_tensor_lamb.cu): MOMENT_MODE_0 folds wd*p into the gradient
        # *before* the moment updates; MOMENT_MODE_1 (AdamW) adds wd*p to the
        # final update. The two diverge after the first step because the
        # moments see different gradients.
        lr, b1, b2, eps, clip_norm = 0.02, 0.9, 0.999, 1e-6, 1.0
        np_params, grads_seq = _random_problem(seed=7, steps=4)

        ref = [p.copy() for p in np_params]
        ms = [np.zeros_like(p) for p in np_params]
        vs = [np.zeros_like(p) for p in np_params]
        for step, grads in enumerate(grads_seq, start=1):
            gnorm = np.sqrt(sum(np.sum(g.astype(np.float64) ** 2) for g in grads))
            scale = clip_norm / gnorm if gnorm > clip_norm else 1.0
            bc1 = 1.0 - b1**step
            bc2 = 1.0 - b2**step
            for i, g in enumerate(grads):
                g = g * scale
                if not adam_w_mode and wd != 0.0:
                    g = g + wd * ref[i]
                ms[i] = b1 * ms[i] + (1.0 - b1) * g
                vs[i] = b2 * vs[i] + (1.0 - b2) * g * g
                update = (ms[i] / bc1) / (np.sqrt(vs[i] / bc2) + eps)
                if adam_w_mode and wd != 0.0:
                    update = update + wd * ref[i]
                if wd == 0.0 and not use_nvlamb:
                    trust = 1.0
                else:
                    w_norm = np.linalg.norm(ref[i])
                    u_norm = np.linalg.norm(update)
                    trust = w_norm / u_norm if (w_norm > 0 and u_norm > 0) else 1.0
                ref[i] = ref[i] - lr * trust * update

        ours = _run_jax(
            FusedLAMB(
                lr=lr, weight_decay=wd, adam_w_mode=adam_w_mode,
                use_nvlamb=use_nvlamb, max_grad_norm=clip_norm, eps=eps,
            ),
            np_params,
            grads_seq,
        )
        for a, b in zip(ours, ref):
            np.testing.assert_allclose(a, b.astype(np.float32), rtol=2e-4, atol=1e-5)

    def test_decay_modes_diverge(self):
        # guards against the two branches silently collapsing into one
        np_params, grads_seq = _random_problem(seed=8, steps=3)
        a = _run_jax(FusedLAMB(lr=0.02, weight_decay=0.1, adam_w_mode=True),
                     np_params, grads_seq)
        b = _run_jax(FusedLAMB(lr=0.02, weight_decay=0.1, adam_w_mode=False),
                     np_params, grads_seq)
        assert any(np.max(np.abs(x - y)) > 1e-5 for x, y in zip(a, b))


class TestFusedNovoGrad:
    def test_decreases_loss(self):
        # NovoGrad normalizes each tensor's grad by its norm, so per-step
        # movement is ~lr — size the fixture accordingly
        opt = FusedNovoGrad(lr=0.1)
        target = jnp.asarray(np.ones(16, np.float32))
        params = {"w": jnp.zeros((16,))}
        state = opt.init(params)

        def loss_fn(p):
            return jnp.sum(jnp.square(p["w"] - target))

        first = None
        for _ in range(150):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            if first is None:
                first = float(loss)
            params, state = opt.step(state, grads, params)
        assert float(loss_fn(params)) < 0.05 * first

    def test_second_moment_is_scalar_per_tensor(self):
        opt = FusedNovoGrad(lr=0.01)
        params = {"w": jnp.ones((4, 4))}
        state = opt.init(params)
        g = {"w": jnp.full((4, 4), 2.0)}
        _, state = opt.step(state, g, params)
        assert np.asarray(state["exp_avg_sq"]["w"]).shape == ()
        # first step: v = ||g||^2 = 4*16 = 64
        np.testing.assert_allclose(float(state["exp_avg_sq"]["w"]), 64.0)


class TestLARC:
    def test_clip_reduces_effective_lr(self):
        base = FusedSGD(lr=1.0)
        larc = LARC(base, trust_coefficient=0.001)
        params = {"w": jnp.ones((4,))}
        state = larc.init(params)
        g = {"w": jnp.ones((4,))}
        new_params, _ = larc.step(state, g, params)
        # local_lr = 0.001*||p||/||g|| = 0.001 << lr=1 → clipped
        delta = np.abs(np.asarray(new_params["w"]) - 1.0)
        np.testing.assert_allclose(delta, 0.001, rtol=1e-4)


class TestMixedPrecisionLamb:
    def test_has_master(self):
        opt = FusedMixedPrecisionLamb(lr=0.01)
        params = {"w": jnp.ones((8,), jnp.bfloat16)}
        state = opt.init(params)
        assert state["master"]["w"].dtype == jnp.float32
        g = {"w": jnp.ones((8,), jnp.bfloat16)}
        new_params, state = opt.step(state, g, params)
        assert new_params["w"].dtype == jnp.bfloat16

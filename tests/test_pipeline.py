"""Pipeline-parallel schedule tests on the 8-device virtual CPU mesh.

Philosophy (SURVEY.md §4): the reference tests its schedules with a tiny
linear model and analytic/serial expectations
(tests/L0/run_transformer/run_pipeline_parallel_test.py); here the
compiled pp=4 pipeline (and its autodiff backward) is compared against
the identical serial computation on one device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
    forward_backward_no_pipelining,
    get_forward_backward_func,
    pipeline,
    pipeline_stage_specs,
)

NUM_LAYERS = 4
HIDDEN = 16
MICRO = 8  # microbatches
MB = 2     # rows per microbatch (per dp shard)


def make_params(key):
    """Stacked dense layers: (L, h, h) weights + (L, h) biases."""
    kw, kb = jax.random.split(key)
    return {
        "w": 0.3 * jax.random.normal(kw, (NUM_LAYERS, HIDDEN, HIDDEN)),
        "b": 0.01 * jax.random.normal(kb, (NUM_LAYERS, HIDDEN)),
    }


def serial_loss(params, x, y):
    """Dense single-device reference: all layers, full batch, MSE."""
    h = x
    for l in range(NUM_LAYERS):
        h = jnp.tanh(h @ params["w"][l] + params["b"][l])
    return jnp.mean((h - y) ** 2)


def _stage_scan(local_params, x):
    def body(h, lp):
        return jnp.tanh(h @ lp["w"] + lp["b"]), None

    out, _ = jax.lax.scan(body, x, local_params)
    return out


@pytest.mark.parametrize("remat", [False, True])
def test_pipeline_matches_serial(remat):
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4
    )
    try:
        params = make_params(jax.random.PRNGKey(0))
        layer_specs = {"w": P(None, None, None), "b": P(None, None)}
        stage_specs = pipeline_stage_specs(layer_specs)
        dp = mesh.shape["dp"]
        x = jax.random.normal(jax.random.PRNGKey(1), (MICRO * MB * dp, HIDDEN))
        y = jax.random.normal(jax.random.PRNGKey(2), (MICRO * MB * dp, HIDDEN))

        def pp_loss(params, x, y):
            # local dp shard → microbatches
            mbs = {
                "x": x.reshape(MICRO, MB, HIDDEN),
                "y": y.reshape(MICRO, MB, HIDDEN),
            }
            per_micro = pipeline(
                first_fn=lambda mb: mb["x"],
                stage_fn=lambda h: _stage_scan(params, h),
                last_fn=lambda h, mb: jnp.mean((h - mb["y"]) ** 2),
                microbatches=mbs,
                remat=remat,
            )
            return jax.lax.pmean(jnp.mean(per_micro), "dp")

        grad_fn = jax.jit(
            jax.shard_map(
                jax.value_and_grad(pp_loss),
                mesh=mesh,
                in_specs=(stage_specs, P("dp"), P("dp")),
                out_specs=(P(), stage_specs),
            )
        )
        placed = jax.device_put(
            params,
            jax.tree.map(lambda s: NamedSharding(mesh, s), stage_specs,
                         is_leaf=lambda x: isinstance(x, P)),
        )
        loss, grads = grad_fn(placed, x, y)

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(params, x, y)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )
    finally:
        parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("num_chunks", [2, 4])
def test_interleaved_pipeline_matches_serial(num_chunks):
    """pp=4 x V chunks circular schedule == serial dense math, fwd+grads.
    Layers are assigned chunk-major: chunk v holds layers
    [v*pp*Lc + p*Lc, ...) — i.e. the stacked dim is reshaped
    (V, pp, Lc) so global stage v*pp+p gets its contiguous slice."""
    pp = 4
    per_chunk = 2 if num_chunks == 2 else 1
    NUM_L = pp * num_chunks * per_chunk
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4
    )
    try:
        kw, kb = jax.random.split(jax.random.PRNGKey(0))
        params = {
            "w": 0.3 * jax.random.normal(kw, (NUM_L, HIDDEN, HIDDEN)),
            "b": 0.01 * jax.random.normal(kb, (NUM_L, HIDDEN)),
        }

        def serial(params, x, y):
            h = x
            for l in range(NUM_L):
                h = jnp.tanh(h @ params["w"][l] + params["b"][l])
            return jnp.mean((h - y) ** 2)

        # chunk-major layout: (L,) → (V, pp, per_chunk) → shard dim 1
        def to_stages(p):
            return jax.tree.map(
                lambda a: a.reshape(
                    (num_chunks, pp, per_chunk) + a.shape[1:]
                ),
                p,
            )

        stage_specs = {
            "w": P(None, "pp", None, None, None),
            "b": P(None, "pp", None, None),
        }
        dp = mesh.shape["dp"]
        x = jax.random.normal(jax.random.PRNGKey(1), (MICRO * MB * dp, HIDDEN))
        y = jax.random.normal(jax.random.PRNGKey(2), (MICRO * MB * dp, HIDDEN))

        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving,
        )

        def pp_loss(sp, x, y):
            # sp leaves: (V, 1, per_chunk, ...) local → (V, per_chunk, ...)
            sp = jax.tree.map(lambda a: a[:, 0], sp)
            mbs = {
                "x": x.reshape(MICRO, MB, HIDDEN),
                "y": y.reshape(MICRO, MB, HIDDEN),
            }

            def chunk_fn(h, v):
                lp = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, v, 0, keepdims=False
                    ),
                    sp,
                )
                return _stage_scan(lp, h)

            per_micro = forward_backward_pipelining_with_interleaving(
                first_fn=lambda mb: mb["x"],
                chunk_fn=chunk_fn,
                last_fn=lambda h, mb: jnp.mean((h - mb["y"]) ** 2),
                microbatches=mbs,
                num_model_chunks=num_chunks,
            )
            return jax.lax.pmean(jnp.mean(per_micro), "dp")

        grad_fn = jax.jit(
            jax.shard_map(
                jax.value_and_grad(pp_loss),
                mesh=mesh,
                in_specs=(stage_specs, P("dp"), P("dp")),
                out_specs=(P(), stage_specs),
            )
        )
        staged = to_stages(params)
        placed = jax.device_put(
            staged,
            jax.tree.map(lambda s: NamedSharding(mesh, s), stage_specs,
                         is_leaf=lambda x: isinstance(x, P)),
        )
        loss, grads = grad_fn(placed, x, y)
        ref_loss, ref_grads = jax.value_and_grad(serial)(params, x, y)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        got = jax.tree.map(
            lambda a: np.asarray(a).reshape((NUM_L,) + a.shape[3:]),
            jax.device_get(grads),
        )
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(a, np.asarray(b), rtol=1e-4,
                                       atol=1e-6)
    finally:
        parallel_state.destroy_model_parallel()


def test_interleaved_requires_divisible_microbatches():
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4
    )
    try:
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving,
        )

        def run(x):
            return forward_backward_pipelining_with_interleaving(
                first_fn=lambda mb: mb,
                chunk_fn=lambda h, v: h,
                last_fn=lambda h, mb: jnp.mean(h),
                microbatches=x,
                num_model_chunks=2,
            )

        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(
                jax.shard_map(
                    run, mesh=mesh, in_specs=(P(),), out_specs=P()
                )
            )(jnp.ones((6, 2, HIDDEN)))  # 6 % 4 != 0
    finally:
        parallel_state.destroy_model_parallel()


def test_no_pipelining_matches_serial():
    mesh = parallel_state.initialize_model_parallel()
    try:
        params = make_params(jax.random.PRNGKey(0))
        dp = mesh.shape["dp"]
        x = jax.random.normal(jax.random.PRNGKey(1), (MICRO * MB * dp, HIDDEN))
        y = jax.random.normal(jax.random.PRNGKey(2), (MICRO * MB * dp, HIDDEN))

        def loss_fn(params, x, y):
            mbs = {
                "x": x.reshape(MICRO, MB, HIDDEN),
                "y": y.reshape(MICRO, MB, HIDDEN),
            }
            per_micro = forward_backward_no_pipelining(
                first_fn=lambda mb: mb["x"],
                stage_fn=lambda h: _stage_scan(params, h),
                last_fn=lambda h, mb: jnp.mean((h - mb["y"]) ** 2),
                microbatches=mbs,
            )
            return jax.lax.pmean(jnp.mean(per_micro), "dp")

        specs = {"w": P(), "b": P()}
        grad_fn = jax.jit(
            jax.shard_map(
                jax.value_and_grad(loss_fn),
                mesh=mesh,
                in_specs=(specs, P("dp"), P("dp")),
                out_specs=(P(), specs),
            )
        )
        loss, grads = grad_fn(params, x, y)
        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(params, x, y)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_get_forward_backward_func_dispatch():
    # pp>1 dispatches the 1F1B family, never the forward-only schedules
    assert (
        get_forward_backward_func(None, 4)
        is not forward_backward_no_pipelining
    )
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        _fwd_bwd_no_pipelining,
    )

    assert get_forward_backward_func(None, 1) is _fwd_bwd_no_pipelining


class TestMicrobatchCalculators:
    def test_constant(self):
        calc = build_num_microbatches_calculator(64, 4, 2)
        assert isinstance(calc, ConstantNumMicroBatches)
        assert calc.get() == 8
        assert calc.get_current_global_batch_size() == 64
        calc.update(10_000)
        assert calc.get() == 8

    def test_constant_indivisible_raises(self):
        with pytest.raises(ValueError):
            ConstantNumMicroBatches(10, 4, 2)

    def test_rampup(self):
        calc = build_num_microbatches_calculator(
            64, 4, 2, rampup_batch_size=[8, 8, 700]
        )
        assert isinstance(calc, RampupBatchsizeNumMicroBatches)
        assert calc.get_current_global_batch_size() == 8
        assert calc.get() == 1
        calc.update(100)  # one increment per 100 samples
        assert calc.get_current_global_batch_size() == 16
        calc.update(700)
        assert calc.get_current_global_batch_size() == 64
        calc.update(10_000)
        assert calc.get_current_global_batch_size() == 64
        assert calc.get() == 8

    def test_rampup_bad_increment(self):
        with pytest.raises(ValueError):
            build_num_microbatches_calculator(
                64, 4, 2, rampup_batch_size=[8, 9, 700]
            )


def test_lm_head_runs_once_per_microbatch():
    """The pipeline exit (head + loss) must execute exactly num_micro
    times per device, not once per tick (VERDICT r2 weak #4: the old
    schedule paid (num_micro+pp-1) head applications).  Executions are
    counted with a host callback on the virtual mesh."""
    pp_size = 4
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp_size
    )
    try:
        params = make_params(jax.random.PRNGKey(0))
        stage_specs = pipeline_stage_specs(
            {"w": P(None, None, None), "b": P(None, None)}
        )
        x = jnp.ones((MICRO, MB, HIDDEN))
        count = [0]

        def cb():
            count[0] += 1
            return jnp.int32(0)

        def loss(params, x):
            def last_fn(h, mb):
                tok = jax.experimental.io_callback(
                    cb, jax.ShapeDtypeStruct((), jnp.int32)
                )
                return jnp.sum(h) + 0.0 * tok

            return jnp.mean(pipeline(
                first_fn=lambda mb: mb,
                stage_fn=lambda h: _stage_scan(params, h),
                last_fn=last_fn,
                microbatches=x,
                remat=False,
            ))

        f = jax.jit(jax.shard_map(
            loss, mesh=mesh, in_specs=(stage_specs, P()), out_specs=P()
        ))
        jax.block_until_ready(f(params, x))
        n_dev = len(mesh.devices.flatten())
        per_device = count[0] / n_dev
        assert per_device == MICRO, (
            f"head executed {per_device}x per device, expected {MICRO} "
            f"(old tax: {MICRO + pp_size - 1})"
        )
    finally:
        parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("micro", [1, 2, 4, 8])
def test_1f1b_matches_serial(micro):
    """True 1F1B (fwd/bwd interleaved in one scan, O(pp) activation
    state) == serial dense math, losses and grads (reference:
    fwd_bwd_pipelining_without_interleaving.py:112-149 steady state).
    micro < pp (1, 2) exercises the pure-bubble regime."""
    from apex_tpu.transformer.pipeline_parallel import pipeline_1f1b

    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4
    )
    try:
        params = make_params(jax.random.PRNGKey(0))
        layer_specs = {"w": P(None, None, None), "b": P(None, None)}
        stage_specs = pipeline_stage_specs(layer_specs)
        dp = mesh.shape["dp"]
        x = jax.random.normal(jax.random.PRNGKey(1), (micro * MB * dp, HIDDEN))
        y = jax.random.normal(jax.random.PRNGKey(2), (micro * MB * dp, HIDDEN))

        def fb(params, x, y):
            mbs = {
                "x": x.reshape(micro, MB, HIDDEN),
                "y": y.reshape(micro, MB, HIDDEN),
            }
            losses, grads = pipeline_1f1b(
                first_fn=lambda prm, mb: mb["x"],
                stage_fn=lambda prm, h: _stage_scan(prm, h),
                last_fn=lambda prm, h, mb: jnp.mean((h - mb["y"]) ** 2),
                params=params,
                microbatches=mbs,
            )
            # mean over microbatches and dp, like the GPipe-path test
            loss = jax.lax.pmean(jnp.mean(losses), "dp")
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            return loss, grads

        fb_fn = jax.jit(
            jax.shard_map(
                fb, mesh=mesh,
                in_specs=(stage_specs, P("dp"), P("dp")),
                out_specs=(P(), stage_specs),
            )
        )
        placed = jax.device_put(
            params,
            jax.tree.map(lambda s: NamedSharding(mesh, s), stage_specs,
                         is_leaf=lambda x: isinstance(x, P)),
        )
        loss, grads = fb_fn(placed, x, y)

        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(params, x, y)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )
    finally:
        parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("V,micro", [(2, 4), (2, 8), (3, 4), (3, 8)])
def test_1f1b_interleaved_matches_serial(V, micro):
    """Interleaved 1F1B (V chunks/rank, fwd+bwd in one scan, O(pp·V)
    activation state) == serial dense math, losses and grads
    (reference: fwd_bwd_pipelining_with_interleaving.py:22-308).
    micro ∈ {pp, 2pp} covers the minimum and a multi-group schedule."""
    from apex_tpu.transformer.pipeline_parallel import (
        pipeline_1f1b_interleaved,
    )

    pp_size = 4
    L = V * pp_size  # one layer per (chunk, rank) global stage
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=pp_size
    )
    try:
        kw, kb = jax.random.split(jax.random.PRNGKey(0))
        params = {
            "w": 0.3 * jax.random.normal(kw, (V, pp_size, HIDDEN, HIDDEN)),
            "b": 0.01 * jax.random.normal(kb, (V, pp_size, HIDDEN)),
        }
        # chunk v of rank p is global stage v*pp + p → shard axis 1
        stage_specs = {"w": P(None, "pp", None, None), "b": P(None, "pp", None)}
        dp = mesh.shape["dp"]
        x = jax.random.normal(jax.random.PRNGKey(1), (micro * MB * dp, HIDDEN))
        y = jax.random.normal(jax.random.PRNGKey(2), (micro * MB * dp, HIDDEN))

        def serial(params, x, y):
            h = x
            for v in range(V):
                for p in range(pp_size):
                    h = jnp.tanh(h @ params["w"][v, p] + params["b"][v, p])
            return jnp.mean((h - y) ** 2)

        def fb(params, x, y):
            mbs = {
                "x": x.reshape(micro, MB, HIDDEN),
                "y": y.reshape(micro, MB, HIDDEN),
            }

            def chunk_fn(prm, h, v):
                w = jax.lax.dynamic_index_in_dim(prm["w"], v, 0, False)[0]
                b = jax.lax.dynamic_index_in_dim(prm["b"], v, 0, False)[0]
                return jnp.tanh(h @ w + b)

            losses, grads = pipeline_1f1b_interleaved(
                first_fn=lambda prm, mb: mb["x"],
                chunk_fn=chunk_fn,
                last_fn=lambda prm, h, mb: jnp.mean((h - mb["y"]) ** 2),
                params=params,
                microbatches=mbs,
                num_model_chunks=V,
            )
            loss = jax.lax.pmean(jnp.mean(losses), "dp")
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            return loss, grads

        fb_fn = jax.jit(
            jax.shard_map(
                fb, mesh=mesh,
                in_specs=(stage_specs, P("dp"), P("dp")),
                out_specs=(P(), stage_specs),
            )
        )
        placed = jax.device_put(
            params,
            jax.tree.map(lambda s: NamedSharding(mesh, s), stage_specs,
                         is_leaf=lambda x: isinstance(x, P)),
        )
        loss, grads = fb_fn(placed, x, y)

        ref_loss, ref_grads = jax.value_and_grad(serial)(params, x, y)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_1f1b_interleaved_rejects_indivisible_micro():
    from apex_tpu.transformer.pipeline_parallel import (
        pipeline_1f1b_interleaved,
    )

    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4
    )
    try:
        params = {"w": jnp.zeros((2, 4, HIDDEN, HIDDEN))}
        with pytest.raises(ValueError, match="not divisible"):
            jax.shard_map(
                lambda prm, mbs: pipeline_1f1b_interleaved(
                    lambda p_, m: m, lambda p_, h, v: h,
                    lambda p_, h, m: jnp.sum(h),
                    prm, mbs, num_model_chunks=2,
                ),
                mesh=mesh,
                in_specs=({"w": P(None, "pp", None, None)}, P()),
                out_specs=(P(), {"w": P(None, "pp", None, None)}),
            )(params, jnp.ones((6, MB, HIDDEN)))
    finally:
        parallel_state.destroy_model_parallel()


def test_dispatcher_returns_1f1b_family():
    """get_forward_backward_func hands out the production fwd+bwd
    schedules — 1F1B for pp>1, interleaved 1F1B with virtual stages,
    the sequential (losses, grads) wrapper for pp=1 (reference:
    schedules/__init__.py:1-39 always returns a forward-backward
    function; VERDICT r3 missing #2)."""
    import functools

    from apex_tpu.transformer.pipeline_parallel import (
        get_forward_backward_func,
        pipeline_1f1b,
        pipeline_1f1b_interleaved,
    )

    fn = get_forward_backward_func(pipeline_model_parallel_size=4)
    assert fn is pipeline_1f1b
    fn = get_forward_backward_func(
        virtual_pipeline_model_parallel_size=2,
        pipeline_model_parallel_size=4,
    )
    assert isinstance(fn, functools.partial)
    assert fn.func is pipeline_1f1b_interleaved
    assert fn.keywords == {"num_model_chunks": 2}


def test_dispatcher_no_pipelining_losses_grads():
    """The pp=1 dispatch obeys the same (losses, grads) contract."""
    from apex_tpu.transformer.pipeline_parallel import (
        get_forward_backward_func,
    )

    fn = get_forward_backward_func(pipeline_model_parallel_size=1)
    params = make_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (MICRO, MB, HIDDEN))
    y = jax.random.normal(jax.random.PRNGKey(2), (MICRO, MB, HIDDEN))
    losses, grads = fn(
        lambda prm, mb: mb["x"],
        lambda prm, h: _stage_scan(prm, h),
        lambda prm, h, mb: jnp.mean((h - mb["y"]) ** 2),
        params,
        {"x": x, "y": y},
    )
    ref_loss, ref_grads = jax.value_and_grad(serial_loss)(
        params, x.reshape(-1, HIDDEN), y.reshape(-1, HIDDEN)
    )
    np.testing.assert_allclose(
        float(jnp.mean(losses)), float(ref_loss), rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )


def test_dispatcher_no_pipelining_dp_convention():
    """On a dp>1 mesh the pp=1 dispatch returns shard-local grads (the
    1F1B family's convention): caller pmean over dp == true gradient of
    the reported dp-mean loss (regression: without the data-axis cast,
    autodiff psums over dp and the dispatched grads come out dp× too
    large)."""
    from apex_tpu.transformer.pipeline_parallel import (
        get_forward_backward_func,
    )

    mesh = parallel_state.initialize_model_parallel()
    try:
        dp = mesh.shape["dp"]
        params = make_params(jax.random.PRNGKey(0))
        x = jax.random.normal(
            jax.random.PRNGKey(1), (2 * MB * dp, HIDDEN))
        y = jax.random.normal(
            jax.random.PRNGKey(2), (2 * MB * dp, HIDDEN))

        def fb(params, x, y):
            fn = get_forward_backward_func(pipeline_model_parallel_size=1)
            losses, grads = fn(
                lambda prm, mb: mb["x"],
                lambda prm, h: _stage_scan(prm, h),
                lambda prm, h, mb: jnp.mean((h - mb["y"]) ** 2),
                params,
                {"x": x.reshape(2, MB, HIDDEN),
                 "y": y.reshape(2, MB, HIDDEN)},
            )
            loss = jax.lax.pmean(jnp.mean(losses), "dp")
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            return loss, grads

        specs = {"w": P(None, None, None), "b": P(None, None)}
        loss, grads = jax.jit(jax.shard_map(
            fb, mesh=mesh, in_specs=(specs, P("dp"), P("dp")),
            out_specs=(P(), specs),
        ))(params, x, y)
        ref_loss, ref_grads = jax.value_and_grad(serial_loss)(params, x, y)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_dispatcher_rejects_virtual_without_pp():
    from apex_tpu.transformer.pipeline_parallel import (
        get_forward_backward_func,
    )

    with pytest.raises(ValueError, match="pipeline_model_parallel_size"):
        get_forward_backward_func(
            virtual_pipeline_model_parallel_size=2,
            pipeline_model_parallel_size=1,
        )


def test_get_forward_backward_func_encdec_dispatch():
    """ModelType.encoder_and_decoder routes to the enc-dec schedule with
    the installed split rank pre-bound (reference: ModelType routing)."""
    import functools

    from apex_tpu.transformer.enums import ModelType
    from apex_tpu.transformer.pipeline_parallel import (
        get_forward_backward_func,
    )
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        _fwd_bwd_encdec,
    )

    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4,
        pipeline_model_parallel_split_rank_=2,
    )
    try:
        fn = get_forward_backward_func(
            pipeline_model_parallel_size=4,
            model_type=ModelType.encoder_and_decoder,
        )
        assert isinstance(fn, functools.partial)
        assert fn.func is _fwd_bwd_encdec
        assert fn.keywords["split_stage"] == 2
    finally:
        parallel_state.destroy_model_parallel()
    # without a split rank installed: clear error
    parallel_state.initialize_model_parallel(pipeline_model_parallel_size_=4)
    try:
        with pytest.raises(RuntimeError):
            get_forward_backward_func(
                pipeline_model_parallel_size=4,
                model_type=ModelType.encoder_and_decoder,
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_encdec_fused_1f1b_grads_match_gpipe_pp4():
    """Enc-dec 1F1B at pp=4 / split=2: TWO decoder stages, so the mem
    cotangent genuinely accumulates across stages before the split
    crossover — vs jax.grad through the fused GPipe schedule (the pp=2
    T5 test has one decoder stage and cannot catch a broken dmem sum)."""
    from apex_tpu.transformer.pipeline_parallel import (
        pipeline_encdec_fused,
        pipeline_encdec_fused_1f1b,
        pipeline_stage_specs,
        sync_replicated_grads,
    )

    PP, H, ROWS, M = 4, 16, 4, 4
    split = 2
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=PP
    )
    try:
        k = jax.random.PRNGKey(0)
        params = {
            "w": 0.3 * jax.random.normal(k, (PP, H, H)),
            "cross": 0.3 * jax.random.normal(
                jax.random.fold_in(k, 1), (PP, H, H)),
            "head": 0.3 * jax.random.normal(
                jax.random.fold_in(k, 2), (H, H)),
        }
        specs = {**pipeline_stage_specs(
            {"w": P(None, None, None), "cross": P(None, None, None)}
        ), "head": P()}
        x = jax.random.normal(jax.random.fold_in(k, 3), (M, ROWS, H))
        y = jax.random.normal(jax.random.fold_in(k, 4), (M, ROWS, H))
        mbs = {"x": x, "y": y}

        def stage_fn(prm, h, mem, stage_idx):
            # self part + gated "cross-attention" consuming mem: every
            # decoder stage contributes a mem cotangent
            gate = (stage_idx >= split).astype(h.dtype)
            h = jnp.tanh(h @ prm["w"][0])
            return h + gate * jnp.tanh(mem @ prm["cross"][0])

        def enc_entry(prm, mb):
            return mb["x"]

        def dec_entry(prm, mb):
            return mb["x"] * 0.5

        def last_fn(prm, h, mb):
            return jnp.mean((h @ prm["head"] - mb["y"]) ** 2)

        def fb_1f1b(params, mbs):
            losses, grads = pipeline_encdec_fused_1f1b(
                enc_entry, dec_entry, stage_fn, last_fn, params, mbs,
                split,
            )
            return jnp.mean(losses), sync_replicated_grads(grads, specs)

        def fb_gpipe(params, mbs):
            def loss(prm):
                per = pipeline_encdec_fused(
                    lambda mb: enc_entry(prm, mb),
                    lambda mb: dec_entry(prm, mb),
                    lambda h, mem, s: stage_fn(prm, h, mem, s),
                    lambda h, mb: last_fn(prm, h, mb),
                    mbs, split, remat=False,
                )
                return jnp.mean(per)

            l, grads = jax.value_and_grad(loss)(params)
            return l, sync_replicated_grads(grads, specs)

        run = lambda f: jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(specs, P()), out_specs=(P(), specs),
        ))
        placed = jax.device_put(params, jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P)))
        l1, g1 = run(fb_1f1b)(placed, mbs)
        l2, g2 = run(fb_gpipe)(placed, mbs)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        for key in ("w", "cross", "head"):
            np.testing.assert_allclose(
                np.asarray(g1[key]), np.asarray(g2[key]),
                rtol=1e-5, atol=1e-6, err_msg=key,
            )
        # the cross grads on decoder stages must be nonzero (mem path
        # live) and zero on encoder stages (gate off)
        g_cross = np.asarray(g1["cross"])
        assert np.abs(g_cross[split:]).max() > 1e-6
        np.testing.assert_allclose(g_cross[:split], 0.0, atol=1e-7)
    finally:
        parallel_state.destroy_model_parallel()

"""Pretraining data source (apex_tpu.data): mmap token files + the
sampler composition — the source half of the Megatron input pipeline
whose sampler half mirrors the reference
(apex/transformer/_data/_batchsampler.py)."""

import numpy as np
import pytest

from apex_tpu.data import (
    IndexedTokenDataset,
    pretraining_batches,
    write_token_file,
)
from apex_tpu.transformer.data import MegatronPretrainingSampler


def _make(tmp_path, n_tokens=1000, dtype="uint16"):
    path = str(tmp_path / "toks.bin")
    tokens = np.arange(n_tokens) % 611  # recognizable, nonuniform
    write_token_file(path, tokens, dtype=dtype)
    return path, tokens


def test_windows_cover_every_token_once(tmp_path):
    path, tokens = _make(tmp_path)
    ds = IndexedTokenDataset(path, seq_len=16)
    assert len(ds) == (1000 - 1) // 16
    seen = []
    for i in range(len(ds)):
        w = ds[i]
        assert w.shape == (17,) and w.dtype == np.int32
        np.testing.assert_array_equal(w, tokens[i * 16: i * 16 + 17])
        seen.extend(w[:-1])  # inputs
    # inputs tile the prefix of the file exactly once
    np.testing.assert_array_equal(seen, tokens[: len(ds) * 16])


def test_target_is_shifted_input(tmp_path):
    path, _ = _make(tmp_path)
    ds = IndexedTokenDataset(path, seq_len=8)
    sampler = MegatronPretrainingSampler(
        total_samples=len(ds), consumed_samples=0, micro_batch_size=4,
        data_parallel_rank=0, data_parallel_size=1,
    )
    toks, tgts = next(iter(pretraining_batches(ds, sampler)))
    assert toks.shape == tgts.shape == (4, 8)
    np.testing.assert_array_equal(toks[:, 1:], tgts[:, :-1])


def test_dp_ranks_get_disjoint_samples(tmp_path):
    path, _ = _make(tmp_path)
    ds = IndexedTokenDataset(path, seq_len=8)

    def first_batch(rank):
        s = MegatronPretrainingSampler(
            total_samples=len(ds), consumed_samples=0, micro_batch_size=2,
            data_parallel_rank=rank, data_parallel_size=4,
        )
        toks, _ = next(iter(pretraining_batches(ds, s)))
        return toks

    batches = [first_batch(r) for r in range(4)]
    # disjoint windows: the 4x2 first-batch inputs across ranks tile
    # the first 8 dataset samples exactly, nothing shared or skipped
    flat = np.sort(np.concatenate([b.ravel() for b in batches]))
    expect = np.sort(np.concatenate([ds_window for ds_window in (
        IndexedTokenDataset(path, seq_len=8)[i][:-1] for i in range(8))]))
    np.testing.assert_array_equal(flat, expect)


def test_dtype_bounds_checked(tmp_path):
    with pytest.raises(ValueError, match="do not fit"):
        write_token_file(str(tmp_path / "x.bin"), [0, 70000],
                         dtype="uint16")
    path = write_token_file(str(tmp_path / "y.bin"),
                            np.arange(100_000) % 70_000, dtype="uint32")
    ds = IndexedTokenDataset(path, seq_len=32)
    assert ds[0][0] == 0


def test_too_small_file_raises(tmp_path):
    path = write_token_file(str(tmp_path / "z.bin"), np.arange(8))
    with pytest.raises(ValueError, match="window"):
        IndexedTokenDataset(path, seq_len=16)


def test_float_token_ids_rejected(tmp_path):
    # astype() would silently truncate in-range floats — reject instead
    with pytest.raises(ValueError, match="integer dtype"):
        write_token_file(str(tmp_path / "f.bin"),
                         np.array([1.0, 2.5, 3.0]), dtype="uint16")
    # exact-valued floats are still floats: the caller must cast
    with pytest.raises(ValueError, match="integer dtype"):
        write_token_file(str(tmp_path / "g.bin"),
                         np.array([1.0, 2.0]), dtype="uint16")
    # explicit integer cast is the sanctioned path
    write_token_file(str(tmp_path / "h.bin"),
                     np.array([1.0, 2.0]).astype(np.int64), dtype="uint16")


def test_legacy_sidecar_max_token_lazy_and_rewritten(tmp_path):
    import json

    path, tokens = _make(tmp_path, n_tokens=500)
    sidecar = path + ".meta.json"
    with open(sidecar) as f:
        meta = json.load(f)
    del meta["max_token"]  # simulate a pre-field legacy sidecar
    with open(sidecar, "w") as f:
        json.dump(meta, f)

    ds = IndexedTokenDataset(path, seq_len=16)
    # construction must NOT have scanned (nothing written back yet)
    with open(sidecar) as f:
        assert "max_token" not in json.load(f)
    # first access scans once and upgrades the sidecar in place
    assert ds.max_token == int(tokens.max())
    with open(sidecar) as f:
        assert json.load(f)["max_token"] == int(tokens.max())
    # a fresh dataset now reads the recorded value (no rescan path)
    ds2 = IndexedTokenDataset(path, seq_len=16)
    assert ds2._max_token == int(tokens.max())

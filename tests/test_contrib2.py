"""Contrib batch 2 tests: groupbn, bottleneck (+ spatial parallel), RNN
stack (vs torch CPU reference), weight norm, fp16_utils, batch samplers."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    convert_network,
    network_to_half,
)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.reparameterization import (
    apply_weight_norm,
    compute_weight,
    remove_weight_norm,
    weight_norm_init,
)
from apex_tpu.rnn import GRU, LSTM, Tanh, mLSTM
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)


class TestGroupBN:
    def test_matches_plain_bn(self):
        bn = BatchNorm2d_NHWC(8, axis_name=None)
        params = bn.init()
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
        out, new_params = bn.apply(params, x)
        xf = np.asarray(x)
        mean = xf.reshape(-1, 8).mean(0)
        var = xf.reshape(-1, 8).var(0)
        expected = (xf - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4,
                                   atol=1e-5)
        # running stats updated
        assert not np.allclose(np.asarray(new_params["running_mean"]), 0)

    def test_fused_add_relu(self):
        bn = BatchNorm2d_NHWC(4, fuse_relu=True, axis_name=None)
        params = bn.init()
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 3, 4))
        z = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 3, 4))
        out, _ = bn.apply(params, x, z=z)
        assert (np.asarray(out) >= 0).all()


class TestBottleneck:
    def test_shapes_and_residual(self):
        blk = Bottleneck(16, 4, 16)
        params = blk.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 16))
        y = blk.apply(params, x)
        assert y.shape == x.shape
        assert (np.asarray(y) >= 0).all()

    def test_projection_path(self):
        blk = Bottleneck(16, 4, 32, stride=2)
        params = blk.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 16))
        y = blk.apply(params, x)
        assert y.shape == (2, 4, 4, 32)

    def test_spatial_matches_dense(self):
        """H-sharded spatial bottleneck == dense bottleneck (the halo
        exchange + psum-BN must be transparent)."""
        mesh = parallel_state.initialize_model_parallel(
            context_parallel_size_=8
        )
        try:
            dense = Bottleneck(8, 4, 8)
            params = dense.init(jax.random.PRNGKey(0))
            x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 8, 8))
            ref = dense.apply(params, x)

            spatial = SpatialBottleneck(8, 4, 8, axis_name="cp")
            pspec = jax.tree.map(lambda _: P(), params)

            fn = jax.jit(
                jax.shard_map(
                    spatial.apply,
                    mesh=mesh,
                    in_specs=(pspec, P(None, "cp")),
                    out_specs=P(None, "cp"),
                )
            )
            got = fn(params, x)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-5
            )
        finally:
            parallel_state.destroy_model_parallel()


class TestRNN:
    def test_lstm_matches_torch(self):
        import torch

        model = LSTM(6, 8, num_layers=1)
        params = model.init(jax.random.PRNGKey(0))
        xs = np.random.default_rng(0).normal(size=(5, 2, 6)).astype(np.float32)
        out = model.apply(params, jnp.asarray(xs))

        t = torch.nn.LSTM(6, 8)
        with torch.no_grad():
            t.weight_ih_l0.copy_(torch.from_numpy(np.asarray(params[0]["w_ih"]).T))
            t.weight_hh_l0.copy_(torch.from_numpy(np.asarray(params[0]["w_hh"]).T))
            t.bias_ih_l0.copy_(torch.from_numpy(np.asarray(params[0]["bias"])))
            t.bias_hh_l0.zero_()
            ref, _ = t(torch.from_numpy(xs))
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_gru_matches_torch(self):
        import torch

        model = GRU(4, 6)
        params = model.init(jax.random.PRNGKey(0))
        xs = np.random.default_rng(1).normal(size=(5, 3, 4)).astype(np.float32)
        out = model.apply(params, jnp.asarray(xs))

        t = torch.nn.GRU(4, 6)
        with torch.no_grad():
            t.weight_ih_l0.copy_(torch.from_numpy(np.asarray(params[0]["w_ih"]).T))
            t.weight_hh_l0.copy_(torch.from_numpy(np.asarray(params[0]["w_hh"]).T))
            t.bias_ih_l0.copy_(torch.from_numpy(np.asarray(params[0]["bias"])))
            t.bias_hh_l0.zero_()
            ref, _ = t(torch.from_numpy(xs))
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_bidirectional_and_stacked(self):
        model = LSTM(4, 6, num_layers=2, bidirectional=True)
        params = model.init(jax.random.PRNGKey(0))
        xs = jax.random.normal(jax.random.PRNGKey(1), (7, 2, 4))
        out = model.apply(params, xs)
        assert out.shape == (7, 2, 12)

    def test_mlstm_and_tanh_run(self):
        for factory in (mLSTM, Tanh):
            model = factory(4, 4)
            params = model.init(jax.random.PRNGKey(0))
            out = model.apply(
                params, jax.random.normal(jax.random.PRNGKey(1), (3, 2, 4))
            )
            assert out.shape == (3, 2, 4)
            assert np.all(np.isfinite(np.asarray(out)))

    def test_lstm_forget_bias(self):
        model = LSTM(4, 8, forget_bias=1.0)
        params = model.init(jax.random.PRNGKey(0))
        b = np.asarray(params[0]["bias"])
        assert (b[8:16] == 1.0).all() and (b[:8] == 0).all()


class TestWeightNorm:
    def test_roundtrip(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
        wn = weight_norm_init(w)
        np.testing.assert_allclose(
            np.asarray(compute_weight(wn)), np.asarray(w), rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(remove_weight_norm(wn)), np.asarray(w), rtol=1e-6
        )

    def test_direction_invariance(self):
        """Scaling v leaves w unchanged (the point of the param split)."""
        w = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
        wn = weight_norm_init(w)
        wn2 = {"g": wn["g"], "v": 3.0 * wn["v"]}
        np.testing.assert_allclose(
            np.asarray(compute_weight(wn2)), np.asarray(w), rtol=1e-6
        )

    def test_apply_to_pytree(self):
        params = {"dense": {"weight": jnp.ones((4, 4)), "bias": jnp.zeros(4)}}
        wn = apply_weight_norm(params)
        assert set(wn["dense"]["weight"]) == {"g", "v"}
        assert wn["dense"]["bias"].shape == (4,)


class TestFP16Utils:
    def test_network_to_half_and_convert(self):
        params = {"w": jnp.ones((2, 2)), "step": jnp.int32(3),
                  "ln": {"scale": jnp.ones(2)}}
        half = network_to_half(params)
        assert half["w"].dtype == jnp.float16
        assert half["step"].dtype == jnp.int32
        conv = convert_network(params, jnp.float16)
        assert conv["w"].dtype == jnp.float16
        assert conv["ln"]["scale"].dtype == jnp.float32  # norm stays fp32

    def test_fp16_optimizer_trains_and_skips_overflow(self):
        opt = FP16_Optimizer(FusedAdam(lr=0.1), dynamic_loss_scale=True)
        params = {"w": jnp.ones((4,), jnp.float16)}
        state = opt.init(params)
        scale0 = float(state["scaler"].loss_scale)

        # build in fp32 then cast: 65536 itself overflows fp16
        grads = {"w": (jnp.full((4,), 0.25) * scale0).astype(jnp.float16)}
        new_params, state = opt.step(state, grads, params)
        assert not np.allclose(np.asarray(new_params["w"]),
                               np.asarray(params["w"]))

        inf_grads = {"w": jnp.full((4,), np.inf, jnp.float16)}
        skipped, state2 = opt.step(state, inf_grads, new_params)
        np.testing.assert_array_equal(
            np.asarray(skipped["w"]), np.asarray(new_params["w"])
        )
        assert float(state2["scaler"].loss_scale) < float(
            state["scaler"].loss_scale
        )

    def test_state_dict_roundtrip(self):
        opt = FP16_Optimizer(FusedAdam(lr=0.1), dynamic_loss_scale=True)
        params = {"w": jnp.ones((4,), jnp.float16)}
        state = opt.init(params)
        d = opt.state_dict(state)
        state2 = opt.load_state_dict(d)
        assert float(state2["scaler"].loss_scale) == float(
            state["scaler"].loss_scale
        )
        np.testing.assert_array_equal(
            np.asarray(state2["master"]["w"]), np.asarray(state["master"]["w"])
        )


class TestSamplers:
    def test_sequential_shards_by_rank(self):
        batches0 = list(MegatronPretrainingSampler(32, 0, 2, 0, 2))
        batches1 = list(MegatronPretrainingSampler(32, 0, 2, 1, 2))
        assert batches0[0] == [0, 1] and batches1[0] == [2, 3]
        assert len(batches0) == 8  # 32 / (2*2)
        flat = sorted(i for b in batches0 + batches1 for i in b)
        assert flat == list(range(32))

    def test_sequential_resume(self):
        batches = list(MegatronPretrainingSampler(32, 16, 2, 0, 2))
        assert batches[0] == [16, 17]

    def test_sequential_errors(self):
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(0, 0, 2, 0, 2)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(8, 8, 2, 0, 2)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(8, 0, 2, 3, 2)

    def test_random_is_epoch_deterministic_and_disjoint(self):
        a0 = list(MegatronPretrainingRandomSampler(64, 0, 2, 0, 2))
        a0b = list(MegatronPretrainingRandomSampler(64, 0, 2, 0, 2))
        assert a0 == a0b
        a1 = list(MegatronPretrainingRandomSampler(64, 0, 2, 1, 2))
        seen0 = {i for b in a0 for i in b}
        seen1 = {i for b in a1 for i in b}
        assert not (seen0 & seen1)

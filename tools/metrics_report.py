"""metrics_report: telemetry JSONL → run summary.

Reads the record stream a :class:`apex_tpu.telemetry.MetricsLogger`
appends (``--metrics-jsonl`` on the example trainers; schema in
docs/observability.md) and reports what a final tokens/s number cannot:

- **throughput/MFU trajectory** — every per-flush ``throughput``
  record, plus headline stats (best / mean / final window), in the
  same ``metric``/``value``/``unit`` shape the ``BENCH_*.json``
  records use so the two are directly comparable (``--bench`` diffs
  against one);
- **step-time breakdown** — host-side phase timings (the logger's
  ``timing()`` meters: data / checkpoint / ...) as per-step
  milliseconds next to the measured ms/step, so "the input pipeline
  ate the speedup" is visible in one table;
- **event timeline** — every subsystem event (checkpoint saves /
  verify outcomes / guard escalations / GC / watchdog stalls /
  comm-bucket estimates) with run-relative timestamps and per-kind
  counts, interleaved with the step indices they landed between;
- **serving summary** — when the stream came from a serving run
  (``apex_tpu/serving/serve.py``'s ``tlm.prefill``/``tlm.decode``
  ``span`` records + ``request_done``/``prefix_hit`` events):
  per-window decode tokens/s, time-to-first-token stats, inter-token
  latency percentiles, request completion counts by reason, chunked-
  prefill progress (``prefill_chunk`` spans), and the prefix-cache
  scoreboard (hit rate, pages shared, prefill tokens skipped);
- **fault / recovery ledger** — when the stream came from a fleet run
  with the fault-tolerance tier engaged: replica faults and
  quarantines, migrations by cause, deadline misses (retried vs
  terminal), hedge spawns/wins/losses, brownout transitions with the
  pressure that drove them, journal replays, and per-class SLO
  attainment (completions not cut off at their deadline).

Usage::

    python tools/metrics_report.py run_metrics.jsonl
    python tools/metrics_report.py run_metrics.jsonl --json out.json
    python tools/metrics_report.py run_metrics.jsonl --bench BENCH_r05.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def load_records(path: str) -> List[dict]:
    """Parse a metrics JSONL file; malformed lines (a crashed writer's
    torn tail) are counted, not fatal."""
    records, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
    if bad:
        print(f"note: skipped {bad} malformed line(s)", file=sys.stderr)
    return records


def _stats(xs: List[float], better=max) -> Dict[str, float]:
    return {
        "mean": sum(xs) / len(xs),
        "best": better(xs),  # max for rates, min for ms/step
        "final": xs[-1],
    }


def _percentile(xs: List[float], q: float) -> float:
    """Nearest-rank percentile (no numpy dependency here)."""
    s = sorted(xs)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def summarize_serving(records: List[dict]) -> Optional[Dict[str, Any]]:
    """The serving section: decode throughput per harvest window, TTFT,
    and inter-token latency from the ``span``/``request_done`` event
    stream ``ContinuousBatcher`` emits.  None when the stream holds no
    serving records (training runs keep their report unchanged)."""
    spans = [r for r in records
             if r.get("kind") == "event" and r.get("event") == "span"]
    done = [r for r in records
            if r.get("kind") == "event"
            and r.get("event") == "request_done"]
    hits = [r for r in records
            if r.get("kind") == "event"
            and r.get("event") == "prefix_hit"]
    decode = [r for r in spans if r.get("span") == "decode"
              and r.get("steps")]
    prefill = [r for r in spans if r.get("span") == "prefill"]
    chunks = [r for r in spans if r.get("span") == "prefill_chunk"]
    if not (decode or prefill or done):
        return None
    out: Dict[str, Any] = {}
    if decode:
        windows = []
        itl: List[float] = []       # per-window mean inter-token s
        wgbs: List[float] = []      # per-window weight-stream GB/s
        for r in decode:
            dur = float(r.get("dur_s", 0.0))
            steps = int(r.get("steps", 0))
            toks = int(r.get("tokens", 0))
            w = {"steps": steps, "tokens": toks,
                 "dur_s": round(dur, 6)}
            if dur > 0 and toks:
                w["tokens_per_sec"] = round(toks / dur, 1)
            if dur > 0 and steps:
                itl.append(dur / steps)
            # every decode step streams the whole weight pool once
            # (serve.py stamps the per-step bytes on the span), so the
            # window's achieved weight bandwidth is steps * bytes / dur
            # — at small batch this IS the decode roofline, and the
            # int8/int4 pools shrink the numerator, not the rate
            wb = r.get("weight_bytes")
            if dur > 0 and steps and wb:
                g = round(steps * float(wb) / dur / 1e9, 6)
                w["weight_stream_gbs"] = g
                wgbs.append(g)
            windows.append(w)
        out["decode_windows"] = windows
        rates = [w["tokens_per_sec"] for w in windows
                 if "tokens_per_sec" in w]
        if rates:
            out["decode_tokens_per_sec"] = _stats(rates)
        wdts = {r["weight_dtype"] for r in decode
                if r.get("weight_dtype")}
        if wdts:
            out["weight_dtype"] = (sorted(wdts)[0] if len(wdts) == 1
                                   else sorted(wdts))
        # the tensor-parallel degree rides the decode spans exactly
        # like weight_dtype; weight_bytes is already PER CHIP (gpt.py
        # stamps each chip's own pool slice), so the GB/s above is the
        # per-chip stream without further division
        tps = {int(r["tp"]) for r in decode if r.get("tp")}
        if tps:
            out["tp"] = (sorted(tps)[0] if len(tps) == 1
                         else sorted(tps))
        if wgbs:
            out["weight_stream_gbs"] = _stats(wgbs)
        if itl:
            # the harvest window quantizes this to window-mean
            # granularity (serve.py docstring) — percentiles are over
            # per-window means, honest about what was measured
            out["inter_token_latency_ms"] = {
                "p50": round(_percentile(itl, 50) * 1e3, 3),
                "p90": round(_percentile(itl, 90) * 1e3, 3),
                "p99": round(_percentile(itl, 99) * 1e3, 3),
                "mean": round(sum(itl) / len(itl) * 1e3, 3),
            }
    if prefill:
        out["prefill_spans"] = len(prefill)
        ptoks = [int(r["tokens"]) for r in prefill if "tokens" in r]
        if ptoks:
            out["prefill_tokens"] = sum(ptoks)
    if chunks:
        cms = [float(r["dispatch_s"]) * 1e3 for r in chunks
               if "dispatch_s" in r]
        out["prefill_chunks"] = {
            "count": len(chunks),
            "tokens": sum(int(r.get("tokens", 0)) for r in chunks),
        }
        if cms:
            out["prefill_chunks"]["mean_ms"] = round(
                sum(cms) / len(cms), 3)
            out["prefill_chunks"]["max_ms"] = round(max(cms), 3)
    if hits:
        # the prefix-cache scoreboard: one prefix_hit event lands per
        # chunked admission (matched_tokens == 0 on a miss)
        matched = [int(r.get("matched_tokens", 0)) for r in hits]
        out["prefix_cache"] = {
            "admissions": len(hits),
            "hits": sum(1 for m in matched if m > 0),
            "hit_rate": round(
                sum(1 for m in matched if m > 0) / len(hits), 4),
            "matched_tokens": sum(matched),
            "pages_shared": sum(
                int(r.get("shared_pages", 0)) for r in hits),
            "prefill_tokens_skipped": sum(
                int(r.get("tokens_skipped", 0)) for r in hits),
            "pages_copied": sum(
                1 for r in hits if r.get("copied")),
        }
    spec = [r for r in records
            if r.get("kind") == "event"
            and r.get("event") == "spec_accept"]
    if spec:
        # the speculation scoreboard: one spec_accept event per verify
        # step (emitted from the commit resolve the speculative window
        # already performs — no extra host syncs behind it)
        drafted = sum(int(r.get("drafted", 0)) for r in spec)
        accepted = sum(int(r.get("accepted", 0)) for r in spec)
        committed = sum(int(r.get("committed", 0)) for r in spec)
        slot_steps = sum(len(r.get("commits", [])) for r in spec)
        offramp = sum(int(r.get("offramp", 0)) for r in spec)
        # commits-per-slot-step doubles as the committed TREE DEPTH
        # histogram (a commit of n is a depth-(n-1) accepted path plus
        # its correction/bonus draw)
        hist: Dict[str, int] = {}
        for r in spec:
            for nc in r.get("commits", []):
                hist[str(int(nc))] = hist.get(str(int(nc)), 0) + 1
        # draft-model host cost: the speculative decode spans stamp
        # the wall seconds spent inside draft() (dur_s includes it, so
        # the ratio is the draft's fraction of the serving wall)
        draft_wall = sum(float(r.get("draft_s", 0.0)) for r in decode)
        spec_wall = sum(float(r.get("dur_s", 0.0)) for r in decode)
        by_source: Dict[str, Dict[str, Any]] = {}
        for r in spec:
            for src, rec in (r.get("by_source") or {}).items():
                tot = by_source.setdefault(
                    src, {"drafted": 0, "accepted": 0})
                tot["drafted"] += int(rec.get("drafted", 0))
                tot["accepted"] += int(rec.get("accepted", 0))
        for src, tot in by_source.items():
            if tot["drafted"]:
                tot["hit_rate"] = round(
                    tot["accepted"] / tot["drafted"], 4)
        out["speculation"] = {
            "verify_steps": len(spec),
            "drafted": drafted,
            "accepted": accepted,
            "committed": committed,
            # tokens committed per slot per verify step (1 = the plain
            # decode rate; k+1 = a fully accepted draft + bonus)
            "accepted_per_step_hist": hist,
            "committed_per_slot_step": (
                round(committed / slot_steps, 4) if slot_steps else None),
            # drafted rows the verify pass computed but threw away —
            # the price of a miss, what the k-selection trade bounds
            "wasted_verify_fraction": (
                round((drafted - accepted) / drafted, 4)
                if drafted else None),
            # commits that rode a non-spine tree branch — every one is
            # a token the chain verifier would have rejected
            "offramp_commits": offramp,
            "draft_wall_s": round(draft_wall, 6),
            "draft_wall_fraction": (
                round(draft_wall / spec_wall, 4) if spec_wall > 0 else None),
            "by_source": by_source,
        }
    if done:
        reasons: Dict[str, int] = {}
        ttfts = []
        for r in done:
            reasons[str(r.get("reason", "?"))] = \
                reasons.get(str(r.get("reason", "?")), 0) + 1
            if isinstance(r.get("ttft_s"), (int, float)):
                ttfts.append(float(r["ttft_s"]))
        out["requests"] = {"completed": len(done), "by_reason": reasons}
        # exact TTFT: the span from each request_admitted event to the
        # prefill span that sampled its first token, both wall-clock
        # event timestamps — NOT the harvest-quantized ttft_s the
        # Completion carries (the first token exists on device when the
        # prefill span lands; the harvest merely SURFACES it later).
        # Correlation is by slot: an admission owns its slot until its
        # prefill completes, so the next prefill span on that slot is
        # its own.
        exact = _exact_ttfts(records)
        source = "exact" if exact else "completion"
        if not exact:
            exact = ttfts          # old streams without admit events
        if exact:
            out["ttft_s"] = {
                "p50": round(_percentile(exact, 50), 6),
                "p95": round(_percentile(exact, 95), 6),
                "mean": round(sum(exact) / len(exact), 6),
                "max": round(max(exact), 6),
                "source": source,
            }
    return out


def _exact_ttfts(records: List[dict]) -> List[float]:
    """Admission-to-first-token spans from exact event timestamps:
    walk the stream in order, pairing each ``request_admitted`` with
    the next ``span=prefill`` event on the same slot."""
    pending: Dict[Any, float] = {}          # slot -> admit t
    exact: List[float] = []
    for r in records:
        if r.get("kind") != "event" or "t" not in r:
            continue
        if r.get("event") == "request_admitted" and "slot" in r:
            pending[r["slot"]] = float(r["t"])
        elif (r.get("event") == "span" and r.get("span") == "prefill"
                and r.get("slot") in pending):
            exact.append(float(r["t"]) - pending.pop(r["slot"]))
    return exact


def summarize_fleet(records: List[dict]) -> Optional[Dict[str, Any]]:
    """The fleet section: per-class TTFT/ITL percentiles from the
    ``trace_request`` records ``tools/load_gen.py``'s replay emits
    (arrival-anchored — queue wait included), plus the routing /
    rejection / migration ledger from the router's own events.  None
    when the stream holds no fleet records."""
    trace = [r for r in records
             if r.get("kind") == "event"
             and r.get("event") == "trace_request"]
    routed = [r for r in records
              if r.get("kind") == "event"
              and r.get("event") == "request_routed"]
    if not (trace or routed):
        return None
    out: Dict[str, Any] = {}
    if routed:
        per: Dict[str, int] = {}
        for r in routed:
            name = str(r.get("replica", "?"))
            per[name] = per.get(name, 0) + 1
        out["routed"] = per
        out["affinity_routed"] = sum(
            1 for r in routed if r.get("affinity", 0))
    for kind, key in (("request_rejected", "rejected"),
                      ("request_migrated", "migrated"),
                      ("replica_dead", "replicas_dead")):
        n = sum(1 for r in records if r.get("kind") == "event"
                and r.get("event") == kind)
        if n:
            out[key] = n
    if trace:
        done = [r for r in trace if "reason" in r]
        out["trace"] = {
            "requests": len(trace),
            "completed": len(done),
            "lost": sum(1 for r in trace if r.get("lost")),
        }
        by_class: Dict[str, Any] = {}
        for name in sorted({str(r.get("slo")) for r in done}):
            rs = [r for r in done if str(r.get("slo")) == name]
            ttfts = [float(r["ttft_s"]) for r in rs
                     if isinstance(r.get("ttft_s"), (int, float))]
            itls = [float(r["itl_ms"]) for r in rs
                    if isinstance(r.get("itl_ms"), (int, float))]
            c: Dict[str, Any] = {"n": len(rs)}
            if ttfts:
                c["ttft_s"] = {
                    "p50": round(_percentile(ttfts, 50), 6),
                    "p99": round(_percentile(ttfts, 99), 6),
                }
            if itls:
                c["itl_ms"] = {
                    "p50": round(_percentile(itls, 50), 3),
                    "p99": round(_percentile(itls, 99), 3),
                }
            by_class[name] = c
        out["by_class"] = by_class
    return out


def summarize_faults(records: List[dict]) -> Optional[Dict[str, Any]]:
    """The fault/recovery section: what the fleet's fault-tolerance
    tier did — replica faults/quarantines, migrations by cause,
    deadline misses split into retried vs terminal, the hedge
    scoreboard, brownout transitions, and journal replays — plus
    per-class SLO attainment over the ``trace_request`` stream (the
    fraction of completions NOT cut off at their deadline).  None when
    the stream holds none of those events."""
    ev = {}
    for r in records:
        if r.get("kind") == "event":
            ev.setdefault(r.get("event"), []).append(r)
    faults = ev.get("replica_fault", [])
    quar = ev.get("replica_quarantined", [])
    misses = ev.get("deadline_miss", [])
    hedges = ev.get("hedge_spawn", [])
    hwins = ev.get("hedge_win", [])
    hlosses = ev.get("hedge_loss", [])
    brown = ev.get("brownout", [])
    replays = ev.get("journal_replayed", [])
    migr = ev.get("request_migrated", [])
    if not (faults or quar or misses or hedges or brown or replays):
        return None
    out: Dict[str, Any] = {}
    if faults:
        per: Dict[str, int] = {}
        for r in faults:
            name = str(r.get("replica", "?"))
            per[name] = per.get(name, 0) + 1
        out["replica_faults"] = {"count": len(faults), "by_replica": per}
    if quar:
        out["quarantined"] = [
            {"replica": r.get("replica"), "cause": r.get("cause")}
            for r in quar]
    if migr:
        by_cause: Dict[str, int] = {}
        for r in migr:
            c = str(r.get("cause", "replica_dead"))
            by_cause[c] = by_cause.get(c, 0) + 1
        out["migrations"] = {"count": len(migr), "by_cause": by_cause}
    if misses:
        retried = sum(1 for r in misses if r.get("retry"))
        out["deadline_misses"] = {
            "count": len(misses),
            "retried": retried,
            "terminal": len(misses) - retried,
        }
    if hedges or hwins or hlosses:
        out["hedging"] = {"spawned": len(hedges), "wins": len(hwins),
                          "losses": len(hlosses)}
    if brown:
        out["brownout"] = {
            "transitions": len(brown),
            "max_level": max(int(r.get("to_level", 0)) for r in brown),
            "ladder": [
                {"from": r.get("from_level"), "to": r.get("to_level"),
                 "free_page_frac": r.get("free_page_frac"),
                 "queue_depth": r.get("queue_depth")}
                for r in brown],
        }
    if replays:
        out["journal_replays"] = [
            {k: r.get(k) for k in ("resumed", "completed", "corrupt",
                                   "gapped")}
            for r in replays]
    # per-class SLO attainment over the trace stream: a completion
    # whose reason is "deadline" burned its budget of time — everything
    # else (eos/budget/...) made its SLO window
    trace = [r for r in ev.get("trace_request", []) if "reason" in r]
    if trace:
        att: Dict[str, Any] = {}
        for name in sorted({str(r.get("slo")) for r in trace}):
            rs = [r for r in trace if str(r.get("slo")) == name]
            missed = sum(1 for r in rs if r.get("reason") == "deadline")
            att[name] = {
                "n": len(rs),
                "deadline_missed": missed,
                "attainment": round(1.0 - missed / len(rs), 4),
            }
        out["slo_attainment"] = att
    return out


def summarize_kv_movement(records: List[dict]
                          ) -> Optional[Dict[str, Any]]:
    """The disaggregation/offload section: page-level KV movement.

    Three event streams feed it — ``kv_handoff`` (prefill→decode
    ownership transfers that MOVED pages instead of recomputing),
    ``page_offload`` (index-only prefix pages staged to the host-RAM
    tier instead of dying at eviction), and ``page_faultin`` (offloaded
    pages adopted back into the device pool at admission).  The hit
    rate scores the offload tier against its recompute alternative:
    fault-in walks that found every page they asked for vs walks that
    fell back to prefill.  None when the stream holds none of these."""
    ev: Dict[str, List[dict]] = {}
    for r in records:
        if r.get("kind") == "event":
            ev.setdefault(r.get("event"), []).append(r)
    handoffs = ev.get("kv_handoff", [])
    offloads = ev.get("page_offload", [])
    faults = ev.get("page_faultin", [])
    if not (handoffs or offloads or faults):
        return None
    out: Dict[str, Any] = {}
    if handoffs:
        durs = [float(r["dur_s"]) * 1e3 for r in handoffs
                if isinstance(r.get("dur_s"), (int, float))]
        routes: Dict[str, int] = {}
        for r in handoffs:
            key = f"{r.get('src', '?')}->{r.get('dst', '?')}"
            routes[key] = routes.get(key, 0) + 1
        out["handoffs"] = {
            "count": len(handoffs),
            "pages": sum(int(r.get("pages", 0)) for r in handoffs),
            "wire_bytes": sum(int(r.get("bytes", 0))
                              for r in handoffs),
            "by_route": routes,
        }
        if durs:
            out["handoffs"]["ms"] = {
                "mean": round(sum(durs) / len(durs), 3),
                "max": round(max(durs), 3),
            }
    if offloads:
        out["offload"] = {
            "events": len(offloads),
            "pages": sum(int(r.get("pages", 0)) for r in offloads),
            "wire_bytes": sum(int(r.get("bytes", 0))
                              for r in offloads),
        }
    if faults:
        durs = [float(r["dur_s"]) * 1e3 for r in faults
                if isinstance(r.get("dur_s"), (int, float))]
        misses = sum(1 for r in faults if int(r.get("misses", 0)) > 0)
        out["faultin"] = {
            "events": len(faults),
            "pages": sum(int(r.get("pages", 0)) for r in faults),
            "wire_bytes": sum(int(r.get("bytes", 0)) for r in faults),
            # a walk that missed fell back to recompute for the tail;
            # hit rate = fully-served fault-ins / all fault-in walks
            "chain_misses": misses,
            "hit_rate": round(1.0 - misses / len(faults), 4),
            "prefill_tokens_saved": sum(int(r.get("tokens", 0))
                                        for r in faults),
        }
        if durs:
            out["faultin"]["ms"] = {
                "mean": round(sum(durs) / len(durs), 3),
                "max": round(max(durs), 3),
            }
    return out


def summarize(records: List[dict]) -> Dict[str, Any]:
    """Aggregate one run's records into the report dict."""
    steps = [r for r in records if r.get("kind") == "step"]
    thr = [r for r in records if r.get("kind") == "throughput"]
    meters = [r for r in records if r.get("kind") == "meters"]
    events = [r for r in records if r.get("kind") == "event"]
    t0 = min((r["t"] for r in records if "t" in r), default=0.0)

    out: Dict[str, Any] = {
        "runs": sorted({r["run"] for r in records if "run" in r}),
        "n_records": len(records),
    }

    if steps:
        scalar_keys = sorted(
            k for k in steps[-1]
            if k not in ("t", "kind", "step", "run")
        )
        out["steps"] = {
            "count": len(steps),
            "first": steps[0].get("step"),
            "last": steps[-1].get("step"),
        }
        out["scalars"] = {}
        for k in scalar_keys:
            xs = [float(r[k]) for r in steps
                  if isinstance(r.get(k), (int, float))]
            if xs:
                out["scalars"][k] = {
                    "first": xs[0], "last": xs[-1],
                    "min": min(xs), "max": max(xs),
                }

    if thr:
        tps = [float(r["tokens_per_sec"]) for r in thr
               if "tokens_per_sec" in r]
        msps = [float(r["ms_per_step"]) for r in thr
                if "ms_per_step" in r]
        mfus = [float(r["mfu"]) for r in thr if "mfu" in r]
        out["throughput"] = {
            "windows": [
                {k: (round(v, 4) if isinstance(v, float) else v)
                 for k, v in r.items()
                 if k in ("step", "ms_per_step", "tokens_per_sec", "mfu")}
                for r in thr
            ],
        }
        if tps:
            # the BENCH_*.json-comparable headline (bench reports the
            # best batch's steady-state rate; "best window" is the
            # live-stream analog)
            out["throughput"]["tokens_per_sec"] = _stats(tps)
            out["metric"] = "run_tokens_per_sec"
            out["value"] = round(max(tps), 1)
            out["unit"] = "tokens/s"
        if msps:
            out["throughput"]["ms_per_step"] = _stats(msps, better=min)
        if mfus:
            out["throughput"]["mfu"] = _stats(mfus)

    if meters:
        final = meters[-1]
        breakdown: Dict[str, Any] = {}
        timings = final.get("timings_ms")
        if timings and steps:
            n = max(len(steps), 1)
            breakdown["host_phase_ms_per_step"] = {
                k: round(v / n, 4) for k, v in timings.items()
            }
        if final.get("counters"):
            breakdown["counters"] = final["counters"]
        if final.get("gauges"):
            breakdown["gauges"] = final["gauges"]
        if breakdown:
            out["meters"] = breakdown

    if events:
        counts: Dict[str, int] = {}
        timeline = []
        for r in events:
            kind = r.get("event", "?")
            counts[kind] = counts.get(kind, 0) + 1
            entry = {"t_rel_s": round(r.get("t", t0) - t0, 3),
                     "event": kind}
            for k in ("step", "path", "ok", "duration_s", "bytes",
                      "restored_step", "consecutive_bad", "bucket",
                      "elapsed_s", "error",
                      # opt_tail (fused optimizer pass) fields: shape
                      # of the pass + its self-timed ms / achieved
                      # GB/s when measured standalone
                      "fused", "buffers", "buffer_bytes",
                      "moment_dtype", "unscale_folded", "self_ms",
                      "gbs",
                      # serving span / request / prefix-cache fields
                      "span", "steps", "slots", "tokens", "dur_s",
                      "weight_dtype", "weight_bytes", "tp",
                      "uid", "slot", "reason", "new_tokens",
                      "ttft_s", "chunk", "start", "matched_tokens",
                      "shared_pages", "tokens_skipped", "copied",
                      # fleet router / failover / trace fields
                      "replica", "slo", "affinity", "replays",
                      "migrated", "itl_ms", "rejected", "lost",
                      # fault-tolerance tier fields: quarantine /
                      # deadline / hedge / brownout / journal events
                      "cause", "retry", "consecutive", "hedged",
                      "primary", "from_level", "to_level",
                      "free_page_frac", "queue_depth", "resumed",
                      "corrupt", "gapped",
                      # disaggregation / offload-tier fields: page
                      # movement routes, sizes, and fault-in misses
                      "src", "dst", "pages", "misses"):
                if k in r:
                    entry[k] = r[k]
            timeline.append(entry)
        out["events"] = {"counts": counts, "timeline": timeline}

    serving = summarize_serving(records)
    if serving:
        out["serving"] = serving

    fleet = summarize_fleet(records)
    if fleet:
        out["fleet"] = fleet

    flt = summarize_faults(records)
    if flt:
        out["faults"] = flt

    kvm = summarize_kv_movement(records)
    if kvm:
        out["kv_movement"] = kvm

    return out


def compare_to_bench(summary: Dict[str, Any], bench_path: str
                     ) -> Optional[Dict[str, Any]]:
    """Ratio of this run's headline tokens/s to a BENCH_*.json record's
    (``{"metric": ..., "value": ..., "unit": "tokens/s"}``)."""
    try:
        with open(bench_path) as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read bench record {bench_path}: {e}",
              file=sys.stderr)
        return None
    bval = bench.get("value")
    if not bval or "value" not in summary:
        return None
    return {
        "bench_metric": bench.get("metric"),
        "bench_value": bval,
        "run_value": summary["value"],
        "run_vs_bench": round(summary["value"] / bval, 3),
    }


def format_report(summary: Dict[str, Any]) -> str:
    lines = []
    runs = ", ".join(summary.get("runs") or ["?"])
    lines.append(f"== metrics report: {runs} "
                 f"({summary.get('n_records', 0)} records) ==")
    st = summary.get("steps")
    if st:
        lines.append(f"steps {st['first']}..{st['last']} "
                     f"({st['count']} logged)")
    for k, s in (summary.get("scalars") or {}).items():
        lines.append(f"  {k}: first {s['first']:.4f}  last {s['last']:.4f}"
                     f"  min {s['min']:.4f}  max {s['max']:.4f}")
    thr = summary.get("throughput")
    if thr:
        lines.append("throughput trajectory (per flush window):")
        for w in thr["windows"]:
            row = f"  step {w.get('step')}: "
            if "ms_per_step" in w:
                row += f"{w['ms_per_step']:.2f} ms/step"
            if "tokens_per_sec" in w:
                row += f"  {w['tokens_per_sec']:,.0f} tokens/s"
            if "mfu" in w:
                row += f"  mfu {w['mfu']:.4f}"
            lines.append(row)
        for key in ("tokens_per_sec", "ms_per_step", "mfu"):
            if key in thr:
                s = thr[key]
                lines.append(
                    f"  {key}: mean {s['mean']:.4g}  best {s['best']:.4g}"
                    f"  final {s['final']:.4g}")
    met = summary.get("meters")
    if met:
        if "host_phase_ms_per_step" in met:
            lines.append("host phase time (ms/step): " + "  ".join(
                f"{k} {v:.3f}" for k, v in
                met["host_phase_ms_per_step"].items()))
        if "counters" in met:
            lines.append("counters: " + "  ".join(
                f"{k}={v}" for k, v in met["counters"].items()))
    sv = summary.get("serving")
    if sv:
        lines.append("serving summary:")
        if "decode_tokens_per_sec" in sv:
            s = sv["decode_tokens_per_sec"]
            lines.append(
                f"  decode tokens/s per window: mean {s['mean']:.4g}  "
                f"best {s['best']:.4g}  final {s['final']:.4g}")
        if "weight_stream_gbs" in sv or "weight_dtype" in sv:
            g = sv.get("weight_stream_gbs")
            row = "  weight stream: "
            if "weight_dtype" in sv:
                wd = sv["weight_dtype"]
                row += (wd if isinstance(wd, str) else "/".join(wd))
                row += " weights"
            if "tp" in sv:
                t = sv["tp"]
                row += (f", tp={t}" if isinstance(t, int)
                        else ", tp=" + "/".join(str(x) for x in t))
            if g:
                row += (f", mean {g['mean']:.4g} GB/s/chip  "
                        f"best {g['best']:.4g} GB/s/chip")
            lines.append(row)
        if "inter_token_latency_ms" in sv:
            i = sv["inter_token_latency_ms"]
            lines.append(
                f"  inter-token latency (window means): "
                f"p50 {i['p50']} ms  p90 {i['p90']} ms  "
                f"p99 {i['p99']} ms")
        if "ttft_s" in sv:
            t = sv["ttft_s"]
            # honesty note: "exact" TTFTs are admitted-event-to-
            # prefill-span wall time — no harvest quantization — but
            # under chunked prefill ADMISSION still progressed one
            # chunk per serving step, so TTFT includes the interleaved
            # decode steps (that interleaving is the point — decode
            # never stalled for a whole prompt); "completion"-sourced
            # TTFTs (old streams) stay harvest-quantized
            if t.get("source") == "exact":
                granularity = ("exact admit-to-first-token spans"
                               + (", chunk-granularity admission"
                                  if "prefill_chunks" in sv else ""))
            else:
                granularity = ("quantized to the harvest cadence"
                               + (", chunk-granularity admission"
                                  if "prefill_chunks" in sv else ""))
            lines.append(
                f"  time-to-first-token: p50 {t['p50']}s  "
                f"p95 {t['p95']}s  max {t['max']}s "
                f"({granularity})")
        if "requests" in sv:
            r = sv["requests"]
            by = "  ".join(f"{k}={v}"
                           for k, v in sorted(r["by_reason"].items()))
            lines.append(f"  requests completed: {r['completed']} ({by})")
        if "prefill_spans" in sv:
            lines.append(
                f"  prefill: {sv['prefill_spans']} admissions, "
                f"{sv.get('prefill_tokens', '?')} prompt tokens")
        if "prefill_chunks" in sv:
            pc = sv["prefill_chunks"]
            row = (f"  prefill chunks: {pc['count']} "
                   f"({pc['tokens']} tokens")
            if "mean_ms" in pc:
                row += (f", mean {pc['mean_ms']} ms, "
                        f"max {pc['max_ms']} ms")
            lines.append(row + ")")
        if "prefix_cache" in sv:
            px = sv["prefix_cache"]
            lines.append(
                f"  prefix cache: {px['hits']}/{px['admissions']} "
                f"admissions hit ({px['hit_rate']:.0%}), "
                f"{px['pages_shared']} pages shared, "
                f"{px['prefill_tokens_skipped']} prefill tokens "
                f"skipped, {px['pages_copied']} CoW copies")
        if "speculation" in sv:
            sp = sv["speculation"]
            row = (f"  speculation: {sp['committed']} tokens in "
                   f"{sp['verify_steps']} verify steps")
            if sp.get("committed_per_slot_step") is not None:
                row += (f" ({sp['committed_per_slot_step']:.2f} "
                        "tokens/slot-step)")
            if sp.get("wasted_verify_fraction") is not None:
                row += (f", wasted-verify "
                        f"{sp['wasted_verify_fraction']:.0%}")
            if sp.get("offramp_commits"):
                row += f", {sp['offramp_commits']} off-ramp commits"
            lines.append(row)
            if sp.get("draft_wall_fraction") is not None:
                lines.append(
                    f"    draft model cost: {sp['draft_wall_s']:.3f} s "
                    f"({sp['draft_wall_fraction']:.0%} of decode wall)")
            if sp.get("accepted_per_step_hist"):
                hist = "  ".join(
                    f"{k}:{v}" for k, v in sorted(
                        sp["accepted_per_step_hist"].items(),
                        key=lambda kv: int(kv[0])))
                lines.append(
                    f"    committed-per-step histogram: {hist}")
            for src, tot in sorted(
                    (sp.get("by_source") or {}).items()):
                row = (f"    [{src}] drafted {tot['drafted']}  "
                       f"accepted {tot['accepted']}")
                if "hit_rate" in tot:
                    row += f"  hit rate {tot['hit_rate']:.0%}"
                lines.append(row)
    fl = summary.get("fleet")
    if fl:
        lines.append("fleet summary:")
        if "routed" in fl:
            routed = "  ".join(f"{k}={v}"
                               for k, v in sorted(fl["routed"].items()))
            lines.append(
                f"  routed: {routed} "
                f"(affinity hits {fl.get('affinity_routed', 0)})")
        ledger = "  ".join(
            f"{k}={fl[k]}" for k in ("rejected", "migrated",
                                     "replicas_dead") if k in fl)
        if ledger:
            lines.append(f"  ledger: {ledger}")
        tr = fl.get("trace")
        if tr:
            lines.append(
                f"  trace: {tr['completed']}/{tr['requests']} "
                f"completed, {tr['lost']} lost")
        for name, c in (fl.get("by_class") or {}).items():
            row = f"  [{name}] n={c['n']}"
            if "ttft_s" in c:
                row += (f"  ttft p50 {c['ttft_s']['p50']}s "
                        f"p99 {c['ttft_s']['p99']}s")
            if "itl_ms" in c:
                row += (f"  itl p50 {c['itl_ms']['p50']}ms "
                        f"p99 {c['itl_ms']['p99']}ms")
            lines.append(row)
    ft = summary.get("faults")
    if ft:
        lines.append("fault / recovery summary:")
        rf = ft.get("replica_faults")
        if rf:
            by = "  ".join(f"{k}={v}"
                           for k, v in sorted(rf["by_replica"].items()))
            lines.append(f"  replica faults: {rf['count']} ({by})")
        if "quarantined" in ft:
            q = "  ".join(f"{r['replica']}({r['cause']})"
                          for r in ft["quarantined"])
            lines.append(f"  quarantined: {q}")
        mg = ft.get("migrations")
        if mg:
            by = "  ".join(f"{k}={v}"
                           for k, v in sorted(mg["by_cause"].items()))
            lines.append(f"  migrations: {mg['count']} ({by})")
        dm = ft.get("deadline_misses")
        if dm:
            lines.append(
                f"  deadline misses: {dm['count']} "
                f"({dm['retried']} retried, {dm['terminal']} terminal)")
        hg = ft.get("hedging")
        if hg:
            lines.append(
                f"  hedging: {hg['spawned']} spawned, "
                f"{hg['wins']} wins, {hg['losses']} losses")
        br = ft.get("brownout")
        if br:
            lines.append(
                f"  brownout: {br['transitions']} transitions "
                f"(peak level {br['max_level']})")
        for jr in ft.get("journal_replays", []):
            lines.append(
                f"  journal replay: {jr.get('resumed', 0)} resumed, "
                f"{jr.get('completed', 0)} already complete, "
                f"{jr.get('corrupt', 0)} corrupt, "
                f"{jr.get('gapped', 0)} gapped")
        for name, a in sorted((ft.get("slo_attainment") or {}).items()):
            lines.append(
                f"  [{name}] slo attainment {a['attainment']:.1%} "
                f"({a['deadline_missed']}/{a['n']} deadline-missed)")
    kvm = summary.get("kv_movement")
    if kvm:
        lines.append("kv movement summary:")
        ho = kvm.get("handoffs")
        if ho:
            routes = "  ".join(f"{k}x{v}"
                               for k, v in sorted(ho["by_route"].items()))
            row = (f"  handoffs: {ho['count']} ({ho['pages']} pages, "
                   f"{ho['wire_bytes']:,} wire bytes; {routes})")
            if "ms" in ho:
                row += (f"  mean {ho['ms']['mean']} ms  "
                        f"max {ho['ms']['max']} ms")
            lines.append(row)
        of = kvm.get("offload")
        if of:
            lines.append(
                f"  offloaded: {of['pages']} pages in {of['events']} "
                f"evictions ({of['wire_bytes']:,} bytes to host)")
        fi = kvm.get("faultin")
        if fi:
            row = (f"  fault-in: {fi['pages']} pages in {fi['events']} "
                   f"walks ({fi['wire_bytes']:,} bytes back), "
                   f"hit rate {fi['hit_rate']:.0%}, "
                   f"{fi['prefill_tokens_saved']} prefill tokens saved")
            if "ms" in fi:
                row += (f"  mean {fi['ms']['mean']} ms  "
                        f"max {fi['ms']['max']} ms")
            lines.append(row)
    ev = summary.get("events")
    if ev:
        lines.append("events: " + "  ".join(
            f"{k}x{v}" for k, v in sorted(ev["counts"].items())))
        for e in ev["timeline"]:
            extra = "  ".join(
                f"{k}={e[k]}" for k in e if k not in ("t_rel_s", "event"))
            lines.append(f"  +{e['t_rel_s']:9.3f}s  {e['event']}  {extra}")
    cmp_ = summary.get("vs_bench")
    if cmp_:
        lines.append(
            f"vs bench {cmp_['bench_metric']}: run {cmp_['run_value']:,} "
            f"/ bench {cmp_['bench_value']:,} = {cmp_['run_vs_bench']}x")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("jsonl", help="metrics JSONL file (MetricsLogger "
                                  "output)")
    ap.add_argument("--json", default=None,
                    help="also write the summary dict here")
    ap.add_argument("--bench", default=None,
                    help="a BENCH_*.json record to compare the "
                         "headline tokens/s against")
    args = ap.parse_args(argv)
    records = load_records(args.jsonl)
    if not records:
        print(f"{args.jsonl}: no records", file=sys.stderr)
        return 1
    summary = summarize(records)
    if args.bench:
        cmp_ = compare_to_bench(summary, args.bench)
        if cmp_:
            summary["vs_bench"] = cmp_
    print(format_report(summary))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=1)
        print(f"wrote {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Fault drill: SIGKILL a training process mid-``save_async`` and prove
the parent's next life resumes from the last valid checkpoint.

This is the resilience subsystem's end-to-end rehearsal of the failure
that actually takes down production runs — preemption landing while the
async checkpoint writer is mid-file — exercised with a real ``kill -9``
(no in-process mocking survives one) across a real process boundary:

1. spawn a toy train loop (``--child`` mode) that checkpoints every
   step via :func:`apex_tpu.checkpoint.save_async`, with each file
   write slowed by ``--write-delay`` so "mid-save" is a wide,
   deterministic target;
2. wait until ``--kill-after-saves`` checkpoints have landed, then
   SIGKILL the child the moment it announces the next save;
3. verify every surviving ``step_<N>`` directory passes
   ``checkpoint.verify`` (checksums intact), the half-written step left
   only a ``.tmp`` husk, and ``restore_latest_valid`` returns the last
   completed step;
4. re-spawn the child, which must resume from exactly that step and
   finish the run.

Exit code 0 = drill passed.  Run it standalone::

    python tools/fault_drill.py --root /tmp/drill --write-delay 0.05

or via the slow test tier (``tests/test_fault_drill.py``).
"""

from __future__ import annotations

import argparse
import os
import re
import shutil
import signal
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _log(msg: str) -> None:
    print(f"[fault-drill] {msg}", flush=True)


# ------------------------------------------------------------------ child
def run_child(root: str, steps: int, write_delay: float) -> int:
    """Toy train loop: resume, then one checkpoint per step, announcing
    SAVING/SAVED so the parent can time its kill."""
    import jax.numpy as jnp

    from apex_tpu import checkpoint as ckpt
    from apex_tpu.utils.autoresume import AutoResume

    if write_delay > 0:
        # stretch each file write so SIGKILL reliably lands mid-save
        orig_open = ckpt._open

        def slow_open(file, mode="r", *args, **kwargs):
            if any(c in mode for c in "wxa"):
                time.sleep(write_delay)
            return orig_open(file, mode, *args, **kwargs)

        ckpt._open = slow_open

    ar = AutoResume(root, interval_steps=1, keep=steps + 1)
    state, start = ar.resume()
    print(f"RESUMED {start}", flush=True)
    for step in range(start + 1, steps + 1):
        state = {"w": jnp.full((256, 256), float(step), jnp.float32),
                 "step": jnp.int32(step)}
        print(f"SAVING {step}", flush=True)
        handle = ckpt.save_async(os.path.join(root, f"step_{step}"), state)
        handle.result(timeout=120)
        print(f"SAVED {step}", flush=True)
    print("DONE", flush=True)
    return 0


# ----------------------------------------------------------------- parent
def _spawn_child(root: str, steps: int, write_delay: float):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--root", root, "--steps", str(steps),
         "--write-delay", str(write_delay)],
        stdout=subprocess.PIPE, text=True, bufsize=1, env=env,
    )


def run_drill(root: str, steps: int, kill_after_saves: int,
              write_delay: float) -> int:
    from apex_tpu import checkpoint as ckpt

    if os.path.isdir(root):
        shutil.rmtree(root)
    os.makedirs(root)

    # ---- leg 1: train, then kill -9 mid-save ------------------------
    child = _spawn_child(root, steps, write_delay)
    last_saved = None
    killed_step = None
    try:
        for line in child.stdout:
            line = line.strip()
            if m := re.fullmatch(r"SAVED (\d+)", line):
                last_saved = int(m.group(1))
            elif (m := re.fullmatch(r"SAVING (\d+)", line)) and \
                    last_saved is not None and \
                    last_saved >= kill_after_saves:
                killed_step = int(m.group(1))
                time.sleep(write_delay * 1.5)  # land inside the writes
                _log(f"SIGKILL at save of step {killed_step} "
                     f"(last completed: {last_saved})")
                child.kill()
                break
        else:
            _log("FAIL: child finished before the kill window")
            return 1
    finally:
        child.wait(timeout=60)
        child.stdout.close()

    # ---- verify what the kill left behind ---------------------------
    entries = sorted(os.listdir(root))
    _log(f"post-kill checkpoint root: {entries}")
    complete = [d for d in entries if re.fullmatch(r"step_(\d+)", d)]
    for d in complete:
        bad = ckpt.verify(os.path.join(root, d))
        if bad:
            _log(f"FAIL: surviving checkpoint {d} fails verify: {bad}")
            return 1
    _log(f"all {len(complete)} surviving checkpoints verify clean")

    tree, step = ckpt.restore_latest_valid(root)
    # on a loaded host the SIGKILL can race past the atomic rename: the
    # "interrupted" save may actually have completed, which is also a
    # correct outcome — what's never acceptable is anything else
    if step not in (last_saved, killed_step):
        _log(f"FAIL: restore_latest_valid returned step {step}, "
             f"expected {last_saved} (or {killed_step} if the kill "
             f"lost the race to the rename)")
        return 1
    if step == killed_step:
        _log(f"note: kill landed after step {killed_step}'s rename — "
             f"the save completed; resuming from it is correct")
    import numpy as np

    if not (np.asarray(tree["w"]) == float(step)).all():
        _log(f"FAIL: restored payload does not match step {step}")
        return 1
    _log(f"restore_latest_valid -> step {step} with intact payload")
    resume_from = step

    # ---- leg 2: resurrection must resume from that step -------------
    child = _spawn_child(root, steps, 0.0)
    out, _ = child.communicate(timeout=300)
    if child.returncode != 0:
        _log(f"FAIL: resumed child exited {child.returncode}")
        return 1
    m = re.search(r"^RESUMED (\d+)$", out, re.M)
    if m is None or int(m.group(1)) != resume_from:
        _log(f"FAIL: resumed child reported RESUMED "
             f"{m.group(1) if m else '<none>'}, expected {resume_from}")
        return 1
    if not re.search(r"^DONE$", out, re.M):
        _log("FAIL: resumed child did not finish the run")
        return 1
    _log(f"resumed from step {resume_from} and completed {steps} steps — "
         f"drill PASSED")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--root", default="/tmp/apex_tpu_fault_drill")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--kill-after-saves", type=int, default=2,
                    help="completed checkpoints required before SIGKILL")
    ap.add_argument("--write-delay", type=float, default=0.05,
                    help="per-file write slowdown in the child (s)")
    ap.add_argument("--child", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.kill_after_saves < 1:
        ap.error("--kill-after-saves must be >= 1")
    if args.child:
        return run_child(args.root, args.steps, args.write_delay)
    return run_drill(args.root, args.steps, args.kill_after_saves,
                     args.write_delay)


if __name__ == "__main__":
    sys.exit(main())

"""SCALE_MFU: MFU vs model scale on the real chip.

PROFILE_r05's roofline argument says the flagship's MFU ceiling
(~0.51 at 185M params / h1024) is a property of the model SCALE — the
h=1024 contraction dims cap single-matmul MXU efficiency near 60% on
v5e — and that the 0.55 target falls out at larger hidden sizes, not
from further tuning at h1024.  This tool measures that claim directly:
the same train step (bf16 + fp32 masters + FusedAdam + remat + flash
attention + auto-CE — byte-for-byte the bench flagship program, only
the config scaled) at increasing hidden size on one chip.

Writes SCALE_MFU.json.  Run (chip required): python tools/scale_mfu.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SEQ = 1024
WARMUP, STEPS = 2, 10

# (layers, hidden, heads, batch): hidden doubles while the optimizer
# state stays inside v5e HBM (16 GB): h2048/12L is ~671M params
# -> ~9.4 GB of bf16 params + fp32 masters + moments
CONFIGS = [
    ("flagship_h1024", 12, 1024, 8, 8),
    ("h1536", 12, 1536, 12, 8),
    ("h2048", 12, 2048, 16, 8),
]


def measure(tag, layers, hidden, heads, batch):
    from bench import FLAGSHIP, _peak_flops
    from apex_tpu.telemetry.metrics import transformer_flops_per_token
    from tools.profile_r05 import build

    params, opt_state, step, n_params = build(
        num_layers=layers, hidden_size=hidden, num_attention_heads=heads,
    )
    vocab = FLAGSHIP["vocab_size"]
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, SEQ), 0, vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    for _ in range(WARMUP):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    float(loss)  # host readback closes the chain (axon tunnel rules)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    final = float(loss)
    dt = (time.perf_counter() - t0) / STEPS
    assert jnp.isfinite(final), f"{tag}: non-finite loss"
    # the shared model-FLOP estimate (6N + 12*L*h*s) — the same
    # numerator the live telemetry's StepStats MFU uses
    flops_per_token = transformer_flops_per_token(
        n_params, layers, hidden, SEQ)
    tok_s = batch * SEQ / dt
    peak = _peak_flops(jax.devices()[0])
    mfu = tok_s * flops_per_token / peak if peak else None
    row = {
        "tag": tag, "layers": layers, "hidden": hidden, "heads": heads,
        "batch": batch, "seq": SEQ, "n_params": n_params,
        "ms_per_step": round(dt * 1e3, 2),
        "tokens_per_sec": round(tok_s, 1),
        "mfu": round(mfu, 4) if mfu else None,
    }
    print(json.dumps(row), flush=True)
    return row


def main():
    plat = jax.devices()[0].platform
    if plat not in ("tpu", "axon"):
        raise SystemExit(f"scale_mfu must run on TPU (got {plat})")
    rows = []
    for cfg in CONFIGS:
        try:
            rows.append(measure(*cfg))
        except AssertionError:
            raise  # non-finite loss is a correctness failure, never OOM
        except Exception as e:
            # OOM at the largest config is a finding, not a failure —
            # keep every completed row of a scarce chip session
            rows.append({"tag": cfg[0], "error": str(e)[:300]})
            print(f"{cfg[0]}: FAILED ({str(e)[:160]})", flush=True)
    doc = {
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
        "note": (
            "same train-step program as the bench flagship (build() from "
            "tools/profile_r05.py), hidden size scaled; PROFILE_r05's "
            "roofline predicts MFU rises with hidden because h=1024 "
            "contraction dims bound MXU efficiency, not any missing "
            "optimization"
        ),
        "rows": rows,
    }
    with open(os.path.join(REPO, "SCALE_MFU.json"), "w") as f:
        json.dump(doc, f, indent=1)
    print("wrote SCALE_MFU.json")


if __name__ == "__main__":
    main()

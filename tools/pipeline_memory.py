"""Pipeline activation-memory profile: compiled temp memory vs microbatch
count (VERDICT r2 item 4's committed artifact).

The compiled GPipe-with-remat schedule keeps per-tick stage inputs for the
backward; the table below measures how compiled temp memory actually
scales with ``num_micro`` at pp=4 (virtual CPU mesh, XLA memory analysis)
for remat on/off, next to the analytic expectation: with remat, the
backward stash is one activation per tick (num_micro + pp - 1 ticks);
without, every stage's full activation set lives until backward.

Writes PIPELINE_MEMORY.json.  Run: python tools/pipeline_memory.py

Reading the numbers (r4 A/B notes):

- the 1f1b absolute temp level moved 1.77 → 3.9 MB between rounds from
  the measurement environment, not the schedule: the round-3
  schedules.py re-measured in the round-4 environment gives 3.874 MB at
  micro=32 vs 3.899 for round-4 code (+0.6%).  The property that
  matters — temp FLAT in num_micro while GPipe grows — holds in both.
- interleaved 1f1b measuring slightly BELOW plain 1f1b (3.66 vs 3.9 MB)
  despite a V×-larger input buffer: each interleaved tick
  rematerializes one chunk (layers/V of a stage), so its per-tick vjp
  workspace is V× smaller — at this config the workspace term
  dominates the buffer term.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    pipeline,
    pipeline_1f1b,
    pipeline_1f1b_interleaved,
    pipeline_stage_specs,
    sync_replicated_grads,
)

LAYERS_PER_STAGE = 2
PP = 4
HIDDEN = 256
MB_ROWS = 8
VOCAB = 1024


def set_config(hidden=256, mb_rows=8, vocab=1024, layers_per_stage=2):
    """Swap the sweep's model scale (the r5 crossover sweep runs a
    hidden=1024 / 64-row config where per-tick activations dominate the
    constant workspace, making the GPipe-vs-1F1B crossover visible)."""
    global HIDDEN, MB_ROWS, VOCAB, LAYERS_PER_STAGE
    HIDDEN, MB_ROWS, VOCAB, LAYERS_PER_STAGE = (
        hidden, mb_rows, vocab, layers_per_stage)


def _setup(num_micro: int):
    """Model, specs, and data shared by both schedules' measurements —
    one definition so the GPipe and 1F1B rows stay comparable."""
    n_layers = PP * LAYERS_PER_STAGE
    params = {
        "w": jnp.zeros((n_layers, HIDDEN, HIDDEN)),
        "b": jnp.zeros((n_layers, HIDDEN)),
        "head": jnp.zeros((HIDDEN, VOCAB)),
    }
    specs = pipeline_stage_specs({"w": P(None, None, None),
                                  "b": P(None, None)})
    specs = {**specs, "head": P()}
    x = jnp.zeros((num_micro, MB_ROWS, HIDDEN))
    y = jnp.zeros((num_micro, MB_ROWS, HIDDEN))
    return params, specs, x, y


def _stage_body(local, h):
    def body(c, lp):
        return jnp.tanh(c @ lp["w"] + lp["b"]), None

    out, _ = jax.lax.scan(body, h, local)
    return out


def _head_loss(head, h, mb):
    return jnp.mean((h @ head)[..., :HIDDEN] * 0 + (h - mb["y"]) ** 2)


def _memory_row(f, params, x, y, **tags):
    mem = f.lower(params, x, y).compile().memory_analysis()
    return {
        **tags,
        "temp_mb": round(mem.temp_size_in_bytes / 1e6, 3),
        "argument_mb": round(mem.argument_size_in_bytes / 1e6, 3),
        "output_mb": round(mem.output_size_in_bytes / 1e6, 3),
    }


def measure(num_micro: int, remat: bool) -> dict:
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=PP
    )
    try:
        params, specs, x, y = _setup(num_micro)

        def loss(params, x, y):
            local = {"w": params["w"], "b": params["b"]}
            per = pipeline(
                first_fn=lambda mb: mb["x"],
                stage_fn=lambda h: _stage_body(local, h),
                last_fn=lambda h, mb: _head_loss(params["head"], h, mb),
                microbatches={"x": x, "y": y},
                remat=remat,
            )
            return jnp.mean(per)

        f = jax.jit(jax.shard_map(
            jax.value_and_grad(loss), mesh=mesh,
            in_specs=(specs, P(), P()), out_specs=(P(), specs),
        ))
        return _memory_row(f, params, x, y, schedule="gpipe",
                           num_micro=num_micro, remat=remat)
    finally:
        parallel_state.destroy_model_parallel()


def measure_1f1b(num_micro: int) -> dict:
    """True 1F1B: in-flight state bounded by 2*pp saved stage inputs —
    temp memory must be ~flat in num_micro."""
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=PP
    )
    try:
        params, specs, x, y = _setup(num_micro)

        def fb(params, x, y):
            losses, grads = pipeline_1f1b(
                first_fn=lambda prm, mb: mb["x"],
                stage_fn=lambda prm, h: _stage_body(
                    {"w": prm["w"], "b": prm["b"]}, h
                ),
                last_fn=lambda prm, h, mb: _head_loss(prm["head"], h, mb),
                params=params,
                microbatches={"x": x, "y": y},
            )
            grads = sync_replicated_grads(grads, specs)
            return jnp.mean(losses), grads

        f = jax.jit(jax.shard_map(
            fb, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs),
        ))
        return _memory_row(f, params, x, y, schedule="1f1b",
                           num_micro=num_micro,
                           remat="per-stage (built in)")
    finally:
        parallel_state.destroy_model_parallel()


def measure_interleaved(num_micro: int, V: int = 2) -> dict:
    """Interleaved 1F1B: (V, 2*pp) saved chunk inputs — temp memory must
    stay ~flat in num_micro (the fwd-only interleaved schedule it
    replaces paid GPipe's O(num_micro))."""
    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=PP
    )
    try:
        params, specs, x, y = _setup(num_micro)
        # same total layers, chunked (V, pp, per, ...)
        per = params["w"].shape[0] // (V * PP)
        params = {
            "w": params["w"].reshape(V, PP, per, HIDDEN, HIDDEN),
            "b": params["b"].reshape(V, PP, per, HIDDEN),
            "head": params["head"],
        }
        specs = {"w": P(None, "pp", None, None, None),
                 "b": P(None, "pp", None, None), "head": P()}

        def fb(params, x, y):
            def chunk_fn(prm, h, v):
                local = {
                    "w": jax.lax.dynamic_index_in_dim(
                        prm["w"], v, 0, False)[0],
                    "b": jax.lax.dynamic_index_in_dim(
                        prm["b"], v, 0, False)[0],
                }
                return _stage_body(local, h)

            losses, grads = pipeline_1f1b_interleaved(
                first_fn=lambda prm, mb: mb["x"],
                chunk_fn=chunk_fn,
                last_fn=lambda prm, h, mb: _head_loss(prm["head"], h, mb),
                params=params,
                microbatches={"x": x, "y": y},
                num_model_chunks=V,
            )
            grads = sync_replicated_grads(grads, specs)
            return jnp.mean(losses), grads

        f = jax.jit(jax.shard_map(
            fb, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs),
        ))
        return _memory_row(f, params, x, y, schedule="1f1b_interleaved",
                           num_micro=num_micro, num_model_chunks=V,
                           remat="per-chunk (built in)")
    finally:
        parallel_state.destroy_model_parallel()


def measure_encdec(num_micro: int, fb_1f1b: bool) -> dict:
    """Enc-dec fused schedules: the 1F1B variant must hold temp ~flat in
    num_micro (O(pp) saved {x, mem} pairs) where vjp-through-GPipe grows
    with the tape."""
    from apex_tpu.transformer.pipeline_parallel import (
        pipeline_encdec_fused,
        pipeline_encdec_fused_1f1b,
    )

    mesh = parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=PP
    )
    try:
        params, specs, x, y = _setup(num_micro)
        split = PP // 2

        def stage_fn(prm, h, mem, stage_idx):
            local = {"w": prm["w"], "b": prm["b"]}
            # homogeneous body with a gated "cross" term standing in for
            # cross-attention: FLOP shape matches the fused T5 design
            gate = (stage_idx >= split).astype(h.dtype)
            h = _stage_body(local, h)
            return h + gate * jnp.tanh(mem @ local["w"][0]) * 0.1

        def enc_entry(prm, mb):
            return mb["x"]

        def dec_entry(prm, mb):
            return mb["x"] * 0.5

        def last_fn(prm, h, mb):
            return _head_loss(prm["head"], h, mb)

        if fb_1f1b:
            def fb(params, x, y):
                losses, grads = pipeline_encdec_fused_1f1b(
                    enc_entry, dec_entry, stage_fn, last_fn,
                    params, {"x": x, "y": y}, split,
                )
                grads = sync_replicated_grads(grads, specs)
                return jnp.mean(losses), grads
        else:
            def fb(params, x, y):
                def loss(prm):
                    per = pipeline_encdec_fused(
                        lambda mb: enc_entry(prm, mb),
                        lambda mb: dec_entry(prm, mb),
                        lambda h, mem, s: stage_fn(prm, h, mem, s),
                        lambda h, mb: last_fn(prm, h, mb),
                        {"x": x, "y": y}, split, remat=True,
                    )
                    return jnp.mean(per)

                l, grads = jax.value_and_grad(loss)(params)
                grads = sync_replicated_grads(grads, specs)
                return l, grads

        f = jax.jit(jax.shard_map(
            fb, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs),
        ))
        return _memory_row(
            f, params, x, y,
            schedule="encdec_1f1b" if fb_1f1b else "encdec_gpipe_vjp",
            num_micro=num_micro,
        )
    finally:
        parallel_state.destroy_model_parallel()


def _config_doc():
    return {
        "pp": PP, "hidden": HIDDEN, "mb_rows": MB_ROWS,
        "vocab": VOCAB, "layers_per_stage": LAYERS_PER_STAGE,
        "activation_mb": MB_ROWS * HIDDEN * 4 / 1e6,
    }


def main():
    rows = []
    for remat in (True, False):
        for num_micro in (2, 4, 8, 16, 32):
            row = measure(num_micro, remat)
            rows.append(row)
            print(json.dumps(row))
    for num_micro in (2, 4, 8, 16, 32):
        row = measure_1f1b(num_micro)
        rows.append(row)
        print(json.dumps(row))
    for num_micro in (4, 8, 16, 32):  # interleaved needs micro % pp == 0
        row = measure_interleaved(num_micro)
        rows.append(row)
        print(json.dumps(row))
    for num_micro in (2, 8, 32):
        for fb_1f1b in (False, True):
            row = measure_encdec(num_micro, fb_1f1b)
            rows.append(row)
            print(json.dumps(row))
    small_config = _config_doc()

    # ---- offset decomposition (r4 verdict: the ~1.5 MB constant the
    # 1f1b temp level sits above gpipe+remat at the small config).
    # Three controlled variants at micro=8 attribute it to measured
    # components rather than guesses: (a) vocab=1 removes the LM-head
    # stash + dhead workspace; (b) mb_rows doubled scales activation-
    # proportional terms; (c) gpipe+remat under the same variants.
    decomp = []
    for tag, hidden, mb_rows, vocab in (
        ("base", 256, 8, 1024),
        ("no_head", 256, 8, 1),
        ("2x_rows", 256, 16, 1024),
    ):
        set_config(hidden=hidden, mb_rows=mb_rows, vocab=vocab)
        a = measure_1f1b(8)
        b = measure(8, True)
        decomp.append({"variant": tag, "config": _config_doc(),
                       "1f1b_temp_mb": a["temp_mb"],
                       "gpipe_remat_temp_mb": b["temp_mb"],
                       "offset_mb": round(a["temp_mb"] - b["temp_mb"], 3)})
        print(json.dumps(decomp[-1]))
    set_config()

    # ---- crossover sweep: hidden=1024 / 64-row microbatches, where a
    # tick's activation (64*1024*4 = 256 KB) dwarfs the constant
    # workspace.  GPipe+remat stashes one activation per tick
    # (num_micro + pp - 1 of them), 1F1B keeps O(pp) in flight — the
    # curves must cross as num_micro grows.
    set_config(hidden=1024, mb_rows=64, vocab=1024)
    large_rows = []
    for num_micro in (4, 8, 16, 32, 64):
        row = measure(num_micro, True)
        large_rows.append(row)
        print(json.dumps(row))
        row = measure_1f1b(num_micro)
        large_rows.append(row)
        print(json.dumps(row))
    large_config = _config_doc()
    set_config()
    crossover = None
    for m in (4, 8, 16, 32, 64):
        g = next(r["temp_mb"] for r in large_rows
                 if r["schedule"] == "gpipe" and r["num_micro"] == m)
        o = next(r["temp_mb"] for r in large_rows
                 if r["schedule"] == "1f1b" and r["num_micro"] == m)
        if o < g:
            crossover = m
            break

    doc = {
        "config": small_config,
        "rows": rows,
        "offset_decomposition": decomp,
        "large_config": large_config,
        "large_rows": large_rows,
        "crossover_num_micro": crossover,
        "notes": (
            "large sweep: gpipe+remat temp grows ~one activation per tick "
            "(num_micro + pp - 1), 1f1b holds O(pp) stage inputs; "
            "crossover_num_micro is the first measured num_micro where "
            "1f1b temp < gpipe+remat temp at the large config (r5 "
            "capture: gpipe 17.8->76.5 MB over micro 4->64 vs 1f1b flat "
            "at 39.1 MB, crossing at micro=32). The small-config ~1.5 MB "
            "constant offset decomposes per offset_decomposition: "
            "removing the LM head (no_head) cuts it ~35% (head-grad "
            "buffers held across the fwd+bwd scan), while doubling "
            "activation rows (2x_rows) leaves it ~flat — the offset is "
            "per-program vjp workspace (1f1b's single scan carries both "
            "fwd and bwd temporaries), constant in num_micro AND in "
            "activation size, i.e. exactly the term that stops "
            "mattering at production scale where the large sweep's "
            "per-tick activations dominate."
        ),
    }
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PIPELINE_MEMORY.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()

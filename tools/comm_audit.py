"""Communication-bytes audit: compile a step, walk the HLO, and report
per-collective bytes-on-wire split by mesh axis (dcn vs ici).

Wall-clock DCN wins cannot be measured on the CI virtual mesh, so this
tool proves the compressed-collectives win STRUCTURALLY: it compiles
the hierarchical gradient-sync step twice (``compression=None`` vs
``compression="int8"``), walks the optimized HLO for collective ops
(all-reduce / all-gather / reduce-scatter / all-to-all /
collective-permute), classifies each by which mesh axis its
``replica_groups`` span, and totals the bytes that cross the slow dcn
axis.  The headline number is the dcn-bytes ratio (uncompressed /
compressed), gated at >= 3.5x by the multichip dryrun.

Bytes-on-wire model (per participating device, ring algorithms):

- all-reduce:       2 * (g-1)/g * operand_bytes
- all-gather:           (g-1)/g * result_bytes
- reduce-scatter:       (g-1)/g * operand_bytes
- all-to-all:           (g-1)/g * operand_bytes
- collective-permute:             operand_bytes

A collective counts toward an axis when any of its replica groups
spans more than one rank of that axis (a flat world-spanning psum
therefore counts as crossing dcn — which is exactly the traffic the
hierarchy exists to avoid).

Run on the 8-device virtual mesh (no TPU needed):

    python tools/comm_audit.py                 # writes COMM_AUDIT.json
    python tools/comm_audit.py --ici-size 4 --block-size 256
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _force_virtual_devices(n: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


# gradient pytree shaped like a small GPT (embedding, per-layer
# attention/MLP/norms, lm head tied) — representative leaf-size mix so
# the audit exercises blocks, padding and the scale sidecar like a real
# model step would
GPT_ISH_SHAPES = {
    "embedding": (8192, 256),
    "position": (1024, 256),
    "layers": {
        "qkv_w": (4, 256, 768), "qkv_b": (4, 768),
        "proj_w": (4, 256, 256), "proj_b": (4, 256),
        "fc1_w": (4, 256, 1024), "fc1_b": (4, 1024),
        "fc2_w": (4, 1024, 256), "fc2_b": (4, 256),
        "ln1_scale": (4, 256), "ln1_bias": (4, 256),
        "ln2_scale": (4, 256), "ln2_bias": (4, 256),
    },
    "final_ln": (256,),
}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
    "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8,
}

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[\d,{} ]*\})\}")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([\d,{} ]*)\}")


def _shape_bytes(dtype: str, dims: str) -> int:
    if dtype not in _DTYPE_BYTES:
        return 0  # token/opaque types carry no payload
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return n * _DTYPE_BYTES[dtype]


_OP_RE = re.compile(
    r"=\s*((?:\([^)]*\)|\S+))\s+("
    + "|".join(_COLLECTIVES)
    + r")(-start|-done)?\((.*)$"
)


def parse_collectives(hlo_text: str):
    """Extract collective ops from HLO text: one record per op with
    the op kind, result/operand payload bytes and replica groups.
    ``-done`` halves of async pairs are skipped (the ``-start`` op
    carries the payload)."""
    out = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m or "%" not in line:
            continue
        if m.group(3) == "-done":
            continue
        op = m.group(2)
        result_bytes = sum(
            _shape_bytes(d, s)
            for d, s in _SHAPE_RE.findall(m.group(1))
        )
        # operands end at the call's closing paren; attributes
        # (replica_groups, to_apply, metadata) follow it
        operand_bytes = sum(
            _shape_bytes(d, s)
            for d, s in _SHAPE_RE.findall(m.group(4).split(")", 1)[0])
        )
        gm = _GROUPS_RE.search(line)
        groups = []
        if gm:
            groups = [
                [int(x) for x in g.split(",") if x.strip()]
                for g in re.findall(r"\{([\d, ]*)\}", gm.group(1))
            ]
        pm = _PAIRS_RE.search(line)
        pairs = []
        if pm:
            pairs = [
                tuple(int(x) for x in p.split(","))
                for p in re.findall(r"\{([\d, ]+)\}", pm.group(1))
            ]
        out.append({
            "op": op,
            "result_bytes": result_bytes,
            "operand_bytes": operand_bytes,
            "replica_groups": groups,
            "pairs": pairs,
        })
    return out


def _wire_bytes(rec) -> float:
    g = max((len(grp) for grp in rec["replica_groups"]), default=1)
    if rec["op"] == "all-reduce":
        return 2.0 * (g - 1) / g * rec["operand_bytes"]
    if rec["op"] == "all-gather":
        return (g - 1) / g * rec["result_bytes"]
    if rec["op"] in ("reduce-scatter", "all-to-all"):
        return (g - 1) / g * rec["operand_bytes"]
    return float(rec["operand_bytes"])  # collective-permute


def classify_and_total(records, mesh, dcn_axis="dcn", ici_axis="ici"):
    """Label each collective by the mesh axes its groups span and total
    the wire bytes per label.  Device ids map to (dcn, ici) coordinates
    through the mesh's device grid."""
    import numpy as np

    names = list(mesh.axis_names)
    di, ii = names.index(dcn_axis), names.index(ici_axis)
    coords = {}
    grid = np.asarray(mesh.devices)
    for idx, dev in np.ndenumerate(grid):
        coords[dev.id] = (idx[di], idx[ii])

    totals = {"dcn": 0.0, "ici": 0.0, "other": 0.0}
    for rec in records:
        groups = rec["replica_groups"] or [
            list(p) for p in rec["pairs"]
        ]
        crosses_dcn = crosses_ici = False
        known = True
        for grp in groups:
            cs = [coords.get(d) for d in grp]
            if any(c is None for c in cs):
                known = False
                break
            crosses_dcn |= len({c[0] for c in cs}) > 1
            crosses_ici |= len({c[1] for c in cs}) > 1
        wb = _wire_bytes(rec)
        if not known or not groups:
            label = "other"
        elif crosses_dcn:
            label = "dcn"  # anything touching the slow axis bills dcn
        elif crosses_ici:
            label = "ici"
        else:
            label = "other"
        rec["axis"] = label
        rec["wire_bytes"] = wb
        totals[label] += wb
    return totals


def audit_fn(jitted, args, mesh, dcn_axis="dcn", ici_axis="ici"):
    """Compile ``jitted`` for ``args``, walk the optimized HLO and
    return ``(per_axis_totals, collective_records)``."""
    txt = jitted.lower(*args).compile().as_text()
    records = parse_collectives(txt)
    totals = classify_and_total(records, mesh, dcn_axis, ici_axis)
    return totals, records


def _shard_map():
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map

    def compat(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    return compat


def audit_gradient_sync(compression, ici_size=4, block_size=256,
                        shapes=GPT_ISH_SHAPES, dtype=None):
    """Compile the hierarchical gradient-sync step over a GPT-shaped
    grad pytree and audit its collectives.  Returns the result dict."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.ops.quantization import CompressionConfig
    from apex_tpu.parallel import (
        all_reduce_gradients,
        hierarchical_data_parallel_mesh,
    )
    from apex_tpu.parallel.distributed import (
        comm_state_specs,
        init_comm_state,
    )

    dtype = dtype or jnp.float32
    mesh = hierarchical_data_parallel_mesh(ici_size=ici_size)
    axes = ("dcn", "ici")
    grads = jax.tree.map(
        lambda s: jnp.zeros(s, dtype), shapes,
        is_leaf=lambda x: isinstance(x, tuple),
    )
    pspec = jax.tree.map(lambda _: P(), grads)
    shard_map = _shard_map()

    cfg = None
    if compression is not None:
        cfg = CompressionConfig(method=compression,
                                block_size=block_size)

    if cfg is not None and cfg.error_feedback:
        cstate = init_comm_state(grads, axes, cfg, mesh=mesh)
        cspecs = comm_state_specs(cstate, axes)
        fn = shard_map(
            lambda g, st: all_reduce_gradients(
                g, axes, compression=cfg, comm_state=st),
            mesh, (pspec, cspecs), (pspec, cspecs),
        )
        args = (grads, cstate)
    else:
        fn = shard_map(
            lambda g: all_reduce_gradients(g, axes, compression=cfg),
            mesh, (pspec,), pspec,
        )
        args = (grads,)

    totals, records = audit_fn(jax.jit(fn), args, mesh)
    n_elems = sum(
        int(jnp.size(l)) for l in jax.tree.leaves(grads)
    )
    return {
        "compression": compression or "none",
        "ici_size": ici_size,
        "block_size": block_size,
        "grad_elements": n_elems,
        "grad_bytes": n_elems * jnp.dtype(dtype).itemsize,
        "bytes_on_wire": {k: round(v, 1) for k, v in totals.items()},
        "collectives": [
            {"op": r["op"], "axis": r["axis"],
             "wire_bytes": round(r["wire_bytes"], 1)}
            for r in records
        ],
    }


def run_audit(ici_size=4, block_size=256):
    """The before/after pair + the headline dcn reduction ratio."""
    base = audit_gradient_sync(None, ici_size, block_size)
    comp = audit_gradient_sync("int8", ici_size, block_size)
    ratio = (base["bytes_on_wire"]["dcn"]
             / max(comp["bytes_on_wire"]["dcn"], 1e-9))
    return {
        "metric": "dcn_gradient_bytes_ratio",
        "value": round(ratio, 2),
        "unit": "x fewer dcn bytes (int8 vs none)",
        "baseline": base,
        "compressed": comp,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ici-size", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=256)
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual device count when no backend is up")
    ap.add_argument("--min-ratio", type=float, default=None,
                    help="exit nonzero unless the dcn-bytes ratio "
                         "meets this floor")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "COMM_AUDIT.json",
    ))
    args = ap.parse_args()
    _force_virtual_devices(args.devices)

    doc = run_audit(args.ici_size, args.block_size)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({
        "metric": doc["metric"], "value": doc["value"],
        "unit": doc["unit"],
        "dcn_bytes_none": doc["baseline"]["bytes_on_wire"]["dcn"],
        "dcn_bytes_int8": doc["compressed"]["bytes_on_wire"]["dcn"],
        "ici_bytes_none": doc["baseline"]["bytes_on_wire"]["ici"],
        "ici_bytes_int8": doc["compressed"]["bytes_on_wire"]["ici"],
    }))
    print(f"wrote {args.out}")
    if args.min_ratio is not None and doc["value"] < args.min_ratio:
        raise SystemExit(
            f"dcn bytes ratio {doc['value']} < floor {args.min_ratio}"
        )


if __name__ == "__main__":
    main()

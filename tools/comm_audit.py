"""Communication-bytes audit: compile a step, walk the HLO, and report
per-collective bytes-on-wire split by mesh axis (dcn vs ici) — plus an
OVERLAP audit of the *scheduled* HLO that proves gradient collectives
have compute to hide behind (``--overlap``).

Wall-clock DCN wins cannot be measured on the CI virtual mesh, so this
tool proves the compressed-collectives win STRUCTURALLY: it compiles
the hierarchical gradient-sync step twice (``compression=None`` vs
``compression="int8"``), walks the optimized HLO for collective ops
(all-reduce / all-gather / reduce-scatter / all-to-all /
collective-permute), classifies each by which mesh axis its
``replica_groups`` span, and totals the bytes that cross the slow dcn
axis.  The headline number is the dcn-bytes ratio (uncompressed /
compressed), gated at >= 3.5x by the multichip dryrun.

Bytes-on-wire model (per participating device, ring algorithms):

- all-reduce:       2 * (g-1)/g * operand_bytes
- all-gather:           (g-1)/g * result_bytes
- reduce-scatter:       (g-1)/g * operand_bytes
- all-to-all:           (g-1)/g * operand_bytes
- collective-permute:             operand_bytes

A collective counts toward an axis when any of its replica groups
spans more than one rank of that axis (a flat world-spanning psum
therefore counts as crossing dcn — which is exactly the traffic the
hierarchy exists to avoid).

Overlap audit (``--overlap``): the bytes model above says nothing about
whether the collective's LATENCY is exposed.  The optimized module is
scheduled (``is_scheduled=true``), so the audit walks the instruction
sequence and, per gradient collective:

- counts literal ``-start``/``-done`` async pairs and the compute
  scheduled inside each window (TPU/GPU backends emit these; the CPU
  backend used on CI executes collectives synchronously and never
  will — so zero pairs on CPU is expected, not a failure);
- computes the SCHEDULABLE overlap from dataflow: every instruction
  that is neither an ancestor of the collective's operands nor a
  descendant of its result could legally execute between start and
  done — that independent compute is exactly what a latency-hiding
  scheduler needs, and its existence is provable on any backend;
- estimates hidden vs exposed time under the ring wire model (bytes /
  per-axis bandwidth vs a FLOP/byte model of the independent compute).
  The estimate is optimistic — independent compute shared between two
  collectives is counted for both — so read it as "could hide", and
  the gate is on the overlappable FRACTION, not the milliseconds.

The overlappable FRACTION reads 1.0 for both loops on this dataflow
criterion (even the deferred reduce's late-layer collectives are
independent of earlier layers' backward, and earlier microbatches'
compute is dataflow-independent of the pipelined loop's final flush —
whether a temporal schedule can exploit that is the estimate's
optimism).  What separates the loops is the independent-compute
VOLUME: with K microbatches the pipelined loop exposes roughly (K-1)
extra whole microbatches of fwd/bwd per reduce round, so the gate
pairs overlappable_frac (sanity: no collective is dataflow-locked)
with overlap-vs-deferred ``independent_compute_ms`` (the pipelining
actually created the windows).

Run on the 8-device virtual mesh (no TPU needed):

    python tools/comm_audit.py                 # writes COMM_AUDIT.json
    python tools/comm_audit.py --ici-size 4 --block-size 256
    python tools/comm_audit.py --overlap       # writes OVERLAP_AUDIT.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _force_virtual_devices(n: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


# gradient pytree shaped like a small GPT (embedding, per-layer
# attention/MLP/norms, lm head tied) — representative leaf-size mix so
# the audit exercises blocks, padding and the scale sidecar like a real
# model step would
GPT_ISH_SHAPES = {
    "embedding": (8192, 256),
    "position": (1024, 256),
    "layers": {
        "qkv_w": (4, 256, 768), "qkv_b": (4, 768),
        "proj_w": (4, 256, 256), "proj_b": (4, 256),
        "fc1_w": (4, 256, 1024), "fc1_b": (4, 1024),
        "fc2_w": (4, 1024, 256), "fc2_b": (4, 256),
        "ln1_scale": (4, 256), "ln1_bias": (4, 256),
        "ln2_scale": (4, 256), "ln2_bias": (4, 256),
    },
    "final_ln": (256,),
}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
    "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8,
}

_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[\d,{} ]*\})\}")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([\d,{} ]*)\}")


def _shape_bytes(dtype: str, dims: str) -> int:
    if dtype not in _DTYPE_BYTES:
        return 0  # token/opaque types carry no payload
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return n * _DTYPE_BYTES[dtype]


_OP_RE = re.compile(
    r"=\s*((?:\([^)]*\)|\S+))\s+("
    + "|".join(_COLLECTIVES)
    + r")(-start|-done)?\((.*)$"
)


#: tlm.<phase> named scopes survive into each op's HLO metadata
#: (``op_name``), which is what lets the audit tell a ZeRO-3
#: param-gather all-gather apart from a gradient-sync one — same op,
#: same axis, different phase.
_PHASE_RE = re.compile(r"tlm\.(\w+)")


def parse_collectives(hlo_text: str):
    """Extract collective ops from HLO text: one record per op with
    the op kind, result/operand payload bytes, replica groups and —
    when the op carries a ``tlm.<phase>`` named scope in its metadata
    — the step phase (``param_gather`` for ZeRO-3 weight gathers,
    ``grad_sync`` for gradient reduces).  ``-done`` halves of async
    pairs are skipped (the ``-start`` op carries the payload)."""
    out = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if not m or "%" not in line:
            continue
        if m.group(3) == "-done":
            continue
        op = m.group(2)
        pm = _PHASE_RE.search(line)
        phase = pm.group(1) if pm else None
        result_bytes = sum(
            _shape_bytes(d, s)
            for d, s in _SHAPE_RE.findall(m.group(1))
        )
        # operands end at the call's closing paren; attributes
        # (replica_groups, to_apply, metadata) follow it
        operand_bytes = sum(
            _shape_bytes(d, s)
            for d, s in _SHAPE_RE.findall(m.group(4).split(")", 1)[0])
        )
        gm = _GROUPS_RE.search(line)
        groups = []
        if gm:
            groups = [
                [int(x) for x in g.split(",") if x.strip()]
                for g in re.findall(r"\{([\d, ]*)\}", gm.group(1))
            ]
        pm = _PAIRS_RE.search(line)
        pairs = []
        if pm:
            pairs = [
                tuple(int(x) for x in p.split(","))
                for p in re.findall(r"\{([\d, ]+)\}", pm.group(1))
            ]
        out.append({
            "op": op,
            "phase": phase,
            "result_bytes": result_bytes,
            "operand_bytes": operand_bytes,
            "replica_groups": groups,
            "pairs": pairs,
        })
    return out


def _wire_bytes(rec) -> float:
    # the ONE ring bytes-on-wire model, shared with the live telemetry
    # stream's per-bucket comm events (they estimate, this measures —
    # delegating keeps the two from ever drifting)
    from apex_tpu.telemetry.events import ring_wire_bytes

    g = max((len(grp) for grp in rec["replica_groups"]), default=1)
    return ring_wire_bytes(rec["op"], g, rec["operand_bytes"],
                           result_bytes=rec["result_bytes"])


def _mesh_coords(mesh, dcn_axis="dcn", ici_axis="ici"):
    """device id -> (dcn, ici) coordinate map for a mesh."""
    import numpy as np

    names = list(mesh.axis_names)
    di, ii = names.index(dcn_axis), names.index(ici_axis)
    coords = {}
    grid = np.asarray(mesh.devices)
    for idx, dev in np.ndenumerate(grid):
        coords[dev.id] = (idx[di], idx[ii])
    return coords


def _axis_label(groups, pairs, coords):
    """'dcn' | 'ici' | 'other' for a collective's replica groups."""
    groups = groups or [list(p) for p in pairs]
    crosses_dcn = crosses_ici = False
    known = True
    for grp in groups:
        cs = [coords.get(d) for d in grp]
        if any(c is None for c in cs):
            known = False
            break
        crosses_dcn |= len({c[0] for c in cs}) > 1
        crosses_ici |= len({c[1] for c in cs}) > 1
    if not known or not groups:
        return "other"
    if crosses_dcn:
        return "dcn"  # anything touching the slow axis bills dcn
    if crosses_ici:
        return "ici"
    return "other"


def classify_and_total(records, mesh, dcn_axis="dcn", ici_axis="ici"):
    """Label each collective by the mesh axes its groups span and total
    the wire bytes per label — and per LEG (``axis/op``), so the
    RS(ici) and AG(ici) halves of the hierarchical reduce are
    accounted separately from the AR(dcn) middle (the int8 gather
    compression's win lives entirely in the ici legs).  Device ids map
    to (dcn, ici) coordinates through the mesh's device grid.
    Returns ``(per_axis_totals, per_leg_totals)``."""
    coords = _mesh_coords(mesh, dcn_axis, ici_axis)
    totals = {"dcn": 0.0, "ici": 0.0, "other": 0.0}
    legs = {}
    for rec in records:
        label = _axis_label(rec["replica_groups"], rec["pairs"], coords)
        wb = _wire_bytes(rec)
        rec["axis"] = label
        rec["wire_bytes"] = wb
        totals[label] += wb
        leg = f"{label}/{rec['op']}"
        legs[leg] = legs.get(leg, 0.0) + wb
    return totals, legs


def audit_fn(jitted, args, mesh, dcn_axis="dcn", ici_axis="ici"):
    """Compile ``jitted`` for ``args``, walk the optimized HLO and
    return ``(per_axis_totals, per_leg_totals, collective_records)``."""
    txt = jitted.lower(*args).compile().as_text()
    records = parse_collectives(txt)
    totals, legs = classify_and_total(records, mesh, dcn_axis, ici_axis)
    return totals, legs, records


def _shard_map():
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map

    def compat(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    return compat


def audit_gradient_sync(compression, ici_size=4, block_size=256,
                        shapes=GPT_ISH_SHAPES, dtype=None):
    """Compile the hierarchical gradient-sync step over a GPT-shaped
    grad pytree and audit its collectives.  Returns the result dict."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.ops.quantization import CompressionConfig
    from apex_tpu.parallel import (
        all_reduce_gradients,
        hierarchical_data_parallel_mesh,
    )
    from apex_tpu.parallel.distributed import (
        comm_state_specs,
        init_comm_state,
    )

    dtype = dtype or jnp.float32
    mesh = hierarchical_data_parallel_mesh(ici_size=ici_size)
    axes = ("dcn", "ici")
    grads = jax.tree.map(
        lambda s: jnp.zeros(s, dtype), shapes,
        is_leaf=lambda x: isinstance(x, tuple),
    )
    pspec = jax.tree.map(lambda _: P(), grads)
    shard_map = _shard_map()

    if isinstance(compression, CompressionConfig):
        cfg = compression
        compression = cfg.method + ("+ici" if cfg.ici_legs else "")
    elif compression is not None:
        cfg = CompressionConfig(method=compression,
                                block_size=block_size)
    else:
        cfg = None

    if cfg is not None and cfg.error_feedback:
        cstate = init_comm_state(grads, axes, cfg, mesh=mesh)
        cspecs = comm_state_specs(cstate, axes)
        fn = shard_map(
            lambda g, st: all_reduce_gradients(
                g, axes, compression=cfg, comm_state=st),
            mesh, (pspec, cspecs), (pspec, cspecs),
        )
        args = (grads, cstate)
    else:
        fn = shard_map(
            lambda g: all_reduce_gradients(g, axes, compression=cfg),
            mesh, (pspec,), pspec,
        )
        args = (grads,)

    totals, legs, records = audit_fn(jax.jit(fn), args, mesh)
    n_elems = sum(
        int(jnp.size(l)) for l in jax.tree.leaves(grads)
    )
    return {
        "compression": compression or "none",
        "ici_size": ici_size,
        "block_size": cfg.block_size if cfg is not None else block_size,
        "grad_elements": n_elems,
        "grad_bytes": n_elems * jnp.dtype(dtype).itemsize,
        "bytes_on_wire": {k: round(v, 1) for k, v in totals.items()},
        "bytes_by_leg": {k: round(v, 1) for k, v in sorted(legs.items())},
        "collectives": [
            {"op": r["op"], "axis": r["axis"],
             "wire_bytes": round(r["wire_bytes"], 1)}
            for r in records
        ],
    }


def run_audit(ici_size=4, block_size=256):
    """The before/after TRIPLE + reduction ratios: compression=None,
    DCN-only int8 (the headline ``value`` stays the dcn ratio for
    record continuity), and int8 with ``ici_legs=True`` (the EQuARX
    gather-leg half) with per-LEG compressed-vs-full ratios — the
    number the multichip dryrun's ici config gates at >= 3x."""
    from apex_tpu.ops.quantization import (
        CompressionConfig as _CC,
    )

    base = audit_gradient_sync(None, ici_size, block_size)
    comp = audit_gradient_sync("int8", ici_size, block_size)
    gather = audit_gradient_sync(
        _CC(block_size=block_size, ici_legs=True), ici_size, block_size
    )
    ratio = (base["bytes_on_wire"]["dcn"]
             / max(comp["bytes_on_wire"]["dcn"], 1e-9))
    ici_ratio = (base["bytes_on_wire"]["ici"]
                 / max(gather["bytes_on_wire"]["ici"], 1e-9))
    # SEMANTIC leg pairing, not name matching: the compressed RS
    # lowers as an int8 all-to-all and the compressed dcn all-reduce
    # as all-to-all + all-gather, so a same-key comparison would
    # silently drop the reduce-scatter leg (the largest one) from the
    # report
    bl, gl = base["bytes_by_leg"], gather["bytes_by_leg"]

    def _ratio(base_bytes, comp_bytes):
        return round(base_bytes / comp_bytes, 2) if comp_bytes else None

    leg_ratios = {
        "rs_ici": _ratio(bl.get("ici/reduce-scatter", 0.0),
                         gl.get("ici/all-to-all", 0.0)),
        "ag_ici": _ratio(bl.get("ici/all-gather", 0.0),
                         gl.get("ici/all-gather", 0.0)),
        "ar_dcn": _ratio(bl.get("dcn/all-reduce", 0.0),
                         gl.get("dcn/all-to-all", 0.0)
                         + gl.get("dcn/all-gather", 0.0)),
    }
    return {
        "metric": "dcn_gradient_bytes_ratio",
        "value": round(ratio, 2),
        "unit": "x fewer dcn bytes (int8 vs none)",
        "ici_gather_ratio": round(ici_ratio, 2),
        "ici_gather_ratio_unit": "x fewer ici bytes (int8 ici_legs "
                                 "vs none, RS+AG legs)",
        "leg_ratios_vs_gather_compressed": leg_ratios,
        "baseline": base,
        "compressed": comp,
        "gather_compressed": gather,
    }


def phase_leg_totals(records):
    """Wire-byte totals keyed ``phase/axis/op`` (phase ``other`` when
    the op carries no ``tlm.*`` scope) — the view that separates the
    ZeRO-3 param-gather legs from the gradient legs.  Call after
    :func:`classify_and_total` (it stamps ``axis``/``wire_bytes``)."""
    out = {}
    for r in records:
        key = f"{r.get('phase') or 'other'}/{r['axis']}/{r['op']}"
        out[key] = out.get(key, 0.0) + r["wire_bytes"]
    return {k: round(v, 1) for k, v in sorted(out.items())}


def audit_zero3_step(compression, ici_size=4, block_size=256,
                     bucket_kb=64, shapes=GPT_ISH_SHAPES):
    """Compile one ZeRO-3 train step (gather-on-use → grads → RS into
    the shard → sharded update) over a GPT-shaped param pytree and
    audit its collectives, split param-AG vs grad legs by the
    ``tlm.param_gather`` / ``tlm.grad_sync`` HLO metadata."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.ops.quantization import CompressionConfig
    from apex_tpu.parallel import hierarchical_data_parallel_mesh

    mesh = hierarchical_data_parallel_mesh(ici_size=ici_size)
    axes = ("dcn", "ici")
    shard_map = _shard_map()
    params = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s, jnp.float32), shapes,
        is_leaf=lambda x: isinstance(x, tuple),
    )
    if isinstance(compression, str):
        compression = CompressionConfig(method=compression,
                                        block_size=block_size,
                                        error_feedback=False)
    opt = DistributedFusedAdam(
        lr=1e-2, axis_name=axes, shard_params=True,
        bucket_bytes=bucket_kb * 1024, compression=compression)
    layout = opt.build_layout(params, mesh=mesh)
    pspec = jax.tree.map(lambda _: P(), params)
    sspec, stspec = opt.shard_spec(), opt.state_specs()

    def step(sh, st, g):
        p, st = opt.gather_params(sh, st)
        # grads must DEPEND on the gathered weights or DCE folds the
        # gather away; + 0*p is free and keeps the dataflow honest
        g = jax.tree.map(lambda gi, pi: gi + 0.0 * pi, g, p)
        return opt.step(st, g, sh)

    fn = jax.jit(shard_map(
        step, mesh, (sspec, stspec, pspec), (sspec, stspec),
    ))
    sh = jax.ShapeDtypeStruct(
        (ici_size * layout.shard_size,), jnp.float32)
    st = {"step": jax.ShapeDtypeStruct((), jnp.int32),
          "exp_avg": sh, "exp_avg_sq": sh}
    totals, legs, records = audit_fn(fn, (sh, st, params), mesh)
    phases = phase_leg_totals(records)
    param_ag = sum(v for k, v in phases.items()
                   if k.startswith("param_gather/"))
    grad = sum(v for k, v in phases.items()
               if k.startswith("grad_sync/"))
    cfg = compression
    return {
        "compression": ("none" if cfg is None else
                        cfg.method + ("+ici" if cfg.ici_legs else "")),
        "ici_size": ici_size,
        "bucket_kb": bucket_kb,
        "shard_elements": layout.shard_size,
        "bytes_on_wire": {k: round(v, 1) for k, v in totals.items()},
        "bytes_by_phase_leg": phases,
        "param_ag_wire_bytes": round(param_ag, 1),
        "grad_wire_bytes": round(grad, 1),
    }


def run_zero3_audit(ici_size=4, block_size=256, bucket_kb=64):
    """The ZeRO-3 before/after pair: full-width param gathers vs int8
    (``ici_legs=True``) ones, with the headline ``value`` the param-AG
    wire-bytes ratio the multichip dryrun's zero3 config gates at
    ≥ 3x, plus the grad-leg ratio for completeness (the grads ride the
    same chunk-preserving int8 legs as the DDP path)."""
    from apex_tpu.ops.quantization import CompressionConfig as _CC

    base = audit_zero3_step(None, ici_size, block_size, bucket_kb)
    comp = audit_zero3_step(
        _CC(block_size=block_size, ici_legs=True,
            error_feedback=False),
        ici_size, block_size, bucket_kb)
    ratio = (base["param_ag_wire_bytes"]
             / max(comp["param_ag_wire_bytes"], 1e-9))
    grad_ratio = (base["grad_wire_bytes"]
                  / max(comp["grad_wire_bytes"], 1e-9))
    return {
        "metric": "zero3_param_ag_bytes_ratio",
        "value": round(ratio, 2),
        "unit": "x fewer param-AG wire bytes (int8 ici_legs vs "
                "full-width model dtype)",
        "grad_leg_ratio": round(grad_ratio, 2),
        "baseline": base,
        "gather_compressed": comp,
    }


# ------------------------------------------------------------------ overlap
#
# Ring wire model extended with time: per-axis bandwidth for collective
# duration, peak FLOP/s + HBM bandwidth for the compute that could hide
# it.  v4-ish defaults; the gate uses fractions, not absolute ms.
WIRE_MODEL = {
    "flops": 275e12,      # peak bf16 FLOP/s per chip
    "hbm_bytes_s": 1.2e12,
    "dcn_bytes_s": 25e9,  # per-device DCN bandwidth
    "ici_bytes_s": 90e9,  # per-device ICI bandwidth
}

# ops with no meaningful execution cost for the overlap estimate
_FREE_OPS = {
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id",
}

_COMP_HDR_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w\.\-]+)\s+\(.*\)\s*->\s*.*\{\s*$"
)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w\.\-]+)\s*=\s*((?:\([^=]*?\)|\S+))\s+"
    r"([\w\-]+)\("
)


def _shape_elems(dtype: str, dims: str) -> int:
    if dtype not in _DTYPE_BYTES:
        return 0
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return n


def _call_args(rest: str) -> str:
    """The operand list of ``op(...)``: everything up to the paren that
    closes the call (operand TYPES may nest parens for tuples)."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i]
    return rest


def parse_instructions(hlo_text: str):
    """Parse the (scheduled) HLO text into per-computation instruction
    lists, each entry in program order with name, op, payload sizes,
    operand names and — for collectives — replica groups."""
    comps = {}
    cur = None
    for line in hlo_text.splitlines():
        hm = _COMP_HDR_RE.match(line)
        if hm:
            cur = hm.group(2)
            comps[cur] = []
            continue
        if cur is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            if line.strip() == "}":
                cur = None
            continue
        name, result, op = m.group(1), m.group(2), m.group(3)
        rest = line[m.end():]
        args = _call_args(rest)
        operands = re.findall(r"(?<!=)%([\w\.\-]+)", args)
        op_shapes = _SHAPE_RE.findall(args)
        res_shapes = _SHAPE_RE.findall(result)
        gm = _GROUPS_RE.search(line)
        groups = []
        if gm:
            groups = [
                [int(x) for x in g.split(",") if x.strip()]
                for g in re.findall(r"\{([\d, ]*)\}", gm.group(1))
            ]
        pm = _PAIRS_RE.search(line)
        pairs = []
        if pm:
            pairs = [
                tuple(int(x) for x in p.split(","))
                for p in re.findall(r"\{([\d, ]+)\}", pm.group(1))
            ]
        comps[cur].append({
            "name": name,
            "op": op,
            "operands": operands,
            "result_bytes": sum(_shape_bytes(d, s) for d, s in res_shapes),
            "result_elems": sum(_shape_elems(d, s) for d, s in res_shapes),
            "operand_bytes": sum(_shape_bytes(d, s) for d, s in op_shapes),
            "operand_elems": [_shape_elems(d, s) for d, s in op_shapes],
            "replica_groups": groups,
            "pairs": pairs,
        })
    return comps


def _base_collective(op: str):
    for c in _COLLECTIVES:
        if op == c or op == c + "-start" or op == c + "-done":
            return c
    return None


def _compute_time_s(rec, model=WIRE_MODEL) -> float:
    """Rough execution-time estimate for one (non-collective)
    instruction: dots by a FLOP model (contracted extent inferred from
    the element counts), everything else memory-bound."""
    op = rec["op"]
    if op in _FREE_OPS or _base_collective(op):
        return 0.0
    if op in ("dot", "convolution"):
        res = max(rec["result_elems"], 1)
        ops = rec["operand_elems"]
        if len(ops) >= 2 and ops[0] and ops[1]:
            k = (ops[0] * ops[1] / res) ** 0.5
        else:
            k = 1.0
        return 2.0 * res * max(k, 1.0) / model["flops"]
    return (rec["result_bytes"] + rec["operand_bytes"]) \
        / model["hbm_bytes_s"]


def _collective_time_s(rec, label, model=WIRE_MODEL) -> float:
    wb = _wire_bytes(rec)
    bw = model["dcn_bytes_s"] if label == "dcn" else model["ici_bytes_s"]
    return wb / bw


def analyze_overlap(hlo_text: str, mesh=None, dcn_axis="dcn",
                    ici_axis="ici", model=WIRE_MODEL):
    """Walk every computation of a SCHEDULED module and, for each
    collective, measure what a latency-hiding scheduler can put between
    its start and done:

    - async ``-start``/``-done`` pairs: the compute actually scheduled
      inside the window (the backend already committed to the overlap);
    - synchronous collectives: the compute that is dataflow-INDEPENDENT
      of the collective (neither ancestor nor descendant) — legal to
      schedule inside the window, i.e. the structural overlap a
      latency-hiding backend can exploit.

    Returns ``(per_collective_records, summary)``."""
    comps = parse_instructions(hlo_text)
    coords = _mesh_coords(mesh, dcn_axis, ici_axis) if mesh else None
    out = []
    for cname, instrs in comps.items():
        index = {r["name"]: i for i, r in enumerate(instrs)}
        deps = [
            [index[o] for o in r["operands"] if o in index]
            for r in instrs
        ]
        users = [[] for _ in instrs]
        for i, ds in enumerate(deps):
            for d in ds:
                users[d].append(i)

        def closure(start_idx, edges):
            seen = set()
            todo = list(edges[start_idx])
            while todo:
                j = todo.pop()
                if j in seen:
                    continue
                seen.add(j)
                todo.extend(edges[j])
            return seen

        for i, r in enumerate(instrs):
            base = _base_collective(r["op"])
            if base is None or r["op"].endswith("-done"):
                continue
            is_start = r["op"].endswith("-start")
            rec = {
                "computation": cname,
                "op": base,
                "name": r["name"],
                "async_pair": False,
                "result_bytes": r["result_bytes"],
                "operand_bytes": r["operand_bytes"],
                "replica_groups": r["replica_groups"],
                "pairs": r["pairs"],
            }
            if is_start:
                done = next(
                    (j for j in range(i + 1, len(instrs))
                     if instrs[j]["op"] == base + "-done"
                     and r["name"] in instrs[j]["operands"]),
                    None,
                )
                rec["async_pair"] = done is not None
                window = instrs[i + 1:done] if done is not None else []
                hidden = sum(_compute_time_s(w, model) for w in window)
            else:
                anc = closure(i, deps)
                desc = closure(i, users)
                excluded = anc | desc | {i}
                hidden = sum(
                    _compute_time_s(w, model)
                    for j, w in enumerate(instrs)
                    if j not in excluded
                )
            label = (_axis_label(r["replica_groups"], r["pairs"], coords)
                     if coords else "other")
            t = _collective_time_s(rec, label, model)
            rec.update({
                "axis": label,
                "wire_bytes": round(_wire_bytes(rec), 1),
                "collective_s": t,
                "hidden_s": min(hidden, t),
                "independent_compute_s": hidden,
                "exposed_s": max(0.0, t - hidden),
                "overlappable": hidden > 0.0,
            })
            out.append(rec)
    coll = sum(r["collective_s"] for r in out)
    hidden = sum(r["hidden_s"] for r in out)
    exposed = sum(r["exposed_s"] for r in out)
    indep = sum(r["independent_compute_s"] for r in out)
    n = len(out)
    summary = {
        "n_collectives": n,
        "n_async_pairs": sum(1 for r in out if r["async_pair"]),
        "n_overlappable": sum(1 for r in out if r["overlappable"]),
        "overlappable_frac": round(
            sum(1 for r in out if r["overlappable"]) / n, 3
        ) if n else 0.0,
        "collective_ms": round(coll * 1e3, 4),
        "hidden_ms": round(hidden * 1e3, 4),
        "exposed_ms": round(exposed * 1e3, 4),
        "hidden_frac": round(hidden / coll, 3) if coll else 0.0,
        # how much compute each collective could hide behind, on
        # average — the number that separates the pipelined loop
        # (whole microbatches of independent fwd/bwd per round) from
        # the deferred one (only the last backward's tail)
        "independent_compute_ms": round(indep * 1e3, 4),
        "mean_independent_compute_ms_per_collective": round(
            indep / n * 1e3, 5
        ) if n else 0.0,
    }
    for ax in ("dcn", "ici"):
        rs = [r for r in out if r["axis"] == ax]
        summary[f"{ax}_collectives"] = len(rs)
        summary[f"{ax}_overlappable"] = sum(
            1 for r in rs if r["overlappable"]
        )
    return out, summary


# MLP proxy for the audited accumulation loop: per-layer leaves so the
# reverse-order bucket assembly has real structure, matmul fwd/bwd so
# the "independent compute" the analysis finds is genuine dot work
_OVERLAP_LAYERS = 4
_OVERLAP_WIDTH = 128


def _overlap_params(key=0):
    import jax
    import jax.numpy as jnp

    ks = jax.random.split(jax.random.PRNGKey(key),
                          2 * _OVERLAP_LAYERS + 1)
    p = {}
    for l in range(_OVERLAP_LAYERS):
        p[f"l{l}"] = {
            "w": 0.1 * jax.random.normal(
                ks[2 * l], (_OVERLAP_WIDTH, _OVERLAP_WIDTH)),
            "b": jnp.zeros((_OVERLAP_WIDTH,)),
        }
    p["head"] = 0.1 * jax.random.normal(
        ks[-1], (_OVERLAP_WIDTH, 2 * _OVERLAP_WIDTH))
    return p


def _overlap_loss(p, x):
    import jax.numpy as jnp

    h = x
    for l in range(_OVERLAP_LAYERS):
        h = jnp.tanh(h @ p[f"l{l}"]["w"] + p[f"l{l}"]["b"])
    z = h @ p["head"]
    return jnp.sum(z * z) / z.size


def compile_grad_sync_loop(overlap, compression=None, ici_size=4,
                           bucket_bytes=96 * 1024, num_micro=3,
                           rows=16):
    """Compile the K-microbatch accumulate-and-reduce loop (pipelined
    when ``overlap``, the deferred seed pattern otherwise) and return
    ``(scheduled_hlo_text, mesh)``."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel import hierarchical_data_parallel_mesh
    from apex_tpu.parallel.distributed import Reducer

    mesh = hierarchical_data_parallel_mesh(ici_size=ici_size)
    shard_map = _shard_map()
    params = _overlap_params()
    red = Reducer(
        axis_name=("dcn", "ici"), overlap_grad_sync=overlap,
        bucket_bytes=bucket_bytes, compression=compression,
    )

    def step(p, batch):
        acc = red.init(p)
        for k in range(num_micro):
            g = jax.grad(_overlap_loss)(p, batch[k])
            acc = red.accumulate(acc, g)
        grads, _ = red.reduce(acc)
        return grads

    pspec = jax.tree.map(lambda _: P(), params)
    data = jnp.zeros(
        (num_micro, rows * mesh.devices.size, _OVERLAP_WIDTH)
    )
    fn = jax.jit(shard_map(
        step, mesh, (pspec, P(None, ("dcn", "ici"))), pspec,
    ))
    txt = fn.lower(params, data).compile().as_text()
    return txt, mesh


def run_overlap_audit(ici_size=4, bucket_kb=96, num_micro=3):
    """Overlapped vs deferred grad sync through the scheduled-HLO
    analysis, plus the int8-compressed overlapped variant.  The
    headline value is the overlapped loop's overlappable fraction
    (sanity gate: every grad collective has SOME independent compute);
    the discriminating number is independent_compute_ms overlap vs
    deferred — pipelining adds ~(K-1) microbatches of hideable
    compute per round (see the module docstring)."""
    results = {}
    for tag, overlap, comp in (
        ("overlap", True, None),
        ("deferred", False, None),
        ("overlap_int8", True, "int8"),
    ):
        txt, mesh = compile_grad_sync_loop(
            overlap, comp, ici_size=ici_size,
            bucket_bytes=bucket_kb * 1024, num_micro=num_micro,
        )
        records, summary = analyze_overlap(txt, mesh)
        results[tag] = {
            "summary": summary,
            "collectives": [
                {k: rec[k] for k in (
                    "op", "axis", "wire_bytes", "overlappable",
                    "async_pair")}
                for rec in records
            ],
        }
    return {
        "metric": "grad_sync_overlappable_fraction",
        "value": results["overlap"]["summary"]["overlappable_frac"],
        "unit": "fraction of grad collectives with independent compute "
                "to hide behind (pipelined loop)",
        "num_micro": num_micro,
        "bucket_kb": bucket_kb,
        "ici_size": ici_size,
        "wire_model": WIRE_MODEL,
        **results,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ici-size", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=256)
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual device count when no backend is up")
    ap.add_argument("--min-ratio", type=float, default=None,
                    help="exit nonzero unless the dcn-bytes ratio "
                         "meets this floor")
    ap.add_argument("--overlap", action="store_true",
                    help="audit the scheduled HLO of the pipelined "
                         "accumulate-and-reduce loop instead of the "
                         "bytes A/B (writes OVERLAP_AUDIT.json)")
    ap.add_argument("--zero3", action="store_true",
                    help="audit the ZeRO-3 gather-on-use step instead: "
                         "param-AG vs grad legs split by phase "
                         "metadata, full-width vs int8 gathers "
                         "(writes ZERO3_AUDIT.json)")
    ap.add_argument("--num-micro", type=int, default=3)
    ap.add_argument("--bucket-kb", type=int, default=96)
    ap.add_argument("--min-overlappable", type=float, default=None,
                    help="with --overlap: exit nonzero unless the "
                         "overlappable fraction meets this floor")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    _force_virtual_devices(args.devices)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.zero3:
        out_path = args.out or os.path.join(root, "ZERO3_AUDIT.json")
        doc = run_zero3_audit(args.ici_size, args.block_size)
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
        print(json.dumps({
            "metric": doc["metric"], "value": doc["value"],
            "unit": doc["unit"],
            "grad_leg_ratio": doc["grad_leg_ratio"],
            "param_ag_bytes_none":
                doc["baseline"]["param_ag_wire_bytes"],
            "param_ag_bytes_int8":
                doc["gather_compressed"]["param_ag_wire_bytes"],
        }))
        print(f"wrote {out_path}")
        if args.min_ratio is not None and doc["value"] < args.min_ratio:
            raise SystemExit(
                f"param-AG bytes ratio {doc['value']} < floor "
                f"{args.min_ratio}"
            )
        return
    if args.overlap:
        out_path = args.out or os.path.join(root, "OVERLAP_AUDIT.json")
        doc = run_overlap_audit(args.ici_size, args.bucket_kb,
                                args.num_micro)
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
        print(json.dumps({
            "metric": doc["metric"], "value": doc["value"],
            "unit": doc["unit"],
            "overlap": doc["overlap"]["summary"],
            "deferred": doc["deferred"]["summary"],
            "overlap_int8": doc["overlap_int8"]["summary"],
        }))
        print(f"wrote {out_path}")
        if (args.min_overlappable is not None
                and doc["value"] < args.min_overlappable):
            raise SystemExit(
                f"overlappable fraction {doc['value']} < floor "
                f"{args.min_overlappable}"
            )
        return

    args.out = args.out or os.path.join(root, "COMM_AUDIT.json")
    doc = run_audit(args.ici_size, args.block_size)
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({
        "metric": doc["metric"], "value": doc["value"],
        "unit": doc["unit"],
        "dcn_bytes_none": doc["baseline"]["bytes_on_wire"]["dcn"],
        "dcn_bytes_int8": doc["compressed"]["bytes_on_wire"]["dcn"],
        "ici_bytes_none": doc["baseline"]["bytes_on_wire"]["ici"],
        "ici_bytes_int8": doc["compressed"]["bytes_on_wire"]["ici"],
    }))
    print(f"wrote {args.out}")
    if args.min_ratio is not None and doc["value"] < args.min_ratio:
        raise SystemExit(
            f"dcn bytes ratio {doc['value']} < floor {args.min_ratio}"
        )


if __name__ == "__main__":
    main()

"""Per-device memory audit: compile the train step and prove the
live-bytes math — the tool that gates the ZeRO-3 claim.

OOM cannot be demonstrated on a CPU host (the virtual devices share
one heap), so the "replicated DDP cannot hold the h≥4096-class model
in 16 GB HBM" claim is proven STRUCTURALLY, the same way
``tools/comm_audit.py`` proves wire bytes: compile the full training
step (no execution — parameters enter as ``ShapeDtypeStruct``\\ s, so
a ≥1B-param model audits in seconds) and read XLA's buffer-assignment
numbers from ``Compiled.memory_analysis()``:

- ``argument_bytes`` — the per-device bytes of everything the step is
  *handed*: model params + fp32 masters + both moments for replicated
  DDP; the 1/world fp32 shard + 1/world moments for ZeRO-3.  This is
  the persistent training state and it is exact.
- ``temp_bytes`` — XLA's temp allocation (liveness-packed peak of the
  intermediates): activations, gradients and — under ZeRO-3 — the
  transient gathered weights.
- ``peak_bytes`` — ``argument + output + temp − alias`` (donated
  outputs alias their arguments), the per-device high-water mark the
  HBM verdict uses.

``--compare`` compiles replicated-DDP and ZeRO-3 at the same shape and
prints them side by side with the ratio and a per-device HBM verdict;
the multichip dryrun's twelfth config wires this into
``MEMORY_AUDIT.json`` and gates replicated > HBM ≥ zero3 at the
≥1B-param flagship shape.  ``--train-steps N`` additionally
materializes the ZeRO-3 config and runs N real optimizer steps (the
"trains where DDP cannot" half of the gate — slow on a CPU host, so
off by default).

Run on the 8-device virtual mesh (no TPU needed):

    python tools/memory_audit.py --compare            # flagship ≥1B shape
    python tools/memory_audit.py --compare --layers 2 --hidden 256
    python tools/memory_audit.py --train-steps 8 --layers 2 --hidden 256
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _force_virtual_devices(n: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


#: The ≥1B-param flagship audit shape: h=2048 x 20 layers ≈ 1.07B
#: params — the smallest config that proves the "replicated DDP
#: exceeds 16 GB/device, ZeRO-3 fits" claim (h≥4096 scales the same
#: math up).  seq/batch are tiny: the claim is about STATE bytes, and
#: small activations keep the CPU compile fast.
FLAGSHIP_1B = dict(vocab=32768, layers=20, hidden=2048, heads=16,
                   seq=8, batch=8)

DEFAULT_HBM_GB = 16.0  # v5e per-chip HBM


def _mesh():
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    return parallel_state.initialize_model_parallel()


def _model(vocab, layers, hidden, heads, seq):
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig, GPTModel

    return GPTModel(GPTConfig(
        vocab_size=vocab, num_layers=layers, hidden_size=hidden,
        num_attention_heads=heads, max_position_embeddings=seq,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))


def _param_template(model):
    """ShapeDtypeStruct tree of the model params — no materialization,
    so a ≥1B-param model audits without 4 GB of host allocations."""
    import jax

    return jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0)))


def _n_params(tpl) -> int:
    import jax
    import numpy as np

    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(tpl)))


def _per_device_arg_bytes(avals, in_specs, mesh) -> int:
    """Exact per-device bytes of the step's arguments, from the avals
    and their PartitionSpecs: a replicated leaf costs its FULL size on
    every device, a sharded one 1/extent — the spec-aware sum a naive
    total//device_count gets wrong for replicated DDP state."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    total = 0
    for aval_tree, spec_tree in zip(avals, in_specs):
        leaves, treedef = jax.tree_util.tree_flatten(aval_tree)
        if isinstance(spec_tree, P):
            specs = [spec_tree] * len(leaves)
        else:
            specs = treedef.flatten_up_to(spec_tree)
        for leaf, spec in zip(leaves, specs):
            n = int(np.prod(leaf.shape)) if leaf.shape else 1
            denom = 1
            if spec is not None:
                for entry in spec:
                    if entry is None:
                        continue
                    names = (entry if isinstance(entry, tuple)
                             else (entry,))
                    for ax in names:
                        denom *= mesh.shape[ax]
            total += (n // max(denom, 1)) * np.dtype(leaf.dtype).itemsize
    return total


def build_step(mode, mesh, model, batch=8, bucket_mb=4.0):
    """Compile-ready ``(jitted, example_avals, arg_bytes_per_device)``
    for one train step.

    ``mode``: ``"ddp"`` — replicated params, FusedAdam with fp32
    masters (the seed path ZeRO-3 replaces); ``"zero3"`` — gather-on-
    use sharded params + sharded update.  Both donate their state so
    the peak model reflects in-place training."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu._compat import shard_map

    tpl = _param_template(model)
    specs = model.param_specs()
    seq = model.config.max_position_embeddings
    tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32)

    if mode == "ddp":
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.transformer.tensor_parallel.layers import (
            state_specs_like,
        )

        opt = FusedAdam(lr=1e-2, master_weights=True)
        st_tpl = jax.eval_shape(opt.init, tpl)
        st_specs = state_specs_like(specs, st_tpl)

        def train(p, s, tok_, tgt_):
            loss, grads = jax.value_and_grad(model.loss)(p, tok_, tgt_)
            grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, "dp"), grads)
            p, s = opt.step(s, grads, p)
            return p, s, loss

        in_specs = (specs, st_specs, P("dp"), P("dp"))
        jitted = jax.jit(shard_map(
            train, mesh=mesh,
            in_specs=in_specs,
            out_specs=(specs, st_specs, P()),
        ), donate_argnums=(0, 1))
        avals = (tpl, st_tpl, tok, tok)
        return jitted, avals, _per_device_arg_bytes(avals, in_specs,
                                                    mesh)

    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    opt = DistributedFusedAdam(
        lr=1e-2, shard_params=True,
        bucket_bytes=int(bucket_mb * 1024 * 1024))
    layout = opt.build_layout(tpl, mesh=mesh)
    world = mesh.shape["dp"]
    sspec, st_specs = opt.shard_spec(), opt.state_specs()
    shards_g = jax.ShapeDtypeStruct(
        (world * layout.shard_size,), jnp.float32)
    st_g = {
        "step": jax.ShapeDtypeStruct((), jnp.int32),
        "exp_avg": shards_g, "exp_avg_sq": shards_g,
    }

    def train(sh, s, tok_, tgt_):
        p, s = opt.gather_params(sh, s)
        loss, grads = jax.value_and_grad(model.loss)(p, tok_, tgt_)
        sh, s = opt.step(s, grads, sh)
        return sh, s, loss

    in_specs = (sspec, st_specs, P("dp"), P("dp"))
    jitted = jax.jit(shard_map(
        train, mesh=mesh,
        in_specs=in_specs,
        out_specs=(sspec, st_specs, P()),
    ), donate_argnums=(0, 1))
    avals = (shards_g, st_g, tok, tok)
    return jitted, avals, _per_device_arg_bytes(avals, in_specs, mesh)


def measure(jitted, avals, arg_exact=None) -> dict:
    """Compile and read per-device bytes from the buffer assignment;
    falls back to the spec-aware host-computed ``arg_exact`` (from
    :func:`_per_device_arg_bytes`) when the backend exposes no
    ``memory_analysis`` — every other field is then None, which the
    dryrun gate treats as a loud failure, not a pass."""
    t0 = time.perf_counter()
    compiled = jitted.lower(*avals).compile()
    compile_s = time.perf_counter() - t0
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is None:
        # cost-analysis fallback: no liveness packing, so only the
        # (exact) argument bytes are trustworthy
        out = {"argument_bytes": arg_exact, "output_bytes": None,
               "temp_bytes": None, "alias_bytes": None,
               "peak_bytes": None, "source": "cost_analysis"}
    else:
        arg = int(ma.argument_size_in_bytes)
        outb = int(ma.output_size_in_bytes)
        temp = int(ma.temp_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
        out = {
            "argument_bytes": arg,
            "output_bytes": outb,
            "temp_bytes": temp,
            "alias_bytes": alias,
            # arguments + outputs live across the program, temps are
            # the packed peak of everything else; donated outputs
            # alias arguments and must not double-count
            "peak_bytes": arg + outb + temp - alias,
            "source": "memory_analysis",
        }
    out["compile_s"] = round(compile_s, 2)
    return out


def run_memory_audit(vocab=None, layers=None, hidden=None, heads=None,
                     seq=None, batch=None, bucket_mb=4.0,
                     hbm_gb=DEFAULT_HBM_GB) -> dict:
    """The --compare document: replicated-DDP vs ZeRO-3 per-device
    bytes at one shape, with the ratio and the per-device HBM verdict
    the dryrun gates on."""
    cfg = dict(FLAGSHIP_1B)
    for k, v in dict(vocab=vocab, layers=layers, hidden=hidden,
                     heads=heads, seq=seq, batch=batch).items():
        if v is not None:
            cfg[k] = v
    mesh = _mesh()
    model = _model(cfg["vocab"], cfg["layers"], cfg["hidden"],
                   cfg["heads"], cfg["seq"])
    n_params = _n_params(_param_template(model))
    results = {}
    for mode in ("ddp", "zero3"):
        jitted, avals, arg_bytes = build_step(
            mode, mesh, model, batch=cfg["batch"], bucket_mb=bucket_mb)
        results[mode] = measure(jitted, avals, arg_bytes)
    hbm = hbm_gb * 1e9
    ddp_peak = results["ddp"]["peak_bytes"]
    z3_peak = results["zero3"]["peak_bytes"]
    doc = {
        "metric": "per_device_peak_bytes_ratio",
        "value": (round(ddp_peak / z3_peak, 2)
                  if ddp_peak and z3_peak else None),
        "unit": "x fewer per-device peak bytes (zero3 vs replicated "
                "ddp)",
        "config": cfg,
        "n_params": n_params,
        "world": int(mesh.shape["dp"]),
        "hbm_limit_bytes": int(hbm),
        "replicated_ddp": results["ddp"],
        "zero3": results["zero3"],
        "replicated_exceeds_hbm": (
            bool(ddp_peak > hbm) if ddp_peak else None),
        "zero3_fits_hbm": (bool(z3_peak < hbm) if z3_peak else None),
    }
    return doc


def train_zero3(vocab=None, layers=None, hidden=None, heads=None,
                seq=None, batch=None, steps=8, bucket_mb=4.0,
                lr=1e-4) -> dict:
    """Materialize the config and run ``steps`` real ZeRO-3 optimizer
    steps on the live mesh — the "a ≥1B-param GPT *trains* where
    replicated DDP cannot" half of the dryrun gate.  Memory-frugal by
    construction: the replicated init tree is dropped as soon as the
    shards are built, so the host never holds params + masters +
    moments the way the DDP path would."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu._compat import shard_map
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    cfg = dict(FLAGSHIP_1B)
    for k, v in dict(vocab=vocab, layers=layers, hidden=hidden,
                     heads=heads, seq=seq, batch=batch).items():
        if v is not None:
            cfg[k] = v
    mesh = _mesh()
    model = _model(cfg["vocab"], cfg["layers"], cfg["hidden"],
                   cfg["heads"], cfg["seq"])
    n_params = _n_params(_param_template(model))
    opt = DistributedFusedAdam(
        lr=lr, shard_params=True,
        bucket_bytes=int(bucket_mb * 1024 * 1024))
    opt.build_layout(_param_template(model), mesh=mesh)
    specs = model.param_specs()
    sspec, st_specs = opt.shard_spec(), opt.state_specs()
    t0 = time.perf_counter()
    params = model.init(jax.random.PRNGKey(0))
    place = lambda t, sp: jax.device_put(
        t, jax.tree.map(lambda s: NamedSharding(mesh, s), sp,
                        is_leaf=lambda x: isinstance(x, P)))
    params = place(params, specs)
    shards = jax.jit(shard_map(
        opt.init_shards, mesh=mesh, in_specs=(specs,),
        out_specs=sspec))(params)
    jax.block_until_ready(shards)
    del params  # the replicated tree is gone: shards are the storage
    state = jax.jit(shard_map(
        opt.init, mesh=mesh, in_specs=(sspec,),
        out_specs=st_specs))(shards)
    init_s = time.perf_counter() - t0

    def train(sh, s, tok_, tgt_):
        p, s = opt.gather_params(sh, s)
        loss, grads = jax.value_and_grad(model.loss)(p, tok_, tgt_)
        sh, s = opt.step(s, grads, sh)
        return sh, s, loss

    step = jax.jit(shard_map(
        train, mesh=mesh,
        in_specs=(sspec, st_specs, P("dp"), P("dp")),
        out_specs=(sspec, st_specs, P()),
    ), donate_argnums=(0, 1))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg["vocab"], (cfg["batch"], cfg["seq"])),
        jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    t0 = time.perf_counter()
    for i in range(steps):
        shards, state, loss = step(shards, state, tokens, targets)
        losses.append(float(loss))
        print(f"  zero3 step {i}: loss {losses[-1]:.4f} "
              f"({time.perf_counter() - t0:.1f}s elapsed)",
              flush=True)
    wall = time.perf_counter() - t0
    return {
        "config": cfg,
        "n_params": n_params,
        "steps": steps,
        "losses": [round(x, 5) for x in losses],
        "finite": bool(np.all(np.isfinite(losses))),
        "loss_decreased": bool(losses[-1] < losses[0]),
        "init_s": round(init_s, 1),
        "wall_s": round(wall, 1),
        "ms_per_step": round(wall / steps * 1e3, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=None)
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--hidden", type=int, default=None)
    ap.add_argument("--heads", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--bucket-mb", type=float, default=4.0)
    ap.add_argument("--hbm-gb", type=float, default=DEFAULT_HBM_GB,
                    help="per-device HBM for the fits/exceeds verdict")
    ap.add_argument("--compare", action="store_true",
                    help="replicated-DDP vs ZeRO-3 side by side "
                         "(writes MEMORY_AUDIT.json)")
    ap.add_argument("--train-steps", type=int, default=0,
                    help="ALSO run N real ZeRO-3 steps at the shape "
                         "(slow on CPU hosts; proves the config "
                         "trains, not just compiles)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    _force_virtual_devices(args.devices)

    dims = dict(vocab=args.vocab, layers=args.layers,
                hidden=args.hidden, heads=args.heads, seq=args.seq,
                batch=args.batch)
    doc = run_memory_audit(bucket_mb=args.bucket_mb,
                           hbm_gb=args.hbm_gb, **dims)
    if args.train_steps:
        doc["training"] = train_zero3(steps=args.train_steps,
                                      bucket_mb=args.bucket_mb,
                                      **dims)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_path = args.out or os.path.join(root, "MEMORY_AUDIT.json")
    if args.compare or args.out:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    gb = 1e9
    print(json.dumps({
        "metric": doc["metric"], "value": doc["value"],
        "n_params": doc["n_params"],
        "ddp_peak_gb": round(
            (doc["replicated_ddp"]["peak_bytes"] or 0) / gb, 2),
        "zero3_peak_gb": round(
            (doc["zero3"]["peak_bytes"] or 0) / gb, 2),
        "ddp_argument_gb": round(
            (doc["replicated_ddp"]["argument_bytes"] or 0) / gb, 2),
        "zero3_argument_gb": round(
            (doc["zero3"]["argument_bytes"] or 0) / gb, 2),
        "replicated_exceeds_hbm": doc["replicated_exceeds_hbm"],
        "zero3_fits_hbm": doc["zero3_fits_hbm"],
    }))
    if args.compare or args.out:
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()

"""Per-device memory audit: compile the train step and prove the
live-bytes math — the tool that gates the ZeRO-3 claim.

OOM cannot be demonstrated on a CPU host (the virtual devices share
one heap), so the "replicated DDP cannot hold the h≥4096-class model
in 16 GB HBM" claim is proven STRUCTURALLY, the same way
``tools/comm_audit.py`` proves wire bytes: compile the full training
step (no execution — parameters enter as ``ShapeDtypeStruct``\\ s, so
a ≥1B-param model audits in seconds) and read XLA's buffer-assignment
numbers from ``Compiled.memory_analysis()``:

- ``argument_bytes`` — the per-device bytes of everything the step is
  *handed*: model params + fp32 masters + both moments for replicated
  DDP; the 1/world fp32 shard + 1/world moments for ZeRO-3.  This is
  the persistent training state and it is exact.
- ``temp_bytes`` — XLA's temp allocation (liveness-packed peak of the
  intermediates): activations, gradients and — under ZeRO-3 — the
  transient gathered weights.
- ``peak_bytes`` — ``argument + output + temp − alias`` (donated
  outputs alias their arguments), the per-device high-water mark the
  HBM verdict uses.

``--compare`` compiles replicated-DDP and ZeRO-3 at the same shape and
prints them side by side with the ratio and a per-device HBM verdict;
the multichip dryrun's twelfth config wires this into
``MEMORY_AUDIT.json`` and gates replicated > HBM ≥ zero3 at the
≥1B-param flagship shape.  ``--train-steps N`` additionally
materializes the ZeRO-3 config and runs N real optimizer steps (the
"trains where DDP cannot" half of the gate — slow on a CPU host, so
off by default).

Run on the 8-device virtual mesh (no TPU needed):

    python tools/memory_audit.py --compare            # flagship ≥1B shape
    python tools/memory_audit.py --compare --layers 2 --hidden 256
    python tools/memory_audit.py --train-steps 8 --layers 2 --hidden 256

``--serve`` is the SERVING analog of the train audit: per-device
decode-path bytes (weight pool + KV pool + decode activations) for a
ladder of model tiers at every weight width — fp32 / bf16 / int8 /
int4 pools (``quantize_gpt_weights``) — with an HBM verdict naming the
largest tier that fits at each width.  Pure shape math (eval_shape of
the actual pool builders, no compile, no materialization), so the 20B+
tiers audit in milliseconds:

    python tools/memory_audit.py --serve              # writes MEMORY_AUDIT_SERVE.json
    python tools/memory_audit.py --serve --context 2048 --max-seqs 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _force_virtual_devices(n: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


#: The ≥1B-param flagship audit shape: h=2048 x 20 layers ≈ 1.07B
#: params — the smallest config that proves the "replicated DDP
#: exceeds 16 GB/device, ZeRO-3 fits" claim (h≥4096 scales the same
#: math up).  seq/batch are tiny: the claim is about STATE bytes, and
#: small activations keep the CPU compile fast.
FLAGSHIP_1B = dict(vocab=32768, layers=20, hidden=2048, heads=16,
                   seq=8, batch=8)

DEFAULT_HBM_GB = 16.0  # v5e per-chip HBM


def _mesh():
    from apex_tpu.transformer import parallel_state

    if parallel_state.model_parallel_is_initialized():
        parallel_state.destroy_model_parallel()
    return parallel_state.initialize_model_parallel()


def _model(vocab, layers, hidden, heads, seq):
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig, GPTModel

    return GPTModel(GPTConfig(
        vocab_size=vocab, num_layers=layers, hidden_size=hidden,
        num_attention_heads=heads, max_position_embeddings=seq,
        compute_dtype=jnp.float32, remat=False, attention_impl="xla",
    ))


def _param_template(model):
    """ShapeDtypeStruct tree of the model params — no materialization,
    so a ≥1B-param model audits without 4 GB of host allocations."""
    import jax

    return jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0)))


def _n_params(tpl) -> int:
    import jax
    import numpy as np

    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(tpl)))


def _per_device_arg_bytes(avals, in_specs, mesh) -> int:
    """Exact per-device bytes of the step's arguments, from the avals
    and their PartitionSpecs: a replicated leaf costs its FULL size on
    every device, a sharded one 1/extent — the spec-aware sum a naive
    total//device_count gets wrong for replicated DDP state."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    total = 0
    for aval_tree, spec_tree in zip(avals, in_specs):
        leaves, treedef = jax.tree_util.tree_flatten(aval_tree)
        if isinstance(spec_tree, P):
            specs = [spec_tree] * len(leaves)
        else:
            specs = treedef.flatten_up_to(spec_tree)
        for leaf, spec in zip(leaves, specs):
            n = int(np.prod(leaf.shape)) if leaf.shape else 1
            denom = 1
            if spec is not None:
                for entry in spec:
                    if entry is None:
                        continue
                    names = (entry if isinstance(entry, tuple)
                             else (entry,))
                    for ax in names:
                        denom *= mesh.shape[ax]
            total += (n // max(denom, 1)) * np.dtype(leaf.dtype).itemsize
    return total


def build_step(mode, mesh, model, batch=8, bucket_mb=4.0):
    """Compile-ready ``(jitted, example_avals, arg_bytes_per_device)``
    for one train step.

    ``mode``: ``"ddp"`` — replicated params, FusedAdam with fp32
    masters (the seed path ZeRO-3 replaces); ``"zero3"`` — gather-on-
    use sharded params + sharded update.  Both donate their state so
    the peak model reflects in-place training."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu._compat import shard_map

    tpl = _param_template(model)
    specs = model.param_specs()
    seq = model.config.max_position_embeddings
    tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32)

    if mode == "ddp":
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.transformer.tensor_parallel.layers import (
            state_specs_like,
        )

        opt = FusedAdam(lr=1e-2, master_weights=True)
        st_tpl = jax.eval_shape(opt.init, tpl)
        st_specs = state_specs_like(specs, st_tpl)

        def train(p, s, tok_, tgt_):
            loss, grads = jax.value_and_grad(model.loss)(p, tok_, tgt_)
            grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, "dp"), grads)
            p, s = opt.step(s, grads, p)
            return p, s, loss

        in_specs = (specs, st_specs, P("dp"), P("dp"))
        jitted = jax.jit(shard_map(
            train, mesh=mesh,
            in_specs=in_specs,
            out_specs=(specs, st_specs, P()),
        ), donate_argnums=(0, 1))
        avals = (tpl, st_tpl, tok, tok)
        return jitted, avals, _per_device_arg_bytes(avals, in_specs,
                                                    mesh)

    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    opt = DistributedFusedAdam(
        lr=1e-2, shard_params=True,
        bucket_bytes=int(bucket_mb * 1024 * 1024))
    layout = opt.build_layout(tpl, mesh=mesh)
    world = mesh.shape["dp"]
    sspec, st_specs = opt.shard_spec(), opt.state_specs()
    shards_g = jax.ShapeDtypeStruct(
        (world * layout.shard_size,), jnp.float32)
    st_g = {
        "step": jax.ShapeDtypeStruct((), jnp.int32),
        "exp_avg": shards_g, "exp_avg_sq": shards_g,
    }

    def train(sh, s, tok_, tgt_):
        p, s = opt.gather_params(sh, s)
        loss, grads = jax.value_and_grad(model.loss)(p, tok_, tgt_)
        sh, s = opt.step(s, grads, sh)
        return sh, s, loss

    in_specs = (sspec, st_specs, P("dp"), P("dp"))
    jitted = jax.jit(shard_map(
        train, mesh=mesh,
        in_specs=in_specs,
        out_specs=(sspec, st_specs, P()),
    ), donate_argnums=(0, 1))
    avals = (shards_g, st_g, tok, tok)
    return jitted, avals, _per_device_arg_bytes(avals, in_specs, mesh)


def measure(jitted, avals, arg_exact=None) -> dict:
    """Compile and read per-device bytes from the buffer assignment;
    falls back to the spec-aware host-computed ``arg_exact`` (from
    :func:`_per_device_arg_bytes`) when the backend exposes no
    ``memory_analysis`` — every other field is then None, which the
    dryrun gate treats as a loud failure, not a pass."""
    t0 = time.perf_counter()
    compiled = jitted.lower(*avals).compile()
    compile_s = time.perf_counter() - t0
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is None:
        # cost-analysis fallback: no liveness packing, so only the
        # (exact) argument bytes are trustworthy
        out = {"argument_bytes": arg_exact, "output_bytes": None,
               "temp_bytes": None, "alias_bytes": None,
               "peak_bytes": None, "source": "cost_analysis"}
    else:
        arg = int(ma.argument_size_in_bytes)
        outb = int(ma.output_size_in_bytes)
        temp = int(ma.temp_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
        out = {
            "argument_bytes": arg,
            "output_bytes": outb,
            "temp_bytes": temp,
            "alias_bytes": alias,
            # arguments + outputs live across the program, temps are
            # the packed peak of everything else; donated outputs
            # alias arguments and must not double-count
            "peak_bytes": arg + outb + temp - alias,
            "source": "memory_analysis",
        }
    out["compile_s"] = round(compile_s, 2)
    return out


def run_memory_audit(vocab=None, layers=None, hidden=None, heads=None,
                     seq=None, batch=None, bucket_mb=4.0,
                     hbm_gb=DEFAULT_HBM_GB) -> dict:
    """The --compare document: replicated-DDP vs ZeRO-3 per-device
    bytes at one shape, with the ratio and the per-device HBM verdict
    the dryrun gates on."""
    cfg = dict(FLAGSHIP_1B)
    for k, v in dict(vocab=vocab, layers=layers, hidden=hidden,
                     heads=heads, seq=seq, batch=batch).items():
        if v is not None:
            cfg[k] = v
    mesh = _mesh()
    model = _model(cfg["vocab"], cfg["layers"], cfg["hidden"],
                   cfg["heads"], cfg["seq"])
    n_params = _n_params(_param_template(model))
    results = {}
    for mode in ("ddp", "zero3"):
        jitted, avals, arg_bytes = build_step(
            mode, mesh, model, batch=cfg["batch"], bucket_mb=bucket_mb)
        results[mode] = measure(jitted, avals, arg_bytes)
    hbm = hbm_gb * 1e9
    ddp_peak = results["ddp"]["peak_bytes"]
    z3_peak = results["zero3"]["peak_bytes"]
    doc = {
        "metric": "per_device_peak_bytes_ratio",
        "value": (round(ddp_peak / z3_peak, 2)
                  if ddp_peak and z3_peak else None),
        "unit": "x fewer per-device peak bytes (zero3 vs replicated "
                "ddp)",
        "config": cfg,
        "n_params": n_params,
        "world": int(mesh.shape["dp"]),
        "hbm_limit_bytes": int(hbm),
        "replicated_ddp": results["ddp"],
        "zero3": results["zero3"],
        "replicated_exceeds_hbm": (
            bool(ddp_peak > hbm) if ddp_peak else None),
        "zero3_fits_hbm": (bool(z3_peak < hbm) if z3_peak else None),
    }
    return doc


def train_zero3(vocab=None, layers=None, hidden=None, heads=None,
                seq=None, batch=None, steps=8, bucket_mb=4.0,
                lr=1e-4) -> dict:
    """Materialize the config and run ``steps`` real ZeRO-3 optimizer
    steps on the live mesh — the "a ≥1B-param GPT *trains* where
    replicated DDP cannot" half of the dryrun gate.  Memory-frugal by
    construction: the replicated init tree is dropped as soon as the
    shards are built, so the host never holds params + masters +
    moments the way the DDP path would."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu._compat import shard_map
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    cfg = dict(FLAGSHIP_1B)
    for k, v in dict(vocab=vocab, layers=layers, hidden=hidden,
                     heads=heads, seq=seq, batch=batch).items():
        if v is not None:
            cfg[k] = v
    mesh = _mesh()
    model = _model(cfg["vocab"], cfg["layers"], cfg["hidden"],
                   cfg["heads"], cfg["seq"])
    n_params = _n_params(_param_template(model))
    opt = DistributedFusedAdam(
        lr=lr, shard_params=True,
        bucket_bytes=int(bucket_mb * 1024 * 1024))
    opt.build_layout(_param_template(model), mesh=mesh)
    specs = model.param_specs()
    sspec, st_specs = opt.shard_spec(), opt.state_specs()
    t0 = time.perf_counter()
    params = model.init(jax.random.PRNGKey(0))
    place = lambda t, sp: jax.device_put(
        t, jax.tree.map(lambda s: NamedSharding(mesh, s), sp,
                        is_leaf=lambda x: isinstance(x, P)))
    params = place(params, specs)
    shards = jax.jit(shard_map(
        opt.init_shards, mesh=mesh, in_specs=(specs,),
        out_specs=sspec))(params)
    jax.block_until_ready(shards)
    del params  # the replicated tree is gone: shards are the storage
    state = jax.jit(shard_map(
        opt.init, mesh=mesh, in_specs=(sspec,),
        out_specs=st_specs))(shards)
    init_s = time.perf_counter() - t0

    def train(sh, s, tok_, tgt_):
        p, s = opt.gather_params(sh, s)
        loss, grads = jax.value_and_grad(model.loss)(p, tok_, tgt_)
        sh, s = opt.step(s, grads, sh)
        return sh, s, loss

    step = jax.jit(shard_map(
        train, mesh=mesh,
        in_specs=(sspec, st_specs, P("dp"), P("dp")),
        out_specs=(sspec, st_specs, P()),
    ), donate_argnums=(0, 1))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg["vocab"], (cfg["batch"], cfg["seq"])),
        jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    losses = []
    t0 = time.perf_counter()
    for i in range(steps):
        shards, state, loss = step(shards, state, tokens, targets)
        losses.append(float(loss))
        print(f"  zero3 step {i}: loss {losses[-1]:.4f} "
              f"({time.perf_counter() - t0:.1f}s elapsed)",
              flush=True)
    wall = time.perf_counter() - t0
    return {
        "config": cfg,
        "n_params": n_params,
        "steps": steps,
        "losses": [round(x, 5) for x in losses],
        "finite": bool(np.all(np.isfinite(losses))),
        "loss_decreased": bool(losses[-1] < losses[0]),
        "init_s": round(init_s, 1),
        "wall_s": round(wall, 1),
        "ms_per_step": round(wall / steps * 1e3, 1),
    }


#: The serving tier ladder (all head_dim=128, gelu MLP): chosen so the
#: 16 GB verdict lands one width apart per tier — fp32 carries the 3B,
#: bf16 the 8B, int8 the 13B and int4 the 30B class.  The 13B/30B rows
#: are the quantization claim: those tiers fit ONLY quantized.  The
#: 70B row is the tensor-parallel claim: it exceeds 16 GB at EVERY
#: width single-chip (int4 alone is ~36 GB of pool) and fits only
#: when the quantized pool and head-sharded KV pool are split over a
#: tp group — per-shard verdicts in the per-width ``tp`` sub-rows.
SERVE_TIERS = (
    ("1B", dict(vocab=32768, layers=20, hidden=2048, heads=16)),
    ("3B", dict(vocab=32768, layers=32, hidden=2560, heads=20)),
    ("8B", dict(vocab=32768, layers=32, hidden=4096, heads=32)),
    ("13B", dict(vocab=32768, layers=40, hidden=5120, heads=40)),
    ("30B", dict(vocab=32768, layers=44, hidden=6144, heads=48)),
    ("70B", dict(vocab=32768, layers=80, hidden=8192, heads=64)),
)

WEIGHT_WIDTHS = ("fp32", "bf16", "int8", "int4")

#: tp degrees audited by default — matches the decode_fns warmup grid.
SERVE_TP_DEGREES = (2, 4)


def _tree_bytes(tpl) -> int:
    import jax
    import numpy as np

    return int(sum(
        (int(np.prod(l.shape)) if l.shape else 1)
        * np.dtype(l.dtype).itemsize
        for l in jax.tree.leaves(tpl)))


def _serve_pool_tree(model, width, block=128, tp=1):
    """``eval_shape`` tree of the weight pool at ``width`` — from the
    ACTUAL pool builder (:func:`quantize_gpt_weights`), so scales,
    packing and the full-precision embedding/norm leaves are counted
    as built, not estimated.  ``tp`` is threaded through so the int4
    per-shard packing layout validates the same divisibility rules the
    serving path enforces."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import (
        QUANTIZED_WEIGHT_LEAVES, quantize_gpt_weights,
    )

    tpl = _param_template(model)
    if width == "fp32":
        return tpl
    if width == "bf16":
        def cast(p):
            layers = dict(p["layers"])
            for name in QUANTIZED_WEIGHT_LEAVES:
                if name in layers:
                    leaf = dict(layers[name])
                    leaf["weight"] = leaf["weight"].astype(jnp.bfloat16)
                    layers[name] = leaf
            return {**p, "layers": layers}

        return jax.eval_shape(cast, tpl)
    return jax.eval_shape(
        lambda p: quantize_gpt_weights(p, width, block, tp=tp), tpl)


def _serve_weight_pool_bytes(model, width, block=128) -> int:
    """Whole-pool bytes at ``width`` — what a dp-replicated (tp=1)
    device holds."""
    return _tree_bytes(_serve_pool_tree(model, width, block))


def _serve_pool_specs(model, width, pool, tp):
    """Partition specs matching ``pool``'s pytree — the same specs
    :meth:`GPTModel.decode_fns` shards the served pool with (column
    leaves split the stacked output dim, row leaves the contraction
    dim, the vocab-parallel embedding its vocab rows; norms and row
    biases replicated)."""
    from apex_tpu.models.gpt import _quantized_layer_specs

    specs = model.param_specs()
    if width in ("int8", "int4"):
        specs["layers"] = _quantized_layer_specs(
            specs["layers"], pool["layers"], "tp", tp)
    return specs


def _serve_per_shard_bytes(pool, specs, tp) -> int:
    """Bytes ONE tp shard holds of ``pool`` under ``specs``: each
    leaf's bytes divided by ``tp`` per sharded mesh axis in its spec
    (replicated leaves count in full).  Mirrors gpt.py's
    ``_per_chip_param_bytes`` but works on ``eval_shape`` trees (no
    ``nbytes`` on ShapeDtypeStruct) and needs no live mesh."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    def denom(spec):
        d = 1
        for entry in tuple(spec):
            if entry is None:
                continue
            names = entry if isinstance(entry, tuple) else (entry,)
            d *= tp ** len(names)
        return d

    p_leaves = jax.tree.leaves(pool)
    s_leaves = jax.tree.leaves(specs,
                               is_leaf=lambda t: isinstance(t, P))
    if len(p_leaves) != len(s_leaves):
        raise ValueError(
            f"pool/spec tree mismatch: {len(p_leaves)} pool leaves "
            f"vs {len(s_leaves)} specs")
    return int(sum(
        (int(np.prod(x.shape)) if x.shape else 1)
        * np.dtype(x.dtype).itemsize // denom(s)
        for x, s in zip(p_leaves, s_leaves)))


def _serve_kv_pool_bytes(layers, heads, head_dim, *, max_seqs,
                         context, page_size, kv_dtype) -> int:
    """Exact paged-KV-pool bytes for the serving scenario, from
    ``eval_shape`` of :func:`init_pools` (int8 pools carry their
    per-block scales — counted, not approximated)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.serving.kv_cache import KVCacheConfig, init_pools

    pages_per_seq = -(-context // page_size)
    cfg = KVCacheConfig(
        num_layers=layers, num_heads=heads, head_dim=head_dim,
        num_pages=1 + max_seqs * pages_per_seq, page_size=page_size,
        max_seqs=max_seqs, pages_per_seq=pages_per_seq,
        dtype=jnp.float32, kv_dtype=kv_dtype)
    return _tree_bytes(jax.eval_shape(lambda: init_pools(cfg)))


def run_serve_audit(hbm_gb=DEFAULT_HBM_GB, max_seqs=4, context=1024,
                    page_size=64, block=128,
                    tp=SERVE_TP_DEGREES, draft_tier="1B") -> dict:
    """The --serve document: per-device decode-path bytes (weight pool
    + KV pool + decode activations) for every tier x weight width,
    and the largest tier that fits per width.  KV rides int8 (the
    shipping default since the paged-cache PR) with the fp32 pool
    bytes reported alongside; activations are a structural estimate
    (a handful of (max_seqs, ffn) rows plus the logits row — decode
    activations are microscopic next to the pools).

    Each width row additionally carries per-shard verdicts at every
    tensor-parallel degree in ``tp``: the weight pool divides by the
    decode_fns partition specs (quantized scales shard with their
    blocks), the KV pool head-shards, and a combo that is indivisible
    under the int4 per-shard packing rules reports ``fits_hbm: null``
    with the builder's own error as the note.  Tiers that fit NO width
    single-chip but fit some (width, tp) shard land in
    ``fits_only_tensor_parallel`` — the 70B row is the headline.

    ``draft_tier`` (a tier name, default "1B"; None disables) audits
    model-based speculation co-residency: the draft model's int4
    weight pool + its own int8 paged-KV slice (the
    ``ModelDraftSource`` serving state) are priced ONCE and added to
    every target width row as a ``with_draft`` verdict — the draft is
    replicated per tp shard (it is tiny and drafts on one chip), so
    tp sub-rows add the full draft bytes."""
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig, GPTModel

    hbm = hbm_gb * 1e9
    tiers = []
    largest_fit = {w: None for w in WEIGHT_WIDTHS}
    draft = None
    largest_fit_draft = {w: None for w in WEIGHT_WIDTHS}
    if draft_tier is not None:
        dshape = dict(SERVE_TIERS)[draft_tier]
        dmodel = GPTModel(GPTConfig(
            vocab_size=dshape["vocab"], num_layers=dshape["layers"],
            hidden_size=dshape["hidden"],
            num_attention_heads=dshape["heads"],
            max_position_embeddings=context,
            position_embedding="rope", compute_dtype=jnp.float32,
            remat=False, attention_impl="xla",
        ))
        draft = {
            "tier": draft_tier,
            "weight_width": "int4",
            "weight_pool_bytes": _serve_weight_pool_bytes(
                dmodel, "int4", block),
            "kv_pool_bytes": _serve_kv_pool_bytes(
                dshape["layers"], dshape["heads"],
                dshape["hidden"] // dshape["heads"],
                max_seqs=max_seqs, context=context,
                page_size=page_size, kv_dtype=jnp.int8),
        }
        draft["total_bytes"] = (draft["weight_pool_bytes"]
                                + draft["kv_pool_bytes"])
    for name, shape in SERVE_TIERS:
        head_dim = shape["hidden"] // shape["heads"]
        model = GPTModel(GPTConfig(
            vocab_size=shape["vocab"], num_layers=shape["layers"],
            hidden_size=shape["hidden"],
            num_attention_heads=shape["heads"],
            max_position_embeddings=context,
            position_embedding="rope", compute_dtype=jnp.float32,
            remat=False, attention_impl="xla",
        ))
        n_params = _n_params(_param_template(model))
        kv = {
            "fp32": _serve_kv_pool_bytes(
                shape["layers"], shape["heads"], head_dim,
                max_seqs=max_seqs, context=context,
                page_size=page_size, kv_dtype=None),
            "int8": _serve_kv_pool_bytes(
                shape["layers"], shape["heads"], head_dim,
                max_seqs=max_seqs, context=context,
                page_size=page_size, kv_dtype=jnp.int8),
        }
        act = int(max_seqs * (4 * shape["hidden"] * 4 * 4
                              + shape["vocab"] * 4))
        row = {"tier": name, "shape": dict(shape),
               "n_params": n_params, "kv_pool_bytes": kv,
               "activations_bytes": act, "widths": {}}
        for w in WEIGHT_WIDTHS:
            wp = _serve_weight_pool_bytes(model, w, block)
            total = wp + kv["int8"] + act
            fits = total < hbm
            row["widths"][w] = {
                "weight_pool_bytes": wp,
                "total_bytes": total,
                "fits_hbm": bool(fits),
            }
            if fits:
                largest_fit[w] = name     # tiers ascend in size
            if draft is not None:
                dtot = total + draft["total_bytes"]
                row["widths"][w]["with_draft"] = {
                    "total_bytes": dtot,
                    "fits_hbm": bool(dtot < hbm),
                }
                if dtot < hbm:
                    largest_fit_draft[w] = name
            tp_rows = {}
            for t in tp or ():
                if shape["heads"] % t:
                    tp_rows[str(t)] = {
                        "fits_hbm": None,
                        "note": f"{shape['heads']} heads do not "
                                f"divide tp={t}"}
                    continue
                try:
                    pool = _serve_pool_tree(model, w, block, tp=t)
                except ValueError as e:
                    tp_rows[str(t)] = {"fits_hbm": None,
                                       "note": str(e)}
                    continue
                specs = _serve_pool_specs(model, w, pool, t)
                wps = _serve_per_shard_bytes(pool, specs, t)
                kvs = kv["int8"] // t         # head-sharded pool
                totals = wps + kvs + act
                tp_rows[str(t)] = {
                    "per_shard_weight_pool_bytes": wps,
                    "per_shard_kv_pool_bytes": kvs,
                    "per_shard_total_bytes": totals,
                    "fits_hbm": bool(totals < hbm),
                }
                if draft is not None:
                    # the draft rides every shard in full (replicated)
                    dtp = totals + draft["total_bytes"]
                    tp_rows[str(t)]["with_draft"] = {
                        "per_shard_total_bytes": dtp,
                        "fits_hbm": bool(dtp < hbm),
                    }
            if tp_rows:
                row["widths"][w]["tp"] = tp_rows
        tiers.append(row)
    only_tp = []
    for r in tiers:
        if any(r["widths"][w]["fits_hbm"] for w in WEIGHT_WIDTHS):
            continue
        fits_at = [
            {"width": w, "tp": int(t)}
            for w in WEIGHT_WIDTHS
            for t, c in sorted(r["widths"][w].get("tp", {}).items(),
                               key=lambda kv_: int(kv_[0]))
            if c.get("fits_hbm")
        ]
        if fits_at:
            only_tp.append({"tier": r["tier"], "fits_at": fits_at})
    only_quant = [
        r["tier"] for r in tiers
        if not r["widths"]["fp32"]["fits_hbm"]
        and not r["widths"]["bf16"]["fits_hbm"]
        and (r["widths"]["int8"]["fits_hbm"]
             or r["widths"]["int4"]["fits_hbm"])
    ]
    return {
        "metric": "serve_largest_fit_tier",
        "value": {w: largest_fit[w] for w in WEIGHT_WIDTHS},
        "unit": f"largest tier under {hbm_gb:g} GB HBM per weight "
                f"width (int8 KV)",
        "scenario": {"max_seqs": max_seqs, "context": context,
                     "page_size": page_size, "weight_block": block,
                     "kv_dtype": "int8",
                     "tp_degrees": [int(t) for t in (tp or ())]},
        "hbm_limit_bytes": int(hbm),
        "tiers": tiers,
        "fits_only_quantized": only_quant,
        "fits_only_tensor_parallel": only_tp,
        **({} if draft is None else {
            "draft": draft,
            "draft_co_resident_largest_fit": {
                w: largest_fit_draft[w] for w in WEIGHT_WIDTHS},
        }),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=None)
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--hidden", type=int, default=None)
    ap.add_argument("--heads", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--bucket-mb", type=float, default=4.0)
    ap.add_argument("--hbm-gb", type=float, default=DEFAULT_HBM_GB,
                    help="per-device HBM for the fits/exceeds verdict")
    ap.add_argument("--compare", action="store_true",
                    help="replicated-DDP vs ZeRO-3 side by side "
                         "(writes MEMORY_AUDIT.json)")
    ap.add_argument("--train-steps", type=int, default=0,
                    help="ALSO run N real ZeRO-3 steps at the shape "
                         "(slow on CPU hosts; proves the config "
                         "trains, not just compiles)")
    ap.add_argument("--serve", action="store_true",
                    help="serving audit: decode-path bytes per tier "
                         "at fp32/bf16/int8/int4 weight widths "
                         "(writes MEMORY_AUDIT_SERVE.json)")
    ap.add_argument("--max-seqs", type=int, default=4,
                    help="--serve: concurrent serving slots")
    ap.add_argument("--context", type=int, default=1024,
                    help="--serve: per-slot context budget (tokens)")
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--weight-block", type=int, default=128,
                    help="--serve: quantization block size")
    ap.add_argument("--tp", type=int, action="append", default=None,
                    help="--serve: tensor-parallel degree for "
                         "per-shard verdict rows (repeatable; "
                         "default: 2 and 4)")
    ap.add_argument("--draft-tier", default="1B",
                    choices=[n for n, _ in SERVE_TIERS] + ["none"],
                    help="--serve: co-resident draft-model tier for "
                         "the speculation verdict (int4 pool + its "
                         "own int8 KV slice added to every width "
                         "row; 'none' disables)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    _force_virtual_devices(args.devices)

    if args.serve:
        doc = run_serve_audit(
            hbm_gb=args.hbm_gb, max_seqs=args.max_seqs,
            context=args.context, page_size=args.page_size,
            block=args.weight_block,
            tp=tuple(args.tp) if args.tp else SERVE_TP_DEGREES,
            draft_tier=(None if args.draft_tier == "none"
                        else args.draft_tier))
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        out_path = args.out or os.path.join(
            root, "MEMORY_AUDIT_SERVE.json")
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
        gb = 1e9
        print(json.dumps({
            "metric": doc["metric"], "value": doc["value"],
            "fits_only_quantized": doc["fits_only_quantized"],
            "fits_only_tensor_parallel":
                doc["fits_only_tensor_parallel"],
            **({} if "draft" not in doc else {
                "draft_tier": doc["draft"]["tier"],
                "draft_gb": round(doc["draft"]["total_bytes"] / gb,
                                  3),
                "draft_co_resident_largest_fit":
                    doc["draft_co_resident_largest_fit"],
            }),
            "tiers_gb": {
                r["tier"]: {
                    w: round(r["widths"][w]["total_bytes"] / gb, 2)
                    for w in WEIGHT_WIDTHS}
                for r in doc["tiers"]},
        }))
        print(f"wrote {out_path}")
        return

    dims = dict(vocab=args.vocab, layers=args.layers,
                hidden=args.hidden, heads=args.heads, seq=args.seq,
                batch=args.batch)
    doc = run_memory_audit(bucket_mb=args.bucket_mb,
                           hbm_gb=args.hbm_gb, **dims)
    if args.train_steps:
        doc["training"] = train_zero3(steps=args.train_steps,
                                      bucket_mb=args.bucket_mb,
                                      **dims)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_path = args.out or os.path.join(root, "MEMORY_AUDIT.json")
    if args.compare or args.out:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    gb = 1e9
    print(json.dumps({
        "metric": doc["metric"], "value": doc["value"],
        "n_params": doc["n_params"],
        "ddp_peak_gb": round(
            (doc["replicated_ddp"]["peak_bytes"] or 0) / gb, 2),
        "zero3_peak_gb": round(
            (doc["zero3"]["peak_bytes"] or 0) / gb, 2),
        "ddp_argument_gb": round(
            (doc["replicated_ddp"]["argument_bytes"] or 0) / gb, 2),
        "zero3_argument_gb": round(
            (doc["zero3"]["argument_bytes"] or 0) / gb, 2),
        "replicated_exceeds_hbm": doc["replicated_exceeds_hbm"],
        "zero3_fits_hbm": doc["zero3_fits_hbm"],
    }))
    if args.compare or args.out:
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
